"""Cluster SLI layer (PR 8): watch/informer freshness instrumentation,
multi-process metrics federation, and live SLO evaluation with
burn-rate alerting.

Covers, in rough dependency order:

- Prometheus text exposition round-trip — ``parse(expose(x)) ≡ x`` over
  the ENTIRE live registry (the metrics lint: exposition drift can
  never silently break the federation scraper);
- ``MetricsFederation``: instance-labelled merge, last-scrape-wins,
  counter folding by cumulative delta with reset detection, and the
  ``absorb_snapshot`` compat wrapper sharing ONE delta ledger with the
  scrape path;
- freshness SLIs: store-commit stamping (``Event.ts``), end-to-end
  watch delivery over a real APIServer, informer lag, and the
  scheduler cache's newest-applied-event anchor;
- the ``SLOEngine``: rolling-window good/bad accounting, multi-window
  burn-rate alerting, metric mirroring, flight-recorder dump on breach;
- ``/debug/slo`` (admin envelope) and ``tools/slo_report.py``;
- the FaultGate acceptance: an injected watch stall flips the
  freshness SLOs to violated (alert + dump fire) while a clean run
  stays green.
"""

import json
import time

import pytest

from kubernetes_tpu.apiserver.rest import APIServer
from kubernetes_tpu.apiserver.store import ClusterStore, Event
from kubernetes_tpu.client.restcluster import RestClusterClient
from kubernetes_tpu.metrics.federation import (
    ExpositionError,
    MetricsFederation,
    families_from_registry,
    lint_family,
    metrics_federation,
    parse_exposition,
)
from kubernetes_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from kubernetes_tpu.observability.slo import SLODef, SLOEngine
from kubernetes_tpu.testing import MakeNode, MakePod


def _serve(**kwargs):
    store = ClusterStore()
    server = APIServer(store=store, **kwargs).start()
    return store, server


def _sample_registry() -> MetricsRegistry:
    reg = MetricsRegistry()
    c = reg.register(Counter("t_requests_total", "requests",
                             ("verb", "code")))
    c.inc("GET", "200", amount=3)
    c.inc("POST", "409", amount=1.5)
    g = reg.register(Gauge("t_depth", "queue depth"))
    g.set(7.0)
    h = reg.register(Histogram("t_latency_seconds", "latency",
                               ("kind",), buckets=(0.1, 1.0, 5.0)))
    h.observe_many([0.05, 0.5, 2.0, 9.0], "Pod")
    h.observe(0.2, "Node")
    hu = reg.register(Histogram("t_plain_seconds", "unlabelled",
                                buckets=(0.5, 2.0)))
    hu.observe(0.7)
    # escaping: label values carrying quotes, backslashes, newlines
    # must survive the wire (the federation scraper reads real label
    # values like pod names — a torn escape corrupts the merge)
    e = reg.register(Counter("t_escaped_total", "with \"quotes\"\nand "
                             "backslash \\", ("name",)))
    e.inc('we"ird\\na\nme', amount=2)
    return reg


def _families_equal(truth, parsed) -> None:
    for name, fam in truth.items():
        got = parsed[name]
        assert got.type == fam.type, name
        if fam.samples or fam.histograms:
            assert tuple(got.label_names) == tuple(fam.label_names), name
        assert got.samples == fam.samples, name
        assert set(got.histograms) == set(fam.histograms), name
        for key, series in fam.histograms.items():
            g = got.histograms[key]
            assert g.bucket_edges == series.bucket_edges, (name, key)
            assert g.bucket_counts == series.bucket_counts, (name, key)
            assert g.sum == pytest.approx(series.sum), (name, key)
            assert g.count == series.count, (name, key)


# ---------------------------------------------------------------------------
# exposition round-trip + metrics lint


class TestExpositionRoundTrip:
    def test_parse_expose_is_identity(self):
        reg = _sample_registry()
        _families_equal(families_from_registry(reg),
                        parse_exposition(reg.expose()))

    def test_histogram_renders_cumulative_buckets_with_inf(self):
        reg = _sample_registry()
        text = reg.expose()
        # cumulative on the wire: Pod series 0.05,0.5,2.0,9.0 over
        # edges (0.1, 1.0, 5.0, +Inf) -> cum 1,2,3,4
        assert 't_latency_seconds_bucket{kind="Pod",le="0.1"} 1' in text
        assert 't_latency_seconds_bucket{kind="Pod",le="1"} 2' in text
        assert 't_latency_seconds_bucket{kind="Pod",le="5"} 3' in text
        assert 't_latency_seconds_bucket{kind="Pod",le="+Inf"} 4' in text
        assert 't_latency_seconds_count{kind="Pod"} 4' in text

    def test_malformed_sample_line_raises(self):
        with pytest.raises(ExpositionError):
            parse_exposition("what even is this line\n")
        with pytest.raises(ExpositionError):
            parse_exposition('t_x{unclosed="yes} 1\n')

    def test_lint_flags_invalid_families(self):
        bad_name = families_from_registry(_sample_registry())
        fam = list(bad_name.values())[0]
        fam.name = "0bad-name"
        assert lint_family(fam)
        h = bad_name["t_latency_seconds"]
        h.label_names = ("le",)
        assert any("le" in p for p in lint_family(h))
        c = bad_name["t_requests_total"]
        c.label_names = ("__reserved",)
        assert any("reserved" in p for p in lint_family(c))

    def test_metrics_lint_entire_live_registry(self):
        """The CI metrics lint (satellite): instantiate EVERY metric
        module against the process registry, render the whole thing,
        and require parse(render(x)) ≡ x plus Prometheus-valid names
        and labels — exposition drift can never silently break the
        federation scraper."""
        from kubernetes_tpu.metrics import default_registry
        from kubernetes_tpu.metrics.apf_metrics import apf_metrics
        from kubernetes_tpu.metrics.autoscaler_metrics import (
            autoscaler_metrics,
        )
        from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics
        from kubernetes_tpu.metrics.freshness_metrics import (
            freshness_metrics,
        )
        from kubernetes_tpu.metrics.scheduler_metrics import (
            SchedulerMetrics,
        )
        from kubernetes_tpu.metrics.solver_metrics import solver_metrics

        apf_metrics(), autoscaler_metrics(), fabric_metrics()
        freshness_metrics(), solver_metrics()
        for reg in (default_registry(), SchedulerMetrics().registry):
            truth = families_from_registry(reg)
            parsed = parse_exposition(reg.expose())
            _families_equal(truth, parsed)
            for fam in truth.values():
                assert lint_family(fam) == [], fam.name

    def test_no_duplicate_registrations_across_modules(self):
        """Every metric module keeps its family objects alive in the
        shared registry: a second module registering the same name
        would silently orphan the first module's series. Bind each
        module to ONE fresh registry and require every name to appear
        exactly once."""
        from kubernetes_tpu.metrics.apf_metrics import ApfMetrics
        from kubernetes_tpu.metrics.freshness_metrics import (
            FreshnessMetrics,
        )

        reg = MetricsRegistry()
        seen = {}
        for cls in (ApfMetrics, FreshnessMetrics):
            before = {m.name: m for m in reg.all_metrics()}
            cls(reg)
            for m in reg.all_metrics():
                if m.name in before:
                    assert before[m.name] is m, \
                        f"{cls.__name__} re-registered {m.name}"
                else:
                    assert m.name not in seen, m.name
                    seen[m.name] = cls.__name__


# ---------------------------------------------------------------------------
# federation: merge + fold


class TestFederationMerge:
    def test_merge_two_instances_with_instance_label(self):
        fed = MetricsFederation()
        fed.absorb_text(_sample_registry().expose(), "a")
        fed.absorb_text(_sample_registry().expose(), "b")
        assert fed.instances() == {"a", "b"}
        # 3 + 1.5 per instance
        assert fed.counter_total("t_requests_total") == \
            pytest.approx(9.0)
        merged = fed.series("t_latency_seconds")
        assert merged.label_names == ("instance", "kind")
        assert ("a", "Pod") in merged._series
        assert ("b", "Pod") in merged._series
        assert merged.buckets == (0.1, 1.0, 5.0)

    def test_repeat_scrape_same_instance_never_double_counts(self):
        fed = MetricsFederation()
        text = _sample_registry().expose()
        fed.absorb_text(text, "a")
        fed.absorb_text(text, "a")
        assert fed.counter_total("t_requests_total") == \
            pytest.approx(4.5)
        assert fed.series("t_latency_seconds").count("a", "Pod") == 4

    def test_fold_counter_deltas_and_reset_detection(self):
        local = MetricsRegistry()
        target = local.register(
            Counter("t_requests_total", "x", ("verb", "code")))
        fed = MetricsFederation(fold_registry=local)

        def text(n: float) -> str:
            reg = MetricsRegistry()
            reg.register(Counter("t_requests_total", "x",
                                 ("verb", "code"))).inc(
                "GET", "200", amount=n)
            return reg.expose()

        fed.absorb_text(text(10), "child", fold=True)
        fed.absorb_text(text(25), "child", fold=True)
        assert target.get("GET", "200") == pytest.approx(25)
        # counter reset (child restarted): full new total folds in
        fed.absorb_text(text(4), "child", fold=True)
        assert target.get("GET", "200") == pytest.approx(29)
        # forget_instance restarts the baseline for a NEW child under
        # the same name: its total folds in full, not as a delta
        fed.forget_instance("child")
        assert "child" not in fed.instances()
        fed.absorb_text(text(30), "child", fold=True)
        assert target.get("GET", "200") == pytest.approx(59)

    def test_fold_skips_unknown_and_mismatched_families(self):
        local = MetricsRegistry()
        local.register(Counter("t_requests_total", "x", ("verb",)))
        fed = MetricsFederation(fold_registry=local)
        # remote labels (verb, code) != local (verb,): no fold, no crash
        fed.absorb_text(_sample_registry().expose(), "a", fold=True)
        assert local.get("t_requests_total").collect() == []

    def test_absorb_snapshot_compat_shares_the_fold_ledger(self):
        """The legacy /debug/apf JSON path now routes through the SAME
        federation delta ledger as the scrape path: calling it twice
        with cumulative totals folds the delta (not the sum), and a
        scrape of the same instance afterwards cannot double-count."""
        from kubernetes_tpu.metrics.apf_metrics import ApfMetrics

        apfm = ApfMetrics(MetricsRegistry())
        snap = {"levels": {"workload": {
            "rejected": {"queue-full": 10}, "dispatched_total": 100,
            "seats_dispatched_total": 120, "capacity": 8}}}
        instance = "compat-test-child"
        fed = metrics_federation()
        fed.forget_instance(instance)
        try:
            apfm.absorb_snapshot(snap, instance=instance)
            assert apfm.rejected_requests_total.get(
                "workload", "queue-full") == pytest.approx(10)
            assert apfm.dispatched_requests_total.get("workload") == \
                pytest.approx(100)
            # same totals again: cumulative, so the fold is a no-op
            apfm.absorb_snapshot(snap, instance=instance)
            assert apfm.rejected_requests_total.get(
                "workload", "queue-full") == pytest.approx(10)
            # grown totals: only the delta lands
            snap["levels"]["workload"]["rejected"]["queue-full"] = 17
            apfm.absorb_snapshot(snap, instance=instance)
            assert apfm.rejected_requests_total.get(
                "workload", "queue-full") == pytest.approx(17)
            assert apfm.last_snapshot is snap
        finally:
            fed.forget_instance(instance)

    def test_scrape_live_server_metrics(self):
        store, server = _serve()
        try:
            store.create_pod(MakePod().name("m1").uid("u1").obj())
            fed = MetricsFederation()
            assert fed.scrape(server.url, instance="api") is True
            assert "api" in fed.instances()
            assert fed.scrape_errors == []
        finally:
            server.shutdown_server()

    def test_scrape_failure_is_best_effort(self):
        fed = MetricsFederation()
        assert fed.scrape("http://127.0.0.1:9", instance="gone",
                          timeout=0.5) is False
        assert fed.scrape_errors


# ---------------------------------------------------------------------------
# freshness SLIs


class TestFreshnessInstrumentation:
    def test_store_dispatch_stamps_commit_ts(self):
        store = ClusterStore()
        seen = []
        store.watch(lambda e: seen.append(e))
        t0 = time.time()
        store.create_pod(MakePod().name("f1").uid("u1").obj())
        assert seen and seen[0].ts >= t0
        # batch dispatch stamps once per batch
        seen.clear()
        store.create_pods(
            [MakePod().name(f"fb{i}").uid(f"ub{i}").obj()
             for i in range(3)])
        stamped = [e.ts for e in seen if e.kind == "Pod"]
        assert stamped and all(ts >= t0 for ts in stamped)

    def test_prestamped_event_is_not_restamped(self):
        store = ClusterStore()
        seen = []
        store.watch(lambda e: seen.append(e))
        ev = Event("ADDED", "Pod", MakePod().name("p").uid("u").obj(),
                   ts=123.0)
        store._dispatch(ev)
        assert seen[-1].ts == 123.0

    def test_watch_delivery_measured_end_to_end(self):
        """Commit → client decode over the real wire: the histogram
        grows by the number of delivered stamped events, and the
        measured lag is sane (sub-second on an idle loopback)."""
        from kubernetes_tpu.metrics.freshness_metrics import (
            freshness_metrics,
        )

        fm = freshness_metrics()
        before = fm.watch_delivery_seconds.count("Pod")
        store, server = _serve()
        client = RestClusterClient(server.url, watch_kinds=("Pod",))
        got = []
        handle = client.watch(lambda e: None,
                              batch_fn=lambda evs: got.extend(evs))
        try:
            time.sleep(0.3)
            store.create_pod(MakePod().name("wd1").uid("u1").obj())
            deadline = time.time() + 5
            while time.time() < deadline and \
                    fm.watch_delivery_seconds.count("Pod") <= before:
                time.sleep(0.05)
            grown = fm.watch_delivery_seconds.count("Pod") - before
            assert grown >= 1
            assert fm.watch_delivery_seconds.quantile(0.99, "Pod") < 10.0
        finally:
            handle.stop()
            server.shutdown_server()

    def test_informer_lag_observed_on_dispatch(self):
        from kubernetes_tpu.client.informers import SharedInformerFactory
        from kubernetes_tpu.metrics.freshness_metrics import (
            freshness_metrics,
        )

        fm = freshness_metrics()
        before = fm.informer_lag_seconds.count("Pod")
        store = ClusterStore()
        factory = SharedInformerFactory(store)
        inf = factory.informer_for("Pod")
        inf.add_event_handler(lambda *a: None)
        factory.start()
        try:
            factory.wait_for_cache_sync()
            store.create_pod(MakePod().name("il1").uid("u1").obj())
            deadline = time.time() + 5
            while time.time() < deadline and \
                    fm.informer_lag_seconds.count("Pod") <= before:
                time.sleep(0.02)
            assert fm.informer_lag_seconds.count("Pod") > before
            assert fm.informer_queue_depth.get() >= 1
        finally:
            factory.stop()

    def test_cache_newest_event_anchor_keeps_max(self):
        from kubernetes_tpu.scheduler.cache import SchedulerCache

        cache = SchedulerCache()
        assert cache.last_event_ts == 0.0
        cache.note_event_ts(100.0)
        cache.note_event_ts(50.0)    # relist replay out of order
        assert cache.last_event_ts == 100.0
        cache.note_event_ts(101.0)
        assert cache.last_event_ts == 101.0

    def test_row_summary_shape(self):
        from kubernetes_tpu.metrics.freshness_metrics import (
            FreshnessMetrics,
            freshness_row_summary,
        )

        fm = FreshnessMetrics(MetricsRegistry())
        import kubernetes_tpu.metrics.freshness_metrics as fmod

        prev = fmod._default
        fmod._default = fm
        try:
            fm.watch_delivery_seconds.observe_many(
                [0.001, 0.002, 0.4], "Pod")
            out = freshness_row_summary(
                {"max_staleness_s": 0.25},
                {"watch_delivery": {"violated": True, "events_fast": 3},
                 "schedule_latency": {"violated": False,
                                      "events_fast": 0}})
            assert out["watch_delivery_events"] == 3
            assert out["watch_delivery_p99_ms"] > 0
            assert out["max_snapshot_staleness_ms"] == \
                pytest.approx(250.0)
            # quiet SLOs with zero events are dropped; violations and
            # active SLOs keep their verdicts
            assert out["slo"] == {"watch_delivery": "violated"}
        finally:
            fmod._default = prev


# ---------------------------------------------------------------------------
# the SLO engine


def _latency_slo(threshold=1.0, objective=0.99, name="lat"):
    return SLODef(name=name, description="d", metric="t_lat",
                  threshold_s=threshold, objective=objective)


def _engine(reg, slos, **kw):
    kw.setdefault("enabled", True)
    return SLOEngine(slos=slos, registries=[reg], **kw)


class TestSLOEngine:
    def _hist(self, reg):
        return reg.register(Histogram("t_lat", "x",
                                      buckets=(0.1, 1.0, 5.0)))

    def test_green_run_stays_green(self):
        reg = MetricsRegistry()
        h = self._hist(reg)
        clock = [0.0]
        eng = _engine(reg, [_latency_slo()], clock=lambda: clock[0])
        eng.tick()
        h.observe_many([0.05] * 200)
        clock[0] = 10.0
        out = eng.evaluate()
        s = out["slos"]["lat"]
        assert out["healthy"] is True
        assert s["violated"] is False and s["alerting"] is False
        assert s["events_fast"] == 200
        assert s["burn_fast"] == 0.0
        assert s["budget_remaining_pct"] == 100.0

    def test_violation_without_multiwindow_alert(self):
        reg = MetricsRegistry()
        h = self._hist(reg)
        clock = [0.0]
        eng = _engine(reg, [_latency_slo()], clock=lambda: clock[0])
        eng.tick()
        # 2% bad at a 1% budget: burn 2.0 — violated, but far below
        # the 14.4x page threshold
        h.observe_many([0.05] * 98 + [3.0] * 2)
        clock[0] = 10.0
        s = eng.evaluate()["slos"]["lat"]
        assert s["violated"] is True
        assert s["alerting"] is False
        assert s["burn_fast"] == pytest.approx(2.0)

    def test_multiwindow_burn_alert_latches_once_and_dumps(self,
                                                          monkeypatch):
        from kubernetes_tpu.metrics import default_registry
        from kubernetes_tpu.observability import get_tracer

        tracer = get_tracer()
        dumps = []
        monkeypatch.setattr(tracer, "enabled", True)
        monkeypatch.setattr(
            tracer, "dump",
            lambda *a, **kw: dumps.append(kw.get("reason")) or "/x")
        reg = MetricsRegistry()
        h = self._hist(reg)
        clock = [0.0]
        eng = _engine(reg, [_latency_slo()], clock=lambda: clock[0])
        eng.tick()
        h.observe_many([3.0] * 100)   # 100% bad: burn 100x both windows
        clock[0] = 10.0
        alerts = default_registry().get("slo_alerts_total")
        before = alerts.get("lat") if alerts else 0.0
        s = eng.evaluate()["slos"]["lat"]
        assert s["alerting"] is True
        assert dumps == ["slo-lat"]
        alerts = default_registry().get("slo_alerts_total")
        assert alerts.get("lat") == before + 1
        # still alerting on the next evaluation: latched, no re-fire
        clock[0] = 11.0
        assert eng.evaluate()["slos"]["lat"]["alerting"] is True
        assert dumps == ["slo-lat"]
        assert alerts.get("lat") == before + 1
        # mirrors land in the default registry
        burn = default_registry().get("slo_burn_rate")
        assert burn.get("lat", "fast") >= 14.4
        assert default_registry().get("slo_violated").get("lat") == 1.0

    def test_fast_window_recovers_after_bad_burst_ages_out(self):
        reg = MetricsRegistry()
        h = self._hist(reg)
        clock = [0.0]
        eng = _engine(reg, [_latency_slo()], fast_window_s=60.0,
                      slow_window_s=600.0, clock=lambda: clock[0])
        eng.tick()
        h.observe_many([3.0] * 50)
        clock[0] = 10.0
        assert eng.evaluate()["slos"]["lat"]["violated"] is True
        # 100s later the burst is outside the fast window; fresh good
        # traffic only
        clock[0] = 110.0
        h.observe_many([0.05] * 50)
        s = eng.evaluate()["slos"]["lat"]
        assert s["violated"] is False
        assert s["burn_fast"] == 0.0

    def test_error_ratio_slo_reads_bad_and_total_counters(self):
        reg = MetricsRegistry()
        bad = reg.register(Counter("t_rejected_total", "x", ("r",)))
        ok = reg.register(Counter("t_dispatched_total", "x"))
        slo = SLODef(name="avail", description="d",
                     metric="t_rejected_total", kind="error_ratio",
                     total_metric="t_dispatched_total", objective=0.999)
        clock = [0.0]
        eng = _engine(reg, [slo], clock=lambda: clock[0])
        eng.tick()
        ok.inc(amount=998)
        bad.inc("429", amount=2)
        clock[0] = 5.0
        s = eng.evaluate()["slos"]["avail"]
        # 2 bad / 1000 total at a 0.1% budget: burn 2x
        assert s["burn_fast"] == pytest.approx(2.0, rel=1e-3)
        assert s["violated"] is True
        bad.inc("429", amount=98)
        clock[0] = 6.0
        assert eng.evaluate()["slos"]["avail"]["alerting"] is True

    def test_windowed_p99_comes_from_bucket_deltas(self):
        reg = MetricsRegistry()
        h = self._hist(reg)
        clock = [0.0]
        eng = _engine(reg, [_latency_slo()], fast_window_s=60.0,
                      clock=lambda: clock[0])
        # lifetime history: a horrible warmup entirely before the window
        h.observe_many([4.0] * 100)
        eng.tick()
        clock[0] = 100.0
        h.observe_many([0.05] * 100)
        s = eng.evaluate()["slos"]["lat"]
        # the warmup is outside the window: p99 reflects the fresh
        # traffic, not the lifetime histogram
        assert s["sli_fast_p99_s"] <= 0.1
        assert s["violated"] is False

    def test_disabled_engine_answers_disabled(self):
        eng = SLOEngine(enabled=False)
        assert eng.evaluate() == {"enabled": False, "slos": {}}

    def test_reset_rescales_windows_and_drops_latch(self):
        reg = MetricsRegistry()
        h = self._hist(reg)
        clock = [0.0]
        eng = _engine(reg, [_latency_slo()], clock=lambda: clock[0])
        eng.tick()
        h.observe_many([3.0] * 10)
        clock[0] = 1.0
        assert eng.evaluate()["slos"]["lat"]["violated"] is True
        eng.reset(fast_window_s=30.0, slow_window_s=120.0)
        assert eng.fast_window_s == 30.0
        eng.tick()
        clock[0] = 2.0
        # fresh window: the old bad events are the new baseline
        assert eng.evaluate()["slos"]["lat"]["violated"] is False


# ---------------------------------------------------------------------------
# /debug/slo + the report tool


class TestDebugSloEndpoint:
    def test_get_returns_live_evaluation(self):
        store, server = _serve()
        try:
            client = RestClusterClient(server.url)
            code, doc = client._request("GET", "/debug/slo")
            assert code == 200
            assert doc["enabled"] is True
            assert "snapshot_staleness" in doc["slos"]
            assert "watch_delivery" in doc["slos"]
        finally:
            server.shutdown_server()

    def test_untrusted_identity_is_403(self):
        store, server = _serve(tokens={"tok-w": "workload-user"})
        try:
            client = RestClusterClient(server.url, token="tok-w")
            code, _ = client._request("GET", "/debug/slo")
            assert code == 403
        finally:
            server.shutdown_server()

    def test_non_get_is_405(self):
        store, server = _serve()
        try:
            cp = RestClusterClient(server.url)   # loopback, tokenless
            code, _ = cp._request("POST", "/debug/slo", {})
            assert code == 405
        finally:
            server.shutdown_server()


class TestSloReportTool:
    def test_artifact_rows_table_and_strict_exit(self, tmp_path,
                                                 capsys):
        from tools.slo_report import main

        rows = [
            {"metric": "pods_scheduled_per_sec[clean]", "value": 100,
             "freshness": {"watch_delivery_p99_ms": 4.2,
                           "max_snapshot_staleness_ms": 120.0,
                           "slo": {"watch_delivery": "ok"}}},
            {"metric": "pods_scheduled_per_sec[stalled]", "value": 10,
             "freshness": {"watch_delivery_p99_ms": 900.0,
                           "slo": {"watch_delivery": "violated"}}},
        ]
        path = tmp_path / "rows.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        assert main(["--artifact", str(path)]) == 0
        out = capsys.readouterr().out
        assert "watch_delivery=VIOLATED" in out
        assert "UNHEALTHY" in out
        assert main(["--artifact", str(path), "--strict"]) == 1
        capsys.readouterr()
        # machine-readable mode names the violated SLOs
        assert main(["--artifact", str(path), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["violated"] == ["watch_delivery"]

    def test_live_url_table(self, capsys):
        from tools.slo_report import main

        store, server = _serve()
        try:
            assert main(["--url", server.url]) == 0
            out = capsys.readouterr().out
            assert "snapshot_staleness" in out
            assert "healthy" in out
        finally:
            server.shutdown_server()

    def test_out_file_is_scratch(self, tmp_path, capsys):
        from tools.slo_report import main

        path = tmp_path / "rows.jsonl"
        path.write_text(json.dumps(
            {"metric": "m", "freshness": {"slo": {}}}) + "\n")
        out_file = tmp_path / "slo_report.txt"
        assert main(["--artifact", str(path),
                     "--out", str(out_file)]) == 0
        assert out_file.read_text() == capsys.readouterr().out


# ---------------------------------------------------------------------------
# diag line: the slo[...] segment


class TestDiagSloSegment:
    def test_quiet_when_green(self):
        from kubernetes_tpu.harness import diagfmt

        assert diagfmt.format_slo(
            {"slos": {"lat": {"violated": False}}}) == ""
        assert diagfmt.format_slo({}) == ""

    def test_violated_segment_round_trips_through_parser(self):
        from kubernetes_tpu.harness import diagfmt

        seg = diagfmt.format_slo({"slos": {
            "watch_delivery": {"violated": True, "burn_fast": 22.13,
                               "burn_slow": 8.0,
                               "budget_remaining_pct": 0.0,
                               "alerting": True},
            "snapshot_staleness": {"violated": True, "burn_fast": 3.0,
                                   "burn_slow": 1.0,
                                   "budget_remaining_pct": 40.0},
            "schedule_latency": {"violated": False},
        }})
        assert seg.startswith("slo[")
        line = diagfmt.format_diag(["solve.commit=1.00s/2", seg])
        parsed = diagfmt.parse_diag(line)
        assert parsed["slo"]["violated"] == \
            "snapshot_staleness,watch_delivery"
        assert parsed["slo"]["worst"] == "watch_delivery"
        assert parsed["slo"]["burn_fast"] == pytest.approx(22.1)
        assert parsed["slo"]["alerting"] == "watch_delivery"
        # the other segments survive alongside
        assert parsed["phases"]["solve.commit"]["count"] == 2


# ---------------------------------------------------------------------------
# the FaultGate acceptance: injected watch latency flips the freshness
# SLOs; a clean run stays green


def _bench_slos():
    """Freshness objectives scaled to test timescales (the bench
    harnesses rescale the same way via ``SLOEngine.reset``)."""
    return [
        SLODef(name="watch_delivery", description="d",
               metric="watch_delivery_seconds", threshold_s=0.25,
               objective=0.99),
        SLODef(name="snapshot_staleness", description="d",
               metric="snapshot_staleness_seconds", threshold_s=0.5,
               objective=0.99),
    ]


def _run_sched_over_rest(server, n_pods=24, batch=True):
    """Drive the real scheduler over the REST wire and return once all
    pods are bound (the caller asserts on the SLIs the run produced)."""
    from kubernetes_tpu.config.feature_gates import FeatureGates
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.sidecar import attach_batch_scheduler

    client = RestClusterClient(server.url, qps=None)
    sched = Scheduler.create(
        client, feature_gates=FeatureGates({"TPUBatchScheduler": batch}))
    bs = attach_batch_scheduler(sched, max_batch=32) if batch else None
    try:
        nodes = [MakeNode().name(f"n{i}")
                 .capacity({"cpu": "16", "memory": "32Gi"}).obj()
                 for i in range(4)]
        code, _ = client._request(
            "POST", "/api/v1/nodes",
            {"kind": "NodeList", "items": nodes}, charge=len(nodes))
        assert code == 201
        sched.start()
        # pods are created AFTER the watch streams are up: their events
        # ride the (possibly stalled) live watch, stamped at commit
        pods = [MakePod().name(f"p{i}").uid(f"u{i}")
                .req({"cpu": "100m"}).obj() for i in range(n_pods)]
        code, _ = client._request(
            "POST", "/api/v1/namespaces/default/pods",
            {"kind": "PodList", "items": pods}, charge=len(pods))
        assert code == 201
        deadline = time.time() + 60
        bound = 0
        while time.time() < deadline and bound < n_pods:
            if bs is not None:
                bs.run_batch(pop_timeout=0.05)
            else:
                sched.schedule_one(pop_timeout=0.05)
            bound = sched.metrics.e2e_scheduling_duration.count(
                "scheduled")
        assert bound == n_pods
    finally:
        sched.stop()


class TestFaultGateSloFlip:
    def test_clean_run_stays_green(self):
        from kubernetes_tpu.metrics import default_registry

        eng = SLOEngine(slos=_bench_slos(),
                        registries=[default_registry()], enabled=True)
        eng.tick()
        store, server = _serve()
        try:
            _run_sched_over_rest(server)
        finally:
            server.shutdown_server()
        out = eng.evaluate()
        assert out["healthy"] is True, out
        assert out["slos"]["watch_delivery"]["events_fast"] > 0

    def test_watch_stall_flips_freshness_slos(self, monkeypatch):
        """A FaultGate-injected stall on the pod watch stream delays
        commit→decode delivery past the objective: the freshness SLOs
        flip to violated, the multi-window burn alert fires, and the
        flight-recorder dump lands — the SLI layer detects a real
        injected fabric fault end-to-end."""
        from kubernetes_tpu.apiserver.faults import FaultGate, FaultRule
        from kubernetes_tpu.metrics import default_registry
        from kubernetes_tpu.observability import get_tracer

        tracer = get_tracer()
        dumps = []
        monkeypatch.setattr(tracer, "enabled", True)
        monkeypatch.setattr(
            tracer, "dump",
            lambda *a, **kw: dumps.append(kw.get("reason")) or "/x")
        eng = SLOEngine(slos=_bench_slos(),
                        registries=[default_registry()], enabled=True)
        eng.tick()
        gate = FaultGate()
        gate.add_rule(FaultRule("watch_stall", resource="pods",
                                duration=1.5))
        store, server = _serve(fault_gate=gate)
        try:
            _run_sched_over_rest(server)
        finally:
            server.shutdown_server()
        out = eng.evaluate()
        wd = out["slos"]["watch_delivery"]
        assert wd["violated"] is True, out
        assert wd["alerting"] is True
        assert any(r.startswith("slo-") for r in dumps)
        # the solver snapshot aged past its objective while the watch
        # was stalled (staleness is measured per solve cycle)
        ss = out["slos"]["snapshot_staleness"]
        if ss["events_fast"]:
            assert ss["violated"] is True, out
