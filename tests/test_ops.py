"""Differential correctness tests for the device batch path: the TPU
solver's placements must be valid under the HOST plugins (the correctness
oracle), and its unschedulable verdicts must match the host's — the
"equivalent predicate correctness" ring SURVEY.md section 4 calls for,
which the reference itself lacks."""

import random

import numpy as np
import pytest

from kubernetes_tpu.config.types import (
    KubeSchedulerProfile,
    PluginEntry,
    Plugins,
    PluginSet,
)
from kubernetes_tpu.ops import BatchEncoder, SolverParams, solve_scan
from kubernetes_tpu.scheduler.framework.cycle_state import CycleState
from kubernetes_tpu.scheduler.framework import interface as fw
from kubernetes_tpu.scheduler.framework.plugins import new_in_tree_registry
from kubernetes_tpu.scheduler.framework.runtime import Framework
from kubernetes_tpu.scheduler.snapshot import new_snapshot
from kubernetes_tpu.testing import MakeNode, MakePod

VALIDATE_PLUGINS = Plugins(
    pre_filter=PluginSet(
        enabled=[
            PluginEntry("NodeResourcesFit"),
            PluginEntry("PodTopologySpread"),
            PluginEntry("InterPodAffinity"),
        ]
    ),
    filter=PluginSet(
        enabled=[
            PluginEntry("NodeUnschedulable"),
            PluginEntry("NodeName"),
            PluginEntry("TaintToleration"),
            PluginEntry("NodeAffinity"),
            PluginEntry("NodeResourcesFit"),
            PluginEntry("PodTopologySpread"),
            PluginEntry("InterPodAffinity"),
        ]
    ),
)


class _Deps:
    def __init__(self):
        self._snapshot = None
        self.client = None
        self.pod_nominator = None

    def snapshot(self):
        return self._snapshot


def host_feasible_nodes(existing_pods, nodes, pod):
    """The host oracle: run the real prefilter+filter chain per node."""
    deps = _Deps()
    deps._snapshot = new_snapshot(existing_pods, nodes)
    fwk = Framework(
        new_in_tree_registry(),
        KubeSchedulerProfile(plugins=VALIDATE_PLUGINS),
        Plugins(),
        deps=deps,
    )
    state = CycleState()
    status = fwk.run_pre_filter_plugins(state, pod)
    if not fw.Status.is_ok(status):
        return set()
    out = set()
    for ni in deps._snapshot.list():
        if fw.Status.is_ok(fwk.run_filter_plugins(state, pod, ni)):
            out.add(ni.node.name)
    return out


def replay_validate(nodes, existing_pods, batch_pods, assignments, node_names):
    """Replay device assignments through the host oracle in order."""
    placed = list(existing_pods)
    for pod, a in zip(batch_pods, assignments):
        feasible = host_feasible_nodes(placed, nodes, pod)
        if a < 0:
            assert not feasible, (
                f"device said unschedulable for {pod.name} but host found {feasible}"
            )
        else:
            name = node_names[a]
            assert name in feasible, (
                f"device placed {pod.name} on {name}, host feasible set {feasible}"
            )
            bound = MakePod().obj()
            bound.metadata = pod.metadata
            bound.spec = pod.spec
            bound.spec.node_name = name
            placed.append(bound)


def run_device(nodes, existing_pods, batch_pods):
    snap = new_snapshot(existing_pods, nodes)
    enc = BatchEncoder(snap)
    cluster, batch = enc.encode(batch_pods)
    assignments = solve_scan(cluster, batch)
    return assignments[: len(batch_pods)], cluster.node_names


class TestFitOnly:
    def test_capacity_respected(self):
        nodes = [
            MakeNode().name(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"}).obj()
            for i in range(4)
        ]
        pods = [
            MakePod().name(f"p{i}").uid(f"pu{i}").req({"cpu": "2"}).obj()
            for i in range(10)
        ]
        assignments, names = run_device(nodes, [], pods)
        # 4 nodes * 2 pods of 2cpu fit; the remaining 2 are unschedulable
        assert int(np.sum(assignments >= 0)) == 8
        assert int(np.sum(assignments < 0)) == 2
        replay_validate(nodes, [], pods, assignments, names)

    def test_existing_pods_counted(self):
        nodes = [MakeNode().name("n0").capacity({"cpu": "4", "memory": "8Gi"}).obj()]
        existing = [
            MakePod().name("e").uid("eu").req({"cpu": "3"}).node("n0").obj()
        ]
        pods = [MakePod().name("p").uid("pu").req({"cpu": "2"}).obj()]
        assignments, names = run_device(nodes, existing, pods)
        assert assignments[0] == -1
        replay_validate(nodes, existing, pods, assignments, names)

    def test_pod_count_cap(self):
        nodes = [MakeNode().name("n0").capacity({"cpu": "64", "pods": "2"}).obj()]
        pods = [
            MakePod().name(f"p{i}").uid(f"pu{i}").req({"cpu": "1"}).obj()
            for i in range(4)
        ]
        assignments, names = run_device(nodes, [], pods)
        assert int(np.sum(assignments >= 0)) == 2
        replay_validate(nodes, [], pods, assignments, names)


class TestStaticPredicates:
    def test_node_selector_and_taints(self):
        nodes = [
            MakeNode().name("ssd").label("disk", "ssd")
            .capacity({"cpu": "4", "memory": "8Gi"}).obj(),
            MakeNode().name("hdd").label("disk", "hdd")
            .capacity({"cpu": "4", "memory": "8Gi"}).obj(),
            MakeNode().name("tainted").label("disk", "ssd")
            .capacity({"cpu": "4", "memory": "8Gi"})
            .taint("gpu", "true").obj(),
        ]
        pods = [
            MakePod().name("p").uid("pu").req({"cpu": "1"})
            .node_selector({"disk": "ssd"}).obj()
        ]
        assignments, names = run_device(nodes, [], pods)
        assert names[assignments[0]] == "ssd"
        replay_validate(nodes, [], pods, assignments, names)

    def test_node_name_pin(self):
        nodes = [
            MakeNode().name(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"}).obj()
            for i in range(3)
        ]
        pods = [MakePod().name("p").uid("pu").req({"cpu": "1"}).node("n2").obj()]
        assignments, names = run_device(nodes, [], pods)
        assert names[assignments[0]] == "n2"


class TestSpread:
    def _zone_nodes(self, zones=3, per_zone=2, cpu="16"):
        return [
            MakeNode().name(f"z{z}-n{i}")
            .label("topology.kubernetes.io/zone", f"z{z}")
            .capacity({"cpu": cpu, "memory": "32Gi"}).obj()
            for z in range(zones)
            for i in range(per_zone)
        ]

    def test_hard_spread_batch(self):
        nodes = self._zone_nodes()
        pods = [
            MakePod().name(f"p{i}").uid(f"pu{i}").label("app", "web")
            .req({"cpu": "1"})
            .spread_constraint(
                1, "topology.kubernetes.io/zone", "DoNotSchedule", {"app": "web"}
            ).obj()
            for i in range(9)
        ]
        assignments, names = run_device(nodes, [], pods)
        assert int(np.sum(assignments >= 0)) == 9
        zone_counts = {}
        for a in assignments:
            zone = names[a].split("-")[0]
            zone_counts[zone] = zone_counts.get(zone, 0) + 1
        assert all(c == 3 for c in zone_counts.values()), zone_counts
        replay_validate(nodes, [], pods, assignments, names)

    def test_hostname_spread(self):
        nodes = [
            MakeNode().name(f"n{i}").capacity({"cpu": "16", "memory": "32Gi"}).obj()
            for i in range(4)
        ]
        pods = [
            MakePod().name(f"p{i}").uid(f"pu{i}").label("app", "a")
            .req({"cpu": "1"})
            .spread_constraint(
                1, "kubernetes.io/hostname", "DoNotSchedule", {"app": "a"}
            ).obj()
            for i in range(8)
        ]
        assignments, names = run_device(nodes, [], pods)
        per_node = {}
        for a in assignments:
            per_node[a] = per_node.get(a, 0) + 1
        assert all(c == 2 for c in per_node.values()), per_node
        replay_validate(nodes, [], pods, assignments, names)


class TestInterPodAffinity:
    def test_affinity_follows(self):
        nodes = [
            MakeNode().name("a1").label("topology.kubernetes.io/zone", "za")
            .capacity({"cpu": "8", "memory": "16Gi"}).obj(),
            MakeNode().name("b1").label("topology.kubernetes.io/zone", "zb")
            .capacity({"cpu": "8", "memory": "16Gi"}).obj(),
        ]
        existing = [
            MakePod().name("db").uid("dbu").label("app", "db").node("a1").obj()
        ]
        pods = [
            MakePod().name(f"w{i}").uid(f"wu{i}").req({"cpu": "1"})
            .pod_affinity("app", ["db"], "topology.kubernetes.io/zone").obj()
            for i in range(3)
        ]
        assignments, names = run_device(nodes, existing, pods)
        assert all(names[a] == "a1" for a in assignments)
        replay_validate(nodes, existing, pods, assignments, names)

    def test_anti_affinity_spreads(self):
        nodes = [
            MakeNode().name(f"n{i}").capacity({"cpu": "8", "memory": "16Gi"}).obj()
            for i in range(3)
        ]
        pods = [
            MakePod().name(f"p{i}").uid(f"pu{i}").label("app", "x")
            .req({"cpu": "1"})
            .pod_anti_affinity("app", ["x"], "kubernetes.io/hostname").obj()
            for i in range(4)
        ]
        assignments, names = run_device(nodes, [], pods)
        scheduled = [a for a in assignments if a >= 0]
        # only 3 can land (one per node); the 4th violates anti-affinity
        assert len(scheduled) == 3
        assert len(set(scheduled)) == 3
        replay_validate(nodes, [], pods, assignments, names)

    def test_first_pod_special_case(self):
        nodes = [MakeNode().name("n0").capacity({"cpu": "8", "memory": "16Gi"}).obj()]
        pods = [
            MakePod().name("p").uid("pu").label("app", "grp").req({"cpu": "1"})
            .pod_affinity("app", ["grp"], "kubernetes.io/hostname").obj()
        ]
        assignments, names = run_device(nodes, [], pods)
        assert assignments[0] == 0  # self-selecting group: first pod lands
        replay_validate(nodes, [], pods, assignments, names)

    def test_existing_anti_affinity_blocks(self):
        nodes = [
            MakeNode().name("a1").label("topology.kubernetes.io/zone", "za")
            .capacity({"cpu": "8", "memory": "16Gi"}).obj(),
            MakeNode().name("b1").label("topology.kubernetes.io/zone", "zb")
            .capacity({"cpu": "8", "memory": "16Gi"}).obj(),
        ]
        existing = [
            MakePod().name("hermit").uid("hu").label("app", "h").node("a1")
            .pod_anti_affinity("app", ["web"], "topology.kubernetes.io/zone").obj()
        ]
        pods = [
            MakePod().name("w").uid("wu").label("app", "web").req({"cpu": "1"}).obj()
        ]
        assignments, names = run_device(nodes, existing, pods)
        assert names[assignments[0]] == "b1"
        replay_validate(nodes, existing, pods, assignments, names)


class TestRandomizedDifferential:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_workload(self, seed):
        rng = random.Random(seed)
        zones = ["za", "zb", "zc"]
        nodes = []
        for i in range(12):
            w = (
                MakeNode().name(f"n{i}")
                .label("topology.kubernetes.io/zone", zones[i % 3])
                .capacity({"cpu": str(rng.choice([2, 4, 8])),
                           "memory": f"{rng.choice([4, 8, 16])}Gi"})
            )
            if rng.random() < 0.2:
                w.taint("special", "true")
            nodes.append(w.obj())
        pods = []
        for i in range(40):
            w = (
                MakePod().name(f"p{i}").uid(f"pu{i}")
                .label("app", rng.choice(["a", "b", "c"]))
                .req({"cpu": f"{rng.choice([100, 500, 1000])}m",
                      "memory": f"{rng.choice([128, 512, 1024])}Mi"})
            )
            roll = rng.random()
            if roll < 0.2:
                w.spread_constraint(
                    rng.choice([1, 2]), "topology.kubernetes.io/zone",
                    "DoNotSchedule", {"app": w.pod.metadata.labels["app"]},
                )
            elif roll < 0.3:
                w.pod_anti_affinity(
                    "app", [w.pod.metadata.labels["app"]],
                    "kubernetes.io/hostname",
                )
            elif roll < 0.4:
                w.pod_affinity("app", ["a"], "topology.kubernetes.io/zone")
            if rng.random() < 0.1:
                w.toleration("special", "true", "NoSchedule")
            pods.append(w.obj())
        assignments, names = run_device(nodes, [], pods)
        replay_validate(nodes, [], pods, assignments, names)


class TestFallbackFlags:
    def test_pvc_pod_marked_inexpressible(self):
        nodes = [MakeNode().name("n0").capacity({"cpu": "8", "memory": "16Gi"}).obj()]
        pods = [MakePod().name("p").uid("pu").req({"cpu": "1"}).pvc("claim").obj()]
        assignments, names = run_device(nodes, [], pods)
        assert assignments[0] == -1  # falls back to the serial path
