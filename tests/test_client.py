"""Client layer: workqueue dedup/backoff, informers, leader election."""

import threading
import time

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client import (
    ItemExponentialFailureRateLimiter,
    LeaderElectionConfig,
    LeaderElector,
    RateLimitingQueue,
    SharedInformerFactory,
    WorkQueue,
)
from kubernetes_tpu.testing import MakeNode, MakePod


# ---------------------------------------------------------------- workqueue
def test_workqueue_dedups_while_queued():
    q = WorkQueue()
    q.add("a")
    q.add("a")
    q.add("b")
    assert len(q) == 2
    assert q.get() == "a"
    q.done("a")
    assert q.get() == "b"


def test_workqueue_requeues_item_added_during_processing():
    q = WorkQueue()
    q.add("a")
    item = q.get()
    q.add("a")          # arrives while processing -> marked dirty
    assert len(q) == 0  # not queued yet
    q.done(item)
    assert q.get(timeout=1) == "a"  # exactly one re-delivery


def test_workqueue_get_timeout_and_shutdown():
    q = WorkQueue()
    assert q.get(timeout=0.05) is None
    q.shutdown()
    assert q.get() is None
    q.add("x")  # add after shutdown is dropped
    assert len(q) == 0


def test_rate_limiting_queue_backoff_and_forget():
    rl = ItemExponentialFailureRateLimiter(base_delay=0.01, max_delay=1.0)
    assert rl.when("x") == 0.01
    assert rl.when("x") == 0.02
    assert rl.num_requeues("x") == 2
    rl.forget("x")
    assert rl.when("x") == 0.01

    q = RateLimitingQueue(
        ItemExponentialFailureRateLimiter(base_delay=0.02, max_delay=1.0)
    )
    q.add_rate_limited("a")
    assert q.get(timeout=0.005) is None  # still delayed
    assert q.get(timeout=2.0) == "a"
    q.shutdown()


# ---------------------------------------------------------------- informers
def _mkstore():
    store = ClusterStore()
    store.add_node(MakeNode().name("n1").capacity({"cpu": "4"}).obj())
    store.create_pod(MakePod().name("p1").uid("u1").obj())
    return store


def test_informer_initial_list_and_live_events():
    store = _mkstore()
    factory = SharedInformerFactory(store)
    adds, deletes = [], []
    pods = factory.informer_for("Pod")
    pods.add_event_handler(on_add=lambda o: adds.append(o.name),
                           on_delete=lambda o: deletes.append(o.name))
    node_lister = factory.lister_for("Node")
    factory.start()
    assert factory.wait_for_cache_sync()
    assert adds == ["p1"]                      # replayed initial list
    assert [n.name for n in node_lister.list()] == ["n1"]

    store.create_pod(MakePod().name("p2").uid("u2").obj())
    store.delete_pod("default", "p1")
    deadline = time.monotonic() + 5
    while (adds, deletes) != (["p1", "p2"], ["p1"]):
        if time.monotonic() > deadline:
            raise AssertionError(f"events not delivered: {adds}, {deletes}")
        time.sleep(0.01)
    assert factory.lister_for("Pod").get("p2", "default") is not None
    assert factory.lister_for("Pod").get("p1", "default") is None
    factory.stop()


def test_informer_filter_handler_add_delete_transitions():
    store = ClusterStore()
    factory = SharedInformerFactory(store)
    events = []
    pods = factory.informer_for("Pod")
    pods.add_event_handler(
        on_add=lambda o: events.append(("add", o.name)),
        on_delete=lambda o: events.append(("del", o.name)),
        filter_fn=lambda p: bool(p.spec.node_name),  # only assigned pods
    )
    factory.start()
    assert factory.wait_for_cache_sync()

    pod = MakePod().name("p").uid("u").obj()
    store.create_pod(pod)                 # unassigned: filtered out
    store.bind("default", "p", "u", "n1")  # now assigned: delivered as add
    deadline = time.monotonic() + 5
    while events != [("add", "p")]:
        if time.monotonic() > deadline:
            raise AssertionError(f"unexpected events: {events}")
        time.sleep(0.01)
    factory.stop()


def test_informer_registered_after_start_still_syncs():
    store = _mkstore()
    factory = SharedInformerFactory(store)
    factory.informer_for("Pod")
    factory.start()
    assert factory.wait_for_cache_sync()
    # late registration: must replay the existing list and get live events
    node_lister = factory.lister_for("Node")
    deadline = time.monotonic() + 5
    while not [n.name for n in node_lister.list()] == ["n1"]:
        if time.monotonic() > deadline:
            raise AssertionError("late informer never synced")
        time.sleep(0.01)
    store.add_node(MakeNode().name("n2").capacity({"cpu": "4"}).obj())
    while node_lister.get("n2") is None:
        if time.monotonic() > deadline:
            raise AssertionError("late informer missed live event")
        time.sleep(0.01)
    factory.stop()


def test_informer_survives_handler_exception():
    store = _mkstore()
    factory = SharedInformerFactory(store)
    seen = []
    pods = factory.informer_for("Pod")

    def bad_handler(obj):
        seen.append(obj.name)
        raise RuntimeError("boom")

    pods.add_event_handler(on_add=bad_handler)
    factory.start()
    assert factory.wait_for_cache_sync()
    store.create_pod(MakePod().name("p2").uid("u2").obj())
    deadline = time.monotonic() + 5
    while seen != ["p1", "p2"]:
        if time.monotonic() > deadline:
            raise AssertionError(f"dispatch thread died: {seen}")
        time.sleep(0.01)
    factory.stop()


# ------------------------------------------------------------ leader election
def test_leader_election_single_holder_and_failover():
    store = ClusterStore()
    from kubernetes_tpu.utils.clock import FakeClock

    clock = FakeClock()
    leading = []

    def elector(name):
        return LeaderElector(
            store,
            LeaderElectionConfig(
                identity=name, lease_duration=10.0,
                on_started_leading=lambda: leading.append(name),
            ),
            clock=clock,
        )

    a, b = elector("a"), elector("b")
    assert a.try_acquire_or_renew()
    assert not b.try_acquire_or_renew()   # a holds the lease
    assert a.try_acquire_or_renew()       # renewal succeeds
    clock.step(11.0)                      # lease expires
    assert b.try_acquire_or_renew()       # failover
    assert store.lease_holder("kube-scheduler") == "b"


def test_leader_election_run_loop():
    store = ClusterStore()
    started = threading.Event()
    el = LeaderElector(
        store,
        LeaderElectionConfig(identity="x", retry_period=0.01,
                             on_started_leading=started.set),
    )
    t = el.run_in_thread()
    assert started.wait(2.0)
    assert el.is_leader
    el.stop()
    t.join(timeout=2.0)
    assert not t.is_alive()
