"""Controller manager + control loops, driven end-to-end against the
store (and, where placement matters, a live scheduler)."""

import time

from kubernetes_tpu.api.labels import LabelSelector
from kubernetes_tpu.api.types import (
    DaemonSet,
    Deployment,
    Job,
    PersistentVolume,
    PersistentVolumeClaim,
    ReplicaSet,
    Service,
    StatefulSet,
    StorageClass,
)
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.controllers import ControllerManager, new_controller_initializers
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


def _wait(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            raise AssertionError(f"timeout waiting for {msg}")
        time.sleep(0.02)


def _template(labels=None, cpu="100m"):
    return {
        "metadata": {"labels": labels or {"app": "web"}},
        "spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": cpu}}}
        ]},
    }


def _rs(name, replicas, labels=None):
    labels = labels or {"app": "web"}
    rs = ReplicaSet(
        selector=LabelSelector(match_labels=dict(labels)),
        replicas=replicas,
        template=_template(labels),
    )
    rs.metadata.name = name
    return rs


def test_controller_registry_covers_core_loops():
    names = set(new_controller_initializers())
    assert {"replicaset", "deployment", "statefulset", "daemonset", "job",
            "endpoints", "garbagecollector", "nodelifecycle",
            "persistentvolume-binder"} <= names


def test_replicaset_scales_up_and_down():
    store = ClusterStore()
    cm = ControllerManager(store, controllers=["replicaset"])
    cm.start()
    try:
        store.add_replica_set(_rs("web", 3))
        _wait(lambda: len(store.list_pods()) == 3, msg="3 pods")
        rs = store.get_replica_set("default", "web")
        rs.replicas = 1
        store.update_replica_set(rs)
        _wait(lambda: len(store.list_pods()) == 1, msg="scale down to 1")
        # killed pod is replaced
        store.delete_pod("default", store.list_pods()[0].name)
        _wait(lambda: len(store.list_pods()) == 1, msg="replacement pod")
    finally:
        cm.stop()


def test_idle_controllers_do_not_spin():
    """Status writes must be skipped when unchanged, otherwise the
    controller MODIFY-events itself into a hot reconcile loop."""
    store = ClusterStore()
    cm = ControllerManager(store, controllers=["replicaset", "deployment"])
    cm.start()
    try:
        store.add_replica_set(_rs("web", 2))
        _wait(lambda: len(store.list_pods()) == 2, msg="pods created")
        time.sleep(0.3)  # let status writes settle
        rv_before = store._rv
        time.sleep(1.0)
        assert store._rv - rv_before <= 2, (
            f"idle controllers burned {store._rv - rv_before} RVs/s"
        )
    finally:
        cm.stop()


def test_replicaset_adopts_matching_orphans():
    store = ClusterStore()
    from kubernetes_tpu.testing import MakePod

    orphan = MakePod().name("stray").uid("stray-u").label("app", "web").obj()
    store.create_pod(orphan)
    cm = ControllerManager(store, controllers=["replicaset"])
    cm.start()
    try:
        store.add_replica_set(_rs("web", 2))
        _wait(lambda: len(store.list_pods()) == 2, msg="orphan counted")
        stray = store.get_pod("default", "stray")
        _wait(lambda: any(
            r.get("kind") == "ReplicaSet"
            for r in store.get_pod("default", "stray").metadata.owner_references
        ), msg="orphan adopted")
        del stray
        # deleting the adopted orphan now routes back to the RS
        store.delete_pod("default", "stray")
        _wait(lambda: len(store.list_pods()) == 2, msg="replacement created")
    finally:
        cm.stop()


def test_deployment_creates_rs_and_rolls_template():
    store = ClusterStore()
    cm = ControllerManager(store, controllers=["deployment", "replicaset"])
    cm.start()
    try:
        d = Deployment(
            selector=LabelSelector(match_labels={"app": "web"}),
            replicas=2,
            template=_template(),
        )
        d.metadata.name = "web"
        store.add_deployment(d)
        _wait(lambda: len(store.list_all_replica_sets()) == 1, msg="RS created")
        _wait(lambda: len(store.list_pods()) == 2, msg="2 pods via RS")
        old_rs = store.list_all_replica_sets()[0].name

        d = store.get_deployment("default", "web")
        d.template = _template(cpu="200m")
        store.update_deployment(d)
        _wait(lambda: len(store.list_all_replica_sets()) == 2, msg="new RS")
        def rolled():
            pods = store.list_pods()
            return (len(pods) == 2 and all(
                p.spec.containers[0].resources.requests["cpu"].milli_value() == 200
                for p in pods))
        _wait(rolled, msg="pods rolled to new template")
        new_rs = [rs for rs in store.list_all_replica_sets()
                  if rs.name != old_rs][0]
        assert new_rs.replicas == 2
        assert [rs for rs in store.list_all_replica_sets()
                if rs.name == old_rs][0].replicas == 0
    finally:
        cm.stop()


def test_statefulset_ordered_creation_with_scheduler():
    store = ClusterStore()
    store.add_node(MakeNode().name("n1").capacity(
        {"cpu": "8", "memory": "16Gi"}).obj())
    sched = Scheduler.create(store)
    sched.run()
    cm = ControllerManager(store, controllers=["statefulset"])
    cm.start()
    try:
        ss = StatefulSet(
            selector=LabelSelector(match_labels={"app": "db"}),
            replicas=3,
            template=_template({"app": "db"}),
        )
        ss.metadata.name = "db"
        store.add_stateful_set(ss)
        _wait(lambda: store.get_pod("default", "db-2") is not None
              and store.get_pod("default", "db-2").spec.node_name,
              msg="db-2 bound")
        names = sorted(p.name for p in store.list_pods())
        assert names == ["db-0", "db-1", "db-2"]
        # ordinal order: db-0 must have been created before db-2
        assert (int(store.get_pod("default", "db-0").metadata.resource_version)
                < int(store.get_pod("default", "db-2").metadata.resource_version))
    finally:
        cm.stop()
        sched.stop()


def test_daemonset_runs_one_pod_per_node():
    store = ClusterStore()
    for i in range(3):
        store.add_node(MakeNode().name(f"n{i}").capacity(
            {"cpu": "8", "memory": "16Gi"}).obj())
    sched = Scheduler.create(store)
    sched.run()
    cm = ControllerManager(store, controllers=["daemonset"])
    cm.start()
    try:
        ds = DaemonSet(template=_template({"app": "agent"}))
        ds.metadata.name = "agent"
        store.add_daemon_set(ds)
        def one_per_node():
            hosts = sorted(p.spec.node_name for p in store.list_pods())
            return hosts == ["n0", "n1", "n2"]
        _wait(one_per_node, msg="one daemon pod bound per node")
        # a node added later gets its daemon pod too
        store.add_node(MakeNode().name("n3").capacity(
            {"cpu": "8", "memory": "16Gi"}).obj())
        _wait(lambda: sorted(p.spec.node_name for p in store.list_pods())
              == ["n0", "n1", "n2", "n3"], msg="daemon pod on new node")
    finally:
        cm.stop()
        sched.stop()


def test_job_runs_to_completion_with_pod_phases():
    store = ClusterStore()
    cm = ControllerManager(store, controllers=["job"])
    cm.start()
    try:
        job = Job(completions=4, parallelism=2, template=_template({"app": "batch"}))
        job.metadata.name = "batch"
        store.add_job(job)
        _wait(lambda: len([p for p in store.list_pods()
                           if p.status.phase == "Pending"]) == 2,
              msg="2 parallel pods")
        # simulate kubelet completing pods as they appear
        done = set()
        def finish_pods():
            for p in store.list_pods():
                if p.name not in done and p.status.phase == "Pending":
                    done.add(p.name)
                    store.set_pod_phase(p.namespace, p.name, "Succeeded")
            j = store.get_job("default", "batch")
            return j.status.succeeded >= 4
        _wait(finish_pods, msg="job completes")
        j = store.get_job("default", "batch")
        assert j.status.succeeded == 4
        assert j.status.replicas == 0  # no active pods remain wanted
    finally:
        cm.stop()


def test_endpoints_follow_service_selector_and_bindings():
    store = ClusterStore()
    store.add_node(MakeNode().name("n1").capacity(
        {"cpu": "8", "memory": "16Gi"}).obj())
    sched = Scheduler.create(store)
    sched.run()
    cm = ControllerManager(store, controllers=["endpoints", "replicaset"])
    cm.start()
    try:
        svc = Service(selector={"app": "web"})
        svc.metadata.name = "web"
        store.add_service(svc)
        store.add_replica_set(_rs("web", 2))
        def ready():
            ep = store.get_endpoints("default", "web")
            return ep is not None and len(ep.addresses) == 2
        _wait(ready, msg="2 endpoint addresses")
        ep = store.get_endpoints("default", "web")
        assert all(a.node_name == "n1" for a in ep.addresses)
        # scale down -> endpoints shrink
        rs = store.get_replica_set("default", "web")
        rs.replicas = 1
        store.update_replica_set(rs)
        _wait(lambda: len(store.get_endpoints("default", "web").addresses) == 1,
              msg="endpoints shrink")
    finally:
        cm.stop()
        sched.stop()


def test_garbage_collector_cascades_orphaned_pods():
    store = ClusterStore()
    cm = ControllerManager(store, controllers=["replicaset", "garbagecollector"])
    cm.get("garbagecollector").sweep_interval = 0.1
    cm.start()
    try:
        store.add_replica_set(_rs("web", 2))
        _wait(lambda: len(store.list_pods()) == 2, msg="pods exist")
        store.delete_replica_set("default", "web")
        _wait(lambda: len(store.list_pods()) == 0, msg="cascade delete")
    finally:
        cm.stop()


def test_node_lifecycle_marks_and_evicts_silent_nodes():
    from kubernetes_tpu.utils.clock import FakeClock

    store = ClusterStore()
    clock = FakeClock(start=100.0)
    store.add_node(MakeNode().name("n1").capacity(
        {"cpu": "8", "memory": "16Gi"}).obj())
    cm = ControllerManager(store, controllers=[])
    from kubernetes_tpu.controllers.nodelifecycle import (
        UNREACHABLE_TAINT,
        NodeLifecycleController,
    )

    nlc = NodeLifecycleController(store, cm.factory, clock=clock)
    cm.factory.start()
    assert cm.factory.wait_for_cache_sync()
    try:
        # bind a pod onto n1 manually
        from kubernetes_tpu.testing import MakePod

        store.create_pod(MakePod().name("p").uid("u").obj())
        store.bind("default", "p", "u", "n1")
        _wait(lambda: (nlc.pod_lister.get("p", "default") or MakePod().obj())
              .spec.node_name == "n1", msg="informer sees binding")

        nlc.heartbeat("n1")
        nlc.monitor_node_health()
        assert not any(t.key == UNREACHABLE_TAINT
                       for t in store.get_node("n1").spec.taints)

        clock.step(45.0)  # past the 40s grace period
        nlc.monitor_node_health()
        node = store.get_node("n1")
        assert any(t.key == UNREACHABLE_TAINT for t in node.spec.taints)
        assert any(c.type == "Ready" and c.status == "False"
                   for c in node.status.conditions)
        assert store.get_pod("default", "p") is not None  # not evicted yet

        clock.step(11.0)  # past the eviction grace
        nlc.monitor_node_health()
        assert store.get_pod("default", "p") is None

        # heartbeat returns: node recovers
        nlc.heartbeat("n1")
        nlc.monitor_node_health()
        assert not any(t.key == UNREACHABLE_TAINT
                       for t in store.get_node("n1").spec.taints)
    finally:
        cm.stop()


def test_pv_binder_binds_immediate_claims():
    store = ClusterStore()
    sc = StorageClass(provisioner="x", volume_binding_mode="Immediate")
    sc.metadata.name = "standard"
    store.add_storage_class(sc)
    pv = PersistentVolume(storage_class_name="standard",
                          access_modes=["ReadWriteOnce"])
    pv.metadata.name = "pv-1"
    store.add_pv(pv)
    cm = ControllerManager(store, controllers=["persistentvolume-binder"])
    cm.start()
    try:
        pvc = PersistentVolumeClaim(storage_class_name="standard",
                                    access_modes=["ReadWriteOnce"])
        pvc.metadata.name = "claim-1"
        store.add_pvc(pvc)
        _wait(lambda: store.get_pvc("default", "claim-1").phase == "Bound",
              msg="pvc bound")
        assert store.get_pv("pv-1").claim_ref == "default/claim-1"

        # WaitForFirstConsumer claims are left alone
        sc2 = StorageClass(provisioner="x",
                           volume_binding_mode="WaitForFirstConsumer")
        sc2.metadata.name = "wffc"
        store.add_storage_class(sc2)
        pv2 = PersistentVolume(storage_class_name="wffc",
                               access_modes=["ReadWriteOnce"])
        pv2.metadata.name = "pv-2"
        store.add_pv(pv2)
        pvc2 = PersistentVolumeClaim(storage_class_name="wffc",
                                     access_modes=["ReadWriteOnce"])
        pvc2.metadata.name = "claim-2"
        store.add_pvc(pvc2)
        time.sleep(0.3)
        assert store.get_pvc("default", "claim-2").phase == "Pending"
    finally:
        cm.stop()


def test_disruption_controller_maintains_pdb_status():
    """pkg/controller/disruption: status.disruptionsAllowed =
    currentHealthy - desiredHealthy, percentages against owner scale."""
    from kubernetes_tpu.api.types import PodDisruptionBudget

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["disruption"])
    cm.start()
    try:
        pdb = PodDisruptionBudget(
            label_selector=LabelSelector(match_labels={"app": "db"}),
            min_available=2,
        )
        pdb.metadata.name = "db-pdb"
        store.add_pdb(pdb)
        # three bound (healthy) pods + one pending
        for i in range(3):
            store.create_pod(MakePod().name(f"db-{i}").uid(f"dbu{i}")
                             .label("app", "db").node(f"n{i}").obj())
        store.create_pod(MakePod().name("db-pending").uid("dbu-p")
                         .label("app", "db").obj())
        _wait(lambda: store.get_object(
            "PodDisruptionBudget", "default", "db-pdb"
        ).status.disruptions_allowed == 1, msg="allowed=1 (3 healthy - 2)")
        got = store.get_object("PodDisruptionBudget", "default", "db-pdb")
        assert got.status.current_healthy == 3
        assert got.status.desired_healthy == 2
        assert got.status.expected_pods == 4

        # one healthy pod deleted -> no disruptions left
        store.delete_pod("default", "db-0")
        _wait(lambda: store.get_object(
            "PodDisruptionBudget", "default", "db-pdb"
        ).status.disruptions_allowed == 0, msg="allowed drops to 0")
    finally:
        cm.stop()


def test_disruption_controller_percentage_against_owner_scale():
    from kubernetes_tpu.api.types import PodDisruptionBudget

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["disruption"])
    cm.start()
    try:
        rs = _rs("web", 4, labels={"app": "web"})
        rs.metadata.uid = "rs-uid"
        store.add_replica_set(rs)
        pdb = PodDisruptionBudget(
            label_selector=LabelSelector(match_labels={"app": "web"}),
            max_unavailable="50%",
        )
        pdb.metadata.name = "web-pdb"
        store.add_pdb(pdb)
        # only 3 of the 4 desired replicas exist and are bound
        for i in range(3):
            store.create_pod(
                MakePod().name(f"web-{i}").uid(f"wu{i}")
                .label("app", "web").node(f"n{i}")
                .owner_reference("ReplicaSet", "web", "rs-uid").obj())
        # expected=4 (owner scale), maxUnavailable 50% -> desired=2,
        # healthy=3 -> allowed=1
        _wait(lambda: store.get_object(
            "PodDisruptionBudget", "default", "web-pdb"
        ).status.disruptions_allowed == 1, msg="allowed=1")
        got = store.get_object("PodDisruptionBudget", "default", "web-pdb")
        assert got.status.expected_pods == 4
        assert got.status.desired_healthy == 2
    finally:
        cm.stop()


def test_preemption_blocked_by_live_pdb_status():
    """Preemption must consume the disruption controller's LIVE
    status.disruptionsAllowed: victims under an exhausted PDB are last
    resort (reference filterPodsWithPDBViolation ordering)."""
    from kubernetes_tpu.api.types import PodDisruptionBudget
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.testing import MakeNode

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["disruption"])
    cm.start()
    sched = Scheduler.create(store)
    sched.start()
    try:
        store.add_node(MakeNode().name("n1")
                       .capacity({"cpu": "4", "memory": "8Gi"}).obj())
        # two low-priority pods fill the node: one PDB-protected, one not
        pdb = PodDisruptionBudget(
            label_selector=LabelSelector(match_labels={"app": "prot"}),
            min_available=1,
        )
        pdb.metadata.name = "prot-pdb"
        store.add_pdb(pdb)
        store.create_pod(MakePod().name("protected").uid("u-prot")
                         .label("app", "prot").priority(0)
                         .req({"cpu": "2"}).obj())
        store.create_pod(MakePod().name("fair-game").uid("u-fair")
                         .priority(0).req({"cpu": "2"}).obj())
        for _ in range(50):
            sched.queue.flush_backoff_completed()
            if not sched.schedule_one(pop_timeout=0.0):
                break
        sched.wait_for_inflight_bindings()
        # disruption controller observes both bound; protected PDB has
        # minAvailable=1 over 1 healthy pod -> allowed=0
        _wait(lambda: store.get_object(
            "PodDisruptionBudget", "default", "prot-pdb"
        ).status.disruptions_allowed == 0, msg="pdb exhausted")

        store.create_pod(MakePod().name("vip").uid("u-vip")
                         .priority(1000).req({"cpu": "2"}).obj())
        for _ in range(50):
            sched.queue.flush_backoff_completed()
            if not sched.schedule_one(pop_timeout=0.0):
                break
        sched.wait_for_inflight_bindings()
        _wait(lambda: store.get_pod("default", "fair-game") is None,
              msg="non-protected victim evicted")
        assert store.get_pod("default", "protected") is not None
    finally:
        sched.stop()
        cm.stop()


def test_disruption_percentage_rounds_up():
    """maxUnavailable percentages round UP (reference
    GetScaledValueFromIntOrPercent roundUp=true): 30% of 7 allows 3
    unavailable -> desiredHealthy 4, not floor(2.1)=2 -> 5."""
    from kubernetes_tpu.api.types import PodDisruptionBudget

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["disruption"])
    cm.start()
    try:
        rs = _rs("w7", 7, labels={"app": "w7"})
        rs.metadata.uid = "rs7-uid"
        store.add_replica_set(rs)
        pdb = PodDisruptionBudget(
            label_selector=LabelSelector(match_labels={"app": "w7"}),
            max_unavailable="30%",
        )
        pdb.metadata.name = "w7-pdb"
        store.add_pdb(pdb)
        for i in range(7):
            store.create_pod(
                MakePod().name(f"w7-{i}").uid(f"w7u{i}")
                .label("app", "w7").node(f"n{i}")
                .owner_reference("ReplicaSet", "w7", "rs7-uid").obj())
        _wait(lambda: store.get_object(
            "PodDisruptionBudget", "default", "w7-pdb"
        ).status.disruptions_allowed == 3, msg="ceil(2.1)=3 allowed")
        got = store.get_object("PodDisruptionBudget", "default", "w7-pdb")
        assert got.status.desired_healthy == 4
        assert got.status.expected_pods == 7
    finally:
        cm.stop()


# ----------------------------------------------------------------------
# controller breadth (reference controllermanager.go:387 registers 38):
# namespace, resourcequota, serviceaccount, ttl-after-finished, cronjob,
# nodeipam
def test_namespace_controller_deletes_content_and_finalizes():
    from kubernetes_tpu.api.types import Namespace, ObjectMeta

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["namespace"])
    cm.start()
    try:
        store.add_namespace(Namespace(metadata=ObjectMeta(name="doomed")))
        pod = MakePod().name("p1").uid("u1").obj()
        pod.metadata.namespace = "doomed"
        store.create_pod(pod)
        # request deletion: phase -> Terminating
        ns = store.get_namespace("doomed")
        ns2 = Namespace(metadata=ns.metadata, phase="Terminating")
        store.update_object("Namespace", ns2)
        _wait(lambda: store.get_pod("doomed", "p1") is None,
              msg="namespace content deleted")
        _wait(lambda: store.get_namespace("doomed") is None,
              msg="namespace finalized")
    finally:
        cm.stop()


def test_resourcequota_controller_and_admission():
    from kubernetes_tpu.api.resource import parse_quantity
    from kubernetes_tpu.api.types import ObjectMeta, ResourceQuota
    from kubernetes_tpu.apiserver.admission import (
        AdmissionError, AdmissionRequest, ResourceQuotaAdmission,
    )

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["resourcequota"])
    cm.start()
    try:
        store.add_resource_quota(ResourceQuota(
            metadata=ObjectMeta(name="q", namespace="default"),
            hard={"pods": parse_quantity("2"),
                  "requests.cpu": parse_quantity("1")},
        ))
        store.create_pod(MakePod().name("q1").uid("qu1")
                         .req({"cpu": "500m"}).obj())
        _wait(lambda: (
            (q := store.get_resource_quota("default", "q").used.get("pods"))
            is not None and int(q.value()) == 1
        ), msg="quota usage pods=1")
        used = store.get_resource_quota("default", "q").used
        assert int(used["requests.cpu"].milli_value()) == 500

        # admission: a pod pushing cpu past 1 full core is rejected
        plugin = ResourceQuotaAdmission(store)
        big = MakePod().name("big").uid("bu").req({"cpu": "600m"}).obj()
        try:
            plugin.validate(AdmissionRequest(
                operation="CREATE", kind="Pod", namespace="default",
                obj=big,
            ))
            raise AssertionError("quota admission should have rejected")
        except AdmissionError:
            pass
        small = MakePod().name("small").uid("su").req({"cpu": "400m"}).obj()
        req_small = AdmissionRequest(
            operation="CREATE", kind="Pod", namespace="default", obj=small,
        )
        plugin.validate(req_small)
        # "small" admitted -> in-flight charge holds 400m; another 400m
        # pod would exceed 1 CPU with the phantom charge...
        small2 = MakePod().name("small2").uid("s2").req({"cpu": "400m"}).obj()
        req_small2 = AdmissionRequest(
            operation="CREATE", kind="Pod", namespace="default", obj=small2,
        )
        try:
            plugin.validate(req_small2)
            raise AssertionError("in-flight charge should block small2")
        except AdmissionError:
            pass
        # ...but a downstream create failure rolls the charge back
        # IMMEDIATELY (no 30s TTL wait), freeing the headroom
        plugin.rollback(req_small)
        plugin.validate(req_small2)
    finally:
        cm.stop()


def test_serviceaccount_controller_ensures_default():
    from kubernetes_tpu.api.types import Namespace, ObjectMeta

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["serviceaccount"])
    cm.start()
    try:
        store.add_namespace(Namespace(metadata=ObjectMeta(name="team-a")))
        _wait(lambda: store.get_service_account("team-a", "default")
              is not None, msg="default SA created")
        # deleted -> recreated
        store.delete_object("ServiceAccount", "team-a", "default")
        _wait(lambda: store.get_service_account("team-a", "default")
              is not None, msg="default SA recreated")
    finally:
        cm.stop()


def test_ttl_after_finished_deletes_expired_job():
    from kubernetes_tpu.api.types import Job, ObjectMeta, WorkloadStatus

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["ttl-after-finished"])
    cm.start()
    try:
        job = Job(
            metadata=ObjectMeta(name="done", namespace="default"),
            completions=1,
            ttl_seconds_after_finished=1,
            status=WorkloadStatus(succeeded=1,
                                  completion_time=time.time() - 0.5),
        )
        store.add_job(job)
        _wait(lambda: store.get_job("default", "done") is None,
              timeout=8.0, msg="expired job deleted")
        # a job with no ttl survives
        store.add_job(Job(
            metadata=ObjectMeta(name="keep", namespace="default"),
            completions=1,
            status=WorkloadStatus(succeeded=1,
                                  completion_time=time.time() - 10),
        ))
        time.sleep(0.5)
        assert store.get_job("default", "keep") is not None
    finally:
        cm.stop()


def test_cronjob_controller_creates_job_on_schedule():
    from kubernetes_tpu.api.types import CronJob, ObjectMeta
    from kubernetes_tpu.controllers.cronjob import (
        cron_matches, next_fire_after,
    )

    # cron matcher semantics
    t = time.mktime((2026, 7, 30, 12, 30, 0, 3, 0, -1))  # Thu July 30 12:30
    assert cron_matches("* * * * *", t)
    assert cron_matches("30 12 * * *", t)
    assert cron_matches("*/15 * * * *", t)
    assert not cron_matches("31 12 * * *", t)
    assert next_fire_after("* * * * *", t) == (int(t) // 60 + 1) * 60
    # stepped ranges (a-b/n — standard cron)
    assert cron_matches("20-40/10 * * * *", t)      # 20,30,40
    assert not cron_matches("20-40/15 * * * *", t)  # 20,35
    # DOM/DOW OR rule (vixie cron): when BOTH are restricted, either
    # matches — 2026-07-30 is a Thursday (DOW 4), not the 13th
    assert cron_matches("30 12 13 * 4", t)      # not 13th, but Thursday
    assert cron_matches("30 12 30 * 5", t)      # 30th, though not Friday
    assert not cron_matches("30 12 13 * 5", t)  # neither 13th nor Friday
    # only one restricted: AND as before
    assert not cron_matches("30 12 13 * *", t)
    assert cron_matches("30 12 * * 4", t)

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["cronjob"])
    ctrl = cm.get("cronjob")
    # anchor in the past so "* * * * *" is due immediately
    store.add_cron_job(CronJob(
        metadata=ObjectMeta(
            name="tick", namespace="default",
            creation_timestamp=time.time() - 120,
        ),
        schedule="* * * * *",
        job_template={"metadata": {"labels": {"app": "tick"}},
                      "spec": {"containers": [{"name": "c"}]}},
    ))
    cm.start()
    try:
        _wait(lambda: any(
            j.metadata.name.startswith("tick-")
            for j in store.list_jobs()
        ), msg="cron job created a Job")
        job = next(j for j in store.list_jobs()
                   if j.metadata.name.startswith("tick-"))
        assert any(r.get("kind") == "CronJob"
                   for r in job.metadata.owner_references)
        cj = store.get_cron_job("default", "tick")
        assert cj.last_schedule_time is not None
    finally:
        cm.stop()


def test_nodeipam_allocates_and_recycles_cidrs():
    store = ClusterStore()
    cm = ControllerManager(store, controllers=["nodeipam"])
    cm.start()
    try:
        for i in range(3):
            store.add_node(MakeNode().name(f"ip{i}")
                           .capacity({"cpu": "4"}).obj())
        _wait(lambda: all(
            store.get_node(f"ip{i}").spec.pod_cidr for i in range(3)
        ), msg="pod CIDRs allocated")
        cidrs = {store.get_node(f"ip{i}").spec.pod_cidr for i in range(3)}
        assert len(cidrs) == 3  # unique
        assert all(c.endswith("/24") and c.startswith("10.244.")
                   for c in cidrs)
        # release on delete, reuse for the next node
        released = store.get_node("ip0").spec.pod_cidr
        store.delete_node("ip0")
        time.sleep(0.2)
        store.add_node(MakeNode().name("ip3").capacity({"cpu": "4"}).obj())
        _wait(lambda: store.get_node("ip3").spec.pod_cidr,
              msg="reused CIDR allocated")
        assert store.get_node("ip3").spec.pod_cidr == released
    finally:
        cm.stop()


def test_cron_day_of_week_is_sunday_zero():
    from kubernetes_tpu.controllers.cronjob import cron_matches

    # 2026-08-02 is a Sunday
    sunday = time.mktime((2026, 8, 2, 9, 0, 0, 0, 0, -1))
    monday = time.mktime((2026, 8, 3, 9, 0, 0, 0, 0, -1))
    assert cron_matches("0 9 * * 0", sunday)
    assert not cron_matches("0 9 * * 0", monday)
    assert cron_matches("0 9 * * 1", monday)


def test_quota_admission_burst_cannot_overshoot():
    """Synchronous charging: a burst of creates admitted before the
    controller recomputes status must still respect hard caps."""
    from kubernetes_tpu.api.resource import parse_quantity
    from kubernetes_tpu.api.types import ObjectMeta, ResourceQuota
    from kubernetes_tpu.apiserver.admission import (
        AdmissionError, AdmissionRequest, ResourceQuotaAdmission,
    )

    store = ClusterStore()
    store.add_resource_quota(ResourceQuota(
        metadata=ObjectMeta(name="q", namespace="default"),
        hard={"pods": parse_quantity("3")},
    ))
    plugin = ResourceQuotaAdmission(store)
    admitted = 0
    rejected = 0
    for i in range(10):  # no controller running: status.used stays {}
        pod = MakePod().name(f"burst{i}").uid(f"bu{i}").obj()
        try:
            plugin.validate(AdmissionRequest(
                operation="CREATE", kind="Pod", namespace="default",
                obj=pod,
            ))
            admitted += 1
        except AdmissionError:
            rejected += 1
    assert admitted == 3 and rejected == 7


def test_cronjob_resume_runs_only_latest_missed_fire():
    """A day of missed '* * * * *' fires must NOT burst into a Job per
    missed minute on resume — only the most recent unmet schedule time
    runs (reference syncOne + getRecentUnmetScheduleTimes)."""
    from kubernetes_tpu.api.types import CronJob, ObjectMeta

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["cronjob"])
    store.add_cron_job(CronJob(
        metadata=ObjectMeta(
            name="lag", namespace="default",
            creation_timestamp=time.time() - 24 * 3600,
        ),
        schedule="* * * * *",
        job_template={"spec": {"containers": [{"name": "c"}]}},
    ))
    cm.start()
    try:
        _wait(lambda: any(
            j.metadata.name.startswith("lag-") for j in store.list_jobs()
        ), msg="latest fire ran")
        time.sleep(1.5)  # several controller passes
        jobs = [j for j in store.list_jobs()
                if j.metadata.name.startswith("lag-")]
        assert len(jobs) <= 2, [j.metadata.name for j in jobs]
    finally:
        cm.stop()


def test_podgc_collects_orphans_and_excess_terminated():
    store = ClusterStore()
    cm = ControllerManager(store, controllers=["podgc"])
    ctrl = cm.get("podgc")
    ctrl.terminated_threshold = 2
    store.add_node(MakeNode().name("n1").capacity({"cpu": "8"}).obj())
    store.add_node(MakeNode().name("gone").capacity({"cpu": "8"}).obj())
    # orphan: bound to a node that will disappear
    store.create_pod(MakePod().name("orphan").uid("ou").node("gone").obj())
    # 4 terminated pods, threshold 2 -> oldest 2 collected
    for i in range(4):
        p = MakePod().name(f"done{i}").uid(f"du{i}").node("n1").obj()
        p.status.phase = "Succeeded"
        p.metadata.creation_timestamp = 100.0 + i
        store.create_pod(p)
    cm.start()
    try:
        store.delete_node("gone")
        _wait(lambda: store.get_pod("default", "orphan") is None,
              msg="orphan collected")
        _wait(lambda: store.get_pod("default", "done0") is None
              and store.get_pod("default", "done1") is None,
              msg="oldest terminated collected")
        assert store.get_pod("default", "done3") is not None
    finally:
        cm.stop()


def test_ttl_controller_annotates_by_cluster_size():
    from kubernetes_tpu.controllers.nodettl import (
        TTL_ANNOTATION, ttl_for_cluster_size,
    )

    assert ttl_for_cluster_size(50) == 0
    assert ttl_for_cluster_size(300) == 15
    assert ttl_for_cluster_size(5000) == 300

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["ttl"])
    cm.start()
    try:
        for i in range(3):
            store.add_node(MakeNode().name(f"t{i}").capacity(
                {"cpu": "4"}).obj())
        _wait(lambda: all(
            store.get_node(f"t{i}").metadata.annotations.get(TTL_ANNOTATION)
            == "0" for i in range(3)
        ), msg="small-cluster ttl annotation")
    finally:
        cm.stop()


def test_pvc_protection_blocks_delete_while_in_use():
    from kubernetes_tpu.api.resource import parse_quantity
    from kubernetes_tpu.api.types import ObjectMeta, PersistentVolumeClaim

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["pvc-protection"])
    cm.start()
    try:
        store.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name="data", namespace="default"),
            requests={"storage": parse_quantity("1Gi")},
        ))
        _wait(lambda: "kubernetes.io/pvc-protection" in
              store.get_pvc("default", "data").metadata.finalizers,
              msg="finalizer attached")
        user = MakePod().name("user").uid("uu").pvc("data").obj()
        store.create_pod(user)
        # delete while in use: only MARKED
        store.delete_object("PersistentVolumeClaim", "default", "data")
        time.sleep(0.3)
        pvc = store.get_pvc("default", "data")
        assert pvc is not None
        assert pvc.metadata.deletion_timestamp is not None
        # last user goes away -> finalizer removed -> physical delete
        store.delete_pod("default", "user")
        _wait(lambda: store.get_pvc("default", "data") is None,
              msg="pvc deleted after last user")
    finally:
        cm.stop()


def test_hpa_scales_deployment_toward_target():
    """podautoscaler semantics: desired = ceil(current * avg/target),
    10% tolerance band, [min,max] clamp, status published."""
    from kubernetes_tpu.api.types import Deployment, HorizontalPodAutoscaler
    from kubernetes_tpu.controllers.horizontalpodautoscaler import (
        USAGE_ANNOTATION,
    )

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["deployment", "replicaset",
                                               "horizontalpodautoscaler"])
    cm.start()
    try:
        d = Deployment(
            selector=LabelSelector(match_labels={"app": "web"}),
            replicas=2,
            template=_template(cpu="1000m"),
        )
        d.metadata.name = "web"
        store.add_deployment(d)
        _wait(lambda: len(store.list_pods()) == 2, msg="2 pods via RS")
        hpa = HorizontalPodAutoscaler(
            scale_target_ref={"kind": "Deployment", "name": "web"},
            min_replicas=1, max_replicas=8,
            target_cpu_utilization_percentage=50,
        )
        hpa.metadata.name = "web-hpa"
        store.add_hpa(hpa)
        # every pod reports 1000m usage against 1000m request: 100%
        # utilization vs the 50% target -> scale 2 -> 4
        def annotate_all():
            for p in store.list_pods():
                if USAGE_ANNOTATION not in p.metadata.annotations:
                    p2 = store.get_pod(p.namespace, p.name)
                    from kubernetes_tpu.api.types import shallow_copy
                    up = shallow_copy(p2)
                    up.metadata = shallow_copy(p2.metadata)
                    up.metadata.annotations = dict(p2.metadata.annotations)
                    up.metadata.annotations[USAGE_ANNOTATION] = "1000"
                    store.update_pod(up)
        annotate_all()
        _wait(lambda: store.get_deployment("default", "web").replicas == 4,
              msg="scaled 2 -> 4")
        got = store.get_hpa("default", "web-hpa")
        assert got.current_cpu_utilization_percentage == 100
        assert got.last_scale_time is not None
        # usage drops to 100m (10% vs 50% target) -> scale toward 1 (min)
        _wait(lambda: len([p for p in store.list_pods()]) == 4,
              msg="4 pods after scale-up")
        for p in store.list_pods():
            from kubernetes_tpu.api.types import shallow_copy
            up = shallow_copy(p)
            up.metadata = shallow_copy(p.metadata)
            up.metadata.annotations = dict(p.metadata.annotations)
            up.metadata.annotations[USAGE_ANNOTATION] = "100"
            store.update_pod(up)
        _wait(lambda: store.get_deployment("default", "web").replicas == 1,
              msg="scaled down to min")
    finally:
        cm.stop()


def test_endpointslice_mirrors_service_backends_in_slices():
    from kubernetes_tpu.api.types import ObjectMeta, Service, ServicePort
    from kubernetes_tpu.controllers.endpointslice import SERVICE_NAME_LABEL

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["endpointslice"])
    ctrl = cm.get("endpointslice")
    ctrl.max_endpoints_per_slice = 2  # force slicing
    cm.start()
    try:
        store.add_service(Service(
            metadata=ObjectMeta(name="web", namespace="default"),
            selector={"app": "web"},
            ports=[ServicePort(name="http", port=80, target_port=8080)],
            cluster_ip="10.0.0.60",
        ))
        for i in range(5):
            store.create_pod(
                MakePod().name(f"be{i}").uid(f"beu{i}")
                .label("app", "web").node("n1").obj())
        _wait(lambda: sum(
            len(es.endpoints) for es in store.list_endpoint_slices()
            if es.metadata.labels.get(SERVICE_NAME_LABEL) == "web"
        ) == 5, msg="5 endpoints mirrored")
        slices = [es for es in store.list_endpoint_slices()
                  if es.metadata.labels.get(SERVICE_NAME_LABEL) == "web"]
        assert len(slices) == 3  # 2+2+1
        assert all(len(es.endpoints) <= 2 for es in slices)
        # shrink: slices rewritten and excess deleted
        for i in range(4):
            store.delete_pod("default", f"be{i}")
        _wait(lambda: sum(
            len(es.endpoints) for es in store.list_endpoint_slices()
            if es.metadata.labels.get(SERVICE_NAME_LABEL) == "web"
        ) == 1, msg="slices shrank")
        slices = [es for es in store.list_endpoint_slices()
                  if es.metadata.labels.get(SERVICE_NAME_LABEL) == "web"]
        assert len(slices) == 1
    finally:
        cm.stop()


def test_attachdetach_maintains_node_attach_state():
    from kubernetes_tpu.api.resource import parse_quantity
    from kubernetes_tpu.api.types import (
        ObjectMeta, PersistentVolume, PersistentVolumeClaim,
    )

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["attachdetach"])
    cm.start()
    try:
        store.add_node(MakeNode().name("n1").capacity({"cpu": "8"}).obj())
        store.add_pv(PersistentVolume(
            metadata=ObjectMeta(name="pv-a"),
            capacity={"storage": parse_quantity("1Gi")},
        ))
        store.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name="data", namespace="default"),
            volume_name="pv-a", phase="Bound",
        ))
        store.create_pod(MakePod().name("u").uid("uu").node("n1")
                         .pvc("data").obj())
        _wait(lambda: store.get_node("n1").status.volumes_attached
              == ["pv-a"], msg="volume attached")
        store.delete_pod("default", "u")
        _wait(lambda: store.get_node("n1").status.volumes_attached == [],
              msg="volume detached after last consumer")
    finally:
        cm.stop()


def test_attachdetach_honors_kubelet_in_use_report():
    """The safe-detach interlock: a volume the kubelet still reports in
    volumesInUse stays attached even after its last desired consumer."""
    from kubernetes_tpu.api.resource import parse_quantity
    from kubernetes_tpu.api.types import (
        ObjectMeta, PersistentVolume, PersistentVolumeClaim, shallow_copy,
    )

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["attachdetach"])
    cm.start()
    try:
        store.add_node(MakeNode().name("n1").capacity({"cpu": "8"}).obj())
        store.add_pv(PersistentVolume(
            metadata=ObjectMeta(name="pv-b"),
            capacity={"storage": parse_quantity("1Gi")},
        ))
        store.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name="data", namespace="default"),
            volume_name="pv-b", phase="Bound",
        ))
        store.create_pod(MakePod().name("u").uid("uu").node("n1")
                         .pvc("data").obj())
        _wait(lambda: store.get_node("n1").status.volumes_attached
              == ["pv-b"], msg="attached")
        # kubelet reports the volume mounted
        store.mutate_object(
            "Node", "", "n1",
            lambda n: n.status.__setattr__("volumes_in_use", ["pv-b"])
            or True,
        )
        store.delete_pod("default", "u")
        time.sleep(0.5)
        assert store.get_node("n1").status.volumes_attached == ["pv-b"], \
            "detached while kubelet still reported the mount"
        # kubelet unmounts: detach proceeds
        store.mutate_object(
            "Node", "", "n1",
            lambda n: n.status.__setattr__("volumes_in_use", []) or True,
        )
        _wait(lambda: store.get_node("n1").status.volumes_attached == [],
              msg="detached after unmount report")
    finally:
        cm.stop()


# ---------------------------------------------------------------------------
# round-3 breadth controllers (VERDICT r2 #7)


def test_csr_approve_sign_clean_flow():
    """certificates trio: a kubelet CSR is auto-approved, then signed;
    stale CSRs are cleaned (app/certificates.go:38,170)."""
    from kubernetes_tpu.api.types import CertificateSigningRequest, ObjectMeta
    from kubernetes_tpu.controllers.certificates import (
        KUBELET_SERVING_SIGNER, sign_request,
    )

    store = ClusterStore()
    cm = ControllerManager(
        store, controllers=["csrapproving", "csrsigning", "csrcleaner"])
    cm.start()
    try:
        store.create_object("CertificateSigningRequest",
                            CertificateSigningRequest(
                                metadata=ObjectMeta(name="node-csr-1"),
                                request="CSR-PAYLOAD",
                                signer_name=KUBELET_SERVING_SIGNER,
                                username="system:node:n1",
                            ))
        _wait(lambda: (
            (c := store.get_object("CertificateSigningRequest", "",
                                   "node-csr-1")) is not None
            and c.approved and c.certificate
        ), msg="CSR approved and signed")
        csr = store.get_object("CertificateSigningRequest", "", "node-csr-1")
        assert csr.certificate == sign_request("CSR-PAYLOAD",
                                               KUBELET_SERVING_SIGNER)
        # an unrecognized signer is left pending
        store.create_object("CertificateSigningRequest",
                            CertificateSigningRequest(
                                metadata=ObjectMeta(name="other-csr"),
                                request="X",
                                signer_name="example.com/custom",
                                username="system:node:n1",
                            ))
        time.sleep(0.3)
        other = store.get_object("CertificateSigningRequest", "", "other-csr")
        assert not other.approved and not other.certificate
        # cleaner: age the signed CSR past the approved TTL and sweep
        cleaner = cm.get("csrcleaner")
        cleaner.approved_ttl = 0.0
        cleaner.enqueue_key("sweep")
        _wait(lambda: store.get_object("CertificateSigningRequest", "",
                                       "node-csr-1") is None,
              msg="stale approved CSR cleaned")
        # pending CSR under its (24h) TTL survives the sweep
        assert store.get_object("CertificateSigningRequest", "",
                                "other-csr") is not None
    finally:
        cm.stop()


def test_bootstrapsigner_and_tokencleaner():
    from kubernetes_tpu.api.types import ConfigMap, ObjectMeta, Secret
    from kubernetes_tpu.controllers.bootstraptoken import (
        BOOTSTRAP_TOKEN_SECRET_TYPE, sign_payload,
    )

    store = ClusterStore()
    store.create_object("ConfigMap", ConfigMap(
        metadata=ObjectMeta(name="cluster-info", namespace="kube-public"),
        data={"kubeconfig": "apiVersion: v1\nclusters: []\n"},
    ))
    cm = ControllerManager(store,
                           controllers=["bootstrapsigner", "tokencleaner"])
    cm.start()
    try:
        store.create_object("Secret", Secret(
            metadata=ObjectMeta(name="bootstrap-token-abc123",
                                namespace="kube-system"),
            type=BOOTSTRAP_TOKEN_SECRET_TYPE,
            data={"token-id": "abc123", "token-secret": "s3cr3t",
                  "usage-bootstrap-signing": "true"},
        ))
        _wait(lambda: "jws-kubeconfig-abc123" in (
            store.get_object("ConfigMap", "kube-public",
                             "cluster-info").data
        ), msg="cluster-info signed")
        info = store.get_object("ConfigMap", "kube-public", "cluster-info")
        assert info.data["jws-kubeconfig-abc123"] == sign_payload(
            info.data["kubeconfig"], "abc123", "s3cr3t")
        # expired token: cleaned, and its signature drops off
        store.create_object("Secret", Secret(
            metadata=ObjectMeta(name="bootstrap-token-old999",
                                namespace="kube-system"),
            type=BOOTSTRAP_TOKEN_SECRET_TYPE,
            data={"token-id": "old999", "token-secret": "x",
                  "usage-bootstrap-signing": "true",
                  "expiration": str(time.time() - 10)},
        ))
        cm.get("tokencleaner").enqueue_key("sweep")
        _wait(lambda: store.get_object("Secret", "kube-system",
                                       "bootstrap-token-old999") is None,
              msg="expired token cleaned")
        _wait(lambda: "jws-kubeconfig-old999" not in (
            store.get_object("ConfigMap", "kube-public",
                             "cluster-info").data
        ), msg="stale signature removed")
    finally:
        cm.stop()


def test_endpointslicemirroring_for_selectorless_service():
    from kubernetes_tpu.api.types import (
        EndpointAddress, Endpoints, ObjectMeta, Service,
    )

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["endpointslicemirroring"])
    cm.start()
    try:
        store.add_service(Service(
            metadata=ObjectMeta(name="ext", namespace="default"),
            selector={},  # selectorless: endpoints managed manually
        ))
        store.create_object("Endpoints", Endpoints(
            metadata=ObjectMeta(name="ext", namespace="default"),
            addresses=[EndpointAddress(ip="10.0.0.9")],
        ))
        def mirrored():
            return [
                es for es in store.list_endpoint_slices()
                if es.metadata.labels.get(
                    "endpointslice.kubernetes.io/managed-by")
                == "endpointslicemirroring-controller.k8s.io"
            ]
        _wait(lambda: len(mirrored()) == 1, msg="mirrored slice exists")
        assert mirrored()[0].endpoints[0].ip == "10.0.0.9"
        # deleting the Endpoints drops the mirror
        store.delete_object("Endpoints", "default", "ext")
        _wait(lambda: not mirrored(), msg="mirror removed")
    finally:
        cm.stop()


def test_volume_expand_grows_pv_capacity():
    from kubernetes_tpu.api.resource import parse_quantity
    from kubernetes_tpu.api.types import (
        ObjectMeta, PersistentVolume, PersistentVolumeClaim,
    )

    store = ClusterStore()
    store.add_pv(PersistentVolume(
        metadata=ObjectMeta(name="pv1"),
        capacity={"storage": parse_quantity("1Gi")},
        claim_ref="default/c1", phase="Bound",
    ))
    store.add_pvc(PersistentVolumeClaim(
        metadata=ObjectMeta(name="c1", namespace="default"),
        requests={"storage": parse_quantity("1Gi")},
        volume_name="pv1", phase="Bound",
    ))
    cm = ControllerManager(store, controllers=["volumeexpand"])
    cm.start()
    try:
        pvc = store.get_pvc("default", "c1")
        pvc.requests = {"storage": parse_quantity("2Gi")}
        store.update_object("PersistentVolumeClaim", pvc)
        _wait(lambda: store.get_pv("pv1").capacity["storage"].value()
              == parse_quantity("2Gi").value(), msg="PV expanded")
        # shrink request is ignored (volumes only grow)
        pvc = store.get_pvc("default", "c1")
        pvc.requests = {"storage": parse_quantity("1Gi")}
        store.update_object("PersistentVolumeClaim", pvc)
        time.sleep(0.3)
        assert store.get_pv("pv1").capacity["storage"].value() == \
            parse_quantity("2Gi").value()
    finally:
        cm.stop()


def test_ephemeral_volume_creates_owned_pvc():
    from kubernetes_tpu.api.types import Volume

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["ephemeral-volume"])
    cm.start()
    try:
        pod = MakePod().name("p1").uid("pu1").req({"cpu": "1"}).obj()
        pod.spec.volumes.append(Volume(name="scratch", ephemeral=True))
        store.create_pod(pod)
        _wait(lambda: store.get_pvc("default", "p1-scratch") is not None,
              msg="ephemeral PVC created")
        pvc = store.get_pvc("default", "p1-scratch")
        assert any(r.get("uid") == "pu1"
                   for r in pvc.metadata.owner_references)
    finally:
        cm.stop()


def test_clusterrole_aggregation_unions_rules():
    from kubernetes_tpu.api.types import ClusterRole, ObjectMeta, PolicyRule

    store = ClusterStore()
    store.add_cluster_role(ClusterRole(
        metadata=ObjectMeta(name="aggregate-admin"),
        aggregation_label_selectors=[
            {"rbac.example.com/aggregate-to-admin": "true"},
        ],
    ))
    cm = ControllerManager(store, controllers=["clusterrole-aggregation"])
    cm.start()
    try:
        store.add_cluster_role(ClusterRole(
            metadata=ObjectMeta(
                name="widgets-admin",
                labels={"rbac.example.com/aggregate-to-admin": "true"},
            ),
            rules=[PolicyRule(verbs=["*"], resources=["widgets"])],
        ))
        _wait(lambda: any(
            "widgets" in r.resources
            for r in store.get_cluster_role("aggregate-admin").rules
        ), msg="rules aggregated")
        # a second matching role joins the union
        store.add_cluster_role(ClusterRole(
            metadata=ObjectMeta(
                name="gadgets-admin",
                labels={"rbac.example.com/aggregate-to-admin": "true"},
            ),
            rules=[PolicyRule(verbs=["get"], resources=["gadgets"])],
        ))
        _wait(lambda: any(
            "gadgets" in r.resources
            for r in store.get_cluster_role("aggregate-admin").rules
        ), msg="second role aggregated")
        # non-matching roles don't leak in
        assert all(
            "secrets" not in r.resources
            for r in store.get_cluster_role("aggregate-admin").rules
        )
    finally:
        cm.stop()


def test_cronjob_starting_deadline_skips_stale_fires():
    from kubernetes_tpu.api.types import CronJob, ObjectMeta

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["cronjob"])
    try:
        # hourly schedule; "now" pinned 10 min past the hour, deadline
        # 60s: the missed top-of-hour fire is skipped, pointer advances
        now = (int(time.time()) // 3600) * 3600 + 600
        store.add_cron_job(CronJob(
            metadata=ObjectMeta(
                name="stale", namespace="default",
                creation_timestamp=now - 2 * 3600,
            ),
            schedule="0 * * * *",
            starting_deadline_seconds=60.0,
            job_template={"spec": {"containers": [{"name": "c"}]}},
        ))
        ctrl = cm.get("cronjob")
        ctrl.now = lambda: now
        # drive sync directly (no threads): the stale fire must be
        # skipped without creating a Job
        ctrl.sync("default/stale")
        assert store.list_jobs() == []
        cj = store.get_cron_job("default", "stale")
        assert cj.last_schedule_time == now - 600  # pointer advanced
    finally:
        cm.stop()


def test_cronjob_concurrency_forbid_and_replace():
    from kubernetes_tpu.api.types import CronJob, ObjectMeta

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["cronjob"])
    try:
        ctrl = cm.get("cronjob")
        store.add_cron_job(CronJob(
            metadata=ObjectMeta(
                name="fb", namespace="default",
                creation_timestamp=time.time() - 120,
            ),
            schedule="* * * * *",
            concurrency_policy="Forbid",
            job_template={"spec": {"containers": [{"name": "c"}]}},
        ))
        ctrl.sync("default/fb")
        jobs = [j for j in store.list_jobs()
                if j.metadata.name.startswith("fb-")]
        assert len(jobs) == 1
        first = jobs[0].metadata.name
        # next fire due while the first Job is still active: Forbid
        # skips WITHOUT advancing the pointer
        cj = store.get_cron_job("default", "fb")
        before = cj.last_schedule_time
        ctrl.now = lambda: before + 61  # one minute later
        ctrl.sync("default/fb")
        jobs = [j for j in store.list_jobs()
                if j.metadata.name.startswith("fb-")]
        assert [j.metadata.name for j in jobs] == [first]
        assert store.get_cron_job("default", "fb").last_schedule_time \
            == before
        # Replace: the active Job dies, the new fire runs
        cj = store.get_cron_job("default", "fb")
        cj.concurrency_policy = "Replace"
        store.add_cron_job(cj)
        ctrl.sync("default/fb")
        jobs = [j for j in store.list_jobs()
                if j.metadata.name.startswith("fb-")]
        assert len(jobs) == 1 and jobs[0].metadata.name != first
    finally:
        cm.stop()


def test_hpa_downscale_stabilization_window():
    """A brief utilization dip must not flap replicas down: downscales
    clamp to the window's highest recommendation
    (horizontal.go stabilizeRecommendation)."""
    from kubernetes_tpu.api.types import HorizontalPodAutoscaler, ObjectMeta
    from kubernetes_tpu.controllers.horizontalpodautoscaler import (
        USAGE_ANNOTATION,
    )

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["horizontalpodautoscaler"])
    ctrl = cm.get("horizontalpodautoscaler")
    ctrl.DOWNSCALE_STABILIZATION_SECONDS = 3600.0  # effectively forever
    cm.start()
    try:
        rs = _rs("web", 4)
        store.add_replica_set(rs)
        for i in range(4):
            p = MakePod().name(f"w{i}").uid(f"wu{i}") \
                .label("app", "web").req({"cpu": "1"}).obj()
            p.metadata.annotations[USAGE_ANNOTATION] = "900"  # hot: 90%
            p.metadata.owner_references = [{
                "kind": "ReplicaSet", "name": "web",
                "uid": rs.metadata.uid, "controller": True,
            }]
            store.create_pod(p)
        store.add_hpa(HorizontalPodAutoscaler(
            metadata=ObjectMeta(name="web", namespace="default"),
            scale_target_ref={"kind": "ReplicaSet", "name": "web"},
            min_replicas=1, max_replicas=8,
            target_cpu_utilization_percentage=50,
        ))
        # hot fleet: scaled UP immediately (stabilization is downscale-only)
        _wait(lambda: store.get_replica_set("default", "web").replicas == 8,
              msg="scale up to 8")
        # fleet goes idle: the downscale recommendation is clamped by
        # the window's max recommendation (8) -> stays at 8
        for i in range(4):
            p = store.get_pod("default", f"w{i}")
            p.metadata.annotations[USAGE_ANNOTATION] = "10"
            store.update_pod(p)
        time.sleep(2.5)  # several resync ticks
        assert store.get_replica_set("default", "web").replicas == 8
        # with no stabilization, the same dip scales down at once
        ctrl.DOWNSCALE_STABILIZATION_SECONDS = 0.0
        ctrl._recommendations.clear()
        _wait(lambda: store.get_replica_set("default", "web").replicas < 8,
              msg="scale down applies without the window")
    finally:
        cm.stop()
