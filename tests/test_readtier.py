"""Read-tier subsystem (apiserver/readtier.py + harness/watchherd.py).

Tier-1 coverage for the watch-replica tier:

- the in-process mini-cell: one owner apiserver, two ``ReadReplica``
  mirrors, a 10-informer herd through a live writer, and one replica
  hard-killed mid-stream — every informer converges to the owner's
  truth with zero lost and zero double-applied events, relists stay
  confined to the killed replica's informers, and the surviving
  replica's store is identical to the owner's;
- ``FenceStateMachine`` hysteresis: consecutive-sample trip and clear
  thresholds, the half-budget clear bar, counter semantics;
- subscription resume-from-RV: a severed ``ReplicationClient`` resumes
  from its cursor and converges — INCLUDING when a create+delete pair
  landed entirely inside the outage window (the lazily re-encoded
  replay must not stamp the create at the delete's revision; the store
  stamps deletion RVs on a copy for exactly this reason);
- the store's deletion-copy contract directly: committed watch events
  are immutable history, a delete must never rewrite them in place;
- ``RestClusterClient`` read-route re-resolution per transport-retry
  attempt: a read that dies against a dead or fenced replica
  down-marks it and the SAME call re-routes to the owner instead of
  burning its retry budget on the dead pool;
- the ``readtier[...]`` diag segment round-trips through the one
  writer (``diagfmt.format_readtier``) and the one parser
  (``diagfmt.parse_diag``).
"""

from __future__ import annotations

import threading
import time

import pytest

from kubernetes_tpu.apiserver.readtier import (
    FenceStateMachine,
    ReadReplica,
    ReplicationClient,
)
from kubernetes_tpu.apiserver.rest import APIServer
from kubernetes_tpu.apiserver.store import ADDED, DELETED, ClusterStore
from kubernetes_tpu.client.restcluster import RestClusterClient
from kubernetes_tpu.harness import diagfmt
from kubernetes_tpu.harness.burst import make_burst_pods
from kubernetes_tpu.harness.watchherd import run_readtier_mini_cell


# ---------------------------------------------------------------------------
# the mini-cell: run once, assert many invariants


@pytest.fixture(scope="module")
def mini_cell():
    return run_readtier_mini_cell()


class TestReadTierMiniCell:
    def test_every_informer_converged_to_owner_truth(self, mini_cell):
        assert mini_cell["unconverged"] == 0
        assert mini_cell["lost_events"] == 0
        assert mini_cell["truth_objects"] > 0

    def test_replica_kill_relists_are_confined(self, mini_cell):
        # the killed replica's informers must relist (their streams
        # died mid-watch) — and NOBODY else may
        assert mini_cell["relists_on_killed"] >= 1
        assert mini_cell["relists_beyond_killed"] == 0
        assert mini_cell["killed_informers"] > 0

    def test_cursor_handoff_never_double_applies(self, mini_cell):
        # dup_suppressed counts frames the informers' per-key
        # high-water filter caught across the relist handoff — they
        # were suppressed, never re-applied, so convergence (asserted
        # above) plus zero lost events IS the no-double-apply proof
        assert mini_cell["delivered_total"] > 0

    def test_surviving_replica_store_matches_owner(self, mini_cell):
        assert mini_cell["replica_truth_match"] is True

    def test_survivor_never_reseeded(self, mini_cell):
        # the owner stayed up: the survivor's subscription must have
        # held (or resumed from its cursor) — a reseed here would mean
        # the cursor resume path is broken
        assert mini_cell["survivor_stats"]["reseeds"] == 0


# ---------------------------------------------------------------------------
# fence hysteresis


class TestFenceStateMachine:
    def test_trips_after_consecutive_over_budget_samples(self):
        f = FenceStateMachine(lag_budget_s=0.1, trip_after=3)
        assert f.observe(0.2) is None
        assert f.observe(0.2) is None
        assert f.observe(0.2) is True
        assert f.fenced and f.fences == 1

    def test_one_good_sample_resets_the_trip_counter(self):
        f = FenceStateMachine(lag_budget_s=0.1, trip_after=3)
        f.observe(0.2)
        f.observe(0.2)
        assert f.observe(0.05) is None      # hiccup over, streak broken
        f.observe(0.2)
        f.observe(0.2)
        assert not f.fenced                 # needs 3 consecutive again
        assert f.observe(0.2) is True

    def test_clears_only_after_sustained_half_budget_headroom(self):
        f = FenceStateMachine(lag_budget_s=0.1, trip_after=1,
                              clear_after=3)
        assert f.observe(0.5) is True
        assert f.observe(0.04) is None
        assert f.observe(0.04) is None
        # just-under-budget is NOT headroom: the streak resets
        assert f.observe(0.09) is None
        assert f.fenced
        assert f.observe(0.04) is None
        assert f.observe(0.04) is None
        # third consecutive half-budget sample: unfence transition
        assert f.observe(0.04) is False
        assert not f.fenced

    def test_unfence_returns_false_and_refence_counts(self):
        f = FenceStateMachine(lag_budget_s=0.1, trip_after=1,
                              clear_after=2)
        assert f.observe(0.5) is True
        assert f.observe(0.01) is None
        assert f.observe(0.01) is False
        assert not f.fenced
        assert f.observe(0.5) is True
        assert f.fences == 2


# ---------------------------------------------------------------------------
# the store's deletion-copy contract (the read tier's correctness rests
# on committed watch history being immutable)


class TestDeletionCopy:
    def test_delete_does_not_mutate_the_committed_added_event(self):
        store = ClusterStore()
        events = []
        handle = store.watch(events.append)
        try:
            (pod,) = make_burst_pods(1, name_prefix="dc-",
                                     uid_prefix="dcu-")
            store.create_pod(pod)
            added = next(e for e in events if e.type == ADDED)
            create_rv = int(added.obj.metadata.resource_version)
            store.delete_pod(pod.namespace, pod.metadata.name)
            deleted = next(e for e in events if e.type == DELETED)
            # the delete got its own, newer revision — stamped on a
            # COPY, never on the instance the ADDED event references
            assert int(deleted.obj.metadata.resource_version) > create_rv
            assert deleted.obj is not added.obj
            assert int(added.obj.metadata.resource_version) == create_rv
        finally:
            handle.stop()

    def test_bulk_delete_keeps_committed_history_immutable(self):
        store = ClusterStore()
        events = []
        handle = store.watch(events.append)
        try:
            pods = make_burst_pods(3, name_prefix="dcb-",
                                   uid_prefix="dcbu-")
            for p in pods:
                store.create_pod(p)
            created = {e.obj.metadata.name:
                       int(e.obj.metadata.resource_version)
                       for e in events if e.type == ADDED}
            store.delete_pods([(p.namespace, p.metadata.name)
                               for p in pods])
            for e in events:
                if e.type != ADDED:
                    continue
                assert int(e.obj.metadata.resource_version) == \
                    created[e.obj.metadata.name]
        finally:
            handle.stop()


# ---------------------------------------------------------------------------
# subscription resume-from-RV


class TestSubscriptionResume:
    def _truth(self, store):
        return sorted((p.namespace, p.metadata.name,
                       int(p.metadata.resource_version))
                      for p in store.list_pods())

    def _wait_match(self, mirror, store, timeout=10.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._truth(mirror) == self._truth(store):
                return True
            time.sleep(0.02)
        return False

    def test_severed_stream_resumes_from_cursor(self):
        store = ClusterStore()
        owner = APIServer(store=store).start()
        mirror = ClusterStore()
        repl = ReplicationClient(owner.url, mirror, replica_id="tr0")
        try:
            repl.start()
            assert repl.seeded.wait(10.0)
            for p in make_burst_pods(5, name_prefix="sr-",
                                     uid_prefix="sru-"):
                store.create_pod(p)
            assert self._wait_match(mirror, store)
            cursor_before = repl.cursor
            owner.sever_connections()
            for p in make_burst_pods(5, name_prefix="sr2-",
                                     uid_prefix="sr2u-"):
                store.create_pod(p)
            assert self._wait_match(mirror, store)
            assert repl.resumes >= 1
            assert repl.reseeds == 0
            assert repl.cursor > cursor_before
        finally:
            repl.stop()
            owner.shutdown_server()

    def test_delete_inside_the_outage_window_is_not_resurrected(self):
        # the regression the deletion-copy fix closes: a pod created
        # AND deleted while the subscription was down used to replay
        # its create lazily re-encoded at the delete's revision, so
        # the delete that followed was collapsed as a duplicate and
        # the mirror kept the pod forever
        store = ClusterStore()
        owner = APIServer(store=store).start()
        mirror = ClusterStore()
        repl = ReplicationClient(owner.url, mirror, replica_id="tr1")
        try:
            repl.start()
            assert repl.seeded.wait(10.0)
            for p in make_burst_pods(3, name_prefix="dw-",
                                     uid_prefix="dwu-"):
                store.create_pod(p)
            assert self._wait_match(mirror, store)
            owner.sever_connections()
            (ghost,) = make_burst_pods(1, name_prefix="ghost-",
                                       uid_prefix="ghostu-")
            store.create_pod(ghost)
            store.delete_pod(ghost.namespace, ghost.metadata.name)
            (keeper,) = make_burst_pods(1, name_prefix="keep-",
                                        uid_prefix="keepu-",
                                        offset=1)
            store.create_pod(keeper)
            assert self._wait_match(mirror, store)
            names = {p.metadata.name for p in mirror.list_pods()}
            assert ghost.metadata.name not in names
            assert keeper.metadata.name in names
            assert repl.reseeds == 0
        finally:
            repl.stop()
            owner.shutdown_server()


# ---------------------------------------------------------------------------
# client read routing: per-attempt re-resolution


class TestClientReadRouting:
    def _seed(self, store, n, prefix):
        for p in make_burst_pods(n, name_prefix=prefix,
                                 uid_prefix=prefix + "u"):
            store.create_pod(p)

    def test_reads_ride_the_advertised_replica(self):
        store = ClusterStore()
        owner = APIServer(store=store).start()
        rep = ReadReplica(owner.url, replica_id="rt0")
        client = None
        try:
            self._seed(store, 4, "rr-")
            rep.start(seed_timeout=10.0)
            client = RestClusterClient(owner.url)
            client.set_read_replicas({0: [rep.url]})
            pods = client.list_pods()
            assert len(pods) == 4
            assert client.replica_reads >= 1
        finally:
            if client is not None:
                client._drop_conn()
            rep.stop()
            owner.shutdown_server()

    def test_dead_replica_reroutes_within_one_call(self):
        store = ClusterStore()
        owner = APIServer(store=store).start()
        rep = ReadReplica(owner.url, replica_id="rt1")
        client = None
        try:
            self._seed(store, 3, "dr-")
            rep.start(seed_timeout=10.0)
            client = RestClusterClient(owner.url)
            client.set_read_replicas({0: [rep.url]})
            assert len(client.list_pods()) == 3
            rep.kill()
            # the SAME call must down-mark the dead replica on its
            # transport error and re-resolve to the owner — not dial
            # the dead pool until the retry budget runs out
            pods = client.list_pods()
            assert len(pods) == 3
            assert client.replica_reroutes >= 1
        finally:
            if client is not None:
                client._drop_conn()
            owner.shutdown_server()

    def test_fenced_replica_503_redirects_to_owner(self):
        store = ClusterStore()
        owner = APIServer(store=store).start()
        rep = ReadReplica(owner.url, replica_id="rt2")
        client = None
        try:
            self._seed(store, 2, "fr-")
            rep.start(seed_timeout=10.0)
            client = RestClusterClient(owner.url)
            client.set_read_replicas({0: [rep.url]})
            assert len(client.list_pods()) == 2
            rep.server.fenced.set()
            pods = client.list_pods()
            assert len(pods) == 2
            assert client.replica_reroutes >= 1
        finally:
            if client is not None:
                client._drop_conn()
            rep.stop()
            owner.shutdown_server()


# ---------------------------------------------------------------------------
# diag round-trip


class TestReadtierDiag:
    def test_round_trips_through_parse_diag(self):
        seg = diagfmt.format_readtier({
            "replicas": 4, "streams": 320, "lag_p99_ms": 379.58,
            "fenced": 1, "relists": 0,
        })
        parsed = diagfmt.parse_diag(f"    diag: {seg}")
        assert parsed is not None
        rt = parsed["readtier"]
        assert rt["replicas"] == 4
        assert rt["streams"] == 320
        assert rt["lag_p99_ms"] == pytest.approx(379.6, abs=0.05)
        assert rt["fenced"] == 1
        assert rt["relists"] == 0

    def test_empty_info_emits_nothing(self):
        assert diagfmt.format_readtier(None) == ""
        assert diagfmt.format_readtier({}) == ""  # falsy info: no segment
