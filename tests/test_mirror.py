"""Device-resident cluster-state mirror tests (ISSUE 20): the delta
journal's gap semantics, the expressibility contract's reseed paths,
and the PR's hardest promise — over identical seeded event sequences
(capacity churn, pod churn, node death mid-flight, gang waves) the
mirror-on scatter path and the ``KTPU_MIRROR=off`` delta-encode
reference must produce a BIT-IDENTICAL bound set, across mesh sizes
{1, 2, 4} × 3 seeds on the sharded tier.

Also carries the tier-1 sustained mini-cell for the tentpole's
measurable claim: on an open-loop sustained row the host cluster-plane
encode share collapses to near zero (``encode_share < 0.05``) with
zero lost pods — the per-batch pod-row encode (the drained h2d) is all
that remains.
"""

from __future__ import annotations

import copy
import time

import numpy as np
import pytest

from kubernetes_tpu.api.resource import Quantity
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config.feature_gates import FeatureGates
from kubernetes_tpu.ops.mirror import (
    DeltaJournal,
    _pack_entries,
    mirror_enabled,
)
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.sidecar import attach_batch_scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


def _make_sched(store, *, max_batch=32, backend=None):
    sched = Scheduler.create(
        store, feature_gates=FeatureGates({"TPUBatchScheduler": True}),
        provider="GangSchedulingProvider")
    bs = attach_batch_scheduler(sched, max_batch=max_batch,
                                adaptive_chunk=False, backend=backend)
    sched.start()
    return sched, bs


def _pump(sched, bs, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sched.queue.flush_backoff_completed()
        if bs.run_batch(pop_timeout=0.0):
            continue
        if sched.queue.pending_active_count() == 0 and \
                bs._pending is None:
            break
        time.sleep(0.01)
    bs.flush()
    assert sched.wait_for_inflight_bindings()


def _bound_set(store):
    return sorted((p.metadata.name, p.spec.node_name)
                  for p in store.list_pods())


def _set_node_cpu(store, name: str, cpu: str) -> None:
    """Capacity churn: an allocatable-only node update (the scatter
    fast path — everything else about the node is unchanged)."""
    node = copy.deepcopy(store.get_node(name))
    node.status.allocatable["cpu"] = Quantity(cpu)
    node.status.capacity["cpu"] = Quantity(cpu)
    store.update_node(node)


def _gang(w, gangs=2, size=4, cpu="2"):
    out = []
    for g in range(gangs):
        for m in range(size):
            out.append(
                MakePod().name(f"w{w}-g{g}-m{m}").uid(f"gu{w}-{g}-{m}")
                .priority(10).req({"cpu": cpu})
                .label("pod-group.scheduling.k8s.io/name",
                       f"gang-{w}-{g}")
                .label("pod-group.scheduling.k8s.io/min-available",
                       str(size))
                .obj())
    return out


def _run_scenario(scenario: str, seed: int, mirror_on: bool,
                  monkeypatch, *, devices=None, max_batch=32):
    """One arm of the differential: drive a seeded event sequence and
    return (bound set, mirror info). ``devices`` selects the sharded
    tier at that mesh width; None = the process default backend."""
    monkeypatch.setenv("KTPU_MIRROR", "on" if mirror_on else "off")
    rng = np.random.default_rng(seed)
    store = ClusterStore()
    n_nodes = 10
    for i in range(n_nodes):
        store.add_node(MakeNode().name(f"n{i}")
                       .capacity({"cpu": "8", "memory": "16Gi"}).obj())
    backend = None
    if devices is not None:
        from kubernetes_tpu.parallel import ShardedBackend, make_mesh

        backend = ShardedBackend(make_mesh(devices, batch_axis=1))
    sched, bs = _make_sched(store, max_batch=max_batch, backend=backend)
    try:
        assert (bs.session._mirror is not None) == mirror_on

        def wave(w, count):
            store.create_pods([
                MakePod().name(f"w{w}-p{i}").uid(f"u{w}-{i}")
                .req({"cpu": f"{int(rng.integers(1, 6)) * 100}m"})
                .obj()
                for i in range(count)
            ])
            _pump(sched, bs)

        if scenario == "capacity_churn":
            wave(0, 24)
            # shrink two seeded nodes, grow one — three allocatable-only
            # updates the mirror must scatter bit-exactly
            picks = rng.choice(n_nodes, size=3, replace=False)
            _set_node_cpu(store, f"n{picks[0]}", "4")
            _set_node_cpu(store, f"n{picks[1]}", "5")
            _set_node_cpu(store, f"n{picks[2]}", "12")
            wave(1, 24)
            # pod churn: free seeded capacity, then refill it
            bound = [p for p in store.list_pods() if p.spec.node_name]
            for p in rng.choice(bound, size=6, replace=False):
                store.delete_pod(p.metadata.namespace, p.metadata.name)
            wave(2, 16)
        elif scenario == "node_death":
            wave(0, 24)
            # one cycle dispatches a solve that is still in flight when
            # the node dies — the suspect-batch discard plus the
            # node-SET epoch bump both fire mid-sequence
            store.create_pods([
                MakePod().name(f"w1-p{i}").uid(f"u1-{i}")
                .req({"cpu": "300m"}).obj()
                for i in range(24)
            ])
            bs.run_batch(pop_timeout=0.1)
            store.delete_node(f"n{int(rng.integers(0, n_nodes))}")
            _pump(sched, bs)
            wave(2, 16)
        elif scenario == "gang_waves":
            wave(0, 12)
            store.create_pods(_gang(1, gangs=3, size=4))
            _pump(sched, bs)
            picks = rng.choice(n_nodes, size=2, replace=False)
            _set_node_cpu(store, f"n{picks[0]}", "6")
            _set_node_cpu(store, f"n{picks[1]}", "10")
            store.create_pods(_gang(2, gangs=2, size=4))
            _pump(sched, bs)
        else:  # pragma: no cover - scenario typo guard
            raise AssertionError(scenario)
        info = None
        if bs.session._mirror is not None:
            info = bs.session._mirror.info()
        return _bound_set(store), info
    finally:
        sched.stop()
        import gc

        gc.collect()


class TestDeltaJournal:
    def test_contiguous_window(self):
        j = DeltaJournal()
        for s in range(1, 6):
            j.note(s, "pod_add", f"p{s}")
        recs = j.window(1, 5)
        assert [r.seq for r in recs] == [2, 3, 4, 5]
        assert all(r.kind == "pod_add" for r in recs)

    def test_empty_window(self):
        j = DeltaJournal()
        assert j.window(7, 7) == []
        assert j.window(9, 7) == []

    def test_gap_reads_as_none(self):
        j = DeltaJournal()
        j.note(1, "pod_add")
        j.note(3, "pod_add")   # seq 2 bumped by an uninstrumented site
        assert j.window(0, 3) is None
        # a window starting past the gap is fine
        assert [r.seq for r in j.window(2, 3)] == [3]

    def test_ring_eviction_reads_as_none(self):
        j = DeltaJournal(cap=4)
        for s in range(1, 10):
            j.note(s, "pod_add")
        assert j.window(0, 9) is None          # 1..5 evicted
        assert j.window(5, 9) is not None      # still resident

    def test_window_predating_journal_reads_as_none(self):
        j = DeltaJournal()
        j.note(11, "pod_add")
        assert j.window(9, 11) is None


class TestPackEntries:
    def test_add_padding_is_zero(self):
        rows, cols, vals = _pack_entries([(3, 7, -5)], pad_with_zero=True)
        assert rows.shape == (8,) and rows.dtype == np.int32
        assert (rows[1:] == 0).all() and (vals[1:] == 0).all()
        assert (rows[0], cols[0], vals[0]) == (3, 7, -5)

    def test_set_padding_repeats_last(self):
        items = [(1, 2, 9), (4, 5, 6)]
        rows, cols, vals = _pack_entries(items, pad_with_zero=False)
        assert rows.shape == (8,)
        assert (rows[2:] == 4).all() and (vals[2:] == 6).all()

    def test_pow2_buckets(self):
        rows, _, _ = _pack_entries([(0, 0, 1)] * 9, pad_with_zero=True)
        assert rows.shape == (16,)


class TestKillSwitch:
    def test_env_parsing(self, monkeypatch):
        for off in ("off", "0", "false", " OFF "):
            monkeypatch.setenv("KTPU_MIRROR", off)
            assert mirror_enabled() is False
        monkeypatch.setenv("KTPU_MIRROR", "on")
        assert mirror_enabled() is True
        monkeypatch.delenv("KTPU_MIRROR")
        assert mirror_enabled() is True

    def test_off_builds_no_mirror(self, monkeypatch):
        monkeypatch.setenv("KTPU_MIRROR", "off")
        store = ClusterStore()
        store.add_node(MakeNode().name("n0")
                       .capacity({"cpu": "8", "memory": "16Gi"}).obj())
        sched, bs = _make_sched(store)
        try:
            assert bs.session._mirror is None
            assert bs.mirror_info() is None
        finally:
            sched.stop()


class TestMirrorDifferential:
    """Mirror-on ≡ mirror-off bound sets over seeded event sequences
    on the process-default backend, 3 seeds per scenario."""

    @pytest.mark.parametrize("seed", [3, 14, 77])
    def test_capacity_and_pod_churn(self, seed, monkeypatch):
        on, ion = _run_scenario("capacity_churn", seed, True, monkeypatch)
        off, _ = _run_scenario("capacity_churn", seed, False, monkeypatch)
        assert on == off
        # the churn was genuinely scattered, not reseeded around:
        # allocatable updates and pod deletes ride catch_up
        assert ion["events"] > 0
        assert ion["catch_ups"] > 0

    @pytest.mark.parametrize("seed", [3, 14, 77])
    def test_node_death_mid_flight(self, seed, monkeypatch):
        on, _ = _run_scenario("node_death", seed, True, monkeypatch)
        off, _ = _run_scenario("node_death", seed, False, monkeypatch)
        assert on == off
        # nothing was lost: every injected pod is in the store (bound
        # or pending), and the arms agree pod-for-pod
        assert len(on) == 64

    @pytest.mark.parametrize("seed", [3, 14, 77])
    def test_gang_waves(self, seed, monkeypatch):
        on, _ = _run_scenario("gang_waves", seed, True, monkeypatch)
        off, _ = _run_scenario("gang_waves", seed, False, monkeypatch)
        assert on == off
        # gangs landed atomically in both arms
        for w, g, size in ((1, 0, 4), (1, 1, 4), (1, 2, 4),
                           (2, 0, 4), (2, 1, 4)):
            members = [n for (name, n) in on
                       if name.startswith(f"w{w}-g{g}-") and n]
            assert len(members) in (0, size), (w, g, members)


class TestMeshDifferential:
    """The sharded tier: mirror-on ≡ mirror-off across mesh {1, 2, 4}
    × 3 seeds (the scatter routes through GSPMD to the shard owning
    each node column — out_shardings pins the planes layout)."""

    @pytest.mark.parametrize("devices", [1, 2, 4])
    @pytest.mark.parametrize("seed", [3, 14, 77])
    def test_capacity_churn_bit_identical(self, devices, seed,
                                          monkeypatch):
        import jax

        if len(jax.devices()) < devices:
            pytest.skip(f"needs {devices} devices")
        on, ion = _run_scenario("capacity_churn", seed, True,
                                monkeypatch, devices=devices)
        off, _ = _run_scenario("capacity_churn", seed, False,
                               monkeypatch, devices=devices)
        assert on == off
        assert ion["events"] > 0


class TestSustainedMirrorCell:
    """Tier-1 sustained mini-cell: the tentpole's measurable claim at
    compressed scale — host cluster-plane encode share near zero with
    zero lost pods on an open-loop arrival row."""

    def test_encode_share_near_zero_zero_lost(self):
        from kubernetes_tpu.harness.sustained import run_sustained_cell

        cell = run_sustained_cell(pods=600, qps=400.0, max_batch=64,
                                  wait_timeout=120.0)
        assert cell["lost"] == 0
        assert cell["ever_bound"] == cell["injected"] == 600
        # the mirror rode the row (default-on) ...
        assert cell["mirror"] is not None
        # ... and the encode stage is gone from the sustained path:
        # what remains under "encode" is cluster-plane builds (cold
        # seed + rare reseeds), amortized to noise over the row
        assert cell["encode_share"] < 0.05
        assert cell["staleness_verdict"] in (None, "ok")

    def test_mirror_off_reference_still_clean(self, monkeypatch):
        """The differential reference arm stays healthy: KTPU_MIRROR=off
        must not regress the zero-lost invariant (it is the committed
        fallback, not a dead code path)."""
        monkeypatch.setenv("KTPU_MIRROR", "off")
        from kubernetes_tpu.harness.sustained import run_sustained_cell

        cell = run_sustained_cell(pods=300, qps=400.0, max_batch=64,
                                  wait_timeout=120.0)
        assert cell["lost"] == 0
        assert cell["mirror"] is None
