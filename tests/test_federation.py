"""Federated multi-cluster tier (federation/): the cross-cluster
placement, failover, and degradation contracts at CI scale.

The tier-1 surface of the federation PR — the cheap unit contracts
plus two REAL compressed cells:

- ``TestHomeMap`` / ``TestCapacityLedger`` — the routing affinity and
  per-cluster capacity/write facts everything above builds on.
- ``TestFederationScheduler`` — clusters-as-solver-columns placement:
  home affinity, saturation spillover, dead-cluster exclusion, gang
  atomicity by construction, and the serial-oracle ≡ device-solver
  differential.
- ``TestFederationDriver`` — the ``plan_rebalance`` action shapes
  translated to cluster granularity (failover fires exactly once;
  split releases a namespace; move re-homes the hottest tenant).
- ``TestFailoverClient`` — ``failover_cluster`` re-places a dead
  cell's pods on survivors under the same names, and routing survives
  a cell dying mid-send.
- ``TestLossMiniCell`` / ``TestSpillMiniCell`` — in-process 3-cluster
  cells under the open loop: cluster loss mid-storm (zero lost
  fleet-wide, orphans re-bound within the recovery budget, gangs
  never split) and saturation spillover (overflow lands remotely).
- ``TestDegradationDifferential`` — federation down ≡ federation up
  at single-cluster scope: bit-identical bound sets.
- ``TestFederationDiag`` — ``diagfmt.format_federation`` round-trips
  through the shared bracket parser.

The spawned-process storm (real apiserver children, real SIGKILL) is
the committed bench row (``bench.py --config federation``) and the
``--suite federation`` chaos cells — too heavy for tier-1; these
cells walk the same seams in-process.
"""

from __future__ import annotations

import pytest

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.federation import (
    CapacityLedger,
    FederatedClusterClient,
    FederationPolicy,
    FederationScheduler,
    FederationUnavailable,
    GANG_NAME_LABEL,
    HomeMap,
    group_units,
)
from kubernetes_tpu.harness import diagfmt
from kubernetes_tpu.harness.federation import (
    FEDERATION_SCENARIOS,
    _federation_ok,
    run_chaos_federation,
    run_degradation_differential,
    run_federation_mini_cell,
)


def _node(name: str, cpu_milli: int) -> Node:
    return Node.from_dict({
        "metadata": {"name": name,
                     "labels": {"kubernetes.io/hostname": name}},
        "status": {"capacity": {"cpu": f"{cpu_milli}m",
                                "memory": "68719476736",
                                "pods": "110"}},
    })


def _pod(name: str, ns: str = "default", milli: int = 500,
         gang: str = "") -> Pod:
    labels = {GANG_NAME_LABEL: gang} if gang else {}
    pod = Pod.from_dict({
        "metadata": {"name": name, "namespace": ns, "labels": labels},
        "spec": {"containers": [
            {"name": "c", "image": "registry/fake:1",
             "resources": {"requests": {"cpu": f"{milli}m",
                                        "memory": "1048576"}}}]},
    })
    pod.metadata.uid = f"uid-{ns}-{name}"
    return pod


def _ledger(capacities: dict) -> CapacityLedger:
    """cluster id → total milli-cpu, observed as one node each."""
    ledger = CapacityLedger()
    for cid, milli in capacities.items():
        ledger.register(cid)
        ledger.refresh_from(cid, [_node(f"c{cid}-node-0", milli)], [])
    return ledger


# ---------------------------------------------------------------------------
# routing affinity + capacity facts


class TestHomeMap:
    def test_hash_fallback_is_deterministic_and_in_range(self):
        hm = HomeMap([0, 1, 2])
        homes = {hm.home_of(f"ns-{i}") for i in range(40)}
        assert homes <= {0, 1, 2}
        assert hm.home_of("ns-7") == hm.home_of("ns-7")

    def test_pin_beats_hash(self):
        hm = HomeMap([0, 1, 2], pin={"tenant-a": 2})
        assert hm.home_of("tenant-a") == 2

    def test_override_beats_pin(self):
        # the rebalancer's move action re-homes a pinned tenant
        hm = HomeMap([0, 1, 2], pin={"tenant-a": 2})
        hm.overrides["tenant-a"] = 1
        assert hm.home_of("tenant-a") == 1

    def test_spread_releases_affinity_entirely(self):
        # the rebalancer's split action: no home at all → place freely
        hm = HomeMap([0, 1, 2], pin={"tenant-a": 2})
        hm.overrides["tenant-a"] = 1
        hm.spread.add("tenant-a")
        assert hm.home_of("tenant-a") is None


class TestCapacityLedger:
    def test_refresh_computes_capacity_and_usage(self):
        ledger = CapacityLedger()
        bound = _pod("p-0", milli=1000)
        bound.spec.node_name = "c0-node-0"
        pending = _pod("p-1", milli=500)
        cap = ledger.refresh_from(
            0, [_node("c0-node-0", 16000)], [bound, pending])
        assert cap.allocatable_milli == 16000
        # a pending pod is capacity already spoken for on its cluster
        assert cap.used_milli == 1500
        assert (cap.bound, cap.pending) == (1, 1)
        assert ledger.remaining(0) == (14500, cap.remaining()[1])

    def test_admissions_reserve_until_a_refresh_observes_them(self):
        ledger = _ledger({0: 16000})
        routed = _pod("p-0", milli=4000)
        ledger.note_admitted(0, [routed])
        assert ledger.remaining(0)[0] == 12000
        assert ledger.utilization(0) == pytest.approx(0.25)
        # once a refresh OBSERVES the routed pod, the reservation is
        # released (the pod now counts as used — pending or bound)
        ledger.refresh_from(0, [_node("c0-node-0", 16000)], [routed])
        cap = ledger.capacity(0)
        assert cap.admitted_pods == 0
        assert ledger.remaining(0)[0] == 12000

    def test_refresh_never_drops_an_unobserved_reservation(self):
        # the overcommit race: a placement lands AFTER the refresher
        # read the cluster's pod list but BEFORE the refresh commits.
        # The stale list cannot account for the new pod, so its
        # reservation must survive — blanket-clearing here once let
        # the spill storm route one pod more than the cell could bind.
        ledger = _ledger({0: 16000})
        stale_pod_list = []          # read before the placement landed
        ledger.note_admitted(0, [_pod("p-0", milli=4000)])
        ledger.refresh_from(
            0, [_node("c0-node-0", 16000)], stale_pod_list)
        assert ledger.remaining(0)[0] == 12000
        assert ledger.capacity(0).admitted_pods == 1

    def test_re_reserving_a_pod_replaces_not_double_counts(self):
        ledger = _ledger({0: 16000})
        pod = _pod("p-0", milli=4000)
        ledger.note_admitted(0, [pod])
        ledger.note_admitted(0, [pod])
        assert ledger.remaining(0)[0] == 12000
        assert ledger.capacity(0).admitted_pods == 1

    def test_write_counts_are_cumulative_per_cluster_and_tenant(self):
        ledger = _ledger({0: 16000, 1: 16000})
        ledger.note_admitted(0, [_pod("p-0", ns="a"),
                                 _pod("p-1", ns="a")])
        ledger.note_admitted(1, [_pod("p-2", ns="b")])
        writes, ns_writes = ledger.write_counts()
        assert writes == {0: 2.0, 1: 1.0}
        assert ns_writes == {"a": 2.0, "b": 1.0}

    def test_liveness_flags(self):
        ledger = _ledger({0: 1000, 1: 1000})
        ledger.mark_dead(0)
        assert ledger.live_clusters() == [1]
        assert ledger.dead_clusters() == [0]
        assert not ledger.alive(0)
        ledger.mark_alive(0)
        assert ledger.live_clusters() == [0, 1]


# ---------------------------------------------------------------------------
# clusters as solver columns


class TestFederationScheduler:
    def test_gangs_fold_into_one_unit(self):
        pods = [_pod("g-0", gang="fg-0", milli=700),
                _pod("s-0", milli=500),
                _pod("g-1", gang="fg-0", milli=700)]
        units = group_units(pods)
        assert [u.gang for u in units] == ["fg-0", ""]
        assert units[0].milli == 1400
        assert len(units[0].pods) == 2

    def test_home_cluster_wins_while_it_has_room(self):
        ledger = _ledger({0: 16000, 1: 16000})
        sched = FederationScheduler(ledger, home_of=lambda ns: 1)
        (pl,) = sched.place([_pod("p-0")])
        assert pl.cluster == 1
        assert not pl.spilled

    def test_saturated_home_spills_to_a_sibling(self):
        ledger = _ledger({0: 16000, 1: 16000})
        # pin home 0 past the 0.85 saturation threshold
        ledger.note_admitted(0, [_pod("fat", milli=14000)])
        sched = FederationScheduler(ledger, home_of=lambda ns: 0)
        (pl,) = sched.place([_pod("p-0")])
        assert pl.cluster == 1
        assert pl.spilled

    def test_dead_cluster_is_never_chosen(self):
        ledger = _ledger({0: 16000, 1: 16000})
        ledger.mark_dead(0)
        sched = FederationScheduler(ledger, home_of=lambda ns: 0)
        (pl,) = sched.place([_pod("p-0")])
        assert pl.cluster == 1

    def test_gang_places_atomically_on_one_cluster(self):
        ledger = _ledger({0: 16000, 1: 16000})
        pods = [_pod(f"g-{i}", gang="fg-0", milli=800)
                for i in range(4)]
        (pl,) = FederationScheduler(ledger).place(pods)
        assert pl.cluster in (0, 1)
        assert len(pl.unit.pods) == 4

    def test_no_live_cluster_leaves_units_unplaced_not_lost(self):
        ledger = _ledger({0: 16000})
        ledger.mark_dead(0)
        sched = FederationScheduler(ledger, home_of=lambda ns: 0)
        (pl,) = sched.place([_pod("p-0")])
        assert pl.cluster is None
        assert sched.unplaced_units == 1

    def test_down_layer_raises_federation_unavailable(self):
        sched = FederationScheduler(_ledger({0: 16000}))
        sched.set_down(True)
        with pytest.raises(FederationUnavailable):
            sched.place([_pod("p-0")])

    def test_serial_oracle_matches_device_solver(self):
        # the same K-column question through the numpy per-unit oracle
        # and the jitted what-if solver must place identically
        def run(serial: bool):
            ledger = _ledger({0: 16000, 1: 16000, 2: 16000})
            ledger.note_admitted(1, [_pod("fat", milli=14000)])
            sched = FederationScheduler(
                ledger, policy=FederationPolicy(serial=serial),
                home_of=lambda ns: {"a": 0, "b": 1, "c": 2}.get(ns))
            pods = [_pod("p-0", ns="a"), _pod("p-1", ns="b"),
                    _pod("g-0", ns="c", gang="fg-0"),
                    _pod("g-1", ns="c", gang="fg-0")]
            return [(pl.unit.namespace, pl.cluster)
                    for pl in sched.place(pods)]

        assert run(serial=True) == run(serial=False)


# ---------------------------------------------------------------------------
# the rebalancer's action translation


class _StubFedClient:
    def __init__(self):
        self.ledger = CapacityLedger()
        self.home_map = HomeMap([0, 1, 2], pin={"fed-0": 0})
        self.failed: list = []

    def failover_cluster(self, cid: int) -> int:
        self.failed.append(cid)
        return 7


class TestFederationDriver:
    def _driver(self):
        from kubernetes_tpu.federation.rebalancer import (
            _FederationDriver,
        )

        client = _StubFedClient()
        for cid in (0, 1, 2):
            client.ledger.register(cid)
        return client, _FederationDriver(client)

    def test_observe_speaks_the_driver_contract(self):
        client, driver = self._driver()
        obs = driver.observe()
        assert set(obs) == {"epoch", "topology", "slot_writes",
                            "ns_writes", "dead"}
        topo = obs["topology"]
        assert topo.partitions == 3
        assert topo.slots_of_partition(1) == [1]

    def test_failover_fires_exactly_once_per_dead_cluster(self):
        client, driver = self._driver()
        client.ledger.mark_dead(1)
        assert driver.observe()["dead"] == [1]
        report = driver.apply({"op": "failover", "partition": 1})
        assert report == {"cluster": 1, "replaced": 7}
        assert client.failed == [1]
        # a dead CLUSTER stays dead — it must not be re-reported or
        # the planner would re-fire failover every tick forever
        assert driver.observe()["dead"] == []
        assert driver.observe()["topology"].slots_of_partition(1) == []

    def test_split_releases_the_namespace(self):
        client, driver = self._driver()
        driver.apply({"op": "split", "namespace": "fed-0"})
        assert client.home_map.home_of("fed-0") is None

    def test_move_rehomes_the_hottest_tenant(self):
        client, driver = self._driver()
        client.ledger.note_admitted(0, [_pod("p-0", ns="fed-0"),
                                        _pod("p-1", ns="fed-0")])
        report = driver.apply({"op": "move", "assignments": {0: 2}})
        assert report == {"moved": {"fed-0": 2}}
        assert client.home_map.home_of("fed-0") == 2

    def test_buy_and_retire_are_recorded_noops(self):
        _, driver = self._driver()
        assert driver.apply({"op": "buy"}) == {"noop": "buy"}
        assert driver.apply({"op": "retire", "partition": 2}) \
            == {"noop": "retire"}


# ---------------------------------------------------------------------------
# the cross-cluster client's failover path


class TestFailoverClient:
    def _federation(self):
        from kubernetes_tpu.apiserver.store import ClusterStore

        stores = {cid: ClusterStore() for cid in (0, 1)}
        for cid, store in stores.items():
            store.add_node(Node.from_dict({
                "metadata": {"name": f"c{cid}-node-0"},
                "status": {"capacity": {"cpu": "16",
                                        "memory": "68719476736",
                                        "pods": "110"}},
            }))
        ledger = CapacityLedger()
        home_map = HomeMap([0, 1], pin={"a": 0, "b": 1})
        sched = FederationScheduler(ledger, home_of=home_map.home_of)
        client = FederatedClusterClient(stores, sched, ledger,
                                        home_map=home_map)
        for cid, store in stores.items():
            ledger.refresh_from(cid, store.list_nodes(),
                                store.list_pods())
        return stores, ledger, client

    def test_failover_replaces_dead_cell_pods_by_name(self):
        stores, ledger, client = self._federation()
        client.create_pods([_pod(f"a-{i}", ns="a") for i in range(4)]
                           + [_pod("b-0", ns="b")])
        assert {p.metadata.name for p in stores[0].list_pods()} \
            == {f"a-{i}" for i in range(4)}
        replaced = client.failover_cluster(0)
        assert replaced == 4
        # the lost-pod invariant is NAME-keyed: the survivors now hold
        # every name the dead cell held
        assert {p.metadata.name for p in stores[1].list_pods()} \
            == {f"a-{i}" for i in range(4)} | {"b-0"}
        assert client.route_of("a", "a-0") == 1
        assert client.counters()["failovers"] == 1
        assert client.counters()["failover_replaced"] == 4

    def test_gang_continuity_pins_later_chunks(self):
        stores, ledger, client = self._federation()
        client.create_pods([_pod("g-0", ns="a", gang="fg-0")])
        first = client.route_of("a", "g-0")
        client.create_pods([_pod("g-1", ns="a", gang="fg-0")])
        assert client.route_of("a", "g-1") == first

    def test_scheduler_failure_degrades_to_home_routing(self):
        stores, ledger, client = self._federation()
        client.scheduler.set_down(True)
        client.create_pods([_pod("a-0", ns="a"), _pod("b-0", ns="b")])
        assert client.route_of("a", "a-0") == 0
        assert client.route_of("b", "b-0") == 1
        assert client.counters()["fallback_placements"] == 2


# ---------------------------------------------------------------------------
# the real cells, compressed


@pytest.fixture(scope="module")
def loss_cell():
    """One cluster-loss mini-cell shared by every invariant assertion:
    the storm is the expensive part; the checks are reads."""
    return run_federation_mini_cell(scenario="loss-mid", seed=18)


class TestLossMiniCell:
    def test_zero_lost_fleet_wide(self, loss_cell):
        assert loss_cell["lost"] == 0
        assert loss_cell["ever_bound"] == loss_cell["injected"] > 0

    def test_a_cluster_actually_died_and_failed_over(self, loss_cell):
        assert loss_cell["victim"] is not None
        assert loss_cell["failovers"] >= 1
        assert "failover" in loss_cell["rebalancer_actions"]

    def test_orphans_recovered_within_budget(self, loss_cell):
        assert loss_cell["recovery_ratio"] >= 0.8

    def test_gangs_never_split_across_clusters(self, loss_cell):
        assert loss_cell["gang_splits"] == 0


@pytest.fixture(scope="module")
def spill_cell():
    return run_federation_mini_cell(scenario="spill", seed=18)


class TestSpillMiniCell:
    def test_overflow_lands_remotely_with_nothing_lost(self,
                                                       spill_cell):
        assert spill_cell["victim"] is None
        assert spill_cell["spilled"] > 0
        assert spill_cell["lost"] == 0
        assert spill_cell["ever_bound"] == spill_cell["injected"]

    def test_every_cluster_carried_load(self, spill_cell):
        bound = {k: v["bound"]
                 for k, v in spill_cell["per_cluster"].items()}
        assert all(v > 0 for v in bound.values()), bound


class TestDegradationDifferential:
    def test_fed_down_binds_the_identical_set(self):
        res = run_degradation_differential(pods=120, qps=400, seed=18)
        assert res["identical"], (
            f"on={len(res['bound_on'])} down={len(res['bound_down'])}")
        assert res["on"]["lost"] == 0
        assert res["down"]["lost"] == 0
        # the down arm really exercised the fallback path; the up arm
        # never needed it
        assert res["down"]["fallbacks"] > 0
        assert res["on"]["fallbacks"] == 0


# ---------------------------------------------------------------------------
# contracts around the chaos/bench surfaces


class TestFederationContracts:
    def test_scenario_catalog(self):
        assert set(FEDERATION_SCENARIOS) == {
            "spill", "loss-early", "loss-mid", "loss-late",
            "spill-loss"}

    def test_chaos_cell_rejects_unknown_scenarios(self):
        with pytest.raises(ValueError, match="unknown federation"):
            run_chaos_federation(18, scenario="bogus")

    def test_verdict_surface_flips_on_every_invariant(self):
        base = {"scenario": "spill-loss", "lost_pods": 0,
                "injected": 10, "ever_bound": 10, "send_errors": [],
                "gang_splits": 0, "survivor_relists": 0,
                "per_cluster_slo_ok": True, "recovery_ratio": 1.0,
                "victim": 1, "slo_verdicts_ok": True, "spilled": 3,
                "failovers": 1}
        ok, why = _federation_ok(dict(base))
        assert ok and why == ""
        for key, bad in [("lost_pods", 2), ("ever_bound", 9),
                         ("send_errors", ["boom"]), ("gang_splits", 1),
                         ("survivor_relists", 4),
                         ("per_cluster_slo_ok", False),
                         ("recovery_ratio", 0.5),
                         ("slo_verdicts_ok", False), ("spilled", 0),
                         ("failovers", 0)]:
            res = dict(base)
            res[key] = bad
            ok, why = _federation_ok(res)
            assert not ok, key
            assert why, key


class TestFederationDiag:
    def test_round_trips_through_the_bracket_parser(self):
        seg = diagfmt.format_federation({
            "clusters": 3, "spilled": 47, "failovers": 1,
            "lost": 0, "recovery": 1.0})
        assert seg == ("federation[clusters=3 spilled=47 failovers=1 "
                       "lost=0 recovery=1.00]")
        parsed = diagfmt.parse_diag(diagfmt.format_diag([seg]))
        assert parsed["federation"] == {
            "clusters": 3, "spilled": 47, "failovers": 1,
            "lost": 0, "recovery": 1.0}

    def test_quiet_when_empty(self):
        assert diagfmt.format_federation(None) == ""
        assert diagfmt.format_federation({}) == ""
