"""API Priority & Fairness (apiserver/flowcontrol.py + the rest.py
admission path): shuffle-sharded fair queuing, seat/width accounting,
the exemption envelope, honest Retry-After on both admission paths, the
client's APF-aware 429 handling, and the differential guard that the
fairness machinery is free on the uncontended hot path. Reference
anchors: ``apiserver/pkg/util/flowcontrol`` (queueset, shufflesharding),
``filters/priority-and-fairness.go``."""

import http.client
import json
import re
import threading
import time

import pytest

from kubernetes_tpu.apiserver.flowcontrol import (
    FlowControlConfig,
    FlowController,
    FlowSchema,
    PriorityLevelSpec,
    Rejected,
    WidthEstimator,
    default_config,
    shuffle_shard_hand,
)
from kubernetes_tpu.apiserver.rest import APIServer
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client.restcluster import RestClusterClient
from kubernetes_tpu.testing import MakeNode, MakePod


def _serve(**kwargs):
    store = ClusterStore()
    server = APIServer(store=store, **kwargs).start()
    return store, server


def _http(url: str, method: str = "GET", headers=None, body=None):
    rest = url.split("://", 1)[1]
    hostport, _, path = rest.partition("/")
    host, _, port = hostport.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=15)
    try:
        conn.request(method, "/" + path, body=body,
                     headers=headers or {})
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, dict(resp.headers), raw
    finally:
        conn.close()


def _tiny_config(queue_wait_s: float = 0.2,
                 shed_factor: float = 0.8) -> FlowControlConfig:
    """Two seats per level, one queue of two slots for best-effort —
    small enough that a pair of slow requests saturates it."""
    return FlowControlConfig(
        levels=[
            PriorityLevelSpec("system", shares=50, queues=2,
                              queue_length=8, hand_size=2),
            PriorityLevelSpec("best-effort", shares=50, queues=1,
                              queue_length=2, hand_size=1,
                              sheddable=True),
        ],
        schemas=[
            FlowSchema("system", 10, "system",
                       lambda u, g, v, r, ns:
                       u.startswith("system:kube-")),
            FlowSchema("catch-all", 100, "best-effort"),
        ],
        total_seats=4, queue_wait_s=queue_wait_s,
        shed_factor=shed_factor)


# ---------------------------------------------------------------------------
# shuffle sharding + fair dispatch (queueset unit layer)


class TestQueueSet:
    def test_shuffle_shard_hand_is_distinct_and_deterministic(self):
        hand = shuffle_shard_hand(123456789, 16, 4)
        assert len(hand) == len(set(hand)) == 4
        assert all(0 <= i < 16 for i in hand)
        assert hand == shuffle_shard_hand(123456789, 16, 4)

    def test_distinct_flows_spread_across_queues(self):
        from kubernetes_tpu.apiserver.flowcontrol import _flow_hash

        firsts = {tuple(shuffle_shard_hand(_flow_hash("L", f"flow-{i}"),
                                           16, 4))
                  for i in range(64)}
        # 64 flows into C(16,4) hands: collisions allowed, but a
        # degenerate dealer (everyone in one hand) must not pass
        assert len(firsts) > 16

    def test_noisy_flow_does_not_starve_light_flow(self):
        """Capacity 2, 12 queued noisy requests, then 1 light request:
        fair dispatch must serve the light flow long before the noisy
        backlog drains — it sits in its own shuffle-sharded queue with
        the least virtual work."""
        fc = FlowController(FlowControlConfig(
            levels=[PriorityLevelSpec("workload", shares=1, queues=8,
                                      queue_length=64, hand_size=2)],
            schemas=[FlowSchema("all", 1, "workload")],
            total_seats=2, queue_wait_s=30.0))
        level = fc.levels["workload"]
        assert level.capacity == 2
        blockers = [fc.admit("noisy", (), "GET", "pods", "", path="x")
                    for _ in range(2)]
        order = []
        order_lock = threading.Lock()

        def worker(flow: str) -> None:
            t = fc.admit(flow, (), "GET", "pods", "", path="x")
            with order_lock:
                order.append(flow)
            t.release()

        noisy = [threading.Thread(target=worker, args=("noisy",),
                                  daemon=True) for _ in range(12)]
        for t in noisy:
            t.start()
        deadline = time.monotonic() + 5
        while level.queued_requests < 12 and time.monotonic() < deadline:
            time.sleep(0.01)
        light = threading.Thread(target=worker, args=("light",),
                                 daemon=True)
        light.start()
        deadline = time.monotonic() + 5
        while level.queued_requests < 13 and time.monotonic() < deadline:
            time.sleep(0.01)
        for b in blockers:
            b.release()
        light.join(timeout=10)
        for t in noisy:
            t.join(timeout=10)
        assert "light" in order
        # the light flow was served within the first few dispatches,
        # not behind the whole noisy backlog
        assert order.index("light") < 4

    def test_queue_full_rejects_with_computed_retry_after(self):
        fc = FlowController(_tiny_config(queue_wait_s=5.0))
        blockers = [fc.admit("anon", (), "GET", "pods", "", path="x")
                    for _ in range(2)]     # seats gone
        queued = []
        for _ in range(2):                 # queue_length=2 fills
            t = threading.Thread(
                target=lambda: fc.admit("anon", (), "GET", "pods", "",
                                        path="x"),
                daemon=True)
            t.start()
            queued.append(t)
        level = fc.levels["best-effort"]
        deadline = time.monotonic() + 5
        while level.queued_requests < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        with pytest.raises(Rejected) as exc:
            fc.admit("anon", (), "GET", "pods", "", path="x")
        assert exc.value.reason == "queue-full"
        assert 0.05 <= exc.value.retry_after <= 13.0
        assert exc.value.level == "best-effort"
        for b in blockers:
            b.release()

    def test_deadline_exceeded_rejects_with_timeout(self):
        fc = FlowController(_tiny_config(queue_wait_s=0.05))
        blockers = [fc.admit("anon", (), "GET", "pods", "", path="x")
                    for _ in range(2)]
        t0 = time.monotonic()
        with pytest.raises(Rejected) as exc:
            fc.admit("anon", (), "GET", "pods", "", path="x")
        assert exc.value.reason == "timeout"
        assert time.monotonic() - t0 < 2.0
        for b in blockers:
            b.release()
        # the abandoned entry must not strand accounting: seats free,
        # queue empty, a fresh request dispatches immediately
        t = fc.admit("anon", (), "GET", "pods", "", path="x")
        t.release()
        snap = fc.levels["best-effort"].snapshot()
        assert snap["queued_requests"] == 0
        assert snap["executing_seats"] == 0

    def test_shed_mode_protects_unsheddable_levels(self):
        """With aggregate queued demand past shed_factor, sheddable
        levels reject instead of queueing while the system level keeps
        admitting."""
        fc = FlowController(_tiny_config(queue_wait_s=5.0,
                                         shed_factor=0.0))
        blockers = [fc.admit("anon", (), "GET", "pods", "", path="x")
                    for _ in range(2)]
        # one queued request pushes queued seats past factor 0.0
        q = threading.Thread(
            target=lambda: fc.admit("anon", (), "GET", "pods", "",
                                    path="x"), daemon=True)
        q.start()
        level = fc.levels["best-effort"]
        deadline = time.monotonic() + 5
        while level.queued_requests < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        with pytest.raises(Rejected) as exc:
            fc.admit("anon", (), "GET", "pods", "", path="x")
        assert exc.value.reason == "shed"
        # system is NOT sheddable: it queues/admits as normal
        ticket = fc.admit("system:kube-scheduler", (), "POST",
                          "bindings", "", path="x")
        ticket.release()
        for b in blockers:
            b.release()

    def test_admission_overhead_uncontended(self):
        """The fairness machinery must be ~free on the uncontended hot
        path: one admit+release well under 100us on average."""
        fc = FlowController(default_config(400, 200))
        t0 = time.monotonic()
        for _ in range(10_000):
            fc.admit("system:kube-scheduler", (), "POST", "bindings",
                     "default", path="/api/v1/bindings").release()
        assert time.monotonic() - t0 < 2.0


# ---------------------------------------------------------------------------
# classification + width


class TestClassificationAndWidth:
    def test_default_schemas_route_by_identity(self):
        fc = FlowController(default_config(400, 200))
        cases = {
            ("admin", ("system:masters",)): "exempt",
            ("system:kube-scheduler", ()): "system",
            ("system:node:n1", ()): "system",
            ("alice", ()): "workload",
            ("system:anonymous", ()): "best-effort",
            ("token:deadbeef", ()): "best-effort",
        }
        for (user, groups), want in cases.items():
            schema, level = fc.classify(user, groups, "GET", "pods", "")
            got = schema.priority_level
            assert got == want, f"{user} -> {got}, want {want}"

    def test_flow_id_refines_the_distinguisher(self):
        s = FlowSchema("x", 1, "workload")
        assert s.flow_key("alice", "", "t1") != s.flow_key("alice", "",
                                                           "t2")
        assert s.flow_key("alice", "", "") == "alice"

    def test_width_scales_with_declared_items(self):
        w = WidthEstimator(items_per_seat=100, max_seats=10)
        assert w.estimate("POST", "pods", False, False, 1, 0) == 1
        assert w.estimate("POST", "pods", False, False, 500, 0) == 5
        # a 4096-item bulk bind caps at max_seats, never unbounded
        assert w.estimate("POST", "bindings", False, False, 4096, 0) == 10

    def test_list_width_follows_served_sizes(self):
        w = WidthEstimator(list_objects_per_seat=500, max_seats=10)
        assert w.estimate("GET", "pods", True, False, 0, 0) == 1
        w.note_list_size("pods", 3000)
        assert w.estimate("GET", "pods", True, False, 0, 0) >= 4
        # other resources unaffected
        assert w.estimate("GET", "nodes", True, False, 0, 0) == 1

    def test_undeclared_bulk_cannot_launder_width(self):
        # a hostile tenant omitting X-Kubernetes-Request-Items on a
        # collection POST is priced by the per-item byte floor: a
        # ~200-tiny-item body (~20 KiB) costs what declaring honestly
        # would, while a normal single-object create stays at 1 seat
        w = WidthEstimator(items_per_seat=100, bulk_item_bytes=128,
                           max_seats=10)
        assert w.estimate("POST", "configmaps", False, False, 0,
                          20 * 1024, is_collection_mutation=True) >= 2
        assert w.estimate("POST", "pods", False, False, 0, 2048,
                          is_collection_mutation=True) == 1
        # named-object routes keep the coarse large-body fallback only
        assert w.estimate("PUT", "pods", False, False, 0, 20 * 1024,
                          is_collection_mutation=False) == 1

    def test_watch_release_does_not_sample_exec_ewma(self):
        # watch-init tickets release ~instantly at stream attach; those
        # near-zero durations must not collapse avg_exec_s (and with it
        # every 429's computed Retry-After) under a reconnect herd
        fc = FlowController(default_config(10, 10))
        lvl = fc.levels["workload"]
        lvl.avg_exec_s = 0.5
        t = fc.admit(user="alice", groups=("system:authenticated",),
                     verb="GET", resource="pods", namespace="",
                     is_watch=True, path="/api/v1/pods?watch=1")
        assert t.exec_sample is False
        t.release()
        assert lvl.avg_exec_s == 0.5          # untouched
        t2 = fc.admit(user="alice", groups=("system:authenticated",),
                      verb="GET", resource="pods", namespace="",
                      path="/api/v1/pods")
        t2.release()
        assert lvl.avg_exec_s != 0.5          # normal requests sample

    def test_watch_init_width(self):
        w = WidthEstimator(watch_init_seats=2)
        assert w.estimate("GET", "pods", False, True, 0, 0) == 2


# ---------------------------------------------------------------------------
# the server admission path


class TestServerAPF:
    def _saturate(self, store, server, n=2, hold_s=2.0):
        """Jam the best-effort level with slow anonymous list GETs."""
        hold = threading.Event()
        orig = store.list_objects_with_rv

        def slow_list(kind, ns=None):
            hold.wait(hold_s)
            return orig(kind, ns)

        store.list_objects_with_rv = slow_list
        jammers = []
        host, port = server.url.replace("http://", "").split(":")
        for _ in range(n):
            c = http.client.HTTPConnection(host, int(port), timeout=15)
            c.request("GET", "/api/v1/pods")
            jammers.append(c)
        deadline = time.monotonic() + 5
        level = server.flowcontrol.levels["best-effort"]
        while level.executing_seats < n and time.monotonic() < deadline:
            time.sleep(0.01)
        return hold, jammers, orig

    def test_apf_429_computed_retry_after_and_pf_headers(self):
        store, server = _serve(flow_control=_tiny_config(
            queue_wait_s=0.15))
        orig = store.list_objects_with_rv
        try:
            hold, jammers, orig = self._saturate(store, server)
            # seats gone AND the single queue (length 2) fills: the
            # next requests must come back 429 with the computed hint
            extra = []
            host, port = server.url.replace("http://", "").split(":")
            for _ in range(3):
                c = http.client.HTTPConnection(host, int(port),
                                               timeout=15)
                c.request("GET", "/api/v1/pods")
                extra.append(c)
            statuses = []
            got_429 = None
            for c in extra:
                resp = c.getresponse()
                statuses.append(resp.status)
                if resp.status == 429 and got_429 is None:
                    got_429 = (dict(resp.headers),
                               json.loads(resp.read()))
                else:
                    resp.read()
            assert 429 in statuses
            headers, body = got_429
            assert body["reason"] == "TooManyRequests"
            assert headers.get("X-Kubernetes-PF-PriorityLevel") \
                == "best-effort"
            assert headers.get("X-Kubernetes-PF-FlowSchema")
            retry_after = headers.get("Retry-After", "")
            assert re.fullmatch(r"\d+(\.\d+)?", retry_after)
            assert 0.05 <= float(retry_after) <= 13.0
            hold.set()
            for c in jammers + extra:
                try:
                    c.close()
                except OSError:
                    pass
        finally:
            store.list_objects_with_rv = orig
            server.shutdown_server()

    def test_exemption_envelope_at_full_saturation(self):
        """/healthz /livez /readyz /metrics /metrics/resources and the
        debug routes must NEVER be queued, rejected, or charged seats —
        even with every seat occupied and the queues full (the 'flow
        control must never fail a liveness probe' promise, tested)."""
        store, server = _serve(flow_control=_tiny_config(
            queue_wait_s=2.0))
        orig = store.list_objects_with_rv
        try:
            hold, jammers, orig = self._saturate(store, server)
            before = {
                name: lv.snapshot()["dispatched_total"]
                for name, lv in server.flowcontrol.levels.items()
                if lv is not None
            }
            for path in ("/healthz", "/livez", "/readyz", "/metrics",
                         "/metrics/resources", "/debug/faults",
                         "/debug/apf"):
                t0 = time.monotonic()
                code, headers, raw = _http(server.url + path)
                elapsed = time.monotonic() - t0
                assert code == 200, (path, code, raw[:200])
                assert elapsed < 1.0, (path, elapsed)
            # /debug/trace: 200 when tracing is live, 404 when the
            # tracer is disabled — NEVER 429, never queued
            code, _h, _raw = _http(server.url + "/debug/trace")
            assert code in (200, 404)
            after = {
                name: lv.snapshot()["dispatched_total"]
                for name, lv in server.flowcontrol.levels.items()
                if lv is not None
            }
            # no exempt probe consumed a seat or a dispatch
            assert after == before
            hold.set()
            for c in jammers:
                try:
                    c.close()
                except OSError:
                    pass
        finally:
            store.list_objects_with_rv = orig
            server.shutdown_server()

    def test_client_records_pf_level_and_breaker_stays_closed(self):
        """Satellite: the client attributes APF 429s to the rejecting
        priority level in client_retries_total{reason=apf_<level>} and
        the CircuitBreaker does NOT count them as fabric failures —
        overload is not outage."""
        from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

        store, server = _serve(flow_control=_tiny_config(
            queue_wait_s=0.1))
        orig = store.list_objects_with_rv
        fm = fabric_metrics()
        before = fm.client_retries_total.get("GET", "apf_best-effort")
        try:
            hold, jammers, orig = self._saturate(store, server)
            client = RestClusterClient(
                server.url, max_retries=2, retry_after_cap=0.05,
                breaker_threshold=1, binary=False)
            for _ in range(3):
                code, _ = client._request("GET", "/api/v1/pods")
            hold.set()
            assert fm.client_retries_total.get(
                "GET", "apf_best-effort") > before
            # a breaker with threshold 1 would be open after ONE
            # counted failure: APF pushback must not have counted
            assert not client.breaker.is_open
            for c in jammers:
                try:
                    c.close()
                except OSError:
                    pass
        finally:
            store.list_objects_with_rv = orig
            server.shutdown_server()

    def test_bulk_verbs_consume_proportional_seats(self):
        """Per-object rate equivalence, server half: a 500-pod bulk
        create declared via X-Kubernetes-Request-Items reads as ~5
        seats, not 1 — batching cannot launder concurrency."""
        store, server = _serve()
        try:
            client = RestClusterClient(server.url)
            level = server.flowcontrol.levels["best-effort"]
            before = level.snapshot()
            pods = [MakePod().name(f"b{i}").uid(f"u{i}").obj()
                    for i in range(500)]
            code, resp = client._request(
                "POST", "/api/v1/namespaces/default/pods",
                {"kind": "PodList", "items": pods}, charge=500)
            assert code == 201 and resp["created"] == 500
            after = level.snapshot()
            seats = after["seats_dispatched_total"] \
                - before["seats_dispatched_total"]
            requests = after["dispatched_total"] \
                - before["dispatched_total"]
            assert requests == 1
            assert seats == 5
        finally:
            server.shutdown_server()

    def test_watch_init_seats_release_after_attach(self):
        """Watches charge watch-init seats for the attach/replay burst
        only; a long-lived stream must not hold seats."""
        store, server = _serve()
        try:
            import urllib.request

            done = threading.Event()

            def watcher():
                req = urllib.request.Request(
                    server.url + "/api/v1/pods?watch=1")
                with urllib.request.urlopen(req, timeout=10) as resp:
                    done.set()
                    try:
                        resp.read(1)
                    except Exception:  # noqa: BLE001 — server shutdown
                        pass

            t = threading.Thread(target=watcher, daemon=True)
            t.start()
            assert done.wait(5.0)
            time.sleep(0.2)
            snap = server.flowcontrol.levels["best-effort"].snapshot()
            assert snap["executing_seats"] == 0
            assert snap["seats_dispatched_total"] >= 2   # init width
        finally:
            server.shutdown_server()

    def test_legacy_lane_retry_after_is_computed(self):
        """Satellite: the legacy max-in-flight path no longer answers a
        hard-coded `Retry-After: 1` — it reports the lane's expected
        drain time."""
        store, server = _serve(max_readonly_inflight=1,
                               max_mutating_inflight=10,
                               flow_control=None)
        orig = store.list_objects_with_rv
        try:
            hold = threading.Event()

            def slow_list(kind, ns=None):
                hold.wait(2.0)
                return orig(kind, ns)

            store.list_objects_with_rv = slow_list
            host, port = server.url.replace("http://", "").split(":")
            jammer = http.client.HTTPConnection(host, int(port))
            jammer.request("GET", "/api/v1/pods")
            time.sleep(0.2)
            code, headers, raw = _http(server.url + "/api/v1/pods")
            assert code == 429
            retry_after = headers.get("Retry-After", "")
            assert re.fullmatch(r"\d+(\.\d+)?", retry_after)
            assert 0.05 <= float(retry_after) <= 13.0
            hold.set()
            jammer.getresponse().read()
            jammer.close()
        finally:
            store.list_objects_with_rv = orig
            server.shutdown_server()

    def test_debug_apf_snapshot_shape(self):
        store, server = _serve()
        try:
            client = RestClusterClient(server.url)
            client.list_pods()
            code, snap = client._request("GET", "/debug/apf")
            assert code == 200
            assert snap["total_capacity"] > 0
            assert set(snap["levels"]) == {"system", "workload",
                                           "best-effort"}
            lv = snap["levels"]["best-effort"]
            assert lv["dispatched_total"] >= 1
            assert "queue_depths" in lv and "flows" in lv
            assert [s["name"] for s in snap["schemas"]][0] == "exempt"
        finally:
            server.shutdown_server()


# ---------------------------------------------------------------------------
# differential guard: APF must be free when uncontended


class TestDifferentialGuard:
    def _drive(self, server, n: int) -> float:
        """n serial GET+POST pairs over one keep-alive connection;
        returns elapsed seconds."""
        host, port = server.url.replace("http://", "").split(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=15)
        body = json.dumps({
            "kind": "ConfigMap",
            "metadata": {"name": "g", "namespace": "default"}}).encode()
        t0 = time.monotonic()
        for i in range(n):
            conn.request("GET", "/api/v1/pods")
            conn.getresponse().read()
            conn.request("POST", "/api/v1/namespaces/default/configmaps",
                         body=body,
                         headers={"Content-Type": "application/json"})
            conn.getresponse().read()
        elapsed = time.monotonic() - t0
        conn.close()
        return elapsed

    def test_single_tenant_throughput_within_noise_of_legacy(self):
        """With one tenant and no contention, the APF admission path
        must cost the same as the legacy lanes (generous 1.6x bound:
        this guards against a blocking/lock bug on the hot path, not
        against microseconds)."""
        _store_a, apf_server = _serve()
        _store_l, legacy_server = _serve(flow_control=None)
        try:
            # warmup both (connection setup, code paths)
            self._drive(apf_server, 20)
            self._drive(legacy_server, 20)
            apf_t = min(self._drive(apf_server, 150) for _ in range(2))
            legacy_t = min(self._drive(legacy_server, 150)
                           for _ in range(2))
            assert apf_t < legacy_t * 1.6 + 0.2, (
                f"APF path {apf_t:.3f}s vs legacy {legacy_t:.3f}s")
        finally:
            apf_server.shutdown_server()
            legacy_server.shutdown_server()
