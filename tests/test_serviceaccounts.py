"""ServiceAccount identity end-to-end (VERDICT r3 #4/#6/#7): the tokens
controller mints token Secrets, the apiserver's bearer authn resolves
them to ``system:serviceaccount:<ns>:<name>``, ServiceAccount admission
injects the default account into pods, RBAC ServiceAccount subjects
grant those identities, NodeRestriction confines node users, and the
root-ca-cert-publisher provisions the per-namespace trust anchor.

Reference: ``pkg/controller/serviceaccount/tokens_controller.go:124``,
``plugin/pkg/admission/serviceaccount/admission.go:100``,
``plugin/pkg/admission/noderestriction/admission.go:79``,
``pkg/controller/certificates/rootcacertpublisher/publisher.go:56``.
"""

import time

import pytest

from kubernetes_tpu.api.types import (
    Namespace,
    ObjectMeta,
    PolicyRule,
    RBACSubject,
    Role,
    RoleBinding,
    RoleRef,
)
from kubernetes_tpu.apiserver.rbac import provision_bootstrap_policy
from kubernetes_tpu.apiserver.rest import APIServer, RestClient
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.controllers.rootcacertpublisher import ROOT_CA_CONFIGMAP
from kubernetes_tpu.controllers.serviceaccounttoken import (
    SA_TOKEN_TYPE,
    sa_username,
)
from kubernetes_tpu.testing import MakeNode, MakePod


def wait_for(cond, timeout=5.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _sa_token(store, namespace, name):
    """The minted token for a service account, or None."""
    for s in store.list_objects("Secret", namespace):
        if s.type == SA_TOKEN_TYPE and s.metadata.annotations.get(
                "kubernetes.io/service-account.name") == name:
            return s.data.get("token")
    return None


class TestTokensController:
    def _cluster(self):
        store = ClusterStore()
        cm = ControllerManager(
            store, controllers=["serviceaccount", "serviceaccount-token"]
        )
        cm.start()
        return store, cm

    def test_mints_token_secret_and_links_it(self):
        store, cm = self._cluster()
        try:
            store.add_namespace(Namespace(
                metadata=ObjectMeta(name="dev")))
            # serviceaccount controller creates "default", tokens
            # controller mints its secret and links it
            assert wait_for(lambda: _sa_token(store, "dev", "default"))
            sa = store.get_service_account("dev", "default")
            assert len(sa.secrets) == 1
            assert sa.secrets[0].startswith("default-token-")
        finally:
            cm.stop()

    def test_recreated_account_invalidates_old_token(self):
        store, cm = self._cluster()
        try:
            store.add_namespace(Namespace(
                metadata=ObjectMeta(name="dev")))
            assert wait_for(lambda: _sa_token(store, "dev", "default"))
            old = _sa_token(store, "dev", "default")
            store.delete_object("ServiceAccount", "dev", "default")
            # the SA controller recreates "default" (new uid); the old
            # token secret must be replaced, not inherited
            assert wait_for(
                lambda: (_sa_token(store, "dev", "default") or old) != old
            )
            assert _sa_token(store, "dev", "default") != old
        finally:
            cm.stop()


class TestServiceAccountIdentityEndToEnd:
    """VERDICT r3 #4 done-condition: a pod created with no SA gets
    ``default``, its token authenticates, and an RBAC RoleBinding to a
    ServiceAccount subject actually grants."""

    def _serve(self):
        store = ClusterStore()
        authz = provision_bootstrap_policy(store)
        server = APIServer(
            store=store, authorizer=authz,
            tokens={"admin-token": "admin"},
        ).start()
        cm = ControllerManager(
            store, controllers=["serviceaccount", "serviceaccount-token"]
        )
        cm.start()
        return store, server, cm

    def test_default_sa_injected_token_authenticates_rbac_grants(self):
        store, server, cm = self._serve()
        try:
            store.add_namespace(Namespace(
                metadata=ObjectMeta(name="dev")))
            assert wait_for(lambda: _sa_token(store, "dev", "default"))

            # 1. admission injects the default account
            admin = RestClient(server.url, token="admin-token")
            pod = MakePod().name("app").uid("u-app").namespace("dev").obj()
            admin.create(pod)
            created = store.get_pod("dev", "app")
            assert created.spec.service_account_name == "default"

            # 2. the minted token authenticates as the SA identity...
            token = _sa_token(store, "dev", "default")
            sa_client = RestClient(server.url, token=token)
            with pytest.raises(PermissionError):
                sa_client.list("Pod", namespace="dev")  # no grant yet

            # 3. ...and a RoleBinding to a ServiceAccount subject grants
            store.add_role(Role(
                metadata=ObjectMeta(name="pod-reader", namespace="dev"),
                rules=[PolicyRule(verbs=["get", "list"],
                                  resources=["pods"])],
            ))
            store.add_role_binding(RoleBinding(
                metadata=ObjectMeta(name="default-reads", namespace="dev"),
                subjects=[RBACSubject(kind="ServiceAccount",
                                      name="default", namespace="dev")],
                role_ref=RoleRef(kind="Role", name="pod-reader"),
            ))
            pods, _ = sa_client.list("Pod", namespace="dev")
            assert any(p.metadata.name == "app" for p in pods)
            # scoped to its verbs: delete stays forbidden
            with pytest.raises(PermissionError):
                sa_client.delete("Pod", "app", namespace="dev")
        finally:
            cm.stop()
            server.shutdown_server()

    def test_deleted_account_token_stops_authenticating(self):
        store, server, cm = self._serve()
        try:
            store.add_namespace(Namespace(
                metadata=ObjectMeta(name="dev")))
            assert wait_for(lambda: _sa_token(store, "dev", "default"))
            token = _sa_token(store, "dev", "default")
            assert server.resolve_sa_token(token) == sa_username(
                "dev", "default")
            cm.stop()  # freeze controllers: authn must not rely on them
            store.delete_object("ServiceAccount", "dev", "default")
            assert server.resolve_sa_token(token) is None
        finally:
            cm.stop()
            server.shutdown_server()

    def test_explicitly_named_missing_sa_rejected(self):
        store, server, cm = self._serve()
        try:
            store.add_namespace(Namespace(
                metadata=ObjectMeta(name="dev")))
            admin = RestClient(server.url, token="admin-token")
            pod = MakePod().name("app2").uid("u-app2").namespace("dev").obj()
            pod.spec.service_account_name = "no-such-sa"
            with pytest.raises(PermissionError):
                admin.create(pod)
        finally:
            cm.stop()
            server.shutdown_server()


class TestNodeRestriction:
    """VERDICT r3 #7 done-condition: node A's token cannot patch node
    B (nor B's pods), while its own node/pods stay writable."""

    def _serve(self):
        store = ClusterStore()
        authz = provision_bootstrap_policy(store)
        server = APIServer(
            store=store, authorizer=authz,
            tokens={"kubelet-a": "system:node:a",
                    "kubelet-b": "system:node:b",
                    "admin-token": "admin"},
        ).start()
        store.add_node(MakeNode().name("a")
                       .capacity({"cpu": "8", "memory": "16Gi"}).obj())
        store.add_node(MakeNode().name("b")
                       .capacity({"cpu": "8", "memory": "16Gi"}).obj())
        return store, server

    def test_node_cannot_touch_other_node(self):
        store, server = self._serve()
        try:
            a = RestClient(server.url, token="kubelet-a")
            # its own node: the system:nodes RBAC grant + NodeRestriction
            # both pass
            own = a.get("Node", "a", namespace=None)
            own.metadata.labels["touched"] = "yes"
            a.update(own)
            assert store.get_node("a").metadata.labels["touched"] == "yes"
            # node b: RBAC grants nodes to the group, NodeRestriction
            # rejects the cross-node write
            other = a.get("Node", "b", namespace=None)
            other.metadata.labels["touched"] = "yes"
            with pytest.raises(PermissionError):
                a.update(other)
            assert "touched" not in store.get_node("b").metadata.labels
        finally:
            server.shutdown_server()

    def test_node_confined_to_its_own_pods(self):
        store, server = self._serve()
        try:
            for name, node in (("on-a", "a"), ("on-b", "b")):
                p = MakePod().name(name).uid(f"u-{name}").obj()
                store.create_pod(p)
                store.bind("default", name, p.uid, node)
            a = RestClient(server.url, token="kubelet-a")
            # own pod: status update + delete (eviction) allowed
            a.update_pod_status("default", "on-a", "Running")
            # other node's pod: rejected by NodeRestriction
            with pytest.raises(PermissionError):
                a.update_pod_status("default", "on-b", "Failed")
            with pytest.raises(PermissionError):
                a.delete("Pod", "on-b", namespace="default")
            assert a.delete("Pod", "on-a", namespace="default")
        finally:
            server.shutdown_server()


class TestRootCACertPublisher:
    def test_publishes_and_heals_the_trust_anchor(self):
        store = ClusterStore()
        cm = ControllerManager(
            store, controllers=["root-ca-cert-publisher"]
        )
        cm.start()
        try:
            store.add_namespace(Namespace(
                metadata=ObjectMeta(name="dev")))
            assert wait_for(lambda: store.get_object(
                "ConfigMap", "dev", ROOT_CA_CONFIGMAP) is not None)
            bundle = store.get_object(
                "ConfigMap", "dev", ROOT_CA_CONFIGMAP).data["ca.crt"]
            assert "cluster-root-ca-fingerprint" in bundle
            # deletion: recreated
            store.delete_object("ConfigMap", "dev", ROOT_CA_CONFIGMAP)
            assert wait_for(lambda: store.get_object(
                "ConfigMap", "dev", ROOT_CA_CONFIGMAP) is not None)
            # drift: healed back to the CA bundle
            store.mutate_object(
                "ConfigMap", "dev", ROOT_CA_CONFIGMAP,
                lambda cm_: cm_.__setattr__(
                    "data", {"ca.crt": "tampered"}) or True,
            )
            assert wait_for(lambda: store.get_object(
                "ConfigMap", "dev", ROOT_CA_CONFIGMAP
            ).data["ca.crt"] == bundle)
        finally:
            cm.stop()
