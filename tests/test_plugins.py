"""Plugin semantics tests (modeled on the reference's per-plugin table
tests in ``pkg/scheduler/framework/plugins/*_test.go``)."""

import pytest

from kubernetes_tpu.scheduler.framework.cycle_state import CycleState
from kubernetes_tpu.scheduler.framework import interface as fw
from kubernetes_tpu.scheduler.framework.plugins import (
    interpod_affinity as ipa,
    node_affinity as na,
    node_name as nn,
    node_ports as np_,
    node_resources as nr,
    node_unschedulable as nu,
    pod_topology_spread as pts,
    taint_toleration as tt,
)
from kubernetes_tpu.scheduler.snapshot import new_snapshot
from kubernetes_tpu.scheduler.types import NodeInfo
from kubernetes_tpu.testing import MakeNode, MakePod


class FakeHandle:
    """Minimal handle: snapshot + client listers (reference fake listers)."""

    def __init__(self, snapshot=None, client=None):
        self._snapshot = snapshot
        self.client = client
        self.pod_nominator = None

    def snapshot(self):
        return self._snapshot


def node_info_for(node, *pods):
    ni = NodeInfo()
    ni.set_node(node)
    for p in pods:
        ni.add_pod(p)
    return ni


class TestNodeResourcesFit:
    def run_filter(self, pod, node_info):
        plugin = nr.Fit()
        state = CycleState()
        plugin.pre_filter(state, pod)
        return plugin.filter(state, pod, node_info)

    def test_fits(self):
        node = MakeNode().name("n").capacity({"cpu": "4", "memory": "8Gi"}).obj()
        pod = MakePod().name("p").req({"cpu": "1", "memory": "1Gi"}).obj()
        assert self.run_filter(pod, node_info_for(node)) is None

    def test_insufficient_cpu(self):
        node = MakeNode().name("n").capacity({"cpu": "1", "memory": "8Gi"}).obj()
        existing = MakePod().name("e").req({"cpu": "800m"}).node("n").obj()
        pod = MakePod().name("p").req({"cpu": "500m"}).obj()
        status = self.run_filter(pod, node_info_for(node, existing))
        assert status.code == fw.UNSCHEDULABLE
        assert "Insufficient cpu" in status.reasons

    def test_init_containers_max(self):
        node = MakeNode().name("n").capacity({"cpu": "2", "memory": "8Gi"}).obj()
        # init wants 1.5 CPU (max, not sum, with app containers)
        pod = (
            MakePod().name("p")
            .req({"cpu": "1"})
            .init_req({"cpu": "1500m"})
            .obj()
        )
        assert self.run_filter(pod, node_info_for(node)) is None
        smaller = MakeNode().name("n2").capacity({"cpu": "1", "memory": "8Gi"}).obj()
        status = self.run_filter(pod, node_info_for(smaller))
        assert "Insufficient cpu" in status.reasons

    def test_overhead_counts(self):
        node = MakeNode().name("n").capacity({"cpu": "1", "memory": "8Gi"}).obj()
        pod = MakePod().name("p").req({"cpu": "800m"}).overhead({"cpu": "300m"}).obj()
        status = self.run_filter(pod, node_info_for(node))
        assert "Insufficient cpu" in status.reasons

    def test_too_many_pods(self):
        node = MakeNode().name("n").capacity({"cpu": "4", "pods": "1"}).obj()
        existing = MakePod().name("e").node("n").obj()
        pod = MakePod().name("p").obj()
        status = self.run_filter(pod, node_info_for(node, existing))
        assert "Too many pods" in status.reasons

    def test_scalar_resources(self):
        node = MakeNode().name("n").capacity(
            {"cpu": "4", "memory": "8Gi", "example.com/gpu": "2"}
        ).obj()
        pod = MakePod().name("p").req({"example.com/gpu": "4"}).obj()
        status = self.run_filter(pod, node_info_for(node))
        assert "Insufficient example.com/gpu" in status.reasons


class TestBalancedAllocation:
    def test_perfectly_balanced(self):
        node = MakeNode().name("n").capacity({"cpu": "4", "memory": "4Gi"}).obj()
        snap = new_snapshot([], [node])
        plugin = nr.BalancedAllocation(FakeHandle(snap))
        # request 50% of cpu and 50% of memory -> perfectly balanced
        pod = MakePod().name("p").req({"cpu": "2", "memory": "2Gi"}).obj()
        score, status = plugin.score(CycleState(), pod, "n")
        assert status is None
        assert score == fw.MAX_NODE_SCORE

    def test_imbalance_scores_lower(self):
        node = MakeNode().name("n").capacity({"cpu": "4", "memory": "4Gi"}).obj()
        snap = new_snapshot([], [node])
        plugin = nr.BalancedAllocation(FakeHandle(snap))
        pod = MakePod().name("p").req({"cpu": "3", "memory": "1Gi"}).obj()
        score, _ = plugin.score(CycleState(), pod, "n")
        assert score < fw.MAX_NODE_SCORE


class TestLeastMostAllocated:
    def make(self, cls):
        node = MakeNode().name("n").capacity({"cpu": "4", "memory": "4Gi"}).obj()
        snap = new_snapshot(
            [MakePod().name("e").req({"cpu": "2", "memory": "2Gi"}).node("n").obj()],
            [node],
        )
        return cls(FakeHandle(snap))

    def test_least(self):
        plugin = self.make(nr.LeastAllocated)
        pod = MakePod().name("p").req({"cpu": "1", "memory": "1Gi"}).obj()
        score, _ = plugin.score(CycleState(), pod, "n")
        assert score == 25  # 1/4 free on both dimensions

    def test_most(self):
        plugin = self.make(nr.MostAllocated)
        pod = MakePod().name("p").req({"cpu": "1", "memory": "1Gi"}).obj()
        score, _ = plugin.score(CycleState(), pod, "n")
        assert score == 75


class TestSimpleFilters:
    def test_node_name(self):
        plugin = nn.NodeName()
        ni = node_info_for(MakeNode().name("a").obj())
        assert plugin.filter(CycleState(), MakePod().name("p").node("a").obj(), ni) is None
        status = plugin.filter(CycleState(), MakePod().name("p").node("b").obj(), ni)
        assert status.code == fw.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_node_ports_conflict(self):
        plugin = np_.NodePorts()
        existing = MakePod().name("e").host_port(8080).node("n").obj()
        ni = node_info_for(MakeNode().name("n").obj(), existing)
        pod = MakePod().name("p").host_port(8080).obj()
        state = CycleState()
        plugin.pre_filter(state, pod)
        assert plugin.filter(state, pod, ni).code == fw.UNSCHEDULABLE
        other = MakePod().name("q").host_port(8081).obj()
        plugin.pre_filter(state, other)
        assert plugin.filter(state, other, ni) is None

    def test_node_unschedulable(self):
        plugin = nu.NodeUnschedulable()
        ni = node_info_for(MakeNode().name("n").unschedulable().obj())
        pod = MakePod().name("p").obj()
        assert plugin.filter(CycleState(), pod, ni).code == fw.UNSCHEDULABLE_AND_UNRESOLVABLE
        tolerant = (
            MakePod().name("t")
            .toleration("node.kubernetes.io/unschedulable", operator="Exists")
            .obj()
        )
        assert plugin.filter(CycleState(), tolerant, ni) is None

    def test_taint_toleration_filter(self):
        plugin = tt.TaintToleration()
        ni = node_info_for(MakeNode().name("n").taint("gpu", "true").obj())
        pod = MakePod().name("p").obj()
        status = plugin.filter(CycleState(), pod, ni)
        assert status.code == fw.UNSCHEDULABLE_AND_UNRESOLVABLE
        ok = MakePod().name("q").toleration("gpu", "true", "NoSchedule").obj()
        assert plugin.filter(CycleState(), ok, ni) is None

    def test_node_affinity(self):
        plugin = na.NodeAffinity()
        ni = node_info_for(MakeNode().name("n").label("disk", "ssd").obj())
        pod = MakePod().name("p").node_selector({"disk": "ssd"}).obj()
        assert plugin.filter(CycleState(), pod, ni) is None
        bad = MakePod().name("q").node_selector({"disk": "hdd"}).obj()
        assert plugin.filter(CycleState(), bad, ni).code == fw.UNSCHEDULABLE
        aff = MakePod().name("r").node_affinity_in("disk", ["ssd", "nvme"]).obj()
        assert plugin.filter(CycleState(), aff, ni) is None


class TestPodTopologySpread:
    def _spread_state(self, pods, nodes, pod):
        snap = new_snapshot(pods, nodes)
        plugin = pts.PodTopologySpread(FakeHandle(snap))
        state = CycleState()
        assert plugin.pre_filter(state, pod) is None
        return plugin, state, snap

    def test_max_skew_enforced(self):
        nodes = [
            MakeNode().name("a1").label("zone", "za").obj(),
            MakeNode().name("b1").label("zone", "zb").obj(),
        ]
        pods = [
            MakePod().name("e1").label("app", "web").node("a1").obj(),
            MakePod().name("e2").label("app", "web").node("a1").obj(),
        ]
        pod = (
            MakePod().name("p").label("app", "web")
            .spread_constraint(1, "zone", "DoNotSchedule", {"app": "web"})
            .obj()
        )
        plugin, state, snap = self._spread_state(pods, nodes, pod)
        # zone za has 2, zb has 0: adding to za -> skew 3 > 1
        assert plugin.filter(state, pod, snap.get("a1")).code == fw.UNSCHEDULABLE
        # adding to zb -> skew 1-0=1 <= 1 OK
        assert plugin.filter(state, pod, snap.get("b1")) is None

    def test_missing_topology_label(self):
        nodes = [MakeNode().name("a1").label("zone", "za").obj(),
                 MakeNode().name("x").obj()]
        pod = (
            MakePod().name("p").label("app", "web")
            .spread_constraint(1, "zone", "DoNotSchedule", {"app": "web"})
            .obj()
        )
        plugin, state, snap = self._spread_state([], nodes, pod)
        status = plugin.filter(state, pod, snap.get("x"))
        assert status.code == fw.UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_add_remove_pod_extension(self):
        nodes = [
            MakeNode().name("a1").label("zone", "za").obj(),
            MakeNode().name("b1").label("zone", "zb").obj(),
        ]
        pod = (
            MakePod().name("p").label("app", "web")
            .spread_constraint(1, "zone", "DoNotSchedule", {"app": "web"})
            .obj()
        )
        plugin, state, snap = self._spread_state([], nodes, pod)
        ext = plugin.pre_filter_extensions()
        incoming = MakePod().name("v").label("app", "web").node("a1").obj()
        ext.add_pod(state, pod, incoming, snap.get("a1"))
        ext.add_pod(state, pod, incoming, snap.get("a1"))
        status = plugin.filter(state, pod, snap.get("a1"))
        assert status is not None and status.code == fw.UNSCHEDULABLE
        ext.remove_pod(state, pod, incoming, snap.get("a1"))
        ext.remove_pod(state, pod, incoming, snap.get("a1"))
        assert plugin.filter(state, pod, snap.get("a1")) is None


class TestInterPodAffinity:
    def _setup(self, pods, nodes, pod):
        snap = new_snapshot(pods, nodes)
        plugin = ipa.InterPodAffinity(FakeHandle(snap))
        state = CycleState()
        assert plugin.pre_filter(state, pod) is None
        return plugin, state, snap

    def test_required_affinity(self):
        nodes = [
            MakeNode().name("a1").label("zone", "za").obj(),
            MakeNode().name("b1").label("zone", "zb").obj(),
        ]
        pods = [MakePod().name("e").label("app", "db").node("a1").obj()]
        pod = MakePod().name("p").pod_affinity("app", ["db"], "zone").obj()
        plugin, state, snap = self._setup(pods, nodes, pod)
        assert plugin.filter(state, pod, snap.get("a1")) is None
        assert plugin.filter(state, pod, snap.get("b1")).code == fw.UNSCHEDULABLE

    def test_first_pod_of_group_allowed(self):
        nodes = [MakeNode().name("a1").label("zone", "za").obj()]
        pod = (
            MakePod().name("p").label("app", "web")
            .pod_affinity("app", ["web"], "zone").obj()
        )
        plugin, state, snap = self._setup([], nodes, pod)
        assert plugin.filter(state, pod, snap.get("a1")) is None

    def test_anti_affinity(self):
        nodes = [
            MakeNode().name("a1").label("zone", "za").obj(),
            MakeNode().name("b1").label("zone", "zb").obj(),
        ]
        pods = [MakePod().name("e").label("app", "web").node("a1").obj()]
        pod = MakePod().name("p").pod_anti_affinity("app", ["web"], "zone").obj()
        plugin, state, snap = self._setup(pods, nodes, pod)
        assert plugin.filter(state, pod, snap.get("a1")).code == fw.UNSCHEDULABLE
        assert plugin.filter(state, pod, snap.get("b1")) is None

    def test_existing_pods_anti_affinity(self):
        nodes = [
            MakeNode().name("a1").label("zone", "za").obj(),
            MakeNode().name("b1").label("zone", "zb").obj(),
        ]
        # existing pod repels app=web within its zone
        pods = [
            MakePod().name("e").label("app", "db").node("a1")
            .pod_anti_affinity("app", ["web"], "zone").obj()
        ]
        pod = MakePod().name("p").label("app", "web").obj()
        plugin, state, snap = self._setup(pods, nodes, pod)
        assert plugin.filter(state, pod, snap.get("a1")).code == fw.UNSCHEDULABLE
        assert plugin.filter(state, pod, snap.get("b1")) is None

    def test_preferred_scoring(self):
        nodes = [
            MakeNode().name("a1").label("zone", "za").obj(),
            MakeNode().name("b1").label("zone", "zb").obj(),
        ]
        pods = [MakePod().name("e").label("app", "db").node("a1").obj()]
        pod = (
            MakePod().name("p")
            .preferred_pod_affinity(10, "app", ["db"], "zone").obj()
        )
        snap = new_snapshot(pods, nodes)
        plugin = ipa.InterPodAffinity(FakeHandle(snap))
        state = CycleState()
        plugin.pre_score(state, pod, snap.list())
        sa, _ = plugin.score(state, pod, "a1")
        sb, _ = plugin.score(state, pod, "b1")
        assert sa > sb


class TestNodeVolumeLimits:
    """nodevolumelimits semantics (reference csi.go / non_csi.go):
    CSI limits from CSINode allocatable, unbound PVCs resolved through
    the StorageClass provisioner, in-tree limits from node allocatable
    attachable-volumes resources."""

    def _store(self):
        from kubernetes_tpu.apiserver.store import ClusterStore

        return ClusterStore()

    def _csi_setup(self, store, node_name="n1", limit=2,
                   driver="csi.fake.driver"):
        from kubernetes_tpu.api.resource import parse_quantity
        from kubernetes_tpu.api.types import (
            CSINode, CSINodeDriver, ObjectMeta, PersistentVolume,
            PersistentVolumeClaim, StorageClass,
        )

        store.add_csi_node(CSINode(
            metadata=ObjectMeta(name=node_name),
            drivers=[CSINodeDriver(name=driver, node_id=node_name,
                                   allocatable_count=limit)],
        ))
        store.add_storage_class(StorageClass(
            metadata=ObjectMeta(name="sc"), provisioner=driver,
        ))
        for i in range(4):
            store.add_pv(PersistentVolume(
                metadata=ObjectMeta(name=f"pv-{i}"),
                capacity={"storage": parse_quantity("1Gi")},
                storage_class_name="sc", csi_driver=driver,
            ))
            store.add_pvc(PersistentVolumeClaim(
                metadata=ObjectMeta(name=f"claim-{i}", namespace="default"),
                storage_class_name="sc", volume_name=f"pv-{i}",
                phase="Bound",
            ))

    def test_csi_limit_from_csinode(self):
        from kubernetes_tpu.scheduler.framework.plugins import (
            node_volume_limits as nvl,
        )

        store = self._store()
        self._csi_setup(store, limit=2)
        plugin = nvl.CSILimits(FakeHandle(client=store))
        node = MakeNode().name("n1").capacity({"cpu": "8"}).obj()
        existing = [
            MakePod().name(f"e{i}").uid(f"eu{i}").node("n1")
            .pvc(f"claim-{i}").obj()
            for i in range(2)
        ]
        ni = node_info_for(node, *existing)
        pod = MakePod().name("p").uid("pu").pvc("claim-2").obj()
        st = plugin.filter(CycleState(), pod, ni)
        assert st is not None and not st.is_success()  # 3 > limit 2
        # a pod reusing an ALREADY-ATTACHED volume fits (same pv)
        pod2 = MakePod().name("q").uid("qu").pvc("claim-1").obj()
        assert plugin.filter(CycleState(), pod2, ni) is None

    def test_csi_unbound_pvc_counts_via_storage_class(self):
        from kubernetes_tpu.api.types import ObjectMeta, PersistentVolumeClaim
        from kubernetes_tpu.scheduler.framework.plugins import (
            node_volume_limits as nvl,
        )

        store = self._store()
        self._csi_setup(store, limit=2)
        # two unbound claims: no PV yet, driver resolves via the SC
        for name in ("pend-0", "pend-1"):
            store.add_pvc(PersistentVolumeClaim(
                metadata=ObjectMeta(name=name, namespace="default"),
                storage_class_name="sc", phase="Pending",
            ))
        plugin = nvl.CSILimits(FakeHandle(client=store))
        node = MakeNode().name("n1").capacity({"cpu": "8"}).obj()
        existing = [
            MakePod().name("e0").uid("eu0").node("n1").pvc("claim-0").obj(),
            MakePod().name("e1").uid("eu1").node("n1").pvc("pend-0").obj(),
        ]
        ni = node_info_for(node, *existing)
        pod = MakePod().name("p").uid("pu").pvc("pend-1").obj()
        st = plugin.filter(CycleState(), pod, ni)
        assert st is not None and not st.is_success()  # bound+2 pending > 2

    def test_intree_limit_from_node_allocatable(self):
        from kubernetes_tpu.api.types import Volume
        from kubernetes_tpu.scheduler.framework.plugins import (
            node_volume_limits as nvl,
        )

        plugin = nvl.EBSLimits(FakeHandle())
        node = MakeNode().name("n1").capacity({"cpu": "8"}).allocatable({
            "cpu": "8", "attachable-volumes-aws-ebs": "1",
        }).obj()
        existing = MakePod().name("e").uid("eu").node("n1").obj()
        existing.spec.volumes.append(
            Volume(name="v0", aws_elastic_block_store="vol-0"))
        ni = node_info_for(node, existing)
        pod = MakePod().name("p").uid("pu").obj()
        pod.spec.volumes.append(
            Volume(name="v1", aws_elastic_block_store="vol-1"))
        st = plugin.filter(CycleState(), pod, ni)
        assert st is not None and not st.is_success()  # 2 > node limit 1
        # default limit (39) admits the same pod when the node publishes
        # no attachable-volumes resource
        node2 = MakeNode().name("n2").capacity({"cpu": "8"}).obj()
        ni2 = node_info_for(node2, existing)
        assert plugin.filter(CycleState(), pod, ni2) is None

    def test_azure_disk_counts_azure_volumes(self):
        from kubernetes_tpu.api.types import Volume
        from kubernetes_tpu.scheduler.framework.plugins import (
            node_volume_limits as nvl,
        )

        plugin = nvl.AzureDiskLimits(FakeHandle())
        node = MakeNode().name("n1").capacity({"cpu": "8"}).allocatable({
            "cpu": "8", "attachable-volumes-azure-disk": "1",
        }).obj()
        existing = MakePod().name("e").uid("eu").node("n1").obj()
        existing.spec.volumes.append(Volume(name="v0", azure_disk="d0"))
        ni = node_info_for(node, existing)
        pod = MakePod().name("p").uid("pu").obj()
        pod.spec.volumes.append(Volume(name="v1", azure_disk="d1"))
        st = plugin.filter(CycleState(), pod, ni)
        assert st is not None and not st.is_success()
