"""Package-wide unresolved-annotation smoke check (ISSUE 3 satellite).

``from __future__ import annotations`` makes every annotation lazy, so
a name used in an annotation but never imported (the
``self._pools: Dict[str, list]`` bug in sidecar.py) parses fine and
never fails at import — PEP 526 attribute annotations aren't even
stored, so ``typing.get_type_hints`` can't see them either. This test
walks each module's AST, collects EVERY annotation expression
(variable/attribute annotations, parameters, returns), and evaluates
it in the module's namespace: an annotation naming something the
module never imports fails here instead of in a consumer that forces
resolution (dataclass tooling, debuggers, docs generators).
"""

import ast
import importlib
import pkgutil

import kubernetes_tpu


def _iter_modules():
    prefix = kubernetes_tpu.__name__ + "."
    for info in pkgutil.walk_packages(kubernetes_tpu.__path__, prefix):
        if info.name.endswith(".__main__"):
            continue   # importing a CLI entry point runs it
        yield info.name


def _annotation_nodes(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign):
            yield node.annotation
        elif isinstance(node, ast.arg) and node.annotation is not None:
            yield node.annotation
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.returns is not None:
            yield node.returns


def _eval_annotation(node, namespace):
    expr = node
    # quoted forward refs: evaluate the string's CONTENT
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        expr = ast.parse(node.value, mode="eval").body
        expr = ast.copy_location(expr, node)
        ast.fix_missing_locations(expr)
    code = compile(ast.Expression(body=expr), "<annotation>", "eval")
    eval(code, namespace)  # noqa: S307 — our own source, CI-only


def test_every_annotation_in_the_package_resolves():
    failures = []
    for name in _iter_modules():
        try:
            mod = importlib.import_module(name)
        except Exception:  # noqa: BLE001 — optional deps (native .so)
            continue
        source_file = getattr(mod, "__file__", None)
        if not source_file or not source_file.endswith(".py"):
            continue
        with open(source_file, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=source_file)
        namespace = dict(vars(mod))
        for node in _annotation_nodes(tree):
            try:
                _eval_annotation(node, namespace)
            except NameError as e:
                failures.append(f"{name}:{node.lineno}: {e}")
            except Exception:  # noqa: BLE001 — only unresolved NAMES
                pass           # (e.g. subscripting a mock) are the bug
    assert not failures, (
        "unresolved annotations (missing imports under "
        "`from __future__ import annotations`):\n" + "\n".join(failures)
    )
