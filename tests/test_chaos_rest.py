"""Chaos over REST (ISSUE 1 tentpole): the FaultGate middleware, the
/debug/faults admin surface, the client resilience stack surviving
injected wire faults, scheduler degraded mode, and — marked slow — the
full seeded kill/restart matrix with WAL restore
(``kubernetes_tpu.harness.chaos_rest``).

Reference anchors: ``test/e2e/chaosmonkey/chaosmonkey.go`` (disruption
concurrent with workload), client-go's jittered backoff + 410-Gone
relist, ``filters/maxinflight.go`` Retry-After contract.
"""

import json
import threading
import time

import pytest

from kubernetes_tpu.apiserver.faults import FaultGate, FaultRule, resource_of
from kubernetes_tpu.apiserver.rest import APIServer
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client.restcluster import RestClusterClient
from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics
from kubernetes_tpu.testing import MakeNode, MakePod

pytestmark = pytest.mark.chaos


def _serve(**kwargs):
    store = ClusterStore()
    server = APIServer(store=store, **kwargs).start()
    return store, server


# ---------------------------------------------------------------------------
# FaultGate unit behavior (no server)


class TestFaultGate:
    def test_seeded_decisions_replay_exactly(self):
        def run(seed):
            gate = FaultGate(seed=seed)
            gate.add_rule(FaultRule("reset", probability=0.5))
            return [gate.decide("GET", "pods") is not None
                    for _ in range(40)]

        assert run(7) == run(7)
        assert run(7) != run(8)   # different seed, different decisions

    def test_count_limits_a_burst(self):
        gate = FaultGate()
        gate.add_rule(FaultRule("error", code=429, count=3))
        fired = [gate.decide("GET", "pods") for _ in range(5)]
        assert [r is not None for r in fired] == \
            [True, True, True, False, False]
        assert gate.injected_total() == 3

    def test_verb_and_resource_matching(self):
        gate = FaultGate()
        gate.add_rule(FaultRule("reset", verb="POST", resource="pods"))
        assert gate.decide("GET", "pods") is None
        assert gate.decide("POST", "nodes") is None
        assert gate.decide("POST", "pods") is not None

    def test_watch_faults_never_fire_on_plain_requests(self):
        gate = FaultGate()
        gate.add_rule(FaultRule("watch_drop"))
        gate.add_rule(FaultRule("watch_stall"))
        assert gate.decide("GET", "pods") is None
        assert gate.decide("GET", "pods", watch=True) is not None

    def test_configure_rejects_bad_specs(self):
        gate = FaultGate()
        with pytest.raises(ValueError):
            gate.configure({"rules": [{"fault": "nope"}]})
        with pytest.raises(ValueError):
            gate.configure({"rules": [{"fault": "reset",
                                       "probability": 2.0}]})
        with pytest.raises(ValueError):
            gate.configure({"rules": [{"fault": "reset",
                                       "unknown_field": 1}]})
        assert gate.snapshot()["rules"] == []   # nothing half-applied

    def test_resource_of_paths(self):
        assert resource_of("/api/v1/pods") == "pods"
        assert resource_of(
            "/api/v1/namespaces/default/pods/p1/binding") == "pods"
        assert resource_of("/api/v1/pods?watch=1&resourceVersion=3") == \
            "pods"
        assert resource_of("/apis/apps/v1/deployments") == "deployments"
        assert resource_of("/healthz") == ""

    def test_injection_counts_into_fabric_metrics(self):
        before = fabric_metrics().faults_injected_total.get(
            "latency", "pods")
        gate = FaultGate()
        gate.add_rule(FaultRule("latency"))
        assert gate.decide("GET", "pods") is not None
        after = fabric_metrics().faults_injected_total.get(
            "latency", "pods")
        assert after == before + 1


# ---------------------------------------------------------------------------
# the /debug/faults admin endpoint


class TestFaultAdminEndpoint:
    def test_runtime_toggle_per_verb_and_resource(self):
        store, server = _serve()
        try:
            client = RestClusterClient(server.url, max_retries=0)
            code, snap = client._request("GET", "/debug/faults")
            assert code == 200 and snap["rules"] == []
            code, snap = client._request(
                "POST", "/debug/faults",
                {"seed": 11, "rules": [
                    {"fault": "error", "verb": "GET", "resource": "pods",
                     "code": 503, "count": 1, "retry_after": 0.01},
                ]}, body_binary=False)
            assert code == 200 and len(snap["rules"]) == 1
            # fires on GET pods exactly once; nodes and POST unaffected
            code, _ = client._request("GET", "/api/v1/nodes")
            assert code == 200
            code, _ = client._request("GET", "/api/v1/pods")
            assert code == 503
            code, _ = client._request("GET", "/api/v1/pods")
            assert code == 200
            code, snap = client._request("GET", "/debug/faults")
            assert snap["injected"] == {"error/pods": 1}
            # DELETE clears
            code, _ = client._request("DELETE", "/debug/faults")
            assert code == 200
            code, snap = client._request("GET", "/debug/faults")
            assert snap["rules"] == []
        finally:
            server.shutdown_server()

    def test_admin_requires_control_plane_identity(self):
        """Same trust envelope as the binary codec: an ordinary
        authenticated user must not be able to break the wire."""
        store, server = _serve(tokens={"tok": "alice",
                                       "sched": "system:kube-scheduler"})
        try:
            plain = RestClusterClient(server.url, token="tok",
                                      binary=False, max_retries=0)
            code, resp = plain._request("GET", "/debug/faults")
            assert code == 403
            cp = RestClusterClient(server.url, token="sched",
                                   binary=False, max_retries=0)
            code, resp = cp._request("GET", "/debug/faults")
            assert code == 200
        finally:
            server.shutdown_server()

    def test_admin_endpoint_is_never_faulted(self):
        store, server = _serve()
        try:
            client = RestClusterClient(server.url, max_retries=0)
            code, _ = client._request(
                "POST", "/debug/faults",
                {"rules": [{"fault": "reset", "probability": 1.0}]},
                body_binary=False)
            assert code == 200
            # every API request resets; the admin surface still answers
            with pytest.raises(Exception):
                client._request("GET", "/api/v1/pods")
            code, snap = client._request("GET", "/debug/faults")
            assert code == 200
            code, _ = client._request("DELETE", "/debug/faults")
            assert code == 200
        finally:
            server.shutdown_server()


# ---------------------------------------------------------------------------
# client resilience against injected faults


class TestResilientClient:
    def test_client_rides_out_429_bursts_with_capped_retry_after(self):
        store, server = _serve()
        try:
            store.create_pod(MakePod().name("p").uid("u").obj())
            client = RestClusterClient(server.url, max_retries=6,
                                       retry_after_cap=0.05, retry_seed=3)
            admin = RestClusterClient(server.url, max_retries=0)
            # a hostile burst advertising a 60s Retry-After: the cap
            # must keep total stall far below the advertised sleeps
            code, _ = admin._request(
                "POST", "/debug/faults",
                {"rules": [{"fault": "error", "code": 429, "count": 3,
                            "retry_after": 60.0}]}, body_binary=False)
            assert code == 200
            before = fabric_metrics().client_retries_total.get(
                "GET", "http_429")
            t0 = time.monotonic()
            pods = client.list_pods()
            elapsed = time.monotonic() - t0
            assert [p.metadata.name for p in pods] == ["p"]
            assert elapsed < 2.0, "Retry-After cap did not bite"
            assert fabric_metrics().client_retries_total.get(
                "GET", "http_429") >= before + 3
        finally:
            server.shutdown_server()

    def test_client_rides_out_resets_and_truncation(self):
        store, server = _serve()
        try:
            store.create_pod(MakePod().name("p").uid("u").obj())
            client = RestClusterClient(server.url, max_retries=6,
                                       retry_seed=5)
            admin = RestClusterClient(server.url, max_retries=0)
            code, _ = admin._request(
                "POST", "/debug/faults",
                {"rules": [
                    {"fault": "reset", "verb": "GET", "count": 2},
                    {"fault": "truncate", "verb": "GET", "count": 2,
                     "truncate_bytes": 30},
                ]}, body_binary=False)
            assert code == 200
            before = fabric_metrics().client_retries_total.get(
                "GET", "transport")
            assert [p.metadata.name for p in client.list_pods()] == ["p"]
            assert fabric_metrics().client_retries_total.get(
                "GET", "transport") >= before + 1
        finally:
            server.shutdown_server()

    def test_truncation_under_limit_still_ends_the_connection(self):
        """A truncate fault whose response fits under the byte limit
        must still die with its connection — the truncating writer must
        never survive into the next keep-alive request with leftover
        budget (and connection teardown must not traceback)."""
        store, server = _serve()
        try:
            store.create_pod(MakePod().name("p").uid("u").obj())
            client = RestClusterClient(server.url, max_retries=6,
                                       retry_seed=7)
            admin = RestClusterClient(server.url, max_retries=0)
            code, _ = admin._request(
                "POST", "/debug/faults",
                {"rules": [{"fault": "truncate", "verb": "GET",
                            "count": 1, "truncate_bytes": 100_000}]},
                body_binary=False)
            assert code == 200
            # the faulted response (retried if the RST beat the read)
            assert [p.metadata.name for p in client.list_pods()] == ["p"]
            # the next requests flow untouched on a fresh connection
            for _ in range(3):
                assert [p.metadata.name
                        for p in client.list_pods()] == ["p"]
        finally:
            server.shutdown_server()

    def test_retry_budget_exhaustion_surfaces_original_error(self):
        from kubernetes_tpu.client.backoff import RetryBudget

        store, server = _serve()
        try:
            admin = RestClusterClient(server.url, max_retries=0)
            code, _ = admin._request(
                "POST", "/debug/faults",
                {"rules": [{"fault": "reset", "verb": "GET"}]},
                body_binary=False)
            assert code == 200
            client = RestClusterClient(
                server.url, max_retries=50,
                retry_budget=RetryBudget(budget=2, refill_per_second=0.0),
                retry_seed=9)
            t0 = time.monotonic()
            with pytest.raises(OSError):
                client.list_pods()
            # 2 budgeted retries, then the original transport error —
            # NOT 50 backoff rounds
            assert time.monotonic() - t0 < 3.0
        finally:
            server.shutdown_server()

    def test_watch_drop_triggers_deduped_relist(self):
        """A dropped watch stream relists; unchanged objects are NOT
        replayed, a change that happened during the outage arrives as
        MODIFIED with the last-known old object."""
        from kubernetes_tpu.apiserver.store import (
            ADDED,
            DELETED,
            MODIFIED,
        )

        store, server = _serve()
        try:
            store.add_node(MakeNode().name("n1").obj())
            store.create_pod(MakePod().name("steady").uid("u1").obj())
            store.create_pod(MakePod().name("moving").uid("u2").obj())
            client = RestClusterClient(server.url, watch_kinds=("Pod",),
                                       max_retries=6, retry_seed=1)
            seen = []
            lock = threading.Lock()

            def on_events(events):
                with lock:
                    seen.extend((e.type, e.obj.metadata.name,
                                 e.old_obj is not None) for e in events)

            handle = client.watch(lambda e: None, batch_fn=on_events)
            time.sleep(0.4)   # stream established (first list absorbed)
            admin = RestClusterClient(server.url, max_retries=0)
            code, _ = admin._request(
                "POST", "/debug/faults",
                {"rules": [{"fault": "watch_drop", "count": 1}]},
                body_binary=False)
            assert code == 200
            # the bind lands while (or just before) the stream drops;
            # the relist must surface it exactly once
            store.bind("default", "moving", "u2", "n1")
            deadline = time.time() + 10
            while time.time() < deadline:
                with lock:
                    if any(name == "moving" and t in (MODIFIED, ADDED)
                           for t, name, _ in seen):
                        break
                time.sleep(0.05)
            with lock:
                moving = [(t, old) for t, name, old in seen
                          if name == "moving"]
                steady = [t for t, name, _ in seen if name == "steady"]
            assert moving, "bind transition lost across the drop"
            # dedupe: the unchanged pod is never replayed, and no
            # spurious DELETED was synthesized for either
            assert steady == []
            assert DELETED not in [t for t, _ in moving]
        finally:
            handle.stop()
            server.shutdown_server()


# ---------------------------------------------------------------------------
# degraded mode (circuit breaker → scheduler)


class TestDegradedMode:
    def test_breaker_pauses_and_resumes_scheduler(self):
        from kubernetes_tpu.scheduler.scheduler import Scheduler

        store = ClusterStore()
        sched = Scheduler.create(store)
        try:
            sched.start()
            before = fabric_metrics().degraded_mode_seconds.get()
            sched.set_degraded(True)
            assert sched.is_degraded()
            assert fabric_metrics().degraded_mode.get() == 1.0
            # paused: schedule_one refuses to pop
            store.add_node(MakeNode().name("n1")
                           .capacity({"cpu": "4", "memory": "8Gi"}).obj())
            store.create_pod(MakePod().name("p").uid("u")
                             .req({"cpu": "100m"}).obj())
            assert sched.schedule_one(pop_timeout=0.01) is False
            assert store.get_pod("default", "p").spec.node_name == ""
            time.sleep(0.05)
            sched.set_degraded(False)
            assert not sched.is_degraded()
            assert fabric_metrics().degraded_mode.get() == 0.0
            assert fabric_metrics().degraded_mode_seconds.get() \
                >= before + 0.05
            # resumed: the parked pod schedules now
            deadline = time.time() + 10
            while time.time() < deadline and \
                    not sched.schedule_one(pop_timeout=0.05):
                pass
            deadline = time.time() + 10
            while time.time() < deadline and \
                    not store.get_pod("default", "p").spec.node_name:
                time.sleep(0.02)
            assert store.get_pod("default", "p").spec.node_name == "n1"
        finally:
            sched.stop()

    def test_rest_client_breaker_flips_scheduler_degraded(self):
        from kubernetes_tpu.scheduler.scheduler import Scheduler

        store, server = _serve()
        # no watch threads: an in-process shutdown leaves old keep-alive
        # connections half-alive (their handler threads keep serving),
        # which would reset the consecutive-failure count — a SIGKILLed
        # process (the slow chaos suite) kills those too
        client = RestClusterClient(server.url, max_retries=1,
                                   breaker_threshold=2, retry_seed=2,
                                   watch_kinds=())
        sched = Scheduler.create(client)
        try:
            sched.start()
            assert not sched.is_degraded()
            # kill the transport for real: stop serving, close the
            # listening socket, and drop the client's keep-alive conn
            # (a still-connected handler thread would keep answering)
            server.shutdown_server()
            server.server_close()
            client._drop_conn()
            for _ in range(4):
                try:
                    client.list_pods()
                except Exception:  # noqa: BLE001 — expected
                    pass
            assert sched.is_degraded()
        finally:
            sched.stop()


# ---------------------------------------------------------------------------
# informer relist-not-resume (client/informers.py satellite)


class TestInformerResync:
    def test_resync_relists_and_dedupes(self):
        from kubernetes_tpu.client.informers import SharedInformerFactory

        store = ClusterStore()
        store.create_pod(MakePod().name("keep").uid("k").obj())
        store.create_pod(MakePod().name("gone").uid("g").obj())
        factory = SharedInformerFactory(store)
        adds, updates, deletes = [], [], []
        inf = factory.informer_for("Pod")
        inf.add_event_handler(
            on_add=lambda o: adds.append(o.metadata.name),
            on_update=lambda o, n: updates.append(n.metadata.name),
            on_delete=lambda o: deletes.append(o.metadata.name),
        )
        factory.start()
        try:
            assert factory.wait_for_cache_sync()
            assert sorted(adds) == ["gone", "keep"]
            # simulate a missed window: mutate UNDER the informer's
            # nose by feeding the indexer stale state, then resync
            store.add_node(MakeNode().name("n1").obj())
            store.delete_pod("default", "gone")
            store.bind("default", "keep", "k", "n1")
            store.create_pod(MakePod().name("new").uid("n").obj())
            deadline = time.time() + 5
            while time.time() < deadline and (
                    "new" not in adds or "gone" not in deletes):
                time.sleep(0.02)
            adds_before = list(adds)
            updates_before = list(updates)
            factory.resync("Pod")
            time.sleep(0.3)
            # nothing changed since the live events landed → the
            # relist is a no-op: no replayed adds/updates/deletes
            assert adds == adds_before
            assert updates == updates_before
            lister = factory.lister_for("Pod")
            assert {p.metadata.name for p in lister.list()} == \
                {"keep", "new"}
        finally:
            factory.stop()

    def test_resync_surfaces_missed_transitions_as_diff(self):
        from kubernetes_tpu.client.informers import SharedInformerFactory

        store = ClusterStore()
        store.add_node(MakeNode().name("n1").obj())
        store.create_pod(MakePod().name("a").uid("ua").obj())
        store.create_pod(MakePod().name("b").uid("ub").obj())
        factory = SharedInformerFactory(store)
        inf = factory.informer_for("Pod")
        events = []
        inf.add_event_handler(
            on_add=lambda o: events.append(("add", o.metadata.name)),
            on_update=lambda o, n: events.append(
                ("update", n.metadata.name, o.spec.node_name,
                 n.spec.node_name)),
            on_delete=lambda o: events.append(("del", o.metadata.name)),
        )
        # sync the indexer WITHOUT starting the live feed: everything
        # that happens next is a missed window
        for ev in inf._sync():
            inf._dispatch(ev)
        store.bind("default", "a", "ua", "n1")
        store.delete_pod("default", "b")
        store.create_pod(MakePod().name("c").uid("uc").obj())
        diff = inf._relist()
        for ev in diff:
            inf._apply(ev)
            inf._dispatch(ev)
        tail = events[2:]
        # the missed bind arrives as an UPDATE carrying the old
        # (unassigned) object — a bind transition, not a re-add
        assert ("update", "a", "", "n1") in tail
        assert ("del", "b") in tail
        assert ("add", "c") in tail
        assert len(tail) == 3   # nothing else replayed


# ---------------------------------------------------------------------------
# the full wire-level chaos matrix (slow: apiserver subprocess
# SIGKILL + WAL restore per seed)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [11, 23, 37, 41, 53])
def test_chaos_over_rest_survives_kill_restart(seed):
    from kubernetes_tpu.harness.chaos_rest import run_chaos_rest

    result = run_chaos_rest(seed, nodes=20, pods=120,
                            fault_profile="mixed", wait_timeout=120.0)
    assert result["ok"], (
        f"seed {seed}: {result['failure'] or result['invariants']} "
        f"(stats: {result['stats']})"
    )
    # the run was genuinely hostile: faults actually fired
    assert result["stats"]["faults_injected"] > 0
