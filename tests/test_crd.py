"""CRD analog (VERDICT r2 #6; reference
``staging/src/k8s.io/apiextensions-apiserver/``): creating a
CustomResourceDefinition registers a new kind at runtime — plural REST
route, storage table, watch support — with no edit to ``api/types.py``.
Instances participate in owner-reference GC; the WAL re-registers kinds
on restore."""

import threading
import time

from kubernetes_tpu.api.types import (
    CRDNames,
    CustomObject,
    CustomResourceDefinition,
    ObjectMeta,
)
from kubernetes_tpu.apiserver.rest import APIServer, RestClient
from kubernetes_tpu.apiserver.store import ClusterStore


def _crd(kind="Widget", plural="widgets", scope="Namespaced"):
    return CustomResourceDefinition(
        metadata=ObjectMeta(name=f"{plural}.example.com"),
        group="example.com",
        names=CRDNames(plural=plural, kind=kind),
        scope=scope,
    )


def _widget(name, spec=None, ns="default"):
    return CustomObject(
        kind="Widget",
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=spec or {"size": 3},
    )


class TestStoreRegistration:
    def test_create_crd_registers_kind(self):
        store = ClusterStore()
        store.create_object("CustomResourceDefinition", _crd())
        assert "Widget" in store.known_kinds()
        assert store.custom_plural_to_kind("widgets") == "Widget"
        assert store.kind_is_namespaced("Widget")
        store.create_object("Widget", _widget("w1"))
        assert store.get_object("Widget", "default", "w1").spec == {"size": 3}
        assert [o.name for o in store.list_objects("Widget")] == ["w1"]

    def test_cluster_scoped_crd(self):
        store = ClusterStore()
        store.create_object("CustomResourceDefinition", _crd(
            kind="Fleet", plural="fleets", scope="Cluster"))
        assert not store.kind_is_namespaced("Fleet")

    def test_builtin_kind_cannot_be_shadowed(self):
        store = ClusterStore()
        try:
            store.create_object("CustomResourceDefinition",
                                _crd(kind="Pod", plural="pods2"))
            raise AssertionError("shadowing Pod should be rejected")
        except ValueError:
            pass
        assert store.get_object("CustomResourceDefinition", "",
                                "pods2.example.com") is None

    def test_crd_delete_cascades_instances_and_unregisters(self):
        store = ClusterStore()
        store.create_object("CustomResourceDefinition", _crd())
        store.create_object("Widget", _widget("w1"))
        deleted = []
        store.watch(lambda ev: deleted.append(
            (ev.type, ev.kind, ev.obj.metadata.name))
            if ev.type == "DELETED" else None)
        store.delete_object("CustomResourceDefinition", "",
                            "widgets.example.com")
        assert "Widget" not in store.known_kinds()
        assert store.custom_plural_to_kind("widgets") is None
        assert ("DELETED", "Widget", "w1") in deleted

    def test_watch_delivers_custom_events(self):
        store = ClusterStore()
        store.create_object("CustomResourceDefinition", _crd())
        got = []
        store.watch(lambda ev: got.append((ev.type, ev.kind))
                        if ev.kind == "Widget" else None)
        store.create_object("Widget", _widget("w1"))
        w = store.get_object("Widget", "default", "w1")
        store.update_object("Widget", w)
        store.delete_object("Widget", "default", "w1")
        assert got == [("ADDED", "Widget"), ("MODIFIED", "Widget"),
                       ("DELETED", "Widget")]


class TestRestRoutes:
    def test_crud_and_watch_over_http(self):
        store = ClusterStore()
        server = APIServer(store=store).start()
        try:
            client = RestClient(server.url)
            client.create(_crd())
            # new plural route is live immediately
            created = client.create(_widget("w1", spec={"size": 7}))
            assert created.kind == "Widget"
            assert created.spec == {"size": 7}
            got = client.get("Widget", "w1")
            assert got.spec == {"size": 7}
            got.spec = {"size": 9}
            client.update(got)
            items, rv = client.list("Widget", namespace="default")
            assert len(items) == 1 and items[0].spec == {"size": 9}
            # watch: a follow-up create streams an ADDED frame
            events = []
            done = threading.Event()

            def on_event(ev_type, obj):
                events.append((ev_type, obj.metadata.name))
                done.set()

            handle = client.watch("Widget", rv, on_event,
                                  namespace="default")
            client.create(_widget("w2"))
            assert done.wait(5)
            handle.stop()
            assert ("ADDED", "w2") in events
            assert client.delete("Widget", "w1")
            assert client.get("Widget", "w1") is None
            # unknown plural: 404, not a crash
            code, _ = client._request("GET", "/api/v1/gadgets")
            assert code == 404
        finally:
            server.shutdown_server()


class TestGarbageCollection:
    def test_custom_instances_swept_when_owner_vanishes(self):
        from kubernetes_tpu.controllers import ControllerManager
        from kubernetes_tpu.api.types import ReplicaSet

        store = ClusterStore()
        store.create_object("CustomResourceDefinition", _crd())
        rs = ReplicaSet(metadata=ObjectMeta(name="own", namespace="default",
                                            uid="rs-uid"))
        store.add_replica_set(rs)
        w = _widget("dep")
        w.metadata.owner_references = [{
            "kind": "ReplicaSet", "name": "own", "uid": "rs-uid",
            "controller": True,
        }]
        store.create_object("Widget", w)
        cm = ControllerManager(store, controllers=["garbagecollector"])
        gc = cm.get("garbagecollector")
        gc.sweep_interval = 0.2
        cm.start()
        try:
            # owner alive: the instance stays
            time.sleep(0.6)
            assert store.get_object("Widget", "default", "dep") is not None
            store.delete_replica_set("default", "own")
            deadline = time.time() + 10
            while time.time() < deadline and store.get_object(
                    "Widget", "default", "dep") is not None:
                time.sleep(0.1)
            assert store.get_object("Widget", "default", "dep") is None
        finally:
            cm.stop()

    def test_custom_owner_of_pod(self):
        """A custom kind can OWN typed objects: pods owned by a deleted
        Widget get swept (the reference GC is generic over discovered
        resources)."""
        from kubernetes_tpu.controllers import ControllerManager
        from kubernetes_tpu.testing import MakePod

        store = ClusterStore()
        store.create_object("CustomResourceDefinition", _crd())
        w = _widget("boss")
        store.create_object("Widget", w)
        pod = MakePod().name("p1").uid("pu1").obj()
        pod.metadata.owner_references = [{
            "kind": "Widget", "name": "boss", "uid": w.metadata.uid,
            "controller": True,
        }]
        store.create_pod(pod)
        cm = ControllerManager(store, controllers=["garbagecollector"])
        cm.get("garbagecollector").sweep_interval = 0.2
        cm.start()
        try:
            time.sleep(0.6)
            assert store.get_pod("default", "p1") is not None
            store.delete_object("Widget", "default", "boss")
            deadline = time.time() + 10
            while time.time() < deadline and \
                    store.get_pod("default", "p1") is not None:
                time.sleep(0.1)
            assert store.get_pod("default", "p1") is None
        finally:
            cm.stop()


class TestWalRoundtrip:
    def test_custom_kinds_survive_restore(self, tmp_path):
        from kubernetes_tpu.apiserver.wal import attach_wal, restore_store

        store = ClusterStore()
        handle = attach_wal(store, str(tmp_path))
        store.create_object("CustomResourceDefinition", _crd())
        store.create_object("Widget", _widget("w1", spec={"size": 42}))
        handle.close()

        restored = restore_store(str(tmp_path))
        assert "Widget" in restored.known_kinds()
        assert restored.custom_plural_to_kind("widgets") == "Widget"
        got = restored.get_object("Widget", "default", "w1")
        assert got is not None and got.spec == {"size": 42}
        # the restored registry accepts new instances immediately
        restored.create_object("Widget", _widget("w2"))


class TestIrregularPlurals:
    """VERDICT r3 weak #8/#9: spec.names.plural is MANDATORY and
    authoritative — a kind like "Policy" must route and authorize by
    its declared plural ("policies"), never a naive "policys"."""

    def test_plural_required(self):
        store = ClusterStore()
        import pytest

        with pytest.raises(ValueError, match="plural"):
            store.create_object(
                "CustomResourceDefinition",
                _crd(kind="Gadget", plural=""),
            )

    def test_irregular_plural_routes_and_authorizes(self):
        from kubernetes_tpu.api.types import (
            PolicyRule, RBACSubject, Role, RoleBinding, RoleRef,
        )
        from kubernetes_tpu.apiserver.rbac import RBACAuthorizer

        store = ClusterStore()
        server = APIServer(store=store).start()
        try:
            client = RestClient(server.url)
            client.create(_crd(kind="Policy", plural="policies"))
            obj = CustomObject(
                kind="Policy",
                metadata=ObjectMeta(name="p1", namespace="default"),
                spec={"allow": True},
            )
            # the client discovers the declared plural (RESTMapper
            # role) — /policies, not /policys
            created = client.create(obj)
            assert created.kind == "Policy"
            assert client.get("Policy", "p1").spec == {"allow": True}
            code, _ = client._request(
                "GET", "/api/v1/namespaces/default/policies/p1")
            assert code == 200
            code, _ = client._request(
                "GET", "/api/v1/namespaces/default/policys/p1")
            assert code == 404

            # authz rules written against the declared plural match
            # requests arriving with the KIND name
            authz = RBACAuthorizer(store)
            store.add_role(Role(
                metadata=ObjectMeta(name="policy-reader",
                                    namespace="default"),
                rules=[PolicyRule(verbs=["get"],
                                  resources=["policies"])],
            ))
            store.add_role_binding(RoleBinding(
                metadata=ObjectMeta(name="bob-reads",
                                    namespace="default"),
                subjects=[RBACSubject(kind="User", name="bob")],
                role_ref=RoleRef(kind="Role", name="policy-reader"),
            ))
            assert authz.authorize("bob", "get", "Policy", "default")
            assert not authz.authorize("bob", "delete", "Policy",
                                       "default")
        finally:
            server.shutdown_server()


class TestMultiVersionCRDs:
    """Per-CRD version lists served with None-conversion (VERDICT r4
    missing #5 / next #9; reference apiextensions/types.go:23-28): one
    CRD, two served versions, round-trip + watch at each."""

    def _mv_crd(self):
        from kubernetes_tpu.api.types import CRDVersion

        return CustomResourceDefinition(
            metadata=ObjectMeta(name="widgets.stable.example.com"),
            group="stable.example.com",
            names=CRDNames(plural="widgets", kind="Widget"),
            versions=[
                CRDVersion(name="v1beta1", served=True, storage=True),
                CRDVersion(name="v1", served=True),
                CRDVersion(name="v1alpha1", served=False),
            ],
        )

    def test_round_trip_at_each_served_version(self):
        store = ClusterStore()
        server = APIServer(store=store).start()
        try:
            client = RestClient(server.url)
            client.create(self._mv_crd())
            base = "/apis/stable.example.com"
            code, _ = client._request(
                "POST", f"{base}/v1beta1/namespaces/default/widgets",
                {"kind": "Widget", "apiVersion":
                 "stable.example.com/v1beta1",
                 "metadata": {"name": "w-beta"}, "spec": {"size": 1}})
            assert code == 201
            # readable at BOTH served versions, apiVersion stamped per
            # route (None-conversion: same payload)
            code, doc = client._request(
                "GET", f"{base}/v1/namespaces/default/widgets/w-beta")
            assert code == 200
            assert doc["apiVersion"] == "stable.example.com/v1"
            assert doc["spec"]["size"] == 1
            code, doc = client._request(
                "GET",
                f"{base}/v1beta1/namespaces/default/widgets/w-beta")
            assert code == 200
            assert doc["apiVersion"] == "stable.example.com/v1beta1"
            # write at v1, list at v1beta1
            code, _ = client._request(
                "POST", f"{base}/v1/namespaces/default/widgets",
                {"kind": "Widget",
                 "metadata": {"name": "w-ga"}, "spec": {"size": 2}})
            assert code == 201
            code, doc = client._request(
                "GET", f"{base}/v1beta1/namespaces/default/widgets")
            assert code == 200
            assert {i["metadata"]["name"] for i in doc["items"]} == \
                {"w-beta", "w-ga"}
            # the UNSERVED version is a 404 (apiextensions serving
            # rules), as is a wrong group
            code, _ = client._request(
                "GET", f"{base}/v1alpha1/namespaces/default/widgets")
            assert code == 404
            code, _ = client._request(
                "GET",
                "/apis/wrong.example.com/v1/namespaces/default/widgets")
            assert code == 404
        finally:
            server.shutdown_server()

    def test_watch_at_each_served_version(self):
        import json as _json
        import urllib.request

        store = ClusterStore()
        server = APIServer(store=store).start()
        try:
            client = RestClient(server.url)
            client.create(self._mv_crd())
            got = {}
            done = {}
            base = "/apis/stable.example.com"

            def watcher(version):
                req = urllib.request.Request(
                    f"{server.url}{base}/{version}/widgets?watch=1")
                with urllib.request.urlopen(req, timeout=10) as resp:
                    for line in resp:
                        got[version] = _json.loads(line)
                        done[version].set()
                        return

            for v in ("v1beta1", "v1"):
                done[v] = threading.Event()
                threading.Thread(target=watcher, args=(v,),
                                 daemon=True).start()
            time.sleep(0.3)
            code, _ = client._request(
                "POST", f"{base}/v1/namespaces/default/widgets",
                {"kind": "Widget", "metadata": {"name": "live"},
                 "spec": {"size": 9}})
            assert code == 201
            assert done["v1beta1"].wait(5) and done["v1"].wait(5)
            # each stream stamps ITS version on the same payload
            assert got["v1beta1"]["object"]["apiVersion"] == \
                "stable.example.com/v1beta1"
            assert got["v1"]["object"]["apiVersion"] == \
                "stable.example.com/v1"
            assert got["v1"]["object"]["spec"]["size"] == 9
        finally:
            server.shutdown_server()

    def test_discovery_lists_served_versions(self):
        store = ClusterStore()
        server = APIServer(store=store).start()
        try:
            client = RestClient(server.url)
            client.create(self._mv_crd())
            code, doc = client._request("GET", "/apis")
            group = next(g for g in doc["groups"]
                         if g["name"] == "stable.example.com")
            versions = {v["version"] for v in group["versions"]}
            assert versions == {"v1beta1", "v1"}   # v1alpha1 unserved
            code, doc = client._request(
                "GET", "/apis/stable.example.com/v1")
            assert code == 200
            assert any(r["kind"] == "Widget" and r["name"] == "widgets"
                       for r in doc["resources"])
        finally:
            server.shutdown_server()

    def test_storage_version_validation(self):
        from kubernetes_tpu.api.types import CRDVersion

        store = ClusterStore()
        crd = self._mv_crd()
        crd.versions = [CRDVersion(name="v1", served=True),
                        CRDVersion(name="v2", served=True)]
        try:
            store.create_object("CustomResourceDefinition", crd)
            raise AssertionError("CRD without a storage version accepted")
        except ValueError as e:
            assert "storage" in str(e)
