"""Sharded-by-default solve: the devscale harness, the mesh diag
segment, donation accounting through the live session, the sharded
encode stage, and THE differential guarantee — the sharded-default
backend must produce bit-identical assignments (same argmax
tie-breaks) to the single-device backend on identical encoded batches,
across mesh sizes, via subprocesses that force the device count with
XLA_FLAGS before JAX imports (the only way a test controls
``jax.device_count()``; in-process the conftest already pinned 8).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from kubernetes_tpu.harness import diagfmt
from kubernetes_tpu.harness.devscale import ensure_virtual_devices
from tools.perf_report import devscale_flags

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the subprocess body: builds 3 seeded problems (heavy score ties so
# the lowest-index argmax tie-break is genuinely exercised), solves
# each on the DEFAULT backend for this interpreter's device count
# (KTPU_SOLVER=auto → mesh tier when >1 device), asserts equality with
# the serial-equivalent reference scan, and prints the assignments so
# the parent can cross-check bit-identity ACROSS mesh sizes.
_CHILD = r"""
import json
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from kubernetes_tpu.ops import BatchEncoder
from kubernetes_tpu.ops.session import default_backend
from kubernetes_tpu.ops.solver import SolverParams, pack_podin, solve_scan
from kubernetes_tpu.scheduler.snapshot import new_snapshot
from kubernetes_tpu.testing import MakeNode, MakePod


def problem(seed):
    rng = np.random.default_rng(seed)
    nodes = []
    for i in range(40):
        # half the nodes identical -> massive score ties -> the
        # lowest-index tie-break decides
        cpu = 8 if i % 2 == 0 else int(rng.integers(4, 9))
        nodes.append(
            MakeNode().name(f"n{i:03d}")
            .label("topology.kubernetes.io/zone", f"z{i % 4}")
            .capacity({"cpu": str(cpu), "memory": "16Gi"}).obj())
    pods = []
    for i in range(60):
        w = (MakePod().name(f"p{i:03d}").uid(f"u{seed}-{i}")
             .label("app", f"g{i % 3}")
             .req({"cpu": f"{int(rng.integers(1, 4)) * 100}m"}))
        if i % 5 == 0:
            w.spread_constraint(1, "topology.kubernetes.io/zone",
                                "DoNotSchedule", {"app": f"g{i % 3}"})
        if i % 7 == 0:
            w.pod_anti_affinity("app", [f"g{(i + 1) % 3}"],
                                "kubernetes.io/hostname")
        pods.append(w.obj())
    return nodes, pods


be = default_backend()
out = {"devices": jax.device_count(), "backend": be.name,
       "assignments": {}}
params = SolverParams()
for seed in (0, 1, 2):
    nodes, pods = problem(seed)
    snap = new_snapshot([], nodes)
    enc = BatchEncoder(snap, pad_nodes=128,
                       node_shards=getattr(be, "encode_shards", 1))
    cluster, batch = enc.encode(pods, pad_pods=64)
    ref = solve_scan(cluster, batch)[: len(pods)]
    static, state = be.prepare(cluster, batch)
    ints, floats = pack_podin(batch)
    got, _ = be.solve(params, static, state, ints, floats)
    got = np.asarray(got)[: len(pods)]
    assert np.array_equal(ref, got), (
        f"seed {seed}: default backend {be.name} diverged from the "
        f"reference scan: {ref.tolist()} vs {got.tolist()}")
    out["assignments"][str(seed)] = got.tolist()
print(json.dumps(out))
"""


def _run_child(devices: int) -> dict:
    env = ensure_virtual_devices(devices, dict(os.environ))
    env["KTPU_SOLVER"] = "auto"
    env.pop("KTPU_SHARDED_DONATE", None)
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH",
                                                          "")
    proc = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.PIPE, text=True,
                          timeout=240)
    assert proc.returncode == 0, (
        f"differential child (devices={devices}) failed:\n"
        f"{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.splitlines()[-1])


class TestShardedDefaultDifferential:
    """Mesh sizes {1, 2, 4} × 3 seeds: the sharded-default tier is
    bit-identical to the single-device backend."""

    def test_assignments_identical_across_mesh_sizes(self):
        results = {d: _run_child(d) for d in (1, 2, 4)}
        # devices=1 must NOT be the mesh tier; >1 must be
        assert results[1]["backend"] != "sharded"
        assert results[2]["backend"] == "sharded"
        assert results[4]["backend"] == "sharded"
        base = results[1]["assignments"]
        for d in (2, 4):
            assert results[d]["assignments"] == base, (
                f"mesh size {d} diverged from the single-device "
                f"backend")


class TestShardedSessionAccounting:
    """The live session on the mesh tier: donated planes ride the
    donated ledger (never h2d), the staging arm pays the per-cycle
    h↔d copies donation removes, and warming never corrupts the
    resident mirror."""

    def _bind_all(self, donate: bool, prof):
        import time

        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.config.feature_gates import FeatureGates
        from kubernetes_tpu.parallel import ShardedBackend, make_mesh
        from kubernetes_tpu.scheduler.scheduler import Scheduler
        from kubernetes_tpu.sidecar import attach_batch_scheduler
        from kubernetes_tpu.testing import MakeNode, MakePod

        store = ClusterStore()
        for i in range(16):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "16", "memory": "32Gi"}).obj())
        sched = Scheduler.create(
            store,
            feature_gates=FeatureGates({"TPUBatchScheduler": True}))
        bs = attach_batch_scheduler(
            sched, max_batch=64,
            backend=ShardedBackend(make_mesh(4, batch_axis=1),
                                   donate=donate))
        sched.start()
        try:
            for i in range(96):
                store.create_pod(
                    MakePod().name(f"p{i}").uid(f"u{i}")
                    .req({"cpu": "1"}).obj())
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                sched.queue.flush_backoff_completed()
                if bs.run_batch(pop_timeout=0.0):
                    continue
                if bs.flush():
                    continue
                if sched.queue.num_active() == 0 \
                        and sched.queue.num_backoff() == 0:
                    break
                time.sleep(0.02)
            assert sched.wait_for_inflight_bindings()
            bound = sum(1 for p in store.list_pods()
                        if p.spec.node_name)
            info = bs.mesh_info()
        finally:
            sched.stop()
        assert bound == 96
        assert bs.session._active.name == "sharded"
        return prof.summary(), info

    def test_donation_ledgers_and_staging_ab(self):
        from kubernetes_tpu.observability.devprof import (
            DevProfiler,
            get_devprof,
            set_devprof,
        )

        prev = get_devprof()
        try:
            prof_on = DevProfiler(enabled=True, use_listener=False)
            set_devprof(prof_on)
            on, info_on = self._bind_all(donate=True, prof=prof_on)
            prof_off = DevProfiler(enabled=True, use_listener=False)
            set_devprof(prof_off)
            off, info_off = self._bind_all(donate=False, prof=prof_off)
        finally:
            set_devprof(prev)
        # donation on: resident planes ride the donated ledger only
        assert on["donated_bytes"] > 0
        assert info_on == {"devices": 4, "shards": 4, "donated": True}
        assert info_off["donated"] is False
        # the per-cycle h↔d copies of reusable planes exist exactly on
        # the staging arm — transfer totals strictly lower with
        # donation on (the tentpole's acceptance metric)
        assert off["h2d_bytes"] > on["h2d_bytes"]
        assert off["d2h_bytes"] > on["d2h_bytes"]
        assert off["donated_bytes"] == 0

    def test_warm_pad_preserves_resident_mirror_under_donation(self):
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.config.feature_gates import FeatureGates
        from kubernetes_tpu.parallel import ShardedBackend, make_mesh
        from kubernetes_tpu.scheduler.scheduler import Scheduler
        from kubernetes_tpu.sidecar import attach_batch_scheduler
        from kubernetes_tpu.testing import MakeNode, MakePod

        store = ClusterStore()
        for i in range(8):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "64", "memory": "64Gi"}).obj())
        sched = Scheduler.create(
            store,
            feature_gates=FeatureGates({"TPUBatchScheduler": True}))
        bs = attach_batch_scheduler(
            sched, max_batch=32,
            backend=ShardedBackend(make_mesh(4, batch_axis=1),
                                   donate=True))
        sess = bs.session
        pods = [MakePod().name(f"p{i}").uid(f"u{i}")
                .req({"cpu": "1"}).obj() for i in range(8)]
        sess.solve(pods, warming=True)
        before = np.asarray(sess._state.planes).copy()
        # the donated executable consumes its state inputs: without the
        # warm_state clone this would delete the resident buffer
        assert sess.warm_pad(pods[:2], 16) is not None
        after = np.asarray(sess._state.planes)  # still alive
        assert np.array_equal(before, after)


class TestShardedEncode:
    def test_node_sharded_encode_is_bit_identical(self):
        """The sharded encode stage emits per-shard node columns into
        the same arrays the serial fill produces — every plane must be
        bit-identical (the solve's differential guarantee starts
        here)."""
        from kubernetes_tpu.ops import BatchEncoder
        from kubernetes_tpu.scheduler.snapshot import new_snapshot
        from kubernetes_tpu.testing import MakeNode, MakePod

        nodes = [
            MakeNode().name(f"n{i:04d}")
            .label("topology.kubernetes.io/zone", f"z{i % 5}")
            .capacity({"cpu": str(4 + i % 5), "memory": "16Gi"}).obj()
            for i in range(600)   # above ENCODE_SHARD_MIN_NODES
        ]
        pods = []
        for i in range(32):
            w = (MakePod().name(f"p{i}").uid(f"u{i}")
                 .label("app", f"g{i % 2}").req({"cpu": "200m"}))
            if i % 4 == 0:
                w.spread_constraint(
                    2, "topology.kubernetes.io/zone", "DoNotSchedule",
                    {"app": f"g{i % 2}"})
            if i % 2 == 0:
                w.node_selector({"topology.kubernetes.io/zone": "z1"})
            pods.append(w.obj())
        snap = new_snapshot([], nodes)
        c1, b1 = BatchEncoder(snap, pad_nodes=128,
                              node_shards=1).encode(pods, pad_pods=32)
        c8, b8 = BatchEncoder(snap, pad_nodes=128,
                              node_shards=8).encode(pods, pad_pods=32)
        np.testing.assert_array_equal(c1.allocatable, c8.allocatable)
        np.testing.assert_array_equal(c1.requested, c8.requested)
        np.testing.assert_array_equal(c1.pod_count, c8.pod_count)
        np.testing.assert_array_equal(c1.max_pods, c8.max_pods)
        np.testing.assert_array_equal(c1.topo_codes, c8.topo_codes)
        np.testing.assert_array_equal(b1.static_masks, b8.static_masks)
        np.testing.assert_array_equal(b1.affinity_masks,
                                      b8.affinity_masks)
        np.testing.assert_array_equal(b1.static_scores,
                                      b8.static_scores)
        np.testing.assert_array_equal(b1.sc_domain, b8.sc_domain)

    def test_small_clusters_stay_serial(self):
        from kubernetes_tpu.ops.encode import BatchEncoder
        from kubernetes_tpu.scheduler.snapshot import new_snapshot
        from kubernetes_tpu.testing import MakeNode

        snap = new_snapshot([], [
            MakeNode().name("n0").capacity({"cpu": "4"}).obj()])
        enc = BatchEncoder(snap, node_shards=8)
        assert not enc._sharding_active()


class TestMeshDiagSegment:
    def test_round_trip(self):
        seg = diagfmt.format_mesh(
            {"devices": 8, "shards": 8, "donated": True})
        assert seg == "mesh[devices=8 shards=8 donated=1]"
        line = diagfmt.format_diag(
            ["solve.commit=1.00s/2~p99 10ms", seg])
        parsed = diagfmt.parse_diag(line)
        assert parsed["mesh"] == {"devices": 8, "shards": 8,
                                  "donated": 1}

    def test_empty_info_prints_nothing(self):
        assert diagfmt.format_mesh(None) == ""
        assert diagfmt.format_mesh({}) == ""

    def test_devprof_segment_carries_donated_mb(self):
        summary = {
            "cycles": 3, "compiles": 0, "unexpected_compiles": 0,
            "warm_compiles": 0, "device_wait_share": 0.5,
            "pad_waste_pct": 0.0, "h2d_bytes": 1_000_000,
            "d2h_bytes": 1_000, "donated_bytes": 5_000_000,
            "compile_detector": "listener",
        }
        seg = diagfmt.format_devprof(summary)
        assert "donated_mb=5.0" in seg
        parsed = diagfmt.parse_diag("    diag: " + seg)
        assert parsed["devprof"]["donated_mb"] == 5.0


class TestDevscaleFlags:
    """tools/perf_report.py learns the devscale family: scaling bar,
    efficiency gate, and the donation A/B verdict."""

    @staticmethod
    def _round(row):
        return [{"round": 7, "rows": [row]}]

    @staticmethod
    def _row(**over):
        row = {
            "metric": "solve_throughput_devscale[SchedulingBasic "
                      "51200nodes/8192pods]",
            "value": 16000.0, "unit": "pods/s",
            "solve_speedup_vs_1dev": {"1": 1.0, "2": 1.4, "4": 2.5},
            "scaling_efficiency_4dev": 0.63,
            "donation_ab": {
                "devices": 4,
                "on": {"h2d_bytes_per_cycle": 100,
                       "device_wait_share": 0.4},
                "off": {"h2d_bytes_per_cycle": 900,
                        "device_wait_share": 0.6},
                "donation_pays": True,
            },
        }
        row.update(over)
        return row

    def test_healthy_row_has_no_flags(self):
        assert devscale_flags(self._round(self._row())) == []

    def test_flags_speedup_below_bar(self):
        row = self._row(solve_speedup_vs_1dev={"1": 1.0, "4": 1.2})
        (flag,) = devscale_flags(self._round(row))
        assert "speedup 1.2 < 1.5x" in flag["problems"][0]

    def test_flags_efficiency_below_point_six_on_real_hardware(self):
        row = self._row(scaling_efficiency_4dev=0.51)
        (flag,) = devscale_flags(self._round(row))
        assert "efficiency 0.51 < 0.6" in flag["problems"][0]

    def test_virtual_device_rows_exempt_from_efficiency_gate(self):
        """Forced shared-silicon virtual devices understate mesh
        efficiency by construction (the 1-device baseline is intra-op
        multithreaded) — the 0.6 gate polices real meshes only; the
        ≥1.5× speedup bar still applies."""
        row = self._row(scaling_efficiency_4dev=0.47,
                        virtual_devices=True)
        assert devscale_flags(self._round(row)) == []
        row = self._row(scaling_efficiency_4dev=0.47,
                        virtual_devices=True,
                        solve_speedup_vs_1dev={"1": 1.0, "4": 1.2})
        (flag,) = devscale_flags(self._round(row))
        assert "speedup 1.2 < 1.5x" in flag["problems"][0]

    def test_flags_donation_not_paying(self):
        ab = self._row()["donation_ab"]
        ab["donation_pays"] = False
        row = self._row(donation_ab=ab)
        (flag,) = devscale_flags(self._round(row))
        assert "donation A/B not paying" in flag["problems"][0]

    def test_non_devscale_rows_ignored(self):
        row = {"metric": "pods_scheduled_per_sec[x]", "value": 1.0}
        assert devscale_flags(self._round(row)) == []


class TestVirtualDeviceBootstrap:
    def test_sets_and_replaces_flag(self):
        env = ensure_virtual_devices(8, {})
        assert env["XLA_FLAGS"] == \
            "--xla_force_host_platform_device_count=8"
        env = ensure_virtual_devices(4, env)
        assert env["XLA_FLAGS"] == \
            "--xla_force_host_platform_device_count=4"

    def test_preserves_other_flags(self):
        env = ensure_virtual_devices(
            2, {"XLA_FLAGS": "--xla_foo=bar"})
        assert "--xla_foo=bar" in env["XLA_FLAGS"]
        assert "--xla_force_host_platform_device_count=2" \
            in env["XLA_FLAGS"]


@pytest.mark.slow
class TestDevscaleRowSlow:
    def test_quick_row_schema_and_donation_ab(self):
        """The full spawned row at quick scale: arms, speedups, and
        the donation A/B with its acceptance verdict."""
        from kubernetes_tpu.harness.devscale import run_devscale_row

        row = run_devscale_row(nodes=1024, pods=2048, max_batch=1024,
                               device_counts=(1, 2),
                               donation_ab_devices=2)
        assert row["unit"] == "pods/s"
        assert [a["devices"] for a in row["arms"]] == [1, 2]
        assert row["arms"][1]["mesh"]["shards"] == 2
        ab = row["donation_ab"]
        assert ab["on"]["h2d_bytes_per_cycle"] \
            < ab["off"]["h2d_bytes_per_cycle"]
        assert ab["donation_pays"] in (True, False)
