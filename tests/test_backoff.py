"""Client resilience primitives (client/backoff.py): deterministic
jittered backoff, retry budget semantics, circuit-breaker transitions,
and retry_call's original-error contract."""

import random

import pytest

from kubernetes_tpu.client.backoff import (
    Backoff,
    CircuitBreaker,
    RetryBudget,
    retry_call,
)


class TestBackoff:
    def test_deterministic_under_seeded_rng(self):
        a = Backoff(base=0.05, factor=2.0, cap=5.0, jitter=0.4,
                    rng=random.Random(42))
        b = Backoff(base=0.05, factor=2.0, cap=5.0, jitter=0.4,
                    rng=random.Random(42))
        assert [a.delay(i) for i in range(10)] == \
            [b.delay(i) for i in range(10)]

    def test_jitter_stays_within_bounds(self):
        bo = Backoff(base=0.1, factor=2.0, cap=3.0, jitter=0.3,
                     rng=random.Random(7))
        for attempt in range(12):
            raw = min(0.1 * 2.0 ** attempt, 3.0)
            d = bo.delay(attempt)
            assert raw * 0.7 - 1e-12 <= d <= raw * 1.3 + 1e-12
            assert d > 0

    def test_no_jitter_is_exact_exponential(self):
        bo = Backoff(base=0.5, factor=2.0, cap=3.0, jitter=0.0)
        assert [bo.delay(i) for i in range(4)] == [0.5, 1.0, 2.0, 3.0]

    def test_steps_iterator_matches_delay_sequence(self):
        bo = Backoff(base=0.1, factor=3.0, cap=10.0, jitter=0.0)
        steps = bo.steps()
        assert [next(steps) for _ in range(4)] == \
            [0.1, pytest.approx(0.3), pytest.approx(0.9),
             pytest.approx(2.7)]

    def test_rejects_full_jitter(self):
        # jitter=1.0 could produce a zero delay — a hot retry loop
        with pytest.raises(ValueError):
            Backoff(jitter=1.0)


class TestRetryBudget:
    def test_spend_down_then_refuse(self):
        budget = RetryBudget(budget=3, refill_per_second=0.0)
        assert [budget.try_spend() for _ in range(4)] == \
            [True, True, True, False]

    def test_refills_over_time(self):
        budget = RetryBudget(budget=1, refill_per_second=1000.0)
        assert budget.try_spend()
        import time

        time.sleep(0.01)
        assert budget.try_spend()


class TestRetryCall:
    def test_budget_exhaustion_raises_original_error(self):
        budget = RetryBudget(budget=2, refill_per_second=0.0)
        boom = ValueError("the original failure")
        calls = []

        def fn():
            calls.append(1)
            raise boom

        with pytest.raises(ValueError) as exc:
            retry_call(fn, retryable=(ValueError,), budget=budget,
                       max_attempts=10, sleep=lambda s: None)
        # the ORIGINAL exception object, not a wrapper
        assert exc.value is boom
        # first attempt is free, each RETRY spends a token: 2 retries
        # land, the 3rd call's failure finds an empty budget and
        # surfaces immediately
        assert len(calls) == 3

    def test_max_attempts_raises_original_error(self):
        boom = OSError("conn reset")

        def fn():
            raise boom

        with pytest.raises(OSError) as exc:
            retry_call(fn, max_attempts=3, sleep=lambda s: None)
        assert exc.value is boom

    def test_sleeps_follow_backoff_sequence(self):
        bo = Backoff(base=0.1, factor=2.0, cap=5.0, jitter=0.0)
        slept = []
        attempts = []

        def fn():
            attempts.append(1)
            if len(attempts) < 4:
                raise OSError("flaky")
            return "ok"

        assert retry_call(fn, backoff=bo, max_attempts=5,
                          sleep=slept.append) == "ok"
        assert slept == [0.1, pytest.approx(0.2), pytest.approx(0.4)]

    def test_non_retryable_errors_pass_through(self):
        def fn():
            raise KeyError("not transport")

        with pytest.raises(KeyError):
            retry_call(fn, retryable=(OSError,), sleep=lambda s: None)


class TestCircuitBreaker:
    def test_opens_at_threshold_and_closes_on_success(self):
        states = []
        cb = CircuitBreaker(failure_threshold=3, listener=states.append)
        for _ in range(2):
            cb.record_failure()
        assert not cb.is_open and states == []
        cb.record_failure()
        assert cb.is_open and states == [True]
        cb.record_failure()           # already open: no duplicate event
        assert states == [True]
        cb.record_success()
        assert not cb.is_open and states == [True, False]

    def test_success_resets_consecutive_count(self):
        cb = CircuitBreaker(failure_threshold=2)
        cb.record_failure()
        cb.record_success()
        cb.record_failure()
        assert not cb.is_open   # never two CONSECUTIVE failures

    def test_late_listener_replays_current_state(self):
        cb = CircuitBreaker(failure_threshold=1)
        cb.record_failure()
        states = []
        cb.set_listener(states.append)
        assert states == [True]
