"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

This environment's sitecustomize registers a TPU-tunnel PJRT plugin
(platform "axon") in every interpreter and pins JAX_PLATFORMS=axon, so env
vars set here are too late — the working override is jax.config.update
AFTER import, BEFORE first backend use. XLA_FLAGS still applies because no
backend has been initialized yet at conftest time.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1 "
        "(`-m 'not slow'`)")
    config.addinivalue_line(
        "markers", "chaos: fault-injection suites (wire-level seeds are "
        "also marked slow so tier-1 stays fast)")
