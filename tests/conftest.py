"""Test configuration: force an 8-device virtual CPU mesh for sharding tests.

Must run before any jax import (pytest loads conftest first).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
