"""Cache debugger ring: dumps and cache-vs-store consistency comparison."""

import time

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.scheduler.debugger import CacheDebugger
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


def wait_for(cond, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return cond()


def test_debugger_dump_and_consistent_compare():
    store = ClusterStore()
    store.add_node(MakeNode().name("n1").capacity({"cpu": "8", "memory": "16Gi"}).obj())
    sched = Scheduler.create(store)
    sched.run()
    try:
        store.create_pod(MakePod().name("p1").uid("u1").req({"cpu": "500m"}).obj())
        assert wait_for(lambda: store.get_pod("default", "p1").spec.node_name)
        dbg = CacheDebugger(store, sched.cache, sched.queue)
        assert wait_for(lambda: dbg.compare().consistent), vars(dbg.compare())
        d = dbg.dump()
        assert "n1" in d["nodes"]
        assert "default/p1" in d["nodes"]["n1"]["pods"]
        assert d["nodes"]["n1"]["requested_milli_cpu"] == 500
        dbg.dump_to_log()  # smoke: must not raise
    finally:
        sched.stop()


def test_debugger_detects_drift():
    store = ClusterStore()
    store.add_node(MakeNode().name("n1").capacity({"cpu": "8"}).obj())
    sched = Scheduler.create(store)
    sched.run()
    try:
        dbg = CacheDebugger(store, sched.cache, sched.queue)
        assert wait_for(lambda: not dbg.compare().missing_nodes)
        # inject drift: a node the cache never saw (bypass event handlers)
        store._nodes["ghost"] = MakeNode().name("ghost").obj()
        result = dbg.compare()
        assert result.missing_nodes == ["ghost"]
        assert not result.consistent
    finally:
        sched.stop()
