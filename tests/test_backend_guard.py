"""Backend-tier guard (sharded-by-default solve, satellite): fast
import-time assertions on ``ops.session.default_backend``.

The mesh tier must be impossible to reach by accident on a
single-device host — ``default_backend()`` must not even CONSTRUCT a
mesh when ``jax.device_count() == 1`` (no regression of single-device
startup latency), and the explicit ``KTPU_SOLVER=xla|pallas|cpp``
pins must keep selecting the legacy backends no matter how many
devices are visible. Mesh construction is trapped by monkeypatching
the parallel module's constructors to raise, not by inspecting the
returned object — "never constructs" is the contract, not "returns
something else".
"""

from __future__ import annotations

import pytest

import jax

from kubernetes_tpu.ops import session as session_mod


@pytest.fixture
def no_mesh_allowed(monkeypatch):
    """Any mesh construction under this fixture is a test failure."""
    import kubernetes_tpu.parallel as parallel

    def boom(*_a, **_k):
        raise AssertionError(
            "default_backend constructed a mesh on a single-device host")

    monkeypatch.setattr(parallel, "make_mesh", boom)
    monkeypatch.setattr(parallel, "ShardedBackend", boom)


class TestSingleDeviceNeverMeshes:
    @pytest.mark.parametrize("choice", ["", "auto"])
    def test_no_mesh_at_one_device(self, monkeypatch, no_mesh_allowed,
                                   choice):
        monkeypatch.setattr(jax, "device_count", lambda: 1)
        if choice:
            monkeypatch.setenv("KTPU_SOLVER", choice)
        else:
            monkeypatch.delenv("KTPU_SOLVER", raising=False)
        be = session_mod.default_backend()
        # CPU single-device tiering unchanged: native C++ planes solver
        # where the library builds, else the XLA planes scan
        assert be.name in ("cpp", "xla-planes")
        assert not hasattr(be, "mesh")

    def test_unset_on_cpu_never_meshes_even_multi_device(
            self, monkeypatch, no_mesh_allowed):
        """The tier-1 environment itself: 8 forced virtual CPU devices
        with KTPU_SOLVER unset must keep the single-device default —
        virtual host devices share silicon, so the mesh tier is opt-in
        (auto/sharded) on CPU hosts."""
        monkeypatch.delenv("KTPU_SOLVER", raising=False)
        assert jax.device_count() > 1  # conftest forces 8
        be = session_mod.default_backend()
        assert not hasattr(be, "mesh")


class TestLegacyPinsStillPin:
    def test_xla_pin(self, monkeypatch):
        monkeypatch.setenv("KTPU_SOLVER", "xla")
        assert session_mod.default_backend().name == "xla-planes"

    def test_pallas_pin(self, monkeypatch):
        monkeypatch.setenv("KTPU_SOLVER", "pallas")
        be = session_mod.default_backend()
        assert be.name == "pallas"
        assert be.interpret  # cpu host

    def test_cpp_pin(self, monkeypatch):
        monkeypatch.setenv("KTPU_SOLVER", "cpp")
        assert session_mod.default_backend().name == "cpp"


class TestMeshTier:
    def test_auto_multi_device_takes_the_mesh(self, monkeypatch):
        monkeypatch.setenv("KTPU_SOLVER", "auto")
        be = session_mod.default_backend()
        assert be.name == "sharded"
        # power-of-two node axis over the 8 virtual devices; donation
        # is the default contract of the tier
        assert dict(be.mesh.shape)["nodes"] == 8
        assert be.donate
        assert be.encode_shards == 8

    def test_forced_sharded(self, monkeypatch):
        monkeypatch.setenv("KTPU_SOLVER", "sharded")
        assert session_mod.default_backend().name == "sharded"

    def test_donation_env_gate(self, monkeypatch):
        monkeypatch.setenv("KTPU_SOLVER", "auto")
        monkeypatch.setenv("KTPU_SHARDED_DONATE", "0")
        assert not session_mod.default_backend().donate

    def test_mesh_width_is_largest_pow2(self):
        assert [session_mod._mesh_width(n)
                for n in (1, 2, 3, 4, 6, 8, 12, 100)] \
            == [1, 2, 2, 4, 4, 8, 8, 64]
