"""Durable-store tests: WAL + snapshot behind ClusterStore, crash
recovery where the STORE process restarts (reference seam:
etcd3/store.go:86 — etcd's own WAL+snapshot semantics)."""

import json
import os

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.apiserver.wal import attach_wal, restore_store
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


def drain(sched, rounds=300):
    for _ in range(rounds):
        sched.queue.flush_backoff_completed()
        if not sched.schedule_one(pop_timeout=0.0):
            break
    sched.wait_for_inflight_bindings()


class TestWal:
    def test_restore_preserves_objects_and_rv(self, tmp_path):
        store = ClusterStore()
        wal = attach_wal(store, str(tmp_path))
        store.add_node(MakeNode().name("n1")
                       .capacity({"cpu": "8", "memory": "16Gi"}).obj())
        store.create_pod(MakePod().name("a").uid("ua").req({"cpu": "1"}).obj())
        store.create_pod(MakePod().name("b").uid("ub").req({"cpu": "1"}).obj())
        store.bind("default", "a", "ua", "n1")
        store.delete_pod("default", "b")
        rv = store.current_rv()
        # crash: no clean shutdown, just reopen the directory
        restored = restore_store(str(tmp_path))
        assert restored.current_rv() == rv
        assert restored.get_node("n1") is not None
        a = restored.get_pod("default", "a")
        assert a is not None and a.spec.node_name == "n1"
        assert a.uid == "ua"
        assert restored.get_pod("default", "b") is None
        wal.close()

    def test_snapshot_rotation_truncates_log(self, tmp_path):
        store = ClusterStore()
        wal = attach_wal(store, str(tmp_path), snapshot_every=10)
        for i in range(25):
            store.create_pod(MakePod().name(f"p{i}").uid(f"u{i}").obj())
        # at least two rotations happened; log holds < snapshot_every
        with open(os.path.join(str(tmp_path), "wal.jsonl")) as f:
            assert sum(1 for _ in f) < 10
        restored = restore_store(str(tmp_path))
        assert len(restored.list_pods()) == 25
        wal.close()

    def test_torn_tail_write_is_ignored(self, tmp_path):
        store = ClusterStore()
        wal = attach_wal(store, str(tmp_path))
        store.create_pod(MakePod().name("ok").uid("uok").obj())
        wal.close()
        with open(os.path.join(str(tmp_path), "wal.jsonl"), "a") as f:
            f.write('{"t": "PUT", "k": "Pod", "rv": 99, "o": {"trunc')
        restored = restore_store(str(tmp_path))
        assert restored.get_pod("default", "ok") is not None

    def test_scheduler_resumes_on_restored_store(self, tmp_path):
        """Full crash-recovery: store process dies mid-workload; a new
        store restores from disk and a fresh scheduler finishes the
        remaining pods without double-binding the finished ones."""
        store = ClusterStore()
        wal = attach_wal(store, str(tmp_path))
        store.add_node(MakeNode().name("n1")
                       .capacity({"cpu": "8", "memory": "16Gi"}).obj())
        sched = Scheduler.create(store)
        sched.start()
        for i in range(4):
            store.create_pod(MakePod().name(f"done{i}").uid(f"ud{i}")
                             .req({"cpu": "500m"}).obj())
        drain(sched)
        bound_before = {
            p.metadata.name: p.spec.node_name for p in store.list_pods()
        }
        assert all(bound_before.values())
        # pods created but NOT yet scheduled when the store "crashes"
        for i in range(4):
            store.create_pod(MakePod().name(f"todo{i}").uid(f"ut{i}")
                             .req({"cpu": "500m"}).obj())
        sched.stop()
        wal.close()

        restored = restore_store(str(tmp_path))
        sched2 = Scheduler.create(restored)
        sched2.start()
        drain(sched2)
        sched2.stop()
        pods = {p.metadata.name: p for p in restored.list_pods()}
        assert len(pods) == 8
        for name, node in bound_before.items():
            assert pods[name].spec.node_name == node  # no re-bind
        for i in range(4):
            assert pods[f"todo{i}"].spec.node_name  # resumed work
