"""CLI + bootstrap ring: ktpu verbs against a bootstrapped cluster —
the kubectl/kubeadm surface over real HTTP."""

import io
import json
import time

import pytest

from kubernetes_tpu.api.types import RUNNING
from kubernetes_tpu.bootstrap import Cluster
from kubernetes_tpu.cli import run_command
from kubernetes_tpu.testing import MakePod


@pytest.fixture(scope="module")
def cluster():
    c = Cluster.up(nodes=3, capacity={"cpu": "8", "memory": "16Gi"})
    yield c
    c.down()


def ktpu(cluster, *argv):
    out, err = io.StringIO(), io.StringIO()
    rc = run_command(list(argv), client=cluster.client(), out=out, err=err)
    return rc, out.getvalue(), err.getvalue()


def wait_for(cond, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.1)
    return cond()


def test_bootstrap_brings_up_full_cluster(cluster):
    assert cluster.client().healthz()
    nodes, _ = cluster.client().list("Node")
    assert len(nodes) == 3
    # token-authenticated join rejects a bad token
    with pytest.raises(PermissionError):
        cluster.phase_join_nodes(1, token="bad.token")


def test_cli_get_nodes_and_api_resources(cluster):
    rc, out, _ = ktpu(cluster, "get", "nodes")
    assert rc == 0
    assert "hollow-0" in out and "Ready" in out
    rc, out, _ = ktpu(cluster, "api-resources")
    assert rc == 0 and "pods" in out and "storageclasses" in out


def test_cli_create_apply_get_delete_pod(cluster, tmp_path):
    manifest = tmp_path / "pod.yaml"
    manifest.write_text(
        """
kind: Pod
metadata:
  name: cli-pod
  uid: u-cli
spec:
  containers:
  - name: main
    image: app
    resources:
      requests:
        cpu: 250m
"""
    )
    rc, out, _ = ktpu(cluster, "create", "-f", str(manifest))
    assert rc == 0 and "pod/cli-pod created" in out
    # scheduler + hollow kubelet take it to Running
    assert wait_for(
        lambda: cluster.store.get_pod("default", "cli-pod").status.phase == RUNNING
    )
    rc, out, _ = ktpu(cluster, "get", "pods", "-o", "wide")
    assert rc == 0 and "cli-pod" in out and "hollow-" in out

    rc, out, _ = ktpu(cluster, "get", "pod", "cli-pod", "-o", "json")
    doc = json.loads(out)
    assert doc["metadata"]["name"] == "cli-pod"

    rc, out, _ = ktpu(cluster, "describe", "pod", "cli-pod")
    assert rc == 0 and "cli-pod" in out

    rc, out, _ = ktpu(cluster, "delete", "pod", "cli-pod")
    assert rc == 0 and "deleted" in out
    rc, _, err = ktpu(cluster, "get", "pod", "cli-pod")
    assert rc == 1 and "NotFound" in err


def test_cli_apply_is_create_or_update(cluster, tmp_path):
    manifest = tmp_path / "svc.yaml"
    manifest.write_text(
        """
kind: Service
metadata:
  name: web
selector:
  app: web
ports:
- name: http
  port: 80
  targetPort: 8080
"""
    )
    rc, out, _ = ktpu(cluster, "apply", "-f", str(manifest))
    assert rc == 0 and "created" in out
    vip = cluster.client().get("Service", "web").cluster_ip
    assert vip  # registry assigned one
    rc, out, _ = ktpu(cluster, "apply", "-f", str(manifest))
    assert rc == 0 and "configured" in out
    assert cluster.client().get("Service", "web").cluster_ip == vip  # kept


def test_cli_cordon_drain_taint_label(cluster):
    rc, out, _ = ktpu(cluster, "cordon", "hollow-1")
    assert rc == 0
    assert cluster.client().get("Node", "hollow-1").spec.unschedulable
    rc, out, _ = ktpu(cluster, "get", "nodes")
    assert "SchedulingDisabled" in out
    rc, _, _ = ktpu(cluster, "uncordon", "hollow-1")
    assert not cluster.client().get("Node", "hollow-1").spec.unschedulable

    rc, _, _ = ktpu(cluster, "taint", "hollow-1", "dedicated=tpu:NoSchedule")
    taints = cluster.client().get("Node", "hollow-1").spec.taints
    assert any(t.key == "dedicated" and t.effect == "NoSchedule" for t in taints)
    rc, _, _ = ktpu(cluster, "taint", "hollow-1", "dedicated-")
    assert not cluster.client().get("Node", "hollow-1").spec.taints

    rc, _, _ = ktpu(cluster, "label", "node", "hollow-1", "pool=a")
    assert cluster.client().get("Node", "hollow-1").metadata.labels["pool"] == "a"
    rc, _, _ = ktpu(cluster, "label", "node", "hollow-1", "pool-")
    assert "pool" not in cluster.client().get("Node", "hollow-1").metadata.labels


def test_cli_drain_evicts_pods(cluster):
    client = cluster.client()
    client.create(MakePod().name("victim").uid("u-v").req({"cpu": "100m"}).obj())
    assert wait_for(
        lambda: client.get("Pod", "victim") is not None
        and client.get("Pod", "victim").spec.node_name
    )
    node = client.get("Pod", "victim").spec.node_name
    rc, out, _ = ktpu(cluster, "drain", node)
    assert rc == 0 and "evicted" in out
    assert wait_for(lambda: client.get("Pod", "victim") is None)
    ktpu(cluster, "uncordon", node)


def test_cli_scale_and_top(cluster):
    from kubernetes_tpu.api.types import ReplicaSet
    from kubernetes_tpu.api.labels import LabelSelector

    rs = ReplicaSet(selector=LabelSelector(match_labels={"app": "s"}),
                    replicas=1,
                    template={"metadata": {"labels": {"app": "s"}},
                              "spec": {"containers": [
                                  {"name": "c", "image": "app",
                                   "resources": {"requests": {"cpu": "100m"}}}]}})
    rs.metadata.name = "scaleme"
    cluster.client().create(rs)
    rc, out, _ = ktpu(cluster, "scale", "rs", "scaleme", "--replicas", "3")
    assert rc == 0
    assert wait_for(
        lambda: len([p for p in cluster.store.list_pods()
                     if p.metadata.labels.get("app") == "s"]) == 3
    )
    rc, out, _ = ktpu(cluster, "top", "nodes")
    assert rc == 0 and "CPU(requests)" in out
    rc, out, _ = ktpu(cluster, "version")
    assert rc == 0 and "Client Version" in out


def test_kubectl_logs_end_to_end():
    """kubectl logs -> apiserver pods/log -> owning kubelet -> CRI log
    stream (reference registry/core/pod/rest/log.go)."""
    import io
    import time as _time

    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.cli.kubectl import run_command
    from kubernetes_tpu.kubelet import Kubelet
    from kubernetes_tpu.testing import MakePod

    store = ClusterStore()
    server = APIServer(store=store).start()
    kl = Kubelet(store, "n1", capacity={"cpu": "8", "memory": "16Gi"})
    kl.start()
    try:
        pod = MakePod().name("web").uid("u-web").container(image="app").obj()
        store.create_pod(pod)
        store.bind("default", "web", pod.uid, "n1")
        deadline = _time.time() + 5
        while _time.time() < deadline and \
                store.get_pod("default", "web").status.phase != "Running":
            _time.sleep(0.05)
        out = io.StringIO()
        rc = run_command(["--server", server.url, "logs", "web"], out=out)
        assert rc == 0
        assert "container started image=app" in out.getvalue()
        # pod without a kubelet: clean NotFound, not a crash
        p2 = MakePod().name("ghost").uid("u-ghost").obj()
        store.create_pod(p2)
        err = io.StringIO()
        rc = run_command(["--server", server.url, "logs", "ghost"],
                         out=io.StringIO(), err=err)
        assert rc == 1 and "NotFound" in err.getvalue()
    finally:
        kl.stop()
        server.shutdown_server()


def test_kubectl_get_with_selectors():
    """kubectl get -l / --field-selector filter SERVER-side
    (?labelSelector / ?fieldSelector ListOptions)."""
    import io

    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.cli.kubectl import run_command
    from kubernetes_tpu.testing import MakePod

    store = ClusterStore()
    server = APIServer(store=store).start()
    try:
        for i in range(4):
            p = MakePod().name(f"p{i}").uid(f"u{i}") \
                .label("app", "web" if i % 2 == 0 else "db").obj()
            store.create_pod(p)
            if i < 2:
                store.bind("default", f"p{i}", p.uid, "n1")
        out = io.StringIO()
        rc = run_command(["--server", server.url, "get", "pods",
                          "-l", "app=web"], out=out)
        assert rc == 0
        got = out.getvalue()
        assert "p0" in got and "p2" in got
        assert "p1" not in got and "p3" not in got
        out = io.StringIO()
        rc = run_command(["--server", server.url, "get", "pods",
                          "--field-selector", "spec.nodeName=n1",
                          "-l", "app=db"], out=out)
        assert rc == 0
        got = out.getvalue()
        assert "p1" in got and "p0" not in got and "p3" not in got
        # unsupported field: clean 400, not a crash
        from kubernetes_tpu.apiserver.rest import RestClient

        client = RestClient(server.url)
        try:
            client.list("Pod", "default",
                        field_selector="spec.bogusField=x")
            raise AssertionError("bogus field selector accepted")
        except RuntimeError as e:
            assert "field label not supported" in str(e)
    finally:
        server.shutdown_server()


def test_field_selector_validated_even_on_empty_results():
    """An unsupported field is the client's 400 regardless of whether
    any object exists to filter (upstream rejects unconditionally)."""
    from kubernetes_tpu.apiserver.rest import APIServer, RestClient
    from kubernetes_tpu.apiserver.store import ClusterStore

    store = ClusterStore()   # empty cluster
    server = APIServer(store=store).start()
    try:
        client = RestClient(server.url)
        try:
            client.list("Pod", "default",
                        field_selector="spec.bogus=x")
            raise AssertionError("bogus field accepted on empty list")
        except RuntimeError as e:
            assert "field label not supported" in str(e)
        # watches validate too
        code, payload = client._request(
            "GET", "/api/v1/pods?watch=1&fieldSelector=spec.bogus=x")
        assert code == 400
    finally:
        server.shutdown_server()


def test_field_selector_acronym_fields_resolve():
    """status.podIP must resolve to the pod_ip attribute — the naive
    per-capital underscore split produced 'pod_i_p', so '=' selectors
    silently matched nothing and '!=' matched everything."""
    import json as _json
    import threading
    import urllib.request

    from kubernetes_tpu.apiserver.rest import APIServer, RestClient
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.testing import MakePod

    store = ClusterStore()
    server = APIServer(store=store).start()
    try:
        a = MakePod().name("a").uid("u-a").obj()
        a.status.pod_ip = "10.0.0.5"
        b = MakePod().name("b").uid("u-b").obj()
        b.status.pod_ip = "10.0.0.6"
        store.create_pod(a)
        store.create_pod(b)
        client = RestClient(server.url)
        pods, _ = client.list(
            "Pod", "default", field_selector="status.podIP=10.0.0.5")
        assert [p.name for p in pods] == ["a"]
        pods, _ = client.list(
            "Pod", "default", field_selector="status.podIP!=10.0.0.5")
        assert [p.name for p in pods] == ["b"]
        # WATCH honors the same resolution
        got, done = [], threading.Event()

        def watcher():
            req = urllib.request.Request(
                server.url + "/api/v1/namespaces/default/pods"
                "?watch=1&fieldSelector=status.podIP%3D10.0.0.7")
            with urllib.request.urlopen(req, timeout=10) as resp:
                for line in resp:
                    got.append(_json.loads(line))
                    done.set()
                    return

        t = threading.Thread(target=watcher, daemon=True)
        t.start()
        import time as _time

        _time.sleep(0.3)
        noise = MakePod().name("noise").uid("u-n").obj()
        noise.status.pod_ip = "10.0.0.8"
        client.create(noise)
        signal = MakePod().name("signal").uid("u-s").obj()
        signal.status.pod_ip = "10.0.0.7"
        client.create(signal)
        assert done.wait(5)
        assert got[0]["object"]["metadata"]["name"] == "signal"
    finally:
        server.shutdown_server()


def test_selector_scoped_watch_streams_only_matches():
    import json as _json
    import threading
    import urllib.request

    from kubernetes_tpu.apiserver.rest import APIServer, RestClient
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.testing import MakePod

    store = ClusterStore()
    server = APIServer(store=store).start()
    try:
        got, done = [], threading.Event()

        def watcher():
            req = urllib.request.Request(
                server.url + "/api/v1/namespaces/default/pods"
                "?watch=1&labelSelector=app%3Dweb")
            with urllib.request.urlopen(req, timeout=10) as resp:
                for line in resp:
                    got.append(_json.loads(line))
                    done.set()
                    return

        t = threading.Thread(target=watcher, daemon=True)
        t.start()
        import time as _time

        _time.sleep(0.3)
        client = RestClient(server.url)
        client.create(MakePod().name("noise").label("app", "db").obj())
        client.create(MakePod().name("signal").label("app", "web").obj())
        assert done.wait(5)
        assert got[0]["object"]["metadata"]["name"] == "signal"
    finally:
        server.shutdown_server()


def test_kubectl_exec_round_trip_over_http():
    """kubectl exec -> apiserver pods/exec -> owning kubelet -> CRI
    ExecSync (VERDICT r4 next #6; reference kubectl/pkg/cmd/exec)."""
    import io
    import time as _time

    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.cli.kubectl import run_command
    from kubernetes_tpu.kubelet.kubelet import Kubelet
    from kubernetes_tpu.testing import MakePod

    store = ClusterStore()
    server = APIServer(store=store).start()
    kl = Kubelet(store, "n1", capacity={"cpu": "8", "memory": "16Gi"})
    kl.start()
    try:
        pod = MakePod().name("sh").uid("u-sh").container(image="app").obj()
        store.create_pod(pod)
        store.bind("default", "sh", pod.uid, "n1")
        deadline = _time.time() + 5
        while _time.time() < deadline and \
                store.get_pod("default", "sh").status.phase != "Running":
            _time.sleep(0.05)
        out = io.StringIO()
        rc = run_command(["--server", server.url, "exec", "sh", "--",
                          "ls", "/tmp"], out=out)
        assert rc == 0
        assert "exec:" in out.getvalue() and "ls" in out.getvalue()
        # the CRI recorded the exec
        assert any("ls" in str(p) for _, p in kl.runtime.exec_records)
        # unknown pod: clean NotFound
        err = io.StringIO()
        rc = run_command(["--server", server.url, "exec", "ghost", "--",
                          "true"], out=io.StringIO(), err=err)
        assert rc == 1 and "NotFound" in err.getvalue()
        # missing command: client-side error
        err = io.StringIO()
        rc = run_command(["--server", server.url, "exec", "sh"],
                         out=io.StringIO(), err=err)
        assert rc == 1 and "command" in err.getvalue()
    finally:
        kl.stop()
        server.shutdown_server()


def test_kubectl_rollout_status_history_undo_over_http():
    """rollout status/history/undo wired to the deployment controller's
    revision-annotated ReplicaSets (VERDICT r4 next #6; reference
    kubectl/pkg/cmd/rollout/rollout.go)."""
    import io
    import time as _time

    from kubernetes_tpu.api.labels import LabelSelector
    from kubernetes_tpu.api.types import Deployment
    from kubernetes_tpu.apiserver.rest import APIServer, RestClient
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.cli.kubectl import run_command
    from kubernetes_tpu.controllers import ControllerManager

    def wait_for(cond, timeout=10.0):
        deadline = _time.time() + timeout
        while _time.time() < deadline:
            if cond():
                return True
            _time.sleep(0.05)
        return False

    store = ClusterStore()
    server = APIServer(store=store).start()
    cm = ControllerManager(store, controllers=["deployment", "replicaset"])
    cm.start()
    try:
        d = Deployment(
            selector=LabelSelector(match_labels={"app": "web"}),
            replicas=2,
            template={"metadata": {"labels": {"app": "web"}},
                      "spec": {"containers": [{"name": "c",
                                               "image": "app:v1"}]}},
        )
        d.metadata.name = "web"
        d.metadata.annotations["kubernetes.io/change-cause"] = "deploy v1"
        client = RestClient(server.url)
        client.create(d)
        assert wait_for(lambda: len(store.list_pods()) == 2)

        # status: not rolled out until the RS reports ready replicas
        out = io.StringIO()
        run_command(["--server", server.url, "rollout", "status",
                     "deployment/web"], out=out)
        assert "web" in out.getvalue()

        # roll to v2 (a second revision)
        live = client.get("Deployment", "web")
        live.template = {"metadata": {"labels": {"app": "web"}},
                         "spec": {"containers": [{"name": "c",
                                                  "image": "app:v2"}]}}
        live.metadata.annotations["kubernetes.io/change-cause"] = \
            "deploy v2"
        client.update(live)
        assert wait_for(
            lambda: len(store.list_all_replica_sets()) == 2)

        out = io.StringIO()
        rc = run_command(["--server", server.url, "rollout", "history",
                          "deployment/web"], out=out)
        got = out.getvalue()
        assert rc == 0
        assert "deploy v1" in got and "deploy v2" in got

        # undo: back to v1's template, stamped as revision 3
        out = io.StringIO()
        rc = run_command(["--server", server.url, "rollout", "undo",
                          "deployment/web"], out=out)
        assert rc == 0 and "rolled back" in out.getvalue()
        assert wait_for(lambda: (
            client.get("Deployment", "web").template["spec"]
            ["containers"][0]["image"] == "app:v1"
        ))
        # the controller re-activates the v1 RS under a FRESH revision
        from kubernetes_tpu.controllers.deployment import rs_revision

        assert wait_for(lambda: max(
            (rs_revision(rs) for rs in store.list_all_replica_sets()),
            default=0) >= 3)

        # undo --to-revision targets an explicit entry
        err = io.StringIO()
        rc = run_command(["--server", server.url, "rollout", "undo",
                          "deployment/web", "--to-revision", "99"],
                         out=io.StringIO(), err=err)
        assert rc == 1 and "unable to find revision" in err.getvalue()
    finally:
        cm.stop()
        server.shutdown_server()


def test_kubectl_edit_round_trip_over_http(tmp_path):
    """kubectl edit: live object -> $EDITOR -> PUT back (reference
    kubectl/pkg/cmd/editor). A scripted EDITOR stands in for vi."""
    import io
    import os

    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.cli.kubectl import run_command
    from kubernetes_tpu.testing import MakePod

    store = ClusterStore()
    server = APIServer(store=store).start()
    try:
        store.create_pod(MakePod().name("editable").uid("u-e")
                         .label("app", "old").obj())
        editor = tmp_path / "editor.sh"
        editor.write_text("#!/bin/sh\nsed -i 's/old/new/' \"$1\"\n")
        os.chmod(editor, 0o755)
        os.environ["EDITOR"] = str(editor)
        try:
            out = io.StringIO()
            rc = run_command(["--server", server.url, "edit", "pod",
                              "editable"], out=out)
            assert rc == 0 and "edited" in out.getvalue()
            assert store.get_pod("default", "editable") \
                .metadata.labels["app"] == "new"
            # no-change editor: cancelled, object untouched
            noop = tmp_path / "noop.sh"
            noop.write_text("#!/bin/sh\ntrue\n")
            os.chmod(noop, 0o755)
            os.environ["EDITOR"] = str(noop)
            rv = store.get_pod("default",
                               "editable").metadata.resource_version
            out = io.StringIO()
            rc = run_command(["--server", server.url, "edit", "pod",
                              "editable"], out=out)
            assert rc == 0 and "cancelled" in out.getvalue()
            assert store.get_pod(
                "default", "editable").metadata.resource_version == rv
        finally:
            os.environ.pop("EDITOR", None)
    finally:
        server.shutdown_server()


def test_kubectl_port_forward_round_trip():
    """kubectl port-forward: local socket -> apiserver pods/{name}/
    portforward -> owning kubelet -> CRI port endpoint, echo verified
    end-to-end."""
    import io
    import socket
    import threading
    import time as _time

    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.cli.kubectl import Kubectl, run_command
    from kubernetes_tpu.apiserver.rest import RestClient
    from kubernetes_tpu.kubelet.kubelet import Kubelet
    from kubernetes_tpu.testing import MakePod

    store = ClusterStore()
    server = APIServer(store=store).start()
    kl = Kubelet(store, "n1", capacity={"cpu": "8", "memory": "16Gi"})
    kl.start()
    try:
        pod = MakePod().name("web").uid("u-w").container(image="app").obj()
        store.create_pod(pod)
        store.bind("default", "web", pod.uid, "n1")
        deadline = _time.time() + 5
        while _time.time() < deadline and \
                store.get_pod("default", "web").status.phase != "Running":
            _time.sleep(0.05)
        out = io.StringIO()
        k = Kubectl(RestClient(server.url), out=out, err=io.StringIO())
        t = threading.Thread(
            target=k.port_forward,
            args=("web", "default", 0, 8080), kwargs={"once": True},
            daemon=True)
        t.start()
        deadline = _time.time() + 5
        while _time.time() < deadline and \
                not hasattr(k, "forwarding_port"):
            _time.sleep(0.02)
        with socket.create_connection(
                ("127.0.0.1", k.forwarding_port), timeout=5) as c:
            c.sendall(b"GET / HTTP/1.0")
            c.shutdown(socket.SHUT_WR)
            got = b""
            while True:
                chunk = c.recv(65536)
                if not chunk:
                    break
                got += chunk
        assert b"port 8080 echo: GET / HTTP/1.0" in got
        assert b"web" in got
        t.join(timeout=5)
        # unknown pod: clean 400/404 over the wire, not a crash
        err = io.StringIO()
        k2 = Kubectl(RestClient(server.url), out=io.StringIO(), err=err)
        code, resp = k2.client._request(
            "POST", "/api/v1/namespaces/default/pods/ghost/portforward",
            {"port": 80, "data": ""})
        assert code == 404
    finally:
        kl.stop()
        server.shutdown_server()


def test_kubeadm_full_init_phase_sequence():
    """Cluster.up(full_init=True) runs the complete kubeadm phase
    sequence (reference cmd/kubeadm/app/cmd/phases/init): certs,
    wait-control-plane, kubeconfig, upload-config, mark-control-plane
    (labeled + tainted Node), and addons (kube-proxy DaemonSet on every
    node incl. the tainted control plane, CoreDNS Deployment + kube-dns
    Service) — reconciled by the cluster's OWN controllers."""
    import time as _time

    from kubernetes_tpu.bootstrap import Cluster

    c = Cluster.up(nodes=2, capacity={"cpu": "8", "memory": "16Gi"},
                   full_init=True)
    try:
        def wait_for(cond, timeout=20.0):
            deadline = _time.time() + timeout
            while _time.time() < deadline:
                if cond():
                    return True
                _time.sleep(0.1)
            return cond()

        # certs + kubeconfigs minted
        assert "admin" in c.pki and "BEGIN CERTIFICATE" in c.pki["admin"]
        assert c.kubeconfigs["admin"]["server"] == c.apiserver.url
        # upload-config
        cm = c.store.get_object("ConfigMap", "kube-system",
                                "kubeadm-config")
        assert cm is not None and "apiServer" in cm.data[
            "ClusterConfiguration"]
        # mark-control-plane: labeled + tainted
        cp = c.client().get("Node", "control-plane-0", namespace=None)
        assert "node-role.kubernetes.io/control-plane" in \
            cp.metadata.labels
        assert any(t.effect == "NoSchedule" for t in cp.spec.taints)
        # addons: kube-proxy lands on ALL 3 nodes (toleration lets it
        # onto the control plane); coredns only on the workers
        assert wait_for(lambda: len([
            p for p in c.store.list_pods()
            if p.metadata.labels.get("k8s-app") == "kube-proxy"
            and p.spec.node_name]) == 3)
        assert wait_for(lambda: len([
            p for p in c.store.list_pods()
            if p.metadata.labels.get("k8s-app") == "kube-dns"
            and p.spec.node_name]) == 2)
        for p in c.store.list_pods():
            if p.metadata.labels.get("k8s-app") == "kube-dns":
                assert p.spec.node_name != "control-plane-0"
        # kube-dns Service got a ClusterIP from the registry
        assert c.client().get("Service", "kube-dns",
                              "kube-system").cluster_ip
    finally:
        c.down()
