"""The client-certificate identity loop (VERDICT r4 missing #6 /
next #8): bootstrap token → CSR → auto-approve → signed cert →
fingerprint authn → node RBAC identity — kubeadm's TLS bootstrap
(reference ``apiserver/pkg/authentication/request/x509/x509.go``,
``bootstrappolicy`` node-bootstrapper, csrapproving/csrsigning
controllers)."""

import hashlib
import time

import pytest

from kubernetes_tpu.api.types import CertificateSigningRequest
from kubernetes_tpu.apiserver.rest import RestClient
from kubernetes_tpu.bootstrap import Cluster
from kubernetes_tpu.testing import MakePod


@pytest.fixture(scope="module")
def cluster():
    c = Cluster.up(nodes=2, capacity={"cpu": "8", "memory": "16Gi"})
    yield c
    c.down()


def test_join_mints_node_credentials(cluster):
    """phase_join_nodes completes the TLS bootstrap for every node."""
    assert set(cluster.node_credentials) == {"hollow-0", "hollow-1"}
    for cred in cluster.node_credentials.values():
        assert cred.startswith("cert:")


def test_cert_credential_authenticates_as_node_identity(cluster):
    cred = cluster.node_credentials["hollow-0"]
    node_client = RestClient(cluster.apiserver.url, token=cred)
    # the node role reads pods and services cluster-wide
    pods, _ = node_client.list("Pod", "default")
    assert isinstance(pods, list)
    # ...but cannot delete nodes (no such verb in system:node)
    with pytest.raises(PermissionError):
        node_client.delete("Node", "hollow-1", namespace=None)
    # auth can-i through the API agrees on the identity's shape
    code, payload = node_client._request(
        "POST", "/api/v1/selfsubjectaccessreviews",
        {"spec": {"resourceAttributes": {
            "verb": "get", "resource": "pods", "namespace": "default"}}})
    assert payload["status"]["allowed"] is True
    code, payload = node_client._request(
        "POST", "/api/v1/selfsubjectaccessreviews",
        {"spec": {"resourceAttributes": {
            "verb": "delete", "resource": "nodes"}}})
    assert payload["status"]["allowed"] is False


def test_bootstrap_token_is_csr_only(cluster):
    """The bootstrap token may run the CSR flow and NOTHING else
    (reference system:node-bootstrapper)."""
    boot = cluster.client(cluster.bootstrap_token)
    csrs, _ = boot.list("CertificateSigningRequest")
    assert any(c.metadata.name.startswith("node-csr-") for c in csrs)
    with pytest.raises(PermissionError):
        boot.list("Pod", "default")
    with pytest.raises(PermissionError):
        boot.create(MakePod().name("sneak").uid("u-sneak").obj())


def test_csr_username_is_server_stamped(cluster):
    """A client-claimed spec.username must not survive: the server
    stamps the AUTHENTICATED requester (reference CSR strategy
    PrepareForCreate) — otherwise any identity could impersonate a
    bootstrap token and mint node certs."""
    admin = cluster.client(cluster.component_tokens["admin"])
    csr = CertificateSigningRequest(
        request="CN=system:node:evil,O=system:nodes",
        signer_name="kubernetes.io/kube-apiserver-client-kubelet",
        username="system:bootstrap:node",   # claimed — must be ignored
    )
    csr.metadata.name = "evil-claim"
    admin.create(csr)
    live = admin.get("CertificateSigningRequest", "evil-claim",
                     namespace=None)
    assert live.username == "admin"
    # and the approver refuses it (admin is not a bootstrap/node user)
    time.sleep(0.5)
    live = admin.get("CertificateSigningRequest", "evil-claim",
                     namespace=None)
    assert not live.approved and not live.certificate


def test_forged_certificate_does_not_authenticate(cluster):
    """A CSR object whose status.certificate was never produced by the
    cluster CA must not mint an identity, even if written into the
    store directly."""
    forged = CertificateSigningRequest(
        request="CN=system:node:forged,O=system:nodes",
        signer_name="kubernetes.io/kube-apiserver-client-kubelet",
        username="system:bootstrap:node",
        certificate="-----BEGIN CERTIFICATE-----\nnot-from-the-ca\n"
                    "-----END CERTIFICATE-----\n",
    )
    forged.metadata.name = "forged"
    cluster.store.create_object("CertificateSigningRequest", forged)
    fp = hashlib.sha256(forged.certificate.encode()).hexdigest()
    attacker = RestClient(cluster.apiserver.url, token=f"cert:{fp}")
    with pytest.raises(PermissionError):
        attacker.list("Pod", "default")


def test_deleted_csr_revokes_the_credential(cluster):
    """Certificate revocation: the csrcleaner (or an admin delete)
    removing the CSR removes the fingerprint's authn entry."""
    token = cluster.bootstrap_token
    cred = cluster.tls_bootstrap("revoked-node", token)
    node_client = RestClient(cluster.apiserver.url, token=cred)
    node_client.list("Pod", "default")   # authenticates
    admin = cluster.client(cluster.component_tokens["admin"])
    admin.delete("CertificateSigningRequest", "node-csr-revoked-node",
                 namespace=None)
    with pytest.raises(PermissionError):
        node_client.list("Pod", "default")
