"""Shared diag formatter/parser (harness/diagfmt.py) and the
perf-regression report (tools/perf_report.py).

Covers the contracts the telemetry pipeline rests on:

- the ``diag:`` line round-trips through the ONE writer
  (``diagfmt.format_*``) and the ONE parser (``diagfmt.parse_diag``);
- the parser still reads the legacy hand-rolled format frozen into the
  committed ``BENCH_r01..r05`` tails;
- the e2e segment is rendered from the metrics-registry histogram's own
  accessors, so ``diag:`` and ``/metrics`` cannot disagree;
- a synthetic bench history with a deliberate out-of-band regression
  AND a within-noise wobble flags exactly the regression, with phase
  attribution from the row's telemetry;
- every committed ``BENCH_r*.json`` in the repo parses under the driver
  schema (tier-1 smoke: a malformed round fails CI, not a human);
- the headline row's ``telemetry`` sub-object survives into the
  driver-captured stdout tail (the same trap the REST row hit pre-PR 5:
  a row that prints too early falls off the tail).
"""

from __future__ import annotations

import json
import os

import pytest

from kubernetes_tpu.harness import diagfmt
from tools.perf_report import (
    _rows_from_tail,
    build_series,
    detect_regressions,
    load_round,
    load_rounds,
    noise_band,
    render,
    summarize_telemetry,
)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_HEADLINE = ("pods_scheduled_per_sec[SchedulingBasic 5000nodes/"
             "30000pods, TPU batch path]")

# a verbatim line from the committed BENCH_r05.json tail — the legacy
# hand-rolled format the parser must keep reading forever
_LEGACY_DIAG = (
    "    diag: commit=4.32s/8 device=1.34s/14 encode=2.37s/14 "
    "session[hits=7 rebuilds=7 state_only=7] chunk=4096 "
    "max_cycle=1.03s pad_warms=0 "
    "e2e_buckets[<=0.2:84 <=0.5:12441 <=1.0:17127 <=2.0:348]")


# ---------------------------------------------------------------------------
# diagfmt: one writer, one parser


class TestDiagFmtRoundTrip:
    def test_current_format_round_trips(self):
        segs = diagfmt.format_phases({
            "solve.commit": {"total_s": 4.32, "count": 8,
                             "p50_s": 0.4, "p99_s": 0.54},
            "solve.device": {"total_s": 1.34, "count": 14,
                             "p50_s": 0.05, "p99_s": 0.2},
        })
        sess = diagfmt.format_session(
            type("S", (), {"incremental_hits": 7, "rebuilds": 1,
                           "state_only_rebuilds": 1})(),
            chunk=4096, max_cycle_s=0.88, pad_warms=2)
        dev = diagfmt.format_devprof({
            "cycles": 8, "compiles": 1, "unexpected_compiles": 0,
            "warm_compiles": 1, "device_wait_share": 0.61,
            "pad_waste_pct": 12.5, "h2d_bytes": 52_400_000,
            "d2h_bytes": 960_000, "compile_detector": "listener",
            "max_cycle": {"cycle": 3, "block_s": 0.4, "dispatch_s": 0.01,
                          "encode_s": 0.05, "compiles": 0},
        })
        line = diagfmt.format_diag(segs + [sess, dev])
        parsed = diagfmt.parse_diag(line)
        assert parsed["phases"]["solve.commit"] == {
            "total_s": 4.32, "count": 8, "p99_ms": 540.0}
        assert parsed["session"]["hits"] == 7
        assert parsed["chunk"] == 4096
        assert parsed["max_cycle_s"] == pytest.approx(0.88)
        assert parsed["pad_warms"] == 2
        assert parsed["devprof"]["cycles"] == 8
        assert parsed["devprof"]["wait_share"] == pytest.approx(0.61)
        assert parsed["devprof"]["max_cycle_phase"] == "block"
        assert parsed["devprof"]["detector"] == "listener"

    def test_legacy_committed_format_parses(self):
        parsed = diagfmt.parse_diag(_LEGACY_DIAG)
        assert parsed["phases"]["commit"] == {"total_s": 4.32, "count": 8}
        assert parsed["phases"]["device"]["total_s"] == pytest.approx(1.34)
        assert parsed["session"] == {
            "hits": 7, "rebuilds": 7, "state_only": 7}
        assert parsed["chunk"] == 4096
        assert parsed["max_cycle_s"] == pytest.approx(1.03)
        assert parsed["pad_warms"] == 0
        assert parsed["e2e_buckets"] == {
            "0.2": 84, "0.5": 12441, "1.0": 17127, "2.0": 348}

    def test_non_diag_lines_return_none(self):
        assert diagfmt.parse_diag("[headline] batch run 1/3: ...") is None
        assert diagfmt.parse_diag('{"metric": "x"}') is None

    def test_e2e_segment_rendered_from_registry_histogram(self):
        """The e2e text and /metrics share one series: counts in the
        rendered bucket segment must equal the histogram's own
        bucket_counts, and the p99 must be the histogram's interpolated
        quantile — byte-for-byte the same numbers a scrape would see."""
        from kubernetes_tpu.metrics.registry import Histogram

        hist = Histogram("e2e_scheduling_duration_seconds", "t",
                         ("result",))
        for v in (0.1, 0.3, 0.3, 0.7, 0.9, 1.5):
            hist.observe(v, "scheduled")
        segs = diagfmt.format_e2e(hist)
        parsed = diagfmt.parse_diag(diagfmt.format_diag(segs))
        counts = hist.bucket_counts("scheduled")
        edges = list(hist.buckets) + ["inf"]
        expect = {str(edges[i]): c for i, c in enumerate(counts) if c}
        assert parsed["e2e_buckets"] == expect
        assert parsed["e2e_p99_ms"] == pytest.approx(
            hist.quantile(0.99, "scheduled") * 1000, abs=0.5)

    def test_e2e_empty_histogram_renders_nothing(self):
        from kubernetes_tpu.metrics.registry import Histogram

        hist = Histogram("e2e", "t", ("result",))
        assert diagfmt.format_e2e(hist) == []

    def test_pipeline_segment_round_trips(self):
        """The streaming-scheduler segment (ISSUE 14 satellite):
        depth + overlap share through the one writer / one parser."""
        seg = diagfmt.format_pipeline(
            {"depth": 3, "overlap": 0.437, "cycles": 12})
        parsed = diagfmt.parse_diag(diagfmt.format_diag([seg]))
        assert parsed["pipeline"]["depth"] == 3
        assert parsed["pipeline"]["overlap"] == pytest.approx(0.44)
        assert parsed["pipeline"]["cycles"] == 12
        # quiet conventions: no info (pipeline off) renders nothing
        assert diagfmt.format_pipeline(None) == ""
        assert diagfmt.format_pipeline({}) == ""

    def test_mirror_segment_round_trips(self):
        """The device-mirror segment (ISSUE 20 satellite): scatter
        counters + encode share through the one writer / one parser
        (the generic bracket grammar — no parser change)."""
        seg = diagfmt.format_mirror(
            {"events": 42, "scatter_mb": 1.2345, "reseeds": 1,
             "encode_share": 0.0037})
        parsed = diagfmt.parse_diag(diagfmt.format_diag([seg]))
        assert parsed["mirror"]["events"] == 42
        assert parsed["mirror"]["scatter_mb"] == pytest.approx(1.234,
                                                               abs=1e-3)
        assert parsed["mirror"]["encode_share"] == pytest.approx(0.0037)
        assert parsed["mirror"]["reseeds"] == 1
        # quiet conventions: mirror off (None info) renders nothing,
        # and a row without encode_share omits the key
        assert diagfmt.format_mirror(None) == ""
        assert diagfmt.format_mirror({}) == ""
        seg = diagfmt.format_mirror({"events": 1, "scatter_mb": 0.0,
                                     "reseeds": 0})
        parsed = diagfmt.parse_diag(diagfmt.format_diag([seg]))
        assert "encode_share" not in parsed["mirror"]


# ---------------------------------------------------------------------------
# synthetic trajectory: the flagging semantics


def _artifact(dirpath, n: int, value: float, runs=None, telemetry=None,
              diag: str = _LEGACY_DIAG, metric: str = _HEADLINE,
              extra: dict = None) -> None:
    row = {"metric": metric, "value": value, "unit": "pods/s",
           "p99_latency_ms": 994}
    if runs:
        row["runs"] = runs
    if telemetry:
        row["telemetry"] = telemetry
    if extra:
        row.update(extra)
    tail = "\n".join([
        "SchedulingBasic/batch: 30000 pods created",
        diag,
        f"[headline] batch run 1/1: {value} pods/s",
        json.dumps(row),
    ])
    doc = {"n": n, "cmd": "timeout 3600 python bench.py", "rc": 0,
           "tail": tail}
    with open(os.path.join(dirpath, f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump(doc, f)


class TestSyntheticTrajectory:
    def test_flags_regression_not_wobble(self, tmp_path):
        """r3 wobbles -7% (inside the ±30% tunnel band: NOT flagged —
        the r3→r4 false alarm this tool exists to prevent) while r4
        drops -54% (flagged, attributed to its telemetry)."""
        _artifact(tmp_path, 1, 7000.0, runs=[6800.0, 7000.0, 7200.0])
        _artifact(tmp_path, 2, 7150.0, runs=[7000.0, 7150.0, 7300.0])
        _artifact(tmp_path, 3, 6500.0, runs=[6400.0, 6500.0, 6700.0])
        _artifact(tmp_path, 4, 3200.0, runs=[3100.0, 3200.0, 3400.0],
                  telemetry={
                      "cycles": 8, "compiles": 2, "unexpected_compiles": 2,
                      "device_wait_share": 0.82, "pad_waste_pct": 4.0,
                      "max_cycle": {"cycle": 5, "rebuild": "full",
                                    "compiles": 2, "block_s": 2.0},
                  })
        series = build_series(load_rounds(str(tmp_path)))
        assert len(series[_HEADLINE]) == 4
        flags = detect_regressions(series)
        assert len(flags) == 1
        (flag,) = flags
        assert flag["round"] == 4
        assert flag["delta_pct"] < -30.0
        # attribution names the compile-inside-measured-cycle and the
        # wait share — the "what regressed" ships with the flag
        assert "compile" in flag["attribution"]
        assert "device-wait share 82%" in flag["attribution"]
        # and the human rendering marks exactly that row
        text = render(series, flags)
        assert text.count("REGRESSION") == 1

    def test_legacy_rounds_attribute_from_diag_phases(self, tmp_path):
        """Pre-telemetry rounds attribute a flagged drop by comparing
        parsed diag phase totals against the previous round's."""
        fast = ("    diag: commit=1.30s/8 device=0.21s/8 encode=0.28s/8 "
                "session[hits=7 rebuilds=1 state_only=1] chunk=4096 "
                "max_cycle=0.88s pad_warms=0 e2e_buckets[<=1.0:30000]")
        _artifact(tmp_path, 1, 7000.0, diag=fast)
        _artifact(tmp_path, 2, 3000.0, diag=_LEGACY_DIAG)  # commit grew
        flags = detect_regressions(build_series(load_rounds(str(tmp_path))))
        (flag,) = flags
        assert "commit" in flag["attribution"]

    def test_regression_cannot_widen_its_own_band(self, tmp_path):
        """The judging band comes from the PRIOR rounds only: a round
        that regresses AND blows up its own run-to-run spread (the
        classic recompile-in-some-runs signature) is still flagged."""
        _artifact(tmp_path, 1, 4000.0, runs=[3900.0, 4000.0, 4100.0])
        _artifact(tmp_path, 2, 4050.0, runs=[3950.0, 4050.0, 4150.0])
        _artifact(tmp_path, 3, 2800.0, runs=[2000.0, 2800.0, 4100.0])
        flags = detect_regressions(
            build_series(load_rounds(str(tmp_path))))
        (flag,) = flags
        assert flag["round"] == 3
        assert flag["band_pct"] == pytest.approx(30.0)  # prior floor

    def test_persistent_regression_stays_flagged(self, tmp_path):
        """The r5 GangScheduling shape: a drop with NO later recovery
        round stays an open flag and still gates --strict."""
        from tools.perf_report import main, open_regressions

        gang = ("pods_scheduled_per_sec[GangScheduling 5000nodes/"
                "30000pods, TPU batch path]")
        _artifact(tmp_path, 1, 4400.0, metric=gang)
        _artifact(tmp_path, 2, 4390.0, metric=gang)
        _artifact(tmp_path, 3, 2846.0, metric=gang)
        flags = detect_regressions(
            build_series(load_rounds(str(tmp_path))))
        assert len(flags) == 1
        assert "recovered_round" not in flags[0]
        assert open_regressions(flags) == flags
        assert main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_recovered_regression_stops_gating(self, tmp_path):
        """ISSUE 14 satellite: once a later round lands back inside
        the band the drop was judged against, the old flag retires —
        it no longer gates --strict, but stays reported as recovered
        provenance. (The GangScheduling acceptance: the pipeline row
        landing in-band must silence the r5 flag without rewriting
        committed artifacts.)"""
        from tools.perf_report import main, open_regressions

        gang = ("pods_scheduled_per_sec[GangScheduling 5000nodes/"
                "30000pods, TPU batch path]")
        _artifact(tmp_path, 1, 4400.0, metric=gang)
        _artifact(tmp_path, 2, 4390.0, metric=gang)
        _artifact(tmp_path, 3, 2846.0, metric=gang)
        _artifact(tmp_path, 4, 4300.0, metric=gang)   # back in band
        flags = detect_regressions(
            build_series(load_rounds(str(tmp_path))))
        assert len(flags) == 1
        assert flags[0]["recovered_round"] == 4
        assert open_regressions(flags) == []
        assert main(["--dir", str(tmp_path), "--strict"]) == 0
        # the human report still names the recovery
        text = render(build_series(load_rounds(str(tmp_path))), flags)
        assert "recovered" in text
        assert "REGRESSION" not in text

    def test_stray_bench_named_files_are_ignored(self, tmp_path):
        _artifact(tmp_path, 1, 7000.0)
        # matches the glob, not the round-name contract — must be
        # skipped, not crash the loader (and so the tier-1 smoke)
        (tmp_path / "BENCH_rest.json").write_text("not even json")
        rounds = load_rounds(str(tmp_path))
        assert [r["round"] for r in rounds] == [1]

    def test_noise_band_from_repeat_runs(self):
        points = [{"value": 1000.0, "runs": [600.0, 1000.0, 1400.0]}]
        assert noise_band(points) == pytest.approx(0.8)   # spread wins
        assert noise_band([{"value": 1000.0, "runs": None}]) == \
            pytest.approx(0.30)                           # floor

    def test_schema_drift_raises(self, tmp_path):
        p = tmp_path / "BENCH_r01.json"
        p.write_text(json.dumps({"n": 1, "cmd": "x", "rc": 0}))  # no tail
        with pytest.raises(ValueError, match="tail"):
            load_round(str(p))


# ---------------------------------------------------------------------------
# the sustained-arrival family gate (ISSUE 14 satellite)


class TestSustainedFlags:
    _METRIC = ("sustained_arrival[open-loop 5000/s 240nodes/30000pods "
               "seed=14, store-direct replay engine]")

    def _row(self, tmp_path, n, **extra):
        base = {"p99_arrival_to_bind_ms": 180, "lost_pods": 0,
                "rate_normalized_throughput": 0.99,
                "telemetry": {"overlap_share": 0.6,
                              "overlapped_cycles": 40},
                "freshness": {"slo": {"snapshot_staleness": "ok",
                                      "schedule_latency": "ok"}}}
        base.update(extra)
        _artifact(tmp_path, n, 4900.0, metric=self._METRIC, extra=base)

    def test_green_row_passes(self, tmp_path):
        from tools.perf_report import main, sustained_flags

        self._row(tmp_path, 1)
        assert sustained_flags(load_rounds(str(tmp_path))) == []
        assert main(["--dir", str(tmp_path), "--strict"]) == 0

    def test_p99_over_budget_gates_strict(self, tmp_path):
        from tools.perf_report import main, sustained_flags

        self._row(tmp_path, 1, p99_arrival_to_bind_ms=812)
        (flag,) = sustained_flags(load_rounds(str(tmp_path)))
        assert "812ms > 500ms" in flag["problems"][0]
        assert main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_lost_pods_and_red_staleness_flagged(self, tmp_path):
        from tools.perf_report import sustained_flags

        self._row(tmp_path, 1, lost_pods=3,
                  freshness={"slo": {"snapshot_staleness": "violated"}})
        (flag,) = sustained_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "lost_pods=3" in probs
        assert "snapshot_staleness SLO violated" in probs

    def test_zero_overlap_flagged(self, tmp_path):
        from tools.perf_report import sustained_flags

        self._row(tmp_path, 1,
                  telemetry={"overlap_share": 0.0,
                             "overlapped_cycles": 0})
        (flag,) = sustained_flags(load_rounds(str(tmp_path)))
        assert "degenerated" in flag["problems"][0]


class TestUpgradeFlags:
    _METRIC = ("upgrade_roll[open-loop 5000/s 3part+2sched rolling "
               "restart, 30000pods seed=16, REST fabric]")

    def _row(self, tmp_path, n, **extra):
        base = {"p99_arrival_to_bind_ms": 120, "lost_pods": 0,
                "lost_watch_events": 0, "duplicated_events": 0,
                "unmoved_relists": 0, "frozen_ms_max": 330.0,
                "freeze_budget_ms": 2000.0, "codec_failures": 0,
                "codec_renegotiations": 8,
                "rolled_exactly_once": True, "invariants_ok": True,
                "slo_verdicts_ok": True}
        base.update(extra)
        _artifact(tmp_path, n, 4100.0, metric=self._METRIC,
                  extra=base)

    def test_green_roll_passes(self, tmp_path):
        from tools.perf_report import main, upgrade_flags

        self._row(tmp_path, 1)
        assert upgrade_flags(load_rounds(str(tmp_path))) == []
        assert main(["--dir", str(tmp_path), "--strict"]) == 0

    def test_lost_pod_gates_strict(self, tmp_path):
        from tools.perf_report import main, upgrade_flags

        self._row(tmp_path, 1, lost_pods=2)
        (flag,) = upgrade_flags(load_rounds(str(tmp_path)))
        assert "lost_pods=2" in flag["problems"][0]
        assert main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_lost_and_duplicated_events_flagged(self, tmp_path):
        from tools.perf_report import upgrade_flags

        self._row(tmp_path, 1, lost_watch_events=1,
                  duplicated_events=3)
        (flag,) = upgrade_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "lost_watch_events=1" in probs
        assert "duplicated_events=3" in probs

    def test_freeze_budget_overrun_flagged(self, tmp_path):
        from tools.perf_report import upgrade_flags

        self._row(tmp_path, 1, frozen_ms_max=2750.0)
        (flag,) = upgrade_flags(load_rounds(str(tmp_path)))
        assert "frozen_ms_max 2750.0 > budget 2000ms" \
            in flag["problems"][0]

    def test_red_slo_and_p99_flagged(self, tmp_path):
        from tools.perf_report import main, upgrade_flags

        self._row(tmp_path, 1, slo_verdicts_ok=False,
                  p99_arrival_to_bind_ms=812)
        (flag,) = upgrade_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "812ms > 500ms" in probs
        assert "SLO went red" in probs
        assert main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_codec_failure_and_double_roll_flagged(self, tmp_path):
        from tools.perf_report import upgrade_flags

        self._row(tmp_path, 1, codec_failures=1,
                  rolled_exactly_once=False, unmoved_relists=2)
        (flag,) = upgrade_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "codec_failures=1" in probs
        assert "not exactly-once" in probs
        assert "unmoved_relists=2" in probs

    def test_flags_survive_json_mode(self, tmp_path, capsys):
        from tools.perf_report import main

        self._row(tmp_path, 1, lost_pods=1)
        main(["--dir", str(tmp_path), "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert len(doc["upgrade_flags"]) == 1


class TestFederationFlags:
    _SPILL = ("federation_spill[open-loop 300/s 3clusters saturation "
              "spillover, 900pods seed=18, REST fabric]")
    _LOSS = ("federation_loss[open-loop 300/s 3clusters cluster-loss "
             "SIGKILL, 900pods seed=18, REST fabric]")

    def _row(self, tmp_path, n, metric=None, **extra):
        base = {"lost_pods": 0, "gang_splits": 0,
                "survivor_relists": 0, "per_cluster_slo_ok": True,
                "spilled": 31, "failovers": 0, "recovery_ratio": 1.0,
                "slo_verdicts_ok": True, "invariants_ok": True}
        base.update(extra)
        _artifact(tmp_path, n, 280.0,
                  metric=metric or self._SPILL, extra=base)

    def test_green_row_passes(self, tmp_path):
        from tools.perf_report import federation_flags, main

        self._row(tmp_path, 1)
        assert federation_flags(load_rounds(str(tmp_path))) == []
        assert main(["--dir", str(tmp_path), "--strict"]) == 0

    def test_lost_pod_gates_strict(self, tmp_path):
        from tools.perf_report import federation_flags, main

        self._row(tmp_path, 1, lost_pods=2)
        (flag,) = federation_flags(load_rounds(str(tmp_path)))
        assert "lost_pods=2" in flag["problems"][0]
        assert main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_gang_split_and_survivor_relist_flagged(self, tmp_path):
        from tools.perf_report import federation_flags

        self._row(tmp_path, 1, gang_splits=1, survivor_relists=2)
        (flag,) = federation_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "gang_splits=1" in probs
        assert "survivor_relists=2" in probs

    def test_red_per_cluster_slo_gates_strict(self, tmp_path):
        from tools.perf_report import federation_flags, main

        self._row(tmp_path, 1, per_cluster_slo_ok=False)
        (flag,) = federation_flags(load_rounds(str(tmp_path)))
        assert "per-cluster SLO went red" in flag["problems"][0]
        assert main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_low_recovery_ratio_needs_a_failover(self, tmp_path):
        from tools.perf_report import federation_flags

        # no failover happened: a low ratio is vacuous, not a flag
        self._row(tmp_path, 1, recovery_ratio=0.0, failovers=0)
        assert federation_flags(load_rounds(str(tmp_path))) == []
        self._row(tmp_path, 2, metric=self._LOSS,
                  recovery_ratio=0.5, failovers=1)
        (flag,) = federation_flags(load_rounds(str(tmp_path)))
        assert "recovery_ratio 0.50 < 0.8" in flag["problems"][0]

    def test_dry_spill_row_flagged(self, tmp_path):
        from tools.perf_report import federation_flags

        self._row(tmp_path, 1, spilled=0)
        (flag,) = federation_flags(load_rounds(str(tmp_path)))
        assert "spilled=0" in flag["problems"][0]
        # a LOSS row with spilled=0 is fine — spill is not its job
        self._row(tmp_path, 2, metric=self._LOSS, spilled=0,
                  failovers=1)
        flags = federation_flags(load_rounds(str(tmp_path)))
        assert [f["round"] for f in flags] == [1]

    def test_invariant_failure_carries_reason(self, tmp_path):
        from tools.perf_report import federation_flags

        self._row(tmp_path, 1, invariants_ok=False,
                  invariants={"failed": "gang fg-3 split 2 clusters"},
                  slo_verdicts_ok=False)
        (flag,) = federation_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "gang fg-3 split 2 clusters" in probs
        assert "fleet freshness SLO went red" in probs

    def test_flags_survive_json_mode(self, tmp_path, capsys):
        from tools.perf_report import main

        self._row(tmp_path, 1, lost_pods=1)
        main(["--dir", str(tmp_path), "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert len(doc["federation_flags"]) == 1


class TestReadtierFlags:
    _ARM = ("watchherd[320 informers R=4, 289 events open-loop 12/s "
            "seed=16, REST fabric]")
    _SCALING = ("watchherd_scaling[R=4 vs R=0, 320 informers seed=16, "
                "per owner-cpu-second]")
    _CELL = "watchherd_cell[replica_kill seed=16]"

    def _arm(self, tmp_path, n, **extra):
        base = {"replicas": 4, "lost_events": 0,
                "unconverged_informers": 0, "dup_suppressed": 0,
                "relists": 0, "replica_reads": 12,
                "replication_lag_p99_ms": 80.0, "lag_budget_ms": 500.0,
                "invariants_ok": True,
                "freshness": {"slo": {"replication_lag": "ok"}}}
        base.update(extra)
        _artifact(tmp_path, n, 5000.0, metric=self._ARM, extra=base)

    def _scaling(self, tmp_path, n, **extra):
        base = {"read_scaling_x": 10.4, "read_scaling_floor_x": 1.5,
                "write_flat_ok": True, "write_ratio": 1.0,
                "differential_match": True, "invariants_ok": True}
        base.update(extra)
        _artifact(tmp_path, n, 10.4, metric=self._SCALING, extra=base)

    def _cell(self, tmp_path, n, **extra):
        base = {"ok": True, "lost_events": 0,
                "relists_beyond_faulted": 0}
        base.update(extra)
        _artifact(tmp_path, n, 1.0, metric=self._CELL, extra=base)

    def test_green_rows_pass(self, tmp_path):
        from tools.perf_report import main, readtier_flags

        self._arm(tmp_path, 1)
        self._scaling(tmp_path, 2)
        self._cell(tmp_path, 3)
        assert readtier_flags(load_rounds(str(tmp_path))) == []
        assert main(["--dir", str(tmp_path), "--strict"]) == 0

    def test_lost_events_gate_strict(self, tmp_path):
        from tools.perf_report import main, readtier_flags

        self._arm(tmp_path, 1, lost_events=3, unconverged_informers=3)
        (flag,) = readtier_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "lost_events=3" in probs
        assert "unconverged_informers=3" in probs
        assert main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_dups_and_relists_flagged(self, tmp_path):
        from tools.perf_report import readtier_flags

        self._arm(tmp_path, 1, dup_suppressed=2, relists=5)
        (flag,) = readtier_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "dup_suppressed=2" in probs
        assert "relists=5" in probs

    def test_unused_replicas_flagged(self, tmp_path):
        from tools.perf_report import readtier_flags

        # reads never touched a replica while replicas were advertised
        self._arm(tmp_path, 1, replica_reads=0)
        (flag,) = readtier_flags(load_rounds(str(tmp_path)))
        assert "replica_reads=0" in flag["problems"][0]
        # vacuous on the replicas-off differential arm
        self._arm(tmp_path, 2, replicas=0, replica_reads=0)
        flags = readtier_flags(load_rounds(str(tmp_path)))
        assert [f["round"] for f in flags] == [1]

    def test_lag_over_budget_and_red_slo_gate_strict(self, tmp_path):
        from tools.perf_report import main, readtier_flags

        self._arm(tmp_path, 1, replication_lag_p99_ms=740.0,
                  freshness={"slo": {"replication_lag": "violated"}})
        (flag,) = readtier_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "lag p99 740.0ms over the 500ms budget" in probs
        assert "freshness SLO red: replication_lag" in probs
        assert main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_scaling_below_floor_gates_strict(self, tmp_path):
        from tools.perf_report import main, readtier_flags

        self._scaling(tmp_path, 1, read_scaling_x=1.2,
                      invariants_ok=False)
        (flag,) = readtier_flags(load_rounds(str(tmp_path)))
        assert "read scaling 1.20x < 1.5x floor" in flag["problems"][0]
        assert main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_write_regression_and_differential_flagged(self, tmp_path):
        from tools.perf_report import readtier_flags

        self._scaling(tmp_path, 1, write_flat_ok=False,
                      write_ratio=0.7, differential_match=False,
                      invariants_ok=False)
        (flag,) = readtier_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "write throughput regressed" in probs
        assert "differential arms disagree" in probs

    def test_failed_cell_gates_strict(self, tmp_path):
        from tools.perf_report import main, readtier_flags

        self._cell(tmp_path, 1, ok=False,
                   failure="2 relists beyond the killed replica",
                   lost_events=1, relists_beyond_faulted=2)
        (flag,) = readtier_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "2 relists beyond the killed replica" in probs
        assert "lost_events=1" in probs
        assert "relists_beyond_faulted=2" in probs
        assert main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_flags_survive_json_mode(self, tmp_path, capsys):
        from tools.perf_report import main

        self._arm(tmp_path, 1, lost_events=1)
        main(["--dir", str(tmp_path), "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert len(doc["readtier_flags"]) == 1

    def test_committed_watchherd_log_is_strict_clean(self):
        from tools.perf_report import readtier_flags

        path = os.path.join(_REPO_ROOT, "watchherd_rows.log")
        with open(path) as f:
            rows = _rows_from_tail(f.read())
        assert any(r["metric"].startswith("watchherd[") for r in rows)
        assert any(r["metric"].startswith("watchherd_scaling[")
                   for r in rows)
        assert any(r["metric"].startswith("watchherd_cell[")
                   for r in rows)
        fake_round = [{"round": 0, "rows": rows}]
        assert readtier_flags(fake_round) == []
        # the committed scaling row proves the headline claims
        (srow,) = [r for r in rows
                   if r["metric"].startswith("watchherd_scaling[")]
        assert srow["read_scaling_x"] >= srow["read_scaling_floor_x"]
        assert srow["write_flat_ok"] and srow["differential_match"]


# ---------------------------------------------------------------------------
# committed artifacts: the tier-1 smoke over the real trajectory


class TestCommittedArtifacts:
    def test_every_committed_round_parses(self):
        rounds = load_rounds(_REPO_ROOT)
        assert len(rounds) >= 5, "committed BENCH_r*.json went missing"
        for rnd in rounds:
            assert rnd["rows"], f"round {rnd['round']} yielded no rows"

    def test_headline_family_spans_all_rounds(self):
        rounds = load_rounds(_REPO_ROOT)
        series = build_series(rounds)
        points = series.get(_HEADLINE, [])
        assert len(points) == len(rounds), \
            "headline row missing from a committed round"
        assert all(p["value"] > 0 for p in points)

    def test_report_renders_and_cli_exits_zero(self, capsys):
        from tools.perf_report import main

        assert main(["--dir", _REPO_ROOT]) == 0
        out = capsys.readouterr().out
        assert "SchedulingBasic" in out
        assert "noise band" in out

    def test_json_mode_is_machine_readable(self, capsys):
        from tools.perf_report import main

        assert main(["--dir", _REPO_ROOT, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["rounds"] == sorted(doc["rounds"])
        assert _HEADLINE in doc["series"]


# ---------------------------------------------------------------------------
# telemetry stream cross-check


class TestTelemetryStream:
    def test_jsonl_summary_matches_profiler_summary(self, tmp_path):
        """A bench row's committed telemetry sub-object can be
        cross-checked against the raw KTPU_TELEMETRY stream: the same
        cycles aggregate to the same compile count, wait share, pad
        waste and transfer volume through both paths."""
        from kubernetes_tpu.observability.devprof import DevProfiler

        p = DevProfiler(enabled=True, use_listener=False,
                        telemetry_dir=str(tmp_path))
        rec = p.begin_cycle(cycle=-1, pad=256, real=8, warming=True)
        p.phase("block", 1.0)
        p.end_cycle(rec)
        for i in range(4):
            rec = p.begin_cycle(cycle=i, pad=256, real=192)
            p.phase("encode", 0.02)
            p.phase("dispatch", 0.01)
            p.phase("block", 0.10)
            p.add_bytes("h2d", 1_000_000)
            p.add_bytes("d2h", 2_048)
            p.end_cycle(rec)
        p.close()
        live = p.summary()
        stream = summarize_telemetry(str(tmp_path))
        assert stream["files"] == 1
        assert stream["cycles"] == live["cycles"] == 4
        assert stream["warming_cycles"] == 1
        assert stream["h2d_bytes"] == live["h2d_bytes"]
        assert stream["device_wait_share"] == pytest.approx(
            live["device_wait_share"], abs=0.01)
        assert stream["pad_waste_pct"] == pytest.approx(
            live["pad_waste_pct"], abs=0.01)


# ---------------------------------------------------------------------------
# tier-1 guard: telemetry survives the driver tail capture


class TestBenchTailGuard:
    def test_run_one_attaches_devprof_summary(self, monkeypatch):
        """bench.run_one carries the median run's devprof summary into
        the row JSON as ``telemetry`` — the attach point the acceptance
        criterion rests on."""
        import bench
        from kubernetes_tpu.harness.perf import BenchmarkResult

        tel = {"cycles": 8, "compiles": 1, "unexpected_compiles": 0,
               "device_wait_share": 0.4, "pad_waste_pct": 7.5}

        def fake_run_workload(name, ops, **kw):
            return BenchmarkResult(
                name=name, total_pods=1000, measured_pods=1000,
                duration_seconds=1.0, pods_per_second=5000.0,
                throughput={}, metrics={"Perc99": 900.0}, telemetry=tel)

        monkeypatch.setattr(bench, "make_workload", lambda *a, **k: [])
        monkeypatch.setattr(bench, "run_workload", fake_run_workload)
        row = bench.run_one("headline", "SchedulingBasic", 200, 0, 1000,
                            serial_rate=100.0, repeat=1)
        assert row["telemetry"] == tel

    def test_headline_telemetry_survives_tail_capture(self, capsys,
                                                      monkeypatch):
        """The driver captures the LAST bytes of stdout: the headline
        row must print last (so its telemetry cannot fall off the tail
        — the trap the REST row hit pre-PR 5) and the committed-artifact
        parser must recover the sub-object from that tail."""
        import bench

        tel = {"cycles": 8, "compiles": 0, "unexpected_compiles": 0,
               "device_wait_share": 0.35, "pad_waste_pct": 3.1,
               "max_cycle": {"cycle": 6, "rebuild": "none",
                             "compiles": 0, "block_s": 0.2}}

        def fake_run_one(key, name, nodes, init_pods, measure_pods,
                         serial_rate, repeat=1):
            row = {"metric": f"pods_scheduled_per_sec[{name} {key}]",
                   "value": 7000.0, "unit": "pods/s",
                   "vs_baseline": 10.0}
            if key == "headline":
                row["telemetry"] = tel
            return row

        def fake_run_rest_one(nodes, measure_pods, serial_rate, qps,
                              repeat=1):
            return {"metric":
                    "pods_scheduled_per_sec[SchedulingBasic REST fabric]",
                    "value": 4500.0, "unit": "pods/s",
                    "vs_baseline": 70.0,
                    "store_direct_pods_per_sec": 7500.0,
                    "fabric_overhead_ratio": 0.6}

        def fake_run_qos_one(nodes, measure_pods, serial_rate, qps,
                             tenants=3, solo_baseline=None):
            return {"metric": "noisy_tenant_qos[SchedulingBasic]",
                    "value": 3000.0, "unit": "pods/s",
                    "vs_baseline": 48.0, "p99_ratio_vs_solo": 1.3,
                    "qos_ok": True}

        def fake_run_scale10x_one(serial_rate, qps, quick=False):
            return {"metric": "pods_scheduled_per_sec[Scale10x "
                              "50000nodes/500000pods, partitioned "
                              "fabric 4p x 2r]",
                    "value": 4200.0, "unit": "pods/s",
                    "vs_baseline": 68.0,
                    "ab": {"sharding_pays": True},
                    "invariants": {"lost_pods": 0, "double_binds": 0}}

        monkeypatch.setattr(bench, "run_one", fake_run_one)
        monkeypatch.setattr(bench, "run_rest_one", fake_run_rest_one)
        monkeypatch.setattr(bench, "run_qos_one", fake_run_qos_one)
        monkeypatch.setattr(bench, "run_scale10x_one",
                            fake_run_scale10x_one)
        monkeypatch.setattr(bench.sys, "argv",
                            ["bench.py", "--skip-serial"])
        bench.main()
        # simulate the driver's tail capture: keep only the last 2KB
        tail = capsys.readouterr().out[-2048:]
        rows = _rows_from_tail(tail)
        assert rows, "tail capture lost every row"
        headline = rows[-1]
        assert "headline" in headline["metric"]
        assert headline["telemetry"] == tel


# ---------------------------------------------------------------------------
# device-mirror flags (ISSUE 20)


class TestMirrorFlags:
    _ON = ("mirror_sustained[arm=on, open-loop 5000/s 240nodes/"
           "30000pods seed=14, store-direct replay engine]")
    _AB = ("mirror_ab[sustained 30000pods @ 5000/s on/off + seeded "
           "node_kill differential]")

    def _on_row(self, tmp_path, n, **extra):
        base = {"mirror_arm": "on", "encode_share": 0.004,
                "encode_share_budget": 0.05,
                "mirror": {"events": 12, "catch_ups": 9,
                           "scatter_mb": 0.4, "reseeds": 0},
                "reseeds_allowed": 0,
                "h2d_per_cycle_bytes": 106_500,
                "h2d_per_cycle_budget_bytes": 618_497,
                "p99_arrival_to_bind_ms": 180, "p99_budget_ms": 500,
                "lost_pods": 0, "invariants_ok": True}
        base.update(extra)
        _artifact(tmp_path, n, 4900.0, metric=self._ON, extra=base)

    def _ab_row(self, tmp_path, n, **extra):
        base = {"mirror_on_pods_per_sec": 4900.0,
                "mirror_off_pods_per_sec": 4880.0,
                "h2d_per_cycle_on_bytes": 106_500,
                "h2d_per_cycle_off_bytes": 108_000,
                "differential_match": True, "invariants_ok": True}
        base.update(extra)
        _artifact(tmp_path, n, 0.4, metric=self._AB, extra=base)

    def test_green_rows_pass(self, tmp_path):
        from tools.perf_report import main, mirror_flags

        self._on_row(tmp_path, 1)
        self._ab_row(tmp_path, 2)
        assert mirror_flags(load_rounds(str(tmp_path))) == []
        assert main(["--dir", str(tmp_path), "--strict"]) == 0

    def test_encode_share_over_budget_gates_strict(self, tmp_path):
        from tools.perf_report import main, mirror_flags

        self._on_row(tmp_path, 1, encode_share=0.31)
        (flag,) = mirror_flags(load_rounds(str(tmp_path)))
        assert "encode share 0.3100 >= 0.05" in flag["problems"][0]
        assert main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_unexpected_reseed_flagged(self, tmp_path):
        from tools.perf_report import mirror_flags

        self._on_row(tmp_path, 1,
                     mirror={"events": 12, "catch_ups": 9,
                             "scatter_mb": 0.4, "reseeds": 3})
        (flag,) = mirror_flags(load_rounds(str(tmp_path)))
        assert "reseeds=3 > 0 allowed" in flag["problems"][0]

    def test_h2d_over_committed_budget_gates_strict(self, tmp_path):
        from tools.perf_report import main, mirror_flags

        self._on_row(tmp_path, 1, h2d_per_cycle_bytes=700_000)
        (flag,) = mirror_flags(load_rounds(str(tmp_path)))
        assert "700,000B >= the committed donation-row budget " \
               "618,497B" in flag["problems"][0]
        assert main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_off_arm_not_held_to_mirror_budgets(self, tmp_path):
        """The reference arm re-encodes node columns by design: its
        encode share and reseeds are not defects."""
        from tools.perf_report import mirror_flags

        self._on_row(tmp_path, 1, mirror_arm="off", mirror={},
                     encode_share=0.4)
        assert mirror_flags(load_rounds(str(tmp_path))) == []

    def test_lost_pods_and_p99_flag_either_arm(self, tmp_path):
        from tools.perf_report import mirror_flags

        self._on_row(tmp_path, 1, mirror_arm="off", mirror={},
                     lost_pods=2, p99_arrival_to_bind_ms=812)
        (flag,) = mirror_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "lost_pods=2" in probs
        assert "812ms over the 500ms SLO" in probs

    def test_differential_mismatch_gates_strict(self, tmp_path):
        from tools.perf_report import main, mirror_flags

        self._ab_row(tmp_path, 1, differential_match=False,
                     invariants_ok=False)
        (flag,) = mirror_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "differential arms disagree" in probs
        assert main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_scatter_h2d_regression_flagged_with_headroom(self,
                                                          tmp_path):
        from tools.perf_report import mirror_flags

        # within the 10% jitter band: clean
        self._ab_row(tmp_path, 1, h2d_per_cycle_on_bytes=115_000)
        assert mirror_flags(load_rounds(str(tmp_path))) == []
        # past it: the scatter triples cost more than the encode
        self._ab_row(tmp_path, 1, h2d_per_cycle_on_bytes=160_000)
        (flag,) = mirror_flags(load_rounds(str(tmp_path)))
        assert "above the off arm's" in flag["problems"][0]

    def test_chaos_cell_row_flagged(self, tmp_path):
        from tools.perf_report import mirror_flags

        _artifact(tmp_path, 1, 0.0,
                  metric="mirror_cell[node_kill seed=11]",
                  extra={"ok": False, "differential_match": False,
                         "lost_pods": 1,
                         "failure": "differential mismatch"})
        (flag,) = mirror_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "cell failed" in probs
        assert "lost_pods=1" in probs

    def test_flags_survive_json_mode(self, tmp_path, capsys):
        from tools.perf_report import main

        self._on_row(tmp_path, 1, encode_share=0.2)
        main(["--dir", str(tmp_path), "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert len(doc["mirror_flags"]) == 1

    def test_committed_mirror_rows_strict_clean(self, tmp_path):
        """The committed artifact IS the acceptance criterion: the
        checked-in mirror_rows.log rows must hold every mirror_flags
        budget (encode share, per-cycle h2d vs the donation row, zero
        lost, differential match) — a regression that sneaks into the
        committed row fails tier-1, not just --strict CI."""
        path = os.path.join(_REPO_ROOT, "mirror_rows.log")
        assert os.path.exists(path), "mirror_rows.log not committed"
        with open(path) as f:
            tail = f.read()
        doc = {"n": 1, "cmd": "python bench.py --config mirrorab",
               "rc": 0, "tail": tail}
        with open(os.path.join(tmp_path, "BENCH_r01.json"), "w") as fh:
            json.dump(doc, fh)
        from tools.perf_report import mirror_flags

        rounds = load_rounds(str(tmp_path))
        rows = _rows_from_tail(tail)
        kinds = {str(r.get("metric", "")).split("[", 1)[0]
                 for r in rows}
        assert "mirror_sustained" in kinds and "mirror_ab" in kinds
        on_rows = [r for r in rows if r.get("mirror_arm") == "on"]
        assert on_rows and all(
            float(r["encode_share"]) < 0.05 for r in on_rows)
        assert mirror_flags(rounds) == []
