"""Fleet-wide distributed tracing: wire context propagation, the trace
federation's skew-corrected merge, and critical-path attribution.

The tier-1 mini-cell here is the PR's acceptance path: two in-proc
partition apiservers (each with its OWN tracer ring, modeling separate
processes) plus this process as the scheduler replica, all traffic over
real REST. A sampled pod's trace must stitch across the processes with
zero orphan spans, every imported span must carry the half-RTT skew
bound, and the ``KTPU_TRACE=off`` arm must shed the layer entirely —
no ``X-Ktpu-Trace`` header on the wire at all."""

import json
import time
import urllib.request

import pytest

from kubernetes_tpu.apiserver.rest import APIServer
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.metrics.registry import MetricsRegistry
from kubernetes_tpu.observability import get_tracer
from kubernetes_tpu.observability.fleettrace import (
    TraceFederation,
    collect_fleet_trace,
    critical_path,
    phase_of,
)
from kubernetes_tpu.observability.tracer import (
    TRACE_HEADER,
    Tracer,
    format_trace_header,
    parse_trace_header,
)
from kubernetes_tpu.testing import MakeNode, MakePod


@pytest.fixture
def global_tracer():
    t = get_tracer()
    saved = (t.enabled, t.sample_rate, t.seed, t.retain_s)
    t.clear()
    t.configure(enabled=True, sample_rate=1.0)
    yield t
    (t.enabled, t.sample_rate, t.seed, t.retain_s) = saved
    t.clear()


def _pod(name, ns="default", uid=None):
    p = MakePod().name(name).uid(uid or f"u-{ns}-{name}").req(
        {"cpu": "100m", "memory": "50Mi"}).obj()
    p.metadata.namespace = ns
    return p


def _node(name):
    return MakeNode().name(name).capacity(
        {"cpu": "8", "memory": "16Gi", "pods": "110"}).obj()


# ---------------------------------------------------------------------------
# wire format + sampling override (satellite a)


class TestTraceContextWire:
    def test_header_round_trip(self):
        hdr = format_trace_header("pod-uid-1", 42, True)
        ctx = parse_trace_header(hdr)
        assert ctx.trace == "pod-uid-1"
        assert ctx.parent == 42
        assert ctx.sampled is True
        assert parse_trace_header(ctx.header_value()) == ctx
        off = parse_trace_header(format_trace_header("t", 0, False))
        assert off.sampled is False

    def test_malformed_header_is_none_never_raises(self):
        for bad in ("", "justatrace", "t;notanint;1", "t;1;2;3;4",
                    ";;;", None):
            assert parse_trace_header(bad) is None

    def test_inbound_decision_overrides_local_sampling(self):
        # a tracer that would NEVER sample locally must honor an
        # explicit inbound sampled=1 ...
        never = Tracer(component="t", sample_rate=0.0,
                       registry=MetricsRegistry())
        assert never.sampled("uid-x", inbound=True)
        assert not never.sampled("uid-x", inbound=False)
        assert not never.sampled("uid-x")
        # ... and one that ALWAYS would must honor inbound sampled=0
        always = Tracer(component="t", sample_rate=1.0,
                        registry=MetricsRegistry())
        assert not always.sampled("uid-x", inbound=False)
        assert always.sampled("uid-x", inbound=True)
        assert always.sampled("uid-x")
        # the enabled check still wins over everything
        off = Tracer(component="t", enabled=False,
                     registry=MetricsRegistry())
        assert not off.sampled("uid-x", inbound=True)

    def test_bulk_elects_one_context_with_uid_list_attribute(
            self, global_tracer):
        from kubernetes_tpu.client.restcluster import RestClusterClient

        uids = ["bulk-u1", "bulk-u2", "bulk-u3"]
        hdr = RestClusterClient._trace_ctx_for(uids)
        ctx = parse_trace_header(hdr)
        # ONE context for the whole batch, elected deterministically
        assert ctx.trace == "bulk-u1" and ctx.sampled is True
        # no open span -> the sampled-uid list rides a client.batch
        # instant event
        batch = [r for r in global_tracer._ring
                 if r[0] == "client.batch"]
        assert batch and batch[-1][8]["uids"] == uids
        # with an open span, the list annotates THAT span instead
        with global_tracer.span("cycle") as sp:
            RestClusterClient._trace_ctx_for(uids)
            assert sp.attrs.get("trace_uids") == uids
        # nothing sampled -> no header at all
        global_tracer.configure(sample_rate=0.0)
        assert RestClusterClient._trace_ctx_for(uids) is None


# ---------------------------------------------------------------------------
# critical-path analysis (pure, no servers)


class TestCriticalPath:
    def test_phase_classification(self):
        assert phase_of("rest.ingest") == "rest"
        assert phase_of("rest.POST") == "rest"
        assert phase_of("queue.wait") == "queue"
        assert phase_of("solve.encode") == "encode"
        assert phase_of("solve.device") == "solve"
        assert phase_of("solve.commit") == "commit"
        assert phase_of("sched.bind") == "bind"
        assert phase_of("watch.deliver") == "watch"
        assert phase_of("reshard.freeze") == "seam"
        assert phase_of("upgrade.roll") == "seam"
        assert phase_of("unrelated") is None

    def test_priority_sweep_and_unattributed(self):
        t = Tracer(component="t", sample_rate=1.0,
                   registry=MetricsRegistry())
        now = time.monotonic()
        # 1.0s window: queue covers all of it, commit overlays the last
        # 0.4s (commit outranks queue), and a 0.1s head gap is left raw
        t.record("rest.ingest", now - 1.0, now - 0.998, trace="p1")
        t.record("queue.wait", now - 0.9, now - 0.4, trace="p1")
        t.record("solve.commit", now - 0.6, now - 0.2)  # batch-level
        t.record("sched.bind", now - 0.2, now, trace="p1")
        fed = TraceFederation()
        fed.absorb_local(t, "solo")
        cp = critical_path(fed.merged())
        assert cp["pods"] == 1
        pod = cp["per_pod"][0]
        # commit owns [−0.6,−0.4] even though queue.wait covers it too
        assert pod["phases_ms"]["commit"] == pytest.approx(400, abs=20)
        assert pod["phases_ms"]["queue"] == pytest.approx(300, abs=20)
        assert pod["phases_ms"]["bind"] == pytest.approx(200, abs=20)
        # [−0.998,−0.9] has no covering span: ~10% unattributed
        assert 0.05 < cp["unattributed_share"] < 0.15
        assert cp["top"] == "commit"

    def test_seam_spans_attribute_overlapping_stalls(self):
        t = Tracer(component="t", sample_rate=1.0,
                   registry=MetricsRegistry())
        now = time.monotonic()
        t.record("rest.ingest", now - 1.0, now - 0.99, trace="p1")
        t.record("sched.bind", now - 0.1, now, trace="p1")
        # a reshard freeze explains the dead middle of the window
        t.record("reshard.freeze", now - 0.8, now - 0.3, trace="seam:4")
        cp = critical_path(_merged_of(t))
        assert cp["seam_windows"] == 1
        assert cp["per_pod"][0]["phases_ms"]["seam"] == pytest.approx(
            500, abs=25)


def _merged_of(tracer):
    fed = TraceFederation()
    fed.absorb_local(tracer, "solo")
    return fed.merged()


# ---------------------------------------------------------------------------
# the tier-1 mini-cell: 2 partitions + 1 scheduler replica over REST


class TestFleetMiniCell:
    def _spin_up(self, parts=2):
        servers = []
        for i in range(parts):
            s = APIServer(store=ClusterStore(),
                          partition=(i, parts)).start()
            # each server gets its OWN ring: in-proc stand-in for a
            # separate process's flight recorder (rest.py reads
            # server.tracer everywhere)
            s.tracer = Tracer(component=f"partition-{i}",
                              sample_rate=1.0,
                              registry=MetricsRegistry())
            servers.append(s)
        return servers, [s.url for s in servers]

    def test_sampled_trace_stitches_across_processes(self, global_tracer):
        from kubernetes_tpu.client.restcluster import RestClusterClient

        servers, urls = self._spin_up(2)
        client = RestClusterClient(urls[0], partition_urls=urls,
                                   watch_kinds=("Pod",))
        delivered = []
        try:
            client.watch(lambda e: delivered.append(e),
                         batch_fn=lambda evs: delivered.extend(evs))
            time.sleep(0.3)
            # namespaces spread over both partitions so every process
            # participates in the merged timeline
            pods = [_pod(f"ft{i}", ns=f"ns{i}") for i in range(8)]
            assert client.create_objects_bulk("Pod", pods) == 8
            client.create_objects_bulk(
                "Node", [_node(f"ftn{i}") for i in range(2)])
            errs = client.bind_many([
                (p.metadata.namespace, p.metadata.name,
                 p.metadata.uid, "ftn0") for p in pods])
            assert errs == [None] * 8
            # watch.deliver spans land on the scheduler ring once the
            # origin-stamped events arrive
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if any(r[0] == "watch.deliver"
                       for r in global_tracer._ring):
                    break
                time.sleep(0.05)
            else:
                pytest.fail("no watch.deliver span ever recorded "
                            "(origin context lost on the watch path)")
            # both servers saw propagated contexts on the wire
            assert all(s.trace_headers_seen > 0 for s in servers)

            doc, cp = collect_fleet_trace(
                remote=[(f"partition-{i}", u)
                        for i, u in enumerate(urls)],
                local=[("scheduler", global_tracer)])
            instances = doc["otherData"]["instances"]
            assert set(instances) == {"partition-0", "partition-1",
                                      "scheduler"}
            assert doc["otherData"]["scrape_errors"] == []
            events = [e for e in doc["traceEvents"]
                      if e["ph"] in ("X", "i")]
            by_instance = {}
            for e in events:
                by_instance.setdefault(
                    e["args"]["instance"], []).append(e)
            # every process contributed spans to the merged timeline
            assert set(by_instance) == set(instances)

            # skew correction applied: scraped rings carry the half-RTT
            # bound on EVERY imported span; the local ring is exact
            for i in range(2):
                inst = f"partition-{i}"
                bound = instances[inst]["skew_ms"]
                assert bound > 0.0
                assert all(e["args"]["skew_ms"] == bound
                           for e in by_instance[inst])
            assert all(e["args"]["skew_ms"] == 0.0
                       for e in by_instance["scheduler"])

            # the elected bulk trace stitches scheduler -> its
            # partition server -> back to the scheduler (watch hop)
            stitched = [e for e in events
                        if str(e["args"].get("trace", ""))
                        .startswith("u-ns")]
            traces = {}
            for e in stitched:
                traces.setdefault(e["args"]["trace"], set()).add(
                    e["args"]["instance"])
            cross = {t: insts for t, insts in traces.items()
                     if len(insts) >= 2}
            assert cross, f"no trace crossed a process: {traces}"
            best = max(cross.values(), key=len)
            assert "scheduler" in best
            assert any(i.startswith("partition-") for i in best)
            names_of_best = {
                e["name"] for e in stitched
                if e["args"]["trace"] == max(cross, key=lambda t: len(
                    cross[t]))}
            assert "rest.ingest" in names_of_best
            assert "watch.deliver" in names_of_best

            # zero orphan spans: within each instance every nonzero
            # parent id resolves to a span id of the same instance
            ids = {}
            for e in events:
                ids.setdefault(e["args"]["instance"], set()).add(
                    e["args"]["id"])
            orphans = [e for e in events if e["args"]["parent"]
                       and e["args"]["parent"]
                       not in ids[e["args"]["instance"]]]
            assert orphans == [], orphans

            # the aggregate the bench row would carry
            assert cp["pods"] >= 1
            assert cp["max_skew_ms"] > 0.0
            assert cp["max_skew_ms"] <= cp["skew_bound_ms"]
        finally:
            client._stop_watches()
            client._drop_conn()
            for s in servers:
                s.shutdown_server()

    def test_trace_off_arm_sheds_header_on_wire(self, global_tracer):
        from kubernetes_tpu.client.restcluster import RestClusterClient

        global_tracer.configure(enabled=False)
        servers, urls = self._spin_up(2)
        client = RestClusterClient(urls[0], partition_urls=urls,
                                   watch_kinds=("Pod",))
        try:
            client.watch(lambda e: None, batch_fn=lambda evs: None)
            time.sleep(0.3)
            pods = [_pod(f"off{i}", ns=f"ns{i}") for i in range(4)]
            assert client.create_objects_bulk("Pod", pods) == 4
            client.create_objects_bulk("Node", [_node("offn0")])
            client.bind_many([
                (p.metadata.namespace, p.metadata.name,
                 p.metadata.uid, "offn0") for p in pods])
            # the layer is SHED, not just quiet: no request — bulk,
            # bind, or the watch handoff itself — carried the header
            assert all(s.trace_headers_seen == 0 for s in servers), \
                [s.trace_headers_seen for s in servers]
        finally:
            client._stop_watches()
            client._drop_conn()
            for s in servers:
                s.shutdown_server()

    def test_scrape_survives_dead_instance(self, global_tracer):
        fed = TraceFederation()
        ok = fed.scrape("http://127.0.0.1:9", "dead")
        assert ok is False
        assert fed.scrape_errors and "dead" in fed.scrape_errors[0]
        # the merge still renders from whatever WAS imported
        fed.absorb_local(global_tracer, "scheduler")
        doc = fed.merged()
        assert doc["otherData"]["scrape_errors"]
        assert "scheduler" in doc["otherData"]["instances"]


# ---------------------------------------------------------------------------
# /debug/trace clock-offset handshake


class TestClockOffsetEcho:
    def test_server_echoes_monotonic_stamp(self, global_tracer):
        server = APIServer(store=ClusterStore()).start()
        try:
            global_tracer.event("probe")
            t0 = time.monotonic()
            with urllib.request.urlopen(
                    f"{server.url}/debug/trace?echo_mono={t0!r}",
                    timeout=10) as resp:
                doc = json.loads(resp.read())
            other = doc["otherData"]
            assert other["echo_mono"] == pytest.approx(t0)
            # same process here, so the server's monotonic stamp sits
            # between send and now
            assert t0 <= other["server_mono"] <= time.monotonic()
        finally:
            server.shutdown_server()

    def test_federation_offset_near_zero_for_same_host(
            self, global_tracer):
        server = APIServer(store=ClusterStore()).start()
        try:
            global_tracer.event("probe")
            fed = TraceFederation()
            assert fed.scrape(server.url, "api")
            # same clock: the half-RTT estimate must be tiny, and the
            # recorded bound must cover the true offset (zero)
            assert abs(fed._offsets["api"]) <= max(
                0.05, fed._skew_ms["api"] / 1000.0)
            assert fed._skew_ms["api"] > 0.0
        finally:
            server.shutdown_server()
