"""Trace-replay workload engine tests (ISSUE 13): generator
determinism (bit-exact per seed, committed-fixture guard), JSONL round
trip, the shared arrival-injection loop, mesh-adjacency scoring units,
the open-loop ≡ pre-created-burst differential guard at rate=∞, fast
mini-replay cells per scenario family (the tier-1 invariants), one
mini REST replay through the real fabric, the ``replay[...]`` diag
segment round trip, and the perf-report ``replay_*`` family gating."""

import json
import os
import threading
import time

import pytest

from kubernetes_tpu.harness.burst import stream_arrivals
from kubernetes_tpu.scheduler.framework.plugins import mesh_locality
from kubernetes_tpu.workloads import (
    REPLAY_FAMILIES,
    build_family,
    generate_trace,
    load_trace_jsonl,
    write_trace_jsonl,
)
from kubernetes_tpu.workloads.trace import bounded_pareto

DATA_DIR = os.path.join(os.path.dirname(__file__), "data")


# ---------------------------------------------------------------------------
# generator determinism + interchange


class TestTraceGenerator:
    def test_bit_deterministic_per_seed(self, tmp_path):
        """Same seed + parameters → identical events AND identical
        serialized bytes (the determinism contract in COMPONENTS.md)."""
        t1 = generate_trace(42, 120, 20.0)
        t2 = generate_trace(42, 120, 20.0)
        assert t1 == t2
        p1, p2 = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        write_trace_jsonl(t1, str(p1))
        write_trace_jsonl(t2, str(p2))
        assert p1.read_bytes() == p2.read_bytes()

    def test_seed_changes_trace(self):
        assert generate_trace(42, 60, 10.0) != generate_trace(43, 60, 10.0)

    def test_exact_count_and_ordering(self):
        t = generate_trace(7, 200, 30.0)
        assert len(t.events) == 200
        offsets = [e.t for e in t.events]
        assert offsets == sorted(offsets)
        assert all(0.0 <= o < 30.0 for o in offsets)

    def test_family_determinism(self):
        for fam in REPLAY_FAMILIES:
            assert build_family(fam, 11, 0.1) == build_family(fam, 11, 0.1)
            assert build_family(fam, 11, 0.1) != build_family(fam, 23, 0.1)

    def test_jsonl_round_trip_exact(self, tmp_path):
        for fam in REPLAY_FAMILIES:
            t = build_family(fam, 11, 0.08)
            path = str(tmp_path / f"{fam}.jsonl")
            write_trace_jsonl(t, path)
            assert load_trace_jsonl(path) == t

    def test_committed_fixture_matches_generator(self):
        """The committed reference trace IS the generator's output for
        (storm, seed 11, scale 0.05): a drift in any distribution,
        arrival process, or serialization breaks this — the
        cross-session determinism guard."""
        fixture = load_trace_jsonl(
            os.path.join(DATA_DIR, "replay_trace_storm_s11.jsonl"))
        assert fixture == build_family("storm", 11, 0.05)

    def test_heavy_tail_shape(self):
        """Bounded Pareto: bounded, majority small, real tail — the
        Azure/Google cluster-trace shape the padded-bucket discipline
        is stressed by."""
        from random import Random

        rng = Random(5)
        xs = sorted(bounded_pareto(rng, 1.5, 100, 4000)
                    for _ in range(4000))
        assert xs[0] >= 100 and xs[-1] <= 4000
        median = xs[len(xs) // 2]
        assert median < 400            # mass near the floor
        assert xs[-1] > 6 * median     # but a genuine tail

    def test_gang_pod_manifest(self):
        t = build_family("gangs", 11, 0.08)
        gang_events = [e for e in t.events if e.gang]
        assert gang_events
        d = gang_events[0].pod_dict()
        labels = d["metadata"]["labels"]
        assert labels["pod-group.scheduling.k8s.io/name"] == \
            gang_events[0].gang
        assert labels[mesh_locality.MESH_BLOCK_LABEL] == \
            gang_events[0].gang
        assert d["spec"]["priority"] == gang_events[0].priority


# ---------------------------------------------------------------------------
# the shared arrival-injection loop


class TestStreamArrivals:
    def test_immediate_mode_is_chunked_burst(self):
        sent = []
        n = stream_arrivals(((0.0, i) for i in range(1000)),
                            sent.append, chunk=256, time_scale=0.0)
        assert n == 1000
        assert [len(c) for c in sent] == [256, 256, 256, 232]
        assert [i for c in sent for i in c] == list(range(1000))

    def test_paced_mode_honors_due_times(self):
        sent_at = []
        t0 = time.monotonic()
        stream_arrivals(
            [(0.0, "a"), (0.15, "b"), (0.3, "c")],
            lambda items: sent_at.extend(
                (i, time.monotonic() - t0) for i in items),
            chunk=8, time_scale=1.0)
        by_name = dict(sent_at)
        assert by_name["b"] >= 0.13 and by_name["c"] >= 0.27

    def test_stop_event_aborts(self):
        stop = threading.Event()
        stop.set()
        sent = []
        n = stream_arrivals([(5.0, "late")], sent.append, stop=stop)
        assert n == 0 and not sent

    def test_on_sent_stamps_every_item(self):
        stamps = {}
        stream_arrivals(((0.0, i) for i in range(10)),
                        lambda items: None, time_scale=0.0,
                        on_sent=lambda item, off: stamps.__setitem__(
                            item, off))
        assert sorted(stamps) == list(range(10))


# ---------------------------------------------------------------------------
# mesh-adjacency scoring units


class TestMeshLocality:
    def _nodes(self, cols=4, rows=4, cpu="8"):
        from kubernetes_tpu.api.types import Node

        out = []
        for i in range(cols * rows):
            out.append(Node.from_dict({
                "metadata": {
                    "name": f"n{i}",
                    "labels": dict(
                        mesh_locality.mesh_node_labels(i, cols, rows)),
                },
                "status": {"capacity": {
                    "cpu": cpu, "memory": "16Gi", "pods": "110"}},
            }))
        return out

    def _gang_pod(self, block="blk-a"):
        from kubernetes_tpu.api.types import Pod

        return Pod.from_dict({
            "metadata": {"name": "p0",
                         "labels": {mesh_locality.MESH_BLOCK_LABEL:
                                    block}},
            "spec": {"containers": [
                {"name": "c", "image": "x",
                 "resources": {"requests": {"cpu": "1"}}}]},
        })

    def test_anchor_deterministic_and_on_grid(self):
        a1 = mesh_locality.block_anchor("gang-7", 8, 8)
        a2 = mesh_locality.block_anchor("gang-7", 8, 8)
        assert a1 == a2
        assert 0 <= a1[0] < 8 and 0 <= a1[1] < 8
        assert mesh_locality.block_anchor("gang-8", 8, 8) != a1 or True

    def test_score_strictly_decreases_with_distance(self):
        nodes = self._nodes()
        pod = self._gang_pod()
        fn = mesh_locality.profile_scorer(pod, nodes)
        assert fn is not None
        ax, ay = mesh_locality.block_anchor("blk-a", 4, 4)
        by_dist = {}
        for node in nodes:
            x, y = mesh_locality.node_coord(node)
            by_dist.setdefault(abs(x - ax) + abs(y - ay),
                               set()).add(fn(node))
        dists = sorted(by_dist)
        # one score per distance ring, strictly decreasing outward
        assert all(len(v) == 1 for v in by_dist.values())
        scores = [by_dist[d].pop() for d in dists]
        assert scores == sorted(scores, reverse=True)
        assert scores[0] == 100.0   # the anchor node scores MAX

    def test_unlabeled_pod_and_disabled_score_zero(self):
        from kubernetes_tpu.api.types import Pod

        nodes = self._nodes()
        plain = Pod.from_dict({
            "metadata": {"name": "p1"},
            "spec": {"containers": [
                {"name": "c", "image": "x",
                 "resources": {"requests": {"cpu": "1"}}}]},
        })
        assert mesh_locality.profile_scorer(plain, nodes) is None
        mesh_locality.configure(False)
        try:
            assert mesh_locality.profile_scorer(
                self._gang_pod(), nodes) is None
        finally:
            mesh_locality.configure(True)

    def test_profile_component_distinguishes_blocks(self):
        a = mesh_locality.profile_component(self._gang_pod("blk-a"))
        b = mesh_locality.profile_component(self._gang_pod("blk-b"))
        assert a != b and a == ("mesh", "blk-a")
        from kubernetes_tpu.api.types import Pod

        plain = Pod.from_dict({
            "metadata": {"name": "p"},
            "spec": {"containers": [
                {"name": "c", "image": "x",
                 "resources": {"requests": {"cpu": "1"}}}]},
        })
        assert mesh_locality.profile_component(plain) == ()

    def test_unlabeled_grid_scores_none(self):
        from kubernetes_tpu.api.types import Node

        bare = [Node.from_dict({
            "metadata": {"name": "bare"},
            "status": {"capacity": {"cpu": "8", "memory": "8Gi",
                                    "pods": "110"}}})]
        assert mesh_locality.profile_scorer(
            self._gang_pod(), bare) is None


# ---------------------------------------------------------------------------
# engine: differential guard + mini-replay cells


def _pump_store_replay(store, trace, time_scale, *, timeout=120.0,
                       expire=True):
    from kubernetes_tpu.config.feature_gates import FeatureGates
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.sidecar import attach_batch_scheduler
    from kubernetes_tpu.workloads.replay import ReplayEngine

    gates = FeatureGates({"TPUBatchScheduler": True})
    sched = Scheduler.create(store, feature_gates=gates,
                             provider="GangSchedulingProvider")
    bs = attach_batch_scheduler(sched, max_batch=256)
    sched.start()
    eng = ReplayEngine(store, trace, time_scale=time_scale,
                       expire=expire)
    deadline = time.monotonic() + timeout
    eng.start()
    quiet = None
    try:
        while time.monotonic() < deadline:
            sched.queue.flush_backoff_completed()
            if bs.run_batch(pop_timeout=0.01):
                quiet = None
                continue
            busy = (not eng.injection_done.is_set()
                    or eng.due_expiries() > 0
                    or sched.queue.pending_active_count() > 0)
            now = time.monotonic()
            if busy:
                quiet = None
            elif quiet is None:
                quiet = now
            elif now - quiet > 1.0:
                break
            time.sleep(0.005)
        bs.flush()
        sched.wait_for_inflight_bindings(timeout=10.0)
        return eng.finish()
    finally:
        sched.stop()


class TestOpenLoopDifferential:
    def test_rate_inf_equals_precreated_burst(self):
        """The differential guard against today's rows: at rate=∞
        (time_scale=0, no expiry) the replay engine IS a pre-created
        burst — the same pods, all bound, on both paths."""
        from kubernetes_tpu.api.types import Node
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.harness.burst import wait_all_bound
        from kubernetes_tpu.harness.workloads import node_template
        from kubernetes_tpu.workloads.trace import events_to_pods

        trace = generate_trace(17, 80, 10.0, lifetime_modes=None,
                               cpu_hi=1500)
        nodes = [node_template(i, cpu="16") for i in range(12)]

        # arm A: the replay engine at rate=∞
        store_a = ClusterStore()
        for d in nodes:
            store_a.add_node(Node.from_dict(d))
        stats = _pump_store_replay(store_a, trace, 0.0, expire=False)
        assert stats.lost == 0 and not stats.send_errors
        assert stats.ever_bound == len(trace.events)

        # arm B: pre-created burst of the identical pods
        from kubernetes_tpu.config.feature_gates import FeatureGates
        from kubernetes_tpu.scheduler.scheduler import Scheduler
        from kubernetes_tpu.sidecar import attach_batch_scheduler

        store_b = ClusterStore()
        for d in nodes:
            store_b.add_node(Node.from_dict(d))
        store_b.create_pods(events_to_pods(trace.events))
        sched = Scheduler.create(
            store_b, feature_gates=FeatureGates(
                {"TPUBatchScheduler": True}),
            provider="GangSchedulingProvider")
        bs = attach_batch_scheduler(sched, max_batch=256)
        sched.start()
        try:
            deadline = time.monotonic() + 60
            names = [e.name for e in trace.events]
            while time.monotonic() < deadline:
                sched.queue.flush_backoff_completed()
                if not bs.run_batch(pop_timeout=0.01):
                    elapsed = wait_all_bound(store_b, names, 0.01)
                    if elapsed is not None:
                        break
            bs.flush()
            sched.wait_for_inflight_bindings(timeout=10.0)
        finally:
            sched.stop()
        bound_a = {p.metadata.name for p in store_a.list_pods()
                   if p.spec.node_name}
        bound_b = {p.metadata.name for p in store_b.list_pods()
                   if p.spec.node_name}
        assert bound_a == bound_b == set(e.name for e in trace.events)


class TestMiniReplayCells:
    """The tier-1 fast cells: hundreds of pods, seconds each, the
    family invariants as hard asserts."""

    @pytest.mark.parametrize("family", sorted(REPLAY_FAMILIES))
    def test_family_cell(self, family):
        from kubernetes_tpu.workloads import run_replay_cell

        r = run_replay_cell(11, family=family, pods=120,
                            wait_timeout=120.0)
        assert r["ok"], (r["failure"], r["stats"])
        assert r["stats"]["lost"] == 0
        assert r["stats"]["gangs_partial"] == 0
        assert r["stats"]["inversions"] == 0
        assert r["stats"]["ever_bound"] > 0
        if family == "storm":
            # the storm must actually storm: preemptions happened
            assert r["stats"]["preempted"] > 0
        if family in ("gangs", "tenancy"):
            # lifetime churn actually recycled capacity
            assert r["stats"]["expired"] > 0

    def test_gangs_scored_beats_blind(self):
        """Mesh-adjacency acceptance at cell scale: the scored arm's
        mean gang adjacency strictly beats the adjacency-blind arm on
        the same trace (seed fixed, both arms deterministic enough at
        this scale to separate — seeds chosen to keep the gap wide)."""
        from kubernetes_tpu.workloads import run_replay_once

        scored, _ = run_replay_once("gangs", 23, 0.15, 0.2,
                                    rest=False, max_batch=256,
                                    wait_timeout=120.0)
        blind, _ = run_replay_once("gangs", 23, 0.15, 0.2,
                                   rest=False, max_batch=256,
                                   wait_timeout=120.0, scored=False)
        assert scored.mean_gang_adjacency is not None
        assert blind.mean_gang_adjacency is not None
        assert scored.mean_gang_adjacency < blind.mean_gang_adjacency
        assert scored.gangs_partial == blind.gangs_partial == 0


class TestMiniRestReplay:
    def test_storm_over_rest_fabric(self):
        """One mini replay through the REAL fabric (apiserver child,
        APF, watch streams): invariants hold, the row carries SLO
        verdicts and the replay diag segment parses."""
        from kubernetes_tpu.workloads import run_replay_row

        row = run_replay_row("storm", seed=11, scale=0.08,
                             time_scale=0.2, rest=True, max_batch=256,
                             wait_timeout=180.0)
        assert row["invariants_ok"], row["invariants"]
        assert row["lost_pods"] == 0
        assert row["preempted"] > 0
        assert row["gangs"]["partial"] == 0
        assert "slo" in (row.get("freshness") or {}), \
            "row must carry SLO verdicts"
        assert row["metric"].startswith("replay_storm[")
        assert row.get("federation_instances"), \
            "federation must have scraped the child"


# ---------------------------------------------------------------------------
# diag segment + perf_report family


class TestReplayDiag:
    def test_format_parse_round_trip(self):
        from kubernetes_tpu.harness import diagfmt

        seg = diagfmt.format_replay({
            "family": "storm", "rate": 12.5,
            "p99_arrival_to_bind_ms": 842.0, "preempted": 312,
            "gangs_intact": True, "lost": 0, "expired": 47,
            "inversions": 0})
        line = diagfmt.format_diag([seg])
        parsed = diagfmt.parse_diag(line)
        rp = parsed["replay"]
        assert rp["family"] == "storm"
        assert rp["rate"] == 12.5
        assert rp["p99_arrival_to_bind"] == 842
        assert rp["preempted"] == 312
        assert rp["gangs_intact"] == 1
        assert rp["lost"] == 0 and rp["expired"] == 47
        assert rp["inversions"] == 0

    def test_quiet_fields_and_violated(self):
        from kubernetes_tpu.harness import diagfmt

        seg = diagfmt.format_replay({
            "family": "gangs", "rate": 3.0,
            "p99_arrival_to_bind_ms": 55.0, "preempted": 0,
            "gangs_intact": False})
        parsed = diagfmt.parse_diag("    diag: " + seg)
        assert parsed["replay"]["gangs_intact"] == 0
        assert diagfmt.format_replay(None) == ""


class TestPerfReportReplayFamily:
    def _round(self, rows):
        return {"round": 9, "path": "BENCH_r09.json", "rc": 0,
                "rows": rows}

    def test_flags_lost_invariants_slo_and_ab(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "perf_report", os.path.join(
                os.path.dirname(__file__), "..", "tools",
                "perf_report.py"))
        pr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pr)

        bad = {
            "metric": "replay_storm[x]", "unit": "pods/s",
            "value": 10.0, "lost_pods": 3, "invariants_ok": False,
            "invariants": {"zero_lost_pods": False,
                           "no_priority_inversion": True},
            "slo_verdicts_ok": False,
            "slo_gated": ["watch_delivery"],
            "freshness": {"slo": {"watch_delivery": "violated"}},
            "adjacency_ab": {"scored_beats_blind": False,
                             "scored_mean_gang_adjacency": 2.0,
                             "blind_mean_gang_adjacency": 1.5},
        }
        good = {
            "metric": "replay_gangs[y]", "unit": "pods/s",
            "value": 8.0, "lost_pods": 0, "invariants_ok": True,
            "slo_verdicts_ok": True,
            "adjacency_ab": {"scored_beats_blind": True},
        }
        flags = pr.replay_flags([self._round([bad, good])])
        assert len(flags) == 1
        problems = " ".join(flags[0]["problems"])
        assert "lost_pods=3" in problems
        assert "invariants failed" in problems
        assert "slo violated" in problems
        assert "adjacency A/B not paying" in problems

    def test_series_uses_rate_normalized_value(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "perf_report2", os.path.join(
                os.path.dirname(__file__), "..", "tools",
                "perf_report.py"))
        pr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pr)
        row = {
            "metric": "replay_storm[x]", "unit": "pods/s",
            "value": 55.0, "rate_normalized_throughput": 0.91,
            "p99_arrival_to_bind_ms": 300,
        }
        series = pr.build_series([self._round([row])])
        pt = series["replay_storm[x]"][0]
        assert pt["value"] == 0.91       # the trend compares THIS
        assert pt["raw_value"] == 55.0   # raw kept for the table
        assert pt["p99_ms"] == 300
