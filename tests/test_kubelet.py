"""Node-agent ring: CRI state machine, checkpoints, device manager,
probes, and the kubelet sync loop end-to-end against the cluster store."""

import time

import pytest

from kubernetes_tpu.api.types import FAILED, RUNNING, SUCCEEDED
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.kubelet import (
    CheckpointManager,
    CorruptCheckpointError,
    DeviceAllocationError,
    DeviceManager,
    DevicePlugin,
    FakeRuntime,
    Kubelet,
    LIVENESS,
    ProbeManager,
    ProbeSpec,
    READINESS,
    TPU_RESOURCE,
)
from kubernetes_tpu.testing import MakeNode, MakePod


def wait_for(cond, timeout=5.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# ---------------------------------------------------------------------------
# CRI


def test_fake_runtime_lifecycle():
    rt = FakeRuntime()
    sid = rt.run_pod_sandbox("u1", "p", "default")
    cid = rt.create_container(sid, "main", "busybox")
    rt.start_container(cid)
    assert rt.container_status(cid).state == "RUNNING"
    with pytest.raises(RuntimeError):
        rt.remove_container(cid)  # still running
    rt.stop_container(cid)
    st = rt.container_status(cid)
    assert st.state == "EXITED" and st.exit_code == 137
    with pytest.raises(RuntimeError):
        rt.remove_pod_sandbox(sid)  # must stop first
    rt.stop_pod_sandbox(sid)
    rt.remove_pod_sandbox(sid)
    assert rt.list_pod_sandboxes() == []
    assert rt.list_containers() == []


def test_fake_runtime_batch_exit_and_restart_count():
    rt = FakeRuntime(exit_after={"job-image": 0.0})
    sid = rt.run_pod_sandbox("u1", "p", "default")
    cid = rt.create_container(sid, "main", "job-image")
    rt.start_container(cid)
    st = rt.container_status(cid)
    assert st.state == "EXITED" and st.exit_code == 0
    rt.start_container(cid)  # restart bumps counter
    assert rt.container_status(cid).restarts == 1


# ---------------------------------------------------------------------------
# checkpoints


def test_checkpoint_roundtrip_and_corruption(tmp_path):
    cm = CheckpointManager(str(tmp_path))
    cm.create("state", {"a": [1, 2, 3]})
    assert cm.get("state") == {"a": [1, 2, 3]}
    assert cm.list() == ["state"]
    # corrupt the file on disk → integrity error, not silent bad state
    path = tmp_path / "state.ckpt"
    raw = path.read_text().replace("[1, 2, 3]", "[1, 2, 9]")
    path.write_text(raw)
    with pytest.raises(CorruptCheckpointError):
        cm.get("state")
    cm.remove("state")
    assert cm.get("state") is None


# ---------------------------------------------------------------------------
# device manager


def test_device_manager_allocation_and_checkpoint(tmp_path):
    cm = CheckpointManager(str(tmp_path))
    dm = DeviceManager(cm)
    dm.register(DevicePlugin(TPU_RESOURCE, [f"tpu{i}" for i in range(8)]))
    ids = dm.allocate("u1", "main", TPU_RESOURCE, 4)
    assert len(ids) == 4
    assert dm.allocatable()[TPU_RESOURCE] == 4
    with pytest.raises(DeviceAllocationError):
        dm.allocate("u2", "main", TPU_RESOURCE, 5)
    # restart: a fresh manager reloads assignments from the checkpoint
    dm2 = DeviceManager(cm)
    dm2.register(DevicePlugin(TPU_RESOURCE, [f"tpu{i}" for i in range(8)]))
    assert dm2.allocatable()[TPU_RESOURCE] == 4
    assert dm2.devices_of("u1")[TPU_RESOURCE] == sorted(ids)
    dm2.free("u1")
    assert dm2.allocatable()[TPU_RESOURCE] == 8


def test_device_manager_topology_contiguous():
    dm = DeviceManager()
    topo = {f"t{x}{y}": (x, y) for x in range(4) for y in range(4)}
    dm.register(DevicePlugin(TPU_RESOURCE, sorted(topo), topology=topo))
    ids = dm.allocate("u1", "c", TPU_RESOURCE, 4)
    coords = [topo[d] for d in ids]
    # every chosen chip is L1-adjacent to at least one other chosen chip
    for c in coords:
        assert any(
            abs(c[0] - o[0]) + abs(c[1] - o[1]) == 1 for o in coords if o != c
        ), coords


# ---------------------------------------------------------------------------
# probes


def test_probe_thresholds():
    pm = ProbeManager()
    flaky = {"ok": True}
    pm.add("u1", "main", READINESS, ProbeSpec(lambda: flaky["ok"], period=0.0,
                                              failure_threshold=2))
    pm.tick()
    assert pm.pod_ready("u1")
    flaky["ok"] = False
    pm.tick()
    assert pm.pod_ready("u1")  # one failure < threshold
    pm.tick()
    assert not pm.pod_ready("u1")
    flaky["ok"] = True
    pm.tick()
    assert pm.pod_ready("u1")


# ---------------------------------------------------------------------------
# kubelet end-to-end (store-driven, no scheduler needed: bind directly)


@pytest.fixture()
def cluster():
    store = ClusterStore()
    kubelet = Kubelet(store, "n1", capacity={"cpu": "8", "memory": "16Gi"})
    kubelet.start()
    yield store, kubelet
    kubelet.stop()


def _bound_pod(store, name, node="n1", image="app", uid=None, **pod_kw):
    pod = MakePod().name(name).uid(uid or f"u-{name}").container(image=image).obj()
    for k, v in pod_kw.items():
        setattr(pod.spec, k, v)
    store.create_pod(pod)
    store.bind("default", name, pod.uid, node)
    return pod


def test_kubelet_registers_node_and_runs_pod(cluster):
    store, kubelet = cluster
    node = store.get_node("n1")
    assert node is not None and node.status.allocatable["cpu"].value() == 8

    _bound_pod(store, "web")
    assert wait_for(lambda: store.get_pod("default", "web").status.phase == RUNNING)
    pod = store.get_pod("default", "web")
    assert pod.status.pod_ip.startswith("10.88.0.")
    assert pod.status.host_ip == "n1"
    assert kubelet.running_pods()


def test_kubelet_pod_delete_tears_down(cluster):
    store, kubelet = cluster
    p = _bound_pod(store, "web")
    assert wait_for(lambda: kubelet.running_pods())
    store.delete_pod("default", "web")
    assert wait_for(lambda: not kubelet.running_pods())
    assert kubelet.runtime.list_pod_sandboxes() == []
    assert kubelet.volumes.mounted(p.uid) == []


def test_kubelet_job_pod_succeeds():
    store = ClusterStore()
    kubelet = Kubelet(store, "n1", runtime=FakeRuntime(exit_after={"job": 0.0}))
    kubelet.start()
    try:
        _bound_pod(store, "batch", image="job", restart_policy="Never")
        assert wait_for(
            lambda: store.get_pod("default", "batch").status.phase == SUCCEEDED
        )
        # terminal pod released its sandbox
        assert wait_for(lambda: not kubelet.running_pods())
    finally:
        kubelet.stop()


def test_kubelet_crashing_pod_fails_with_never_policy():
    store = ClusterStore()
    kubelet = Kubelet(store, "n1", runtime=FakeRuntime(fail_images={"bad"}))
    kubelet.start()
    try:
        _bound_pod(store, "crash", image="bad", restart_policy="Never")
        assert wait_for(
            lambda: store.get_pod("default", "crash").status.phase == FAILED
        )
    finally:
        kubelet.stop()


def test_kubelet_tpu_device_admission():
    store = ClusterStore()
    dm = DeviceManager()
    dm.register(DevicePlugin(TPU_RESOURCE, ["tpu0", "tpu1"]))
    kubelet = Kubelet(store, "n1", device_manager=dm)
    kubelet.start()
    try:
        node = store.get_node("n1")
        assert node.status.capacity[TPU_RESOURCE].value() == 2

        pod = MakePod().name("train").uid("u-train").req(
            {"cpu": "1", TPU_RESOURCE: "2"}
        ).obj()
        store.create_pod(pod)
        store.bind("default", "train", "u-train", "n1")
        assert wait_for(
            lambda: store.get_pod("default", "train").status.phase == RUNNING
        )
        assert dm.devices_of("u-train")[TPU_RESOURCE] == ["tpu0", "tpu1"]

        # second TPU pod cannot be satisfied → Failed, devices intact
        pod2 = MakePod().name("train2").uid("u-t2").req({TPU_RESOURCE: "1"}).obj()
        store.create_pod(pod2)
        store.bind("default", "train2", "u-t2", "n1")
        assert wait_for(
            lambda: store.get_pod("default", "train2").status.phase == FAILED
        )
        # deleting the first frees chips
        store.delete_pod("default", "train")
        assert wait_for(lambda: dm.allocatable()[TPU_RESOURCE] == 2)
    finally:
        kubelet.stop()


def test_kubelet_liveness_restart(cluster):
    store, kubelet = cluster
    p = _bound_pod(store, "web")
    assert wait_for(lambda: store.get_pod("default", "web").status.phase == RUNNING)
    # inject a failing liveness probe → container restarted, pod stays Running
    healthy = {"ok": False}
    kubelet.probes.add(p.uid, "c0", LIVENESS,
                       ProbeSpec(lambda: healthy["ok"], period=0.0,
                                 failure_threshold=1))
    cid = kubelet._containers_of[p.uid]["c0"]
    assert wait_for(lambda: kubelet.runtime.container_status(cid).restarts >= 1)
    assert store.get_pod("default", "web").status.phase == RUNNING


def test_kubelet_restart_preserves_checkpointed_devices(tmp_path):
    """A restarted kubelet must re-admit its running TPU pods from the
    device checkpoint instead of failing them on re-allocation."""
    store = ClusterStore()
    cm = CheckpointManager(str(tmp_path))
    dm = DeviceManager(cm)
    dm.register(DevicePlugin(TPU_RESOURCE, ["tpu0", "tpu1"]))
    kubelet = Kubelet(store, "n1", device_manager=dm)
    kubelet.start()
    pod = MakePod().name("train").uid("u-train").req({TPU_RESOURCE: "2"}).obj()
    store.create_pod(pod)
    store.bind("default", "train", "u-train", "n1")
    assert wait_for(lambda: store.get_pod("default", "train").status.phase == RUNNING)
    kubelet.stop()

    # "process restart": fresh kubelet, fresh DeviceManager, same checkpoint
    dm2 = DeviceManager(CheckpointManager(str(tmp_path)))
    dm2.register(DevicePlugin(TPU_RESOURCE, ["tpu0", "tpu1"]))
    assert dm2.allocatable()[TPU_RESOURCE] == 0  # assignment survived
    kubelet2 = Kubelet(store, "n1", device_manager=dm2)
    kubelet2.start()
    try:
        time.sleep(0.5)  # several sync ticks
        assert store.get_pod("default", "train").status.phase == RUNNING
        assert dm2.devices_of("u-train")[TPU_RESOURCE] == ["tpu0", "tpu1"]
    finally:
        kubelet2.stop()


class TestEvictionManager:
    def test_pressure_evicts_lowest_priority_and_taints(self):
        from kubernetes_tpu.kubelet.eviction import (
            MEMORY_PRESSURE, MEMORY_PRESSURE_TAINT, CgroupStatsStub,
            EvictionManager,
        )

        store = ClusterStore()
        store.add_node(MakeNode().name("n1")
                       .capacity({"cpu": "8", "memory": "1Gi"}).obj())
        # 900Mi requested on a 1Gi node with a 200Mi threshold: pressure
        low = MakePod().name("bulk").uid("bu").node("n1").priority(0) \
            .req({"memory": "600Mi"}).obj()
        high = MakePod().name("vip").uid("vu").node("n1").priority(1000) \
            .req({"memory": "300Mi"}).obj()
        store.create_pod(low)
        store.create_pod(high)
        mgr = EvictionManager(
            store, "n1", thresholds={"memory.available": "200Mi"},
            stats=CgroupStatsStub(store, "n1", 1024 * 1024 * 1024),
        )
        evicted = mgr.synchronize()
        assert evicted == "default/bulk"  # lowest priority first
        # the reference marks the victim Failed/Evicted (terminal-phase
        # record stays observable); it does NOT delete the object
        victim = store.get_pod("default", "bulk")
        assert victim is not None
        assert victim.status.phase == "Failed"
        assert victim.status.reason == "Evicted"
        assert store.get_pod("default", "vip").status.phase != "Failed"
        node = store.get_node("n1")
        assert any(c.type == MEMORY_PRESSURE and c.status == "True"
                   for c in node.status.conditions)
        assert any(t.key == MEMORY_PRESSURE_TAINT
                   for t in node.spec.taints)
        # signal cleared on the next pass: condition flips, taint lifts
        assert mgr.synchronize() is None
        node = store.get_node("n1")
        assert any(c.type == MEMORY_PRESSURE and c.status == "False"
                   for c in node.status.conditions)
        assert not any(t.key == MEMORY_PRESSURE_TAINT
                       for t in node.spec.taints)

    def test_kubelet_housekeeping_drives_eviction(self):
        import time as _time

        from kubernetes_tpu.kubelet import Kubelet
        from kubernetes_tpu.kubelet.eviction import (
            CgroupStatsStub, EvictionManager,
        )

        store = ClusterStore()
        kl = Kubelet(store, "kn1", capacity={"cpu": "4", "memory": "512Mi",
                                             "pods": "10"})
        kl.start()
        try:
            kl.eviction_manager = EvictionManager(
                store, "kn1", thresholds={"memory.available": "100Mi"},
                stats=CgroupStatsStub(store, "kn1", 512 * 1024 * 1024),
            )
            store.create_pod(MakePod().name("fat").uid("fu").node("kn1")
                             .req({"memory": "500Mi"}).obj())
            deadline = _time.time() + 5
            while _time.time() < deadline and \
                    store.get_pod("default", "fat").status.phase != "Failed":
                _time.sleep(0.05)
            victim = store.get_pod("default", "fat")
            assert victim.status.phase == "Failed"
            assert victim.status.reason == "Evicted"
            assert kl.eviction_manager.evicted == ["default/fat"]
        finally:
            kl.stop()

    def test_rank_consults_stats_provider_usage(self):
        """Pods ABOVE their memory request evict first even when a
        higher-priority pod uses more absolute memory (rankMemoryPressure
        usage-over-request tier)."""
        from kubernetes_tpu.kubelet.eviction import EvictionManager

        store = ClusterStore()
        store.add_node(MakeNode().name("n1")
                       .capacity({"cpu": "8", "memory": "1Gi"}).obj())
        over = MakePod().name("over").uid("o").node("n1").priority(1000) \
            .req({"memory": "100Mi"}).obj()
        within = MakePod().name("within").uid("w").node("n1").priority(0) \
            .req({"memory": "600Mi"}).obj()
        store.create_pod(over)
        store.create_pod(within)

        class Stats:
            def memory_available(self):
                return 0

            def pod_memory_usage(self, pod):
                return {"over": 500 * 2**20, "within": 400 * 2**20}[
                    pod.metadata.name]

        mgr = EvictionManager(store, "n1",
                              thresholds={"memory.available": "100Mi"},
                              stats=Stats())
        ranked = mgr._rank_pods()
        assert [p.metadata.name for p in ranked] == ["over", "within"]


class TestImageGC:
    def test_lru_images_freed_to_low_watermark(self):
        from kubernetes_tpu.api.types import ContainerImage, shallow_copy
        from kubernetes_tpu.kubelet.imagegc import ImageGCManager

        store = ClusterStore()
        store.add_node(MakeNode().name("n1").capacity({"cpu": "8"}).obj())
        node = store.get_node("n1")
        up = shallow_copy(node)
        up.status = shallow_copy(node.status)
        # 4 x 30 bytes on a 100-byte disk: 120% > high 85%
        up.status.images = [
            ContainerImage([f"img{i}"], 30) for i in range(4)
        ]
        store.update_node(up)
        # img3 is in use by a pod; img0 oldest, img2 most recently used
        p = MakePod().name("p").uid("pu").node("n1") \
            .container(image="img3").obj()
        store.create_pod(p)
        mgr = ImageGCManager(store, "n1", capacity_bytes=100,
                             high_threshold_percent=85,
                             low_threshold_percent=60)
        mgr.note_image_used("img0")
        mgr.note_image_used("img1")
        mgr.note_image_used("img2")
        freed = mgr.garbage_collect()
        # target 60 bytes: free img0 then img1 (LRU), keep img2 + in-use
        assert freed == ["img0", "img1"], freed
        remaining = {i.names[0]
                     for i in store.get_node("n1").status.images}
        assert remaining == {"img2", "img3"}
        # below the high watermark now: second pass is a no-op
        assert mgr.garbage_collect() == []

    def test_kubelet_housekeeping_drives_image_gc(self):
        import time as _time

        from kubernetes_tpu.api.types import ContainerImage, shallow_copy
        from kubernetes_tpu.kubelet import Kubelet
        from kubernetes_tpu.kubelet.imagegc import ImageGCManager

        store = ClusterStore()
        kl = Kubelet(store, "gc1", capacity={"cpu": "4", "memory": "1Gi",
                                             "pods": "10"})
        kl.start()
        try:
            node = store.get_node("gc1")
            up = shallow_copy(node)
            up.status = shallow_copy(node.status)
            up.status.images = [ContainerImage([f"i{j}"], 50)
                                for j in range(4)]
            store.update_node(up)
            mgr = ImageGCManager(store, "gc1", capacity_bytes=100,
                                 low_threshold_percent=50)
            mgr.GC_INTERVAL_SECONDS = 0.0
            kl.image_gc_manager = mgr
            deadline = _time.time() + 5
            while _time.time() < deadline and not mgr.freed:
                _time.sleep(0.05)
            assert mgr.freed, "housekeeping never ran image GC"
            assert sum(i.size_bytes
                       for i in store.get_node("gc1").status.images) <= 50
        finally:
            kl.stop()


class TestContainerManager:
    """QoS classes + cgroup tree (VERDICT r2 #10; reference
    cm/container_manager_linux.go:210, qos.go GetPodQOS)."""

    def test_qos_classification(self):
        from kubernetes_tpu.kubelet.cm import (
            BEST_EFFORT, BURSTABLE, GUARANTEED, pod_qos,
        )

        best_effort = MakePod().name("be").obj()
        assert pod_qos(best_effort) == BEST_EFFORT
        burstable = MakePod().name("bu").req({"cpu": "100m"}).obj()
        assert pod_qos(burstable) == BURSTABLE
        guaranteed = MakePod().name("g").req(
            {"cpu": "500m", "memory": "1Gi"}).obj()
        c = guaranteed.spec.containers[0]
        c.resources.limits = dict(c.resources.requests)
        assert pod_qos(guaranteed) == GUARANTEED
        # limits != requests -> burstable
        mixed = MakePod().name("m").req(
            {"cpu": "500m", "memory": "1Gi"}).obj()
        from kubernetes_tpu.api.resource import parse_quantity
        mixed.spec.containers[0].resources.limits = {
            "cpu": parse_quantity("1"), "memory": parse_quantity("1Gi"),
        }
        assert pod_qos(mixed) == BURSTABLE

    def test_cgroup_tree_and_qos_tiers(self):
        from kubernetes_tpu.api.resource import parse_quantity
        from kubernetes_tpu.kubelet.cm import ContainerManager

        cm = ContainerManager(capacity_cpu_milli=8000,
                              capacity_memory=16 * 2**30)
        bu = MakePod().name("bu").uid("bu1").req({"cpu": "500m"}).obj()
        cm.create_pod_cgroup(bu)
        g = MakePod().name("g").uid("g1").req(
            {"cpu": "1", "memory": "1Gi"}).obj()
        gc0 = g.spec.containers[0]
        gc0.resources.limits = dict(gc0.resources.requests)
        cm.create_pod_cgroup(g)
        tree = cm.tree()
        # guaranteed pod parents directly under /kubepods
        assert "/kubepods/podg1" in tree
        assert "/kubepods/burstable/podbu1" in tree
        # cm/helpers_linux.go MilliCPUToShares / MilliCPUToQuota
        assert tree["/kubepods/podg1"].cpu_shares == 1024
        assert tree["/kubepods/podg1"].cpu_quota == 100_000
        assert tree["/kubepods/podg1"].memory_limit == 2**30
        # burstable tier shares track the sum of its pods' requests
        assert tree["/kubepods/burstable"].cpu_shares == 512
        cm.delete_pod_cgroup("bu1")
        assert cm.tree()["/kubepods/burstable"].cpu_shares == 2
        assert "/kubepods/burstable/podbu1" not in cm.tree()

    def test_node_allocatable_admission(self):
        from kubernetes_tpu.kubelet.cm import ContainerManager

        cm = ContainerManager(capacity_cpu_milli=1000,
                              capacity_memory=2**30)
        ok = MakePod().name("a").uid("a1").req({"cpu": "800m"}).obj()
        assert cm.admit(ok) is None
        cm.create_pod_cgroup(ok)
        over = MakePod().name("b").uid("b1").req({"cpu": "500m"}).obj()
        reason = cm.admit(over)
        assert reason is not None and "OutOfcpu" in reason

    def test_kubelet_rejects_over_allocatable_pod(self):
        import time as _time

        store = ClusterStore()
        kl = Kubelet(store, "cmn1", capacity={"cpu": "1", "memory": "1Gi",
                                              "pods": "10"})
        kl.start()
        try:
            store.create_pod(MakePod().name("fits").uid("f1").node("cmn1")
                             .req({"cpu": "800m"}).obj())
            deadline = _time.time() + 5
            while _time.time() < deadline and \
                    store.get_pod("default", "fits").status.phase != "Running":
                _time.sleep(0.05)
            assert store.get_pod("default", "fits").status.phase == "Running"
            assert kl.container_manager.qos_of("f1") == "Burstable"
            store.create_pod(MakePod().name("over").uid("o1").node("cmn1")
                             .req({"cpu": "500m"}).obj())
            deadline = _time.time() + 5
            while _time.time() < deadline and \
                    store.get_pod("default", "over").status.phase != "Failed":
                _time.sleep(0.05)
            assert store.get_pod("default", "over").status.phase == "Failed"
        finally:
            kl.stop()


class TestPLEG:
    def test_relist_generates_lifecycle_events(self):
        from kubernetes_tpu.kubelet.cri import FakeRuntime
        from kubernetes_tpu.kubelet.pleg import (
            CONTAINER_DIED, CONTAINER_REMOVED, CONTAINER_STARTED, PLEG,
        )

        rt = FakeRuntime()
        got = []
        pleg = PLEG(rt, lambda ev: got.append((ev.type, ev.pod_uid)))
        sid = rt.run_pod_sandbox("u1", "p1", "default")
        cid = rt.create_container(sid, "c", "img")
        pleg.relist()          # CREATED state: no events yet
        assert got == []
        rt.start_container(cid)
        events = pleg.relist()
        assert [(e.type, e.pod_uid) for e in events] == \
            [(CONTAINER_STARTED, "u1")]
        rt.stop_container(cid)
        events = pleg.relist()
        assert events[0].type == CONTAINER_DIED
        rt.remove_container(cid)
        events = pleg.relist()
        assert events[0].type == CONTAINER_REMOVED
        assert got and pleg.events_emitted == 3
        assert pleg.healthy()
        # a container that starts AND crashes BETWEEN relists (first
        # sighting already EXITED) must still produce ContainerDied —
        # generic.go generateEvents emits it for any transition into
        # exited, which is the whole crash-loop coverage point
        sid2 = rt.run_pod_sandbox("u2", "p2", "default")
        cid2 = rt.create_container(sid2, "c", "img")
        rt.start_container(cid2)
        rt.stop_container(cid2)
        events = pleg.relist()
        assert [(e.type, e.pod_uid) for e in events] == \
            [(CONTAINER_DIED, "u2")]

    def test_pleg_drives_crash_restart(self):
        """A container exiting in the RUNTIME (no API event) must be
        observed by the PLEG relist and re-synced: restartPolicy Always
        restarts it (the reference's crash-loop path runs through
        plegCh, not the apiserver watch)."""
        import time as _time

        from kubernetes_tpu.kubelet.cri import FakeRuntime

        store = ClusterStore()
        rt = FakeRuntime()
        kl = Kubelet(store, "plegn1", runtime=rt,
                     capacity={"cpu": "4", "memory": "8Gi", "pods": "10"})
        kl.start()
        try:
            store.create_pod(MakePod().name("p").uid("pu").node("plegn1")
                             .req({"cpu": "100m"}).obj())
            deadline = _time.time() + 5
            while _time.time() < deadline and not kl._containers_of.get("pu"):
                _time.sleep(0.05)
            cid = list(kl._containers_of["pu"].values())[0]
            # kill the container BEHIND the kubelet's back
            rt.stop_container(cid)
            deadline = _time.time() + 5
            restarted = False
            while _time.time() < deadline:
                st = rt.container_status(cid)
                if st is not None and st.state == "RUNNING" and \
                        st.restarts >= 1:
                    restarted = True
                    break
                _time.sleep(0.05)
            assert restarted, "PLEG did not drive the crash restart"
        finally:
            kl.stop()


# ---------------------------------------------------------------------------
# Volume manager: desired/actual-state-of-world reconciler
# (reference pkg/kubelet/volumemanager/volume_manager.go:247,
#  reconciler/reconciler.go:77)


def _bound_pvc_pod(store, name, claim, pv_name, node="n1"):
    from kubernetes_tpu.api.resource import parse_quantity
    from kubernetes_tpu.api.types import (
        ObjectMeta, PersistentVolume, PersistentVolumeClaim,
    )

    store.add_pv(PersistentVolume(
        metadata=ObjectMeta(name=pv_name),
        capacity={"storage": parse_quantity("1Gi")},
    ))
    store.add_pvc(PersistentVolumeClaim(
        metadata=ObjectMeta(name=claim, namespace="default"),
        volume_name=pv_name, phase="Bound",
    ))
    pod = MakePod().name(name).uid(f"u-{name}").pvc(claim).obj()
    store.create_pod(pod)
    store.bind("default", name, pod.uid, node)
    return pod


def test_volume_gate_blocks_containers_until_attached(cluster):
    """A pod with a claim-backed volume must NOT start containers until
    the attachdetach controller reports the PV attached
    (WaitForAttachAndMount, volume_manager.go:387)."""
    store, kubelet = cluster
    pod = _bound_pvc_pod(store, "db", "data", "pv-1")
    # reconciler publishes volumesInUse from desired state BEFORE mount
    assert wait_for(
        lambda: store.get_node("n1").status.volumes_in_use == ["pv-1"]
    )
    time.sleep(0.6)  # several sync ticks
    assert store.get_pod("default", "db").status.phase != RUNNING
    assert not kubelet.running_pods(), "sandbox started before attach"
    assert kubelet.volumes.mounted(pod.uid) == []
    # the controller attaches -> mount -> containers start
    store.mutate_object(
        "Node", "", "n1",
        lambda n: n.status.__setattr__("volumes_attached", ["pv-1"]) or True,
    )
    assert wait_for(
        lambda: store.get_pod("default", "db").status.phase == RUNNING
    )
    assert kubelet.volumes.mounted(pod.uid) == ["vol0"]


def test_volume_teardown_ordering(cluster):
    """Unmount happens after the sandbox stops, and only the resulting
    volumesInUse shrink releases the controller's detach interlock."""
    store, kubelet = cluster
    pod = _bound_pvc_pod(store, "db2", "data2", "pv-2")
    store.mutate_object(
        "Node", "", "n1",
        lambda n: n.status.__setattr__("volumes_attached", ["pv-2"]) or True,
    )
    assert wait_for(
        lambda: store.get_pod("default", "db2").status.phase == RUNNING
    )
    assert store.get_node("n1").status.volumes_in_use == ["pv-2"]
    store.delete_pod("default", "db2")
    assert wait_for(lambda: not kubelet.running_pods())
    assert wait_for(
        lambda: store.get_node("n1").status.volumes_in_use == []
    )
    assert kubelet.volumes.mounted(pod.uid) == []
    # the sandbox is long gone by the time the in-use report shrank
    assert kubelet.runtime.list_pod_sandboxes() == []


def test_volume_attach_mount_detach_end_to_end():
    """Full handshake with the real attachdetach controller: attach ->
    mount -> run -> delete -> unmount -> detach."""
    from kubernetes_tpu.controllers import ControllerManager

    store = ClusterStore()
    cm = ControllerManager(store, controllers=["attachdetach"])
    cm.start()
    kubelet = Kubelet(store, "n1", capacity={"cpu": "8", "memory": "16Gi"})
    kubelet.start()
    try:
        _bound_pvc_pod(store, "web", "data3", "pv-3")
        # controller sees the scheduled pod and attaches; kubelet mounts
        assert wait_for(
            lambda: store.get_pod("default", "web").status.phase == RUNNING,
            timeout=10.0,
        )
        assert store.get_node("n1").status.volumes_attached == ["pv-3"]
        assert store.get_node("n1").status.volumes_in_use == ["pv-3"]
        store.delete_pod("default", "web")
        assert wait_for(
            lambda: store.get_node("n1").status.volumes_attached == [],
            timeout=10.0,
        ), "controller never detached after unmount"
    finally:
        kubelet.stop()
        cm.stop()


def test_local_volumes_mount_without_attach(cluster):
    """emptyDir-style volumes are node-local: no attach handshake."""
    from kubernetes_tpu.api.types import Volume

    store, kubelet = cluster
    pod = MakePod().name("scratch").uid("u-scratch").obj()
    pod.spec.volumes.append(Volume(name="tmp", ephemeral=True))
    store.create_pod(pod)
    store.bind("default", "scratch", pod.uid, "n1")
    assert wait_for(
        lambda: store.get_pod("default", "scratch").status.phase == RUNNING
    )
    assert kubelet.volumes.mounted(pod.uid) == ["tmp"]


# ---------------------------------------------------------------------------
# Static / mirror pods (reference pkg/kubelet/config/file.go +
# pkg/kubelet/pod/mirror_client.go)


def test_static_pod_runs_and_publishes_mirror():
    store = ClusterStore()
    manifest = {
        "metadata": {"name": "etcd", "namespace": "kube-system"},
        "spec": {"containers": [{"name": "etcd", "image": "etcd:3"}]},
    }
    kl = Kubelet(store, "cp-1", static_pod_manifests=[manifest])
    kl.start()
    try:
        # the mirror pod appears bound to this node with the mirror
        # annotation, and reaches Running without any scheduler
        assert wait_for(lambda: store.get_pod("kube-system", "etcd-cp-1")
                        is not None)
        mirror = store.get_pod("kube-system", "etcd-cp-1")
        assert mirror.spec.node_name == "cp-1"
        assert "kubernetes.io/config.mirror" in mirror.metadata.annotations
        assert wait_for(lambda: store.get_pod(
            "kube-system", "etcd-cp-1").status.phase == RUNNING)
        assert kl.running_pods()
    finally:
        kl.stop()


def test_mirror_deletion_never_stops_the_static_pod():
    store = ClusterStore()
    manifest = {
        "metadata": {"name": "apiserver", "namespace": "kube-system"},
        "spec": {"containers": [{"name": "a", "image": "apiserver:1"}]},
    }
    kl = Kubelet(store, "cp-1", static_pod_manifests=[manifest])
    kl.start()
    try:
        assert wait_for(lambda: store.get_pod(
            "kube-system", "apiserver-cp-1") is not None and store.get_pod(
            "kube-system", "apiserver-cp-1").status.phase == RUNNING)
        sandboxes_before = kl.runtime.list_pod_sandboxes()
        store.delete_pod("kube-system", "apiserver-cp-1")
        # republished, still Running, container never restarted
        assert wait_for(lambda: store.get_pod(
            "kube-system", "apiserver-cp-1") is not None)
        assert wait_for(lambda: store.get_pod(
            "kube-system", "apiserver-cp-1").status.phase == RUNNING)
        assert kl.runtime.list_pod_sandboxes() == sandboxes_before
    finally:
        kl.stop()


def test_static_pod_survives_kubelet_restart_without_duplication():
    """A kubelet restart must adopt the surviving mirror (stable static
    identity), not double-start the workload under a fresh uid."""
    store = ClusterStore()
    manifest = {
        "metadata": {"name": "etcd", "namespace": "kube-system"},
        "spec": {"containers": [{"name": "etcd", "image": "etcd:3"}]},
    }
    rt = FakeRuntime()
    kl = Kubelet(store, "cp-1", runtime=rt,
                 static_pod_manifests=[manifest])
    kl.start()
    try:
        assert wait_for(lambda: store.get_pod(
            "kube-system", "etcd-cp-1") is not None and store.get_pod(
            "kube-system", "etcd-cp-1").status.phase == RUNNING)
        uid_before = store.get_pod("kube-system", "etcd-cp-1").uid
    finally:
        kl.stop()
    # restart against the SAME store and runtime
    kl2 = Kubelet(store, "cp-1", runtime=rt,
                  static_pod_manifests=[manifest])
    kl2.start()
    try:
        time.sleep(0.6)
        mirror = store.get_pod("kube-system", "etcd-cp-1")
        assert mirror is not None and mirror.uid == uid_before
        # exactly one copy of the workload (no duplicate sandbox)
        assert len([s for s in kl2.runtime.list_pod_sandboxes()]) <= 1
        pods = [p for p in store.list_pods()
                if p.metadata.name == "etcd-cp-1"]
        assert len(pods) == 1
    finally:
        kl2.stop()


def test_static_pod_mirrors_do_not_collide_across_kubelets():
    """Two kubelets loading the SAME manifest get per-node mirror names
    (reference suffixes static pod names with the node name) — without
    the suffix each kubelet would see the other's mirror as a stale
    incarnation and delete/recreate it forever."""
    store = ClusterStore()
    manifest = {
        "metadata": {"name": "kube-proxy", "namespace": "kube-system"},
        "spec": {"containers": [{"name": "p", "image": "proxy:1"}]},
    }
    kl1 = Kubelet(store, "n1", static_pod_manifests=[manifest])
    kl2 = Kubelet(store, "n2", static_pod_manifests=[manifest])
    kl1.start()
    kl2.start()
    try:
        assert wait_for(lambda: store.get_pod(
            "kube-system", "kube-proxy-n1") is not None)
        assert wait_for(lambda: store.get_pod(
            "kube-system", "kube-proxy-n2") is not None)
        m1 = store.get_pod("kube-system", "kube-proxy-n1")
        m2 = store.get_pod("kube-system", "kube-proxy-n2")
        assert m1.spec.node_name == "n1" and m2.spec.node_name == "n2"
        uid1, uid2 = m1.uid, m2.uid
        # both mirrors remain stable (no delete/recreate fight)
        time.sleep(0.6)
        assert store.get_pod("kube-system", "kube-proxy-n1").uid == uid1
        assert store.get_pod("kube-system", "kube-proxy-n2").uid == uid2
    finally:
        kl1.stop()
        kl2.stop()


# ---------------------------------------------------------------------------
# Init containers (reference kuberuntime_manager.go computePodActions:
# one at a time, each to successful completion, before app containers)


def _pod_with_inits(store, name, inits, main="app", node="n1",
                    restart_policy="Always"):
    from kubernetes_tpu.api.types import Container

    pod = MakePod().name(name).uid(f"u-{name}").container(image=main).obj()
    pod.spec.init_containers = [
        Container(name=f"init-{i}", image=img)
        for i, img in enumerate(inits)
    ]
    pod.spec.restart_policy = restart_policy
    store.create_pod(pod)
    store.bind("default", name, pod.uid, node)
    return pod


def test_init_containers_run_sequentially_before_main():
    store = ClusterStore()
    rt = FakeRuntime(exit_after={"init-a": 0.1, "init-b": 0.1})
    kl = Kubelet(store, "n1", runtime=rt)
    kl.start()
    try:
        pod = _pod_with_inits(store, "web", ["init-a", "init-b"])
        # pod stays Pending while inits run; Initialized=False published
        assert wait_for(lambda: any(
            c.type == "Initialized" and c.status == "False"
            for c in store.get_pod("default", "web").status.conditions))
        assert store.get_pod("default", "web").status.phase != RUNNING
        # both inits complete -> main starts -> Running + Initialized
        assert wait_for(lambda: store.get_pod(
            "default", "web").status.phase == RUNNING, timeout=10)
        conds = {c.type: c.status
                 for c in store.get_pod("default", "web").status.conditions}
        assert conds.get("Initialized") == "True"
        # the two init containers ran to completion, one at a time
        inits = [c for c in rt.list_containers()
                 if c.image.startswith("init-")]
        assert len(inits) == 2
        assert all(c.state == "EXITED" and c.exit_code == 0
                   for c in inits)
        # sequencing: init-a finished before init-b started
        a = next(c for c in inits if c.image == "init-a")
        b = next(c for c in inits if c.image == "init-b")
        assert a.finished_at <= b.started_at
    finally:
        kl.stop()


def test_failed_init_container_fails_pod_with_never_policy():
    store = ClusterStore()
    rt = FakeRuntime(fail_images={"bad-init"})
    kl = Kubelet(store, "n1", runtime=rt)
    kl.start()
    try:
        _pod_with_inits(store, "doomed", ["bad-init"],
                        restart_policy="Never")
        assert wait_for(lambda: store.get_pod(
            "default", "doomed").status.phase == FAILED, timeout=10)
        # the main container never started
        assert not any(c.image == "app" for c in rt.list_containers())
    finally:
        kl.stop()


def test_failed_init_container_retries_under_always_policy():
    store = ClusterStore()
    rt = FakeRuntime(fail_images={"flaky-init"})
    kl = Kubelet(store, "n1", runtime=rt)
    kl.start()
    try:
        _pod_with_inits(store, "retrying", ["flaky-init"])
        # the init container is restarted rather than the pod failing
        def restarted():
            cs = [c for c in rt.list_containers()
                  if c.image == "flaky-init"]
            return cs and cs[0].restarts >= 2
        assert wait_for(restarted, timeout=10)
        assert store.get_pod("default", "retrying").status.phase != FAILED
    finally:
        kl.stop()


def test_init_phase_survives_kubelet_restart():
    """A kubelet restart mid-init must resume the init sequence from
    runtime truth — not reconcile init containers as app containers."""
    store = ClusterStore()
    rt = FakeRuntime()   # init never exits on its own: pod is mid-init
    kl = Kubelet(store, "n1", runtime=rt)
    kl.start()
    pod = None
    try:
        pod = _pod_with_inits(store, "web", ["slow-init"])
        assert wait_for(lambda: any(
            c.image == "slow-init" for c in rt.list_containers()))
    finally:
        kl.stop()
    kl2 = Kubelet(store, "n1", runtime=rt)
    kl2.start()
    try:
        time.sleep(0.5)
        # still exactly one init container, no app container, and the
        # pod is still Pending (not Succeeded/restart-looped)
        imgs = [c.image for c in rt.list_containers()]
        assert imgs.count("slow-init") == 1
        assert "app" not in imgs
        assert store.get_pod("default", "web").status.phase != RUNNING
        # init completes (simulated by stopping it with exit 0 via the
        # runtime's batch hook): the adopted kubelet starts the main
        init_cid = next(c.id for c in rt.list_containers()
                        if c.image == "slow-init")
        with rt._lock:
            st = rt._containers[init_cid]
            st.state = "EXITED"
            st.exit_code = 0
            st.finished_at = time.time()
        assert wait_for(lambda: store.get_pod(
            "default", "web").status.phase == RUNNING, timeout=10)
        assert any(c.image == "app" for c in rt.list_containers())
    finally:
        kl2.stop()


# ---------------------------------------------------------------------------
# Graceful termination + lifecycle hooks (reference pod_workers
# terminating state, kuberuntime lifecycle.go)


def test_prestop_hook_and_graceful_stop_order(cluster):
    store, kubelet = cluster
    pod = MakePod().name("web").uid("u-grace").container(image="app").obj()
    pod.spec.containers[0].lifecycle = {
        "preStop": {"exec": {"command": ["/bin/drain"]}},
        "postStart": {"exec": {"command": ["/bin/warm"]}},
    }
    store.create_pod(pod)
    store.bind("default", "web", pod.uid, "n1")
    assert wait_for(lambda: store.get_pod(
        "default", "web").status.phase == RUNNING)
    # postStart ran at container start
    assert any(p[1] == {"exec": {"command": ["/bin/warm"]}}
               for p in kubelet.runtime.exec_records)
    cid = list(kubelet._containers_of[pod.uid].values())[0]
    store.delete_pod("default", "web")
    assert wait_for(lambda: not kubelet.running_pods())
    # preStop ran IN the still-running container before the stop
    pre = [(c, p) for c, p in kubelet.runtime.exec_records
           if p == {"exec": {"command": ["/bin/drain"]}}]
    assert pre == [(cid, {"exec": {"command": ["/bin/drain"]}})]


def test_force_kill_after_grace_deadline():
    """A runtime whose containers ignore the stop request drains until
    the grace deadline, then the kubelet force-releases the sandbox."""
    class StubbornRuntime(FakeRuntime):
        def stop_container(self, container_id, timeout_s=30.0):
            # SIGTERM ignored: the container keeps running
            pass

    store = ClusterStore()
    kl = Kubelet(store, "n1", runtime=StubbornRuntime())
    kl.sync_interval = 0.05
    kl.start()
    try:
        pod = MakePod().name("stuck").uid("u-stuck") \
            .container(image="app").obj()
        pod.spec.termination_grace_period_seconds = 0.4
        store.create_pod(pod)
        store.bind("default", "stuck", pod.uid, "n1")
        assert wait_for(lambda: kl.running_pods())
        t0 = time.time()
        store.delete_pod("default", "stuck")
        # still draining inside the grace window
        time.sleep(0.15)
        assert kl.running_pods(), "released before the grace deadline"
        assert wait_for(lambda: not kl.running_pods(), timeout=5)
        assert time.time() - t0 >= 0.35, "force-kill fired early"
    finally:
        kl.stop()


def test_zero_grace_kills_immediately(cluster):
    store, kubelet = cluster
    pod = MakePod().name("fast").uid("u-fast").container(image="app").obj()
    pod.spec.termination_grace_period_seconds = 0
    store.create_pod(pod)
    store.bind("default", "fast", pod.uid, "n1")
    assert wait_for(lambda: kubelet.running_pods())
    store.delete_pod("default", "fast")
    assert wait_for(lambda: not kubelet.running_pods(), timeout=3)
