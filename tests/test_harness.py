"""Perf-harness smoke tests (small scales; the real numbers come from
bench.py on TPU hardware)."""

import pytest

from kubernetes_tpu.harness import WORKLOADS, make_workload, run_workload


class TestHarness:
    def test_scheduling_basic_serial(self):
        ops = make_workload("SchedulingBasic", nodes=20, init_pods=10,
                            measure_pods=30)
        result = run_workload("SchedulingBasic", ops, use_batch=False,
                              wait_timeout=60)
        assert result.total_pods == 40
        assert result.pods_per_second > 0

    def test_scheduling_basic_batch(self):
        ops = make_workload("SchedulingBasic", nodes=20, init_pods=10,
                            measure_pods=30)
        result = run_workload("SchedulingBasic", ops, use_batch=True,
                              wait_timeout=120)
        assert result.total_pods == 40
        assert result.pods_per_second > 0

    def test_topology_spreading_batch(self):
        ops = make_workload("TopologySpreading", nodes=20, init_pods=0,
                            measure_pods=20)
        result = run_workload("TopologySpreading", ops, use_batch=True,
                              wait_timeout=120)
        assert result.measured_pods == 20

    def test_unschedulable_leaves_pending(self):
        ops = make_workload("Unschedulable", nodes=10, init_pods=5,
                            measure_pods=10)
        result = run_workload("Unschedulable", ops, use_batch=False,
                              wait_timeout=60)
        assert result.total_pods == 15

    def test_data_items_shape(self):
        ops = make_workload("SchedulingBasic", nodes=5, init_pods=0,
                            measure_pods=5)
        result = run_workload("SchedulingBasic", ops, wait_timeout=60)
        items = result.data_items()
        assert items["version"] == "v1"
        metrics = {i["labels"]["Metric"] for i in items["dataItems"]}
        assert "SchedulingThroughput" in metrics

    @pytest.mark.parametrize("name", sorted(WORKLOADS))
    def test_all_workloads_build(self, name):
        ops = make_workload(name, nodes=10, init_pods=4, measure_pods=4)
        assert any(op["opcode"] == "createPods" for op in ops)
