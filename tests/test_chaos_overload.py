"""Overload chaos suite (harness/chaos_overload.py): seeded
multi-tenant abuse cells against the APF-guarded fabric. The fast
smoke cell runs in tier-1; the full shape x seed matrix rides the
``chaos``/``slow`` markers like the other chaos rings."""

import pytest

from kubernetes_tpu.harness.chaos_overload import (
    OVERLOAD_PROFILES,
    overload_fault_spec,
    run_chaos_overload,
)


def _fmt(r):
    return (f"invariants={r['invariants']} failure={r['failure']!r} "
            f"stats={r['stats']}")


class TestOverloadCellSmoke:
    @pytest.mark.chaos
    def test_bulkabuse_cell_holds_invariants(self):
        """One small seeded cell in tier-1: bulk-verb abuse under small
        seat budgets — zero lost pods, exempt envelope intact, no
        starved flow, bulk width proportional."""
        r = run_chaos_overload(seed=11, nodes=6, pods=24, tenants=2,
                               waves=2, overload_profile="bulkabuse",
                               wait_timeout=60.0)
        assert r["ok"], _fmt(r)
        assert r["invariants"]["bulk_width_proportional"]
        assert r["stats"]["aggressor_requests"] > 0

    def test_fault_spec_is_seeded_and_valid(self):
        from kubernetes_tpu.apiserver.faults import FaultRule

        spec = overload_fault_spec(23)
        assert spec["seed"] == 23
        for rule in spec["rules"]:
            FaultRule.from_dict(rule)   # must parse


class TestOverloadMatrix:
    @pytest.mark.chaos
    @pytest.mark.slow
    @pytest.mark.parametrize("profile", sorted(OVERLOAD_PROFILES))
    def test_profile_cells_pass(self, profile):
        """Every overload shape, two seeds each, at matrix scale: the
        acceptance invariants (no starved flow, exempt always served,
        rate equivalence, zero lost pods) hold per cell."""
        for seed in (11, 23):
            r = run_chaos_overload(seed=seed, nodes=12, pods=96,
                                   tenants=4,
                                   overload_profile=profile,
                                   wait_timeout=120.0)
            assert r["ok"], f"{profile}/seed={seed}: {_fmt(r)}"

    @pytest.mark.chaos
    @pytest.mark.slow
    def test_saturation_actually_saturates(self):
        """The saturation cell must drive the workload level to its
        seat capacity — an idle cell proves nothing."""
        r = run_chaos_overload(seed=37, nodes=12, pods=96, tenants=4,
                               overload_profile="saturation",
                               wait_timeout=120.0)
        assert r["ok"], _fmt(r)
        assert r["invariants"]["apf_engaged"]
        assert r["stats"]["workload_peak_seats"] \
            >= r["stats"]["workload_capacity"]
