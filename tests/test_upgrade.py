"""Rolling-upgrade orchestrator (harness/upgrade.py): the fleet never
stops serving.

The tier-1 surface of PR 16 — one REAL compressed roll plus the cheap
contracts around it:

- ``TestRollingMiniCell`` — a 2-partition fleet (in-process apiservers
  with the real wire stack) + 1 scheduler replica rolled one process
  at a time while a writer streams pods in over REST: informer ≡
  server truth at quiesce, zero lost/duplicated watch events, zero
  relists of unmoved slices, no resourceVersion regressions, and the
  mixed-version guard exercised by one client pinned to
  ``codec_version=1`` that must stay pinned (and re-negotiate) across
  every restart seam.
- ``TestUpgradeDiag`` — ``diagfmt.format_upgrade`` round-trips through
  the shared bracket parser and honours the quiet convention.
- ``TestUpgradeContracts`` — scenario validation and the
  ``_upgrade_ok`` verdict surface on synthetic results (every checked
  invariant flips the verdict).

The full-fleet spawned-process roll (3 partitions + 2 replicas at
open-loop 5k QPS) is the committed bench row (``upgrade_rows.log``)
and the ``--suite upgrade`` chaos cells — too heavy for tier-1; this
mini-cell walks the same seams at CI scale.
"""

from __future__ import annotations

import pytest

from kubernetes_tpu.harness import diagfmt
from kubernetes_tpu.harness.upgrade import (
    UPGRADE_SCENARIOS,
    _upgrade_ok,
    run_chaos_upgrade,
    run_upgrade_mini_cell,
)


# ---------------------------------------------------------------------------
# the real roll, compressed


@pytest.fixture(scope="module")
def mini_cell():
    """One rolled fleet shared by every invariant assertion: the roll
    is the expensive part; the checks are reads of its result."""
    return run_upgrade_mini_cell(nodes=200, pods=160, partitions=2)


class TestRollingMiniCell:
    def test_no_errors_and_all_pods_survive(self, mini_cell):
        assert mini_cell["errors"] == []
        assert mini_cell["confirmed"] == 160
        assert mini_cell["server_pods"] == 160
        assert mini_cell["duplicates"] == 0

    def test_every_pod_bound_through_the_roll(self, mini_cell):
        # the scheduler replica was itself restarted mid-stream; every
        # confirmed pod must still end bound on the servers
        assert mini_cell["server_bound"] == mini_cell["server_pods"]
        assert mini_cell["scheduled"] >= mini_cell["confirmed"]

    def test_whole_fleet_rolled_exactly_once(self, mini_cell):
        assert mini_cell["rolled_partitions"] == 2
        assert mini_cell["rolled_replicas"] == 1
        assert all(r["rolled"] for r in mini_cell["partition_records"])

    def test_informer_equals_server_truth_at_quiesce(self, mini_cell):
        # the CompositeCursor contract across BOTH partition seams and
        # the replica seam: nothing missing, nothing extra, nothing
        # stale — summed into lost_watches which MUST be zero
        assert mini_cell["missing"] == []
        assert mini_cell["extra"] == []
        assert mini_cell["stale"] == []
        assert mini_cell["lost_watches"] == 0
        assert mini_cell["informer_pods"] == mini_cell["server_pods"]

    def test_no_relists_of_unmoved_slices(self, mini_cell):
        # a restart seam is a handoff, not a relist: the replumb owns
        # the seam and carries cursors over; an in-loop reconnect that
        # relisted would show up here
        assert mini_cell["unmoved_relists"] == 0

    def test_no_resource_version_regressions(self, mini_cell):
        assert mini_cell["rv_regressions"] == []

    def test_one_topology_epoch_fleet_wide(self, mini_cell):
        # bootstrap epoch 1 + one reroute per rolled partition
        assert mini_cell["epoch"] == 3

    def test_mixed_version_guard_holds_across_seams(self, mini_cell):
        # the v1-pinned client negotiated v1 on every partition, was
        # forced to RE-negotiate across each restart seam (>= one per
        # rolled partition), and was never refused
        assert mini_cell["v1_pin_ok"]
        assert all(v == 1
                   for v in mini_cell["v1_negotiated"].values())
        assert mini_cell["v1_renegotiations"] >= 2
        assert mini_cell["codec_failures"] == 0

    def test_freeze_windows_stayed_bounded(self, mini_cell):
        # in-proc rolls carry no process spawn; the write-freeze
        # window must stay well under the 2 s drain budget
        assert 0.0 < mini_cell["frozen_ms_max"] < 2000.0


# ---------------------------------------------------------------------------
# diag segment: one writer, one parser


class TestUpgradeDiag:
    def test_round_trips_through_shared_parser(self):
        seg = diagfmt.format_upgrade({
            "rolled": 5, "frozen_ms_max": 326.71, "reneg": 8,
            "lost": 0, "relists": 0})
        parsed = diagfmt.parse_diag(diagfmt.format_diag([seg]))
        assert parsed["upgrade"]["rolled"] == 5
        assert parsed["upgrade"]["frozen_ms_max"] == pytest.approx(
            326.7)
        assert parsed["upgrade"]["reneg"] == 8
        assert parsed["upgrade"]["lost"] == 0
        assert parsed["upgrade"]["relists"] == 0

    def test_quiet_convention(self):
        assert diagfmt.format_upgrade(None) == ""
        assert diagfmt.format_upgrade({}) == ""


# ---------------------------------------------------------------------------
# cheap contracts: scenario surface + verdict function


def _green_result() -> dict:
    return {
        "lost_pods": 0, "injected": 200, "ever_bound": 200,
        "send_errors": [], "duplicates": 0, "doubles": 0,
        "lost_watches": 0, "unmoved_relists": 0, "rv_regressions": 0,
        "rolled_exactly_once": True, "epochs": [3],
        "frozen_ms_max": 326.7, "freeze_budget_ms": 2000.0,
        "codec_failures": 0, "v1_pin_ok": True,
        "slo_verdicts_ok": True,
    }


class TestUpgradeContracts:
    def test_scenario_names_are_the_matrix_axes(self):
        # roll order × SIGKILL-mid-roll: the four cells the chaos
        # suite crosses
        assert UPGRADE_SCENARIOS == (
            "partitions-first", "schedulers-first",
            "sigkill-partitions-first", "sigkill-schedulers-first")

    def test_unknown_scenario_rejected(self):
        with pytest.raises(ValueError, match="scenario"):
            run_chaos_upgrade(1, scenario="upside-down")

    def test_green_result_passes(self):
        ok, why = _upgrade_ok(_green_result())
        assert ok, why
        assert why == ""

    @pytest.mark.parametrize("mutation,needle", [
        ({"lost_pods": 1}, "lost_pods"),
        ({"ever_bound": 150}, "all_bound"),
        ({"send_errors": ["boom"]}, "send_errors"),
        ({"duplicates": 2}, "duplicates"),
        ({"doubles": 1}, "doubles"),
        ({"lost_watches": 1}, "lost_watches"),
        ({"unmoved_relists": 1}, "unmoved_relists"),
        ({"rv_regressions": 1}, "rv_regressions"),
        ({"rolled_exactly_once": False}, "rolled_exactly_once"),
        ({"epochs": [2, 3]}, "one_epoch"),
        ({"frozen_ms_max": 2500.0}, "freeze_budget"),
        ({"codec_failures": 1}, "codec_failures"),
        ({"v1_pin_ok": False}, "v1_pin"),
        ({"slo_verdicts_ok": False}, "slo"),
    ])
    def test_each_invariant_flips_the_verdict(self, mutation, needle):
        res = _green_result()
        res.update(mutation)
        ok, why = _upgrade_ok(res)
        assert not ok
        assert needle in why
