"""RBAC authorization (VERDICT r2 #5; reference
``plugin/pkg/auth/authorizer/rbac/rbac.go:159`` + ``pkg/registry/rbac/``
+ bootstrappolicy): Role/ClusterRole/(Cluster)RoleBinding objects, the
store-backed authorizer behind the API server's Authorizer seam,
bootstrap-provisioned component grants, and ``kubectl auth can-i``."""

import io

from kubernetes_tpu.api.types import (
    ClusterRole,
    ClusterRoleBinding,
    ObjectMeta,
    PolicyRule,
    RBACSubject,
    Role,
    RoleBinding,
    RoleRef,
)
from kubernetes_tpu.apiserver.rbac import (
    RBACAuthorizer,
    provision_bootstrap_policy,
    rule_allows,
)
from kubernetes_tpu.apiserver.rest import APIServer, RestClient
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.bootstrap import Cluster
from kubernetes_tpu.cli.kubectl import run_command
from kubernetes_tpu.testing import MakeNode, MakePod


class TestRuleMatching:
    def test_wildcards(self):
        assert rule_allows(PolicyRule(verbs=["*"], resources=["*"]),
                           "delete", "nodes")
        assert rule_allows(PolicyRule(verbs=["get"], resources=["pods"]),
                           "get", "pods")
        assert not rule_allows(PolicyRule(verbs=["get"], resources=["pods"]),
                               "delete", "pods")
        assert not rule_allows(PolicyRule(verbs=["get"], resources=["pods"]),
                               "get", "nodes")

    def test_resource_names_scope(self):
        rule = PolicyRule(verbs=["get"], resources=["configmaps"],
                          resource_names=["the-one"])
        assert rule_allows(rule, "get", "configmaps", "the-one")
        assert not rule_allows(rule, "get", "configmaps", "other")
        # list carries no name: named rules never grant it
        assert not rule_allows(rule, "get", "configmaps", "")


class TestAuthorizer:
    def _store_with_policy(self):
        store = ClusterStore()
        store.add_cluster_role(ClusterRole(
            metadata=ObjectMeta(name="pod-reader"),
            rules=[PolicyRule(verbs=["get", "list", "watch"],
                              resources=["pods"])],
        ))
        store.add_cluster_role_binding(ClusterRoleBinding(
            metadata=ObjectMeta(name="alice-reads"),
            subjects=[RBACSubject(kind="User", name="alice")],
            role_ref=RoleRef(kind="ClusterRole", name="pod-reader"),
        ))
        store.add_role(Role(
            metadata=ObjectMeta(name="deployer", namespace="dev"),
            rules=[PolicyRule(verbs=["*"], resources=["deployments"])],
        ))
        store.add_role_binding(RoleBinding(
            metadata=ObjectMeta(name="bob-deploys", namespace="dev"),
            subjects=[RBACSubject(kind="User", name="bob")],
            role_ref=RoleRef(kind="Role", name="deployer"),
        ))
        return store

    def test_cluster_role_binding_grants_cluster_wide(self):
        authz = RBACAuthorizer(self._store_with_policy())
        assert authz.authorize("alice", "get", "pods", "any-ns")
        assert authz.authorize("alice", "list", "pods")
        assert not authz.authorize("alice", "delete", "pods", "any-ns")
        assert not authz.authorize("mallory", "get", "pods", "any-ns")

    def test_role_binding_is_namespace_scoped(self):
        authz = RBACAuthorizer(self._store_with_policy())
        assert authz.authorize("bob", "create", "deployments", "dev")
        assert not authz.authorize("bob", "create", "deployments", "prod")
        assert not authz.authorize("bob", "create", "deployments", "")

    def test_rolebinding_to_clusterrole_scopes_down(self):
        store = self._store_with_policy()
        store.add_role_binding(RoleBinding(
            metadata=ObjectMeta(name="carol-reads-dev", namespace="dev"),
            subjects=[RBACSubject(kind="User", name="carol")],
            role_ref=RoleRef(kind="ClusterRole", name="pod-reader"),
        ))
        authz = RBACAuthorizer(store)
        assert authz.authorize("carol", "get", "pods", "dev")
        assert not authz.authorize("carol", "get", "pods", "prod")

    def test_group_subjects_and_masters(self):
        store = ClusterStore()
        store.add_cluster_role(ClusterRole(
            metadata=ObjectMeta(name="reader"),
            rules=[PolicyRule(verbs=["get"], resources=["pods"])],
        ))
        store.add_cluster_role_binding(ClusterRoleBinding(
            metadata=ObjectMeta(name="authenticated-read"),
            subjects=[RBACSubject(kind="Group",
                                  name="system:authenticated")],
            role_ref=RoleRef(kind="ClusterRole", name="reader"),
        ))
        authz = RBACAuthorizer(store)
        assert authz.authorize("anyone", "get", "pods", "ns")
        assert not authz.authorize("system:anonymous", "get", "pods", "ns")
        authz.add_user_to_group("root", "system:masters")
        assert authz.authorize("root", "delete", "nodes")

    def test_kind_names_normalize_to_plurals(self):
        # the REST handler passes kinds ("Pod", "Binding"); rules use
        # plurals ("pods", "bindings")
        store = self._store_with_policy()
        authz = RBACAuthorizer(store)
        assert authz.authorize("alice", "get", "Pod", "ns")
        assert not authz.authorize("alice", "get", "Node", "ns")


class TestBootstrapPolicyIntegration:
    """VERDICT done-condition: the scheduler token can bind pods but
    cannot delete nodes — through the real HTTP stack."""

    def _serve(self):
        store = ClusterStore()
        authz = provision_bootstrap_policy(store)
        server = APIServer(
            store=store,
            authorizer=authz,
            tokens={"sched-token": "system:kube-scheduler",
                    "admin-token": "admin"},
        ).start()
        return store, server

    def test_scheduler_can_bind_but_not_delete_nodes(self):
        store, server = self._serve()
        try:
            store.add_node(MakeNode().name("n1")
                           .capacity({"cpu": "8", "memory": "16Gi"}).obj())
            store.create_pod(MakePod().name("p1").uid("u1")
                             .req({"cpu": "1"}).obj())
            sched = RestClient(server.url, token="sched-token")
            # bind succeeds
            sched.bind("default", "p1", "u1", "n1")
            assert store.get_pod("default", "p1").spec.node_name == "n1"
            # delete nodes: forbidden (403 -> PermissionError)
            try:
                sched.delete("Node", "n1", namespace=None)
                raise AssertionError("scheduler deleted a node")
            except PermissionError:
                pass
            # pods it may read and delete (preemption)
            assert sched.get("Pod", "p1") is not None
        finally:
            server.shutdown_server()

    def test_anonymous_is_denied_admin_is_not(self):
        store, server = self._serve()
        try:
            store.add_node(MakeNode().name("n1").obj())
            anon = RestClient(server.url)
            try:
                anon.list("Pod")
                raise AssertionError("anonymous listed pods")
            except PermissionError:
                pass
            admin = RestClient(server.url, token="admin-token")
            admin.list("Pod")  # no raise: system:masters short-circuit
        finally:
            server.shutdown_server()

    def test_pods_log_is_its_own_rbac_resource(self):
        """A role granting only "get pods" must NOT read container logs
        — pods/log is a distinct RBAC resource in the reference
        bootstrap policy (policy.go NodeRules / system:kubelet-api-admin)."""
        store = ClusterStore()
        authz = provision_bootstrap_policy(store)
        store.add_cluster_role(ClusterRole(
            metadata=ObjectMeta(name="pod-reader"),
            rules=[PolicyRule(verbs=["get", "list"],
                              resources=["pods"])],
        ))
        store.add_cluster_role_binding(ClusterRoleBinding(
            metadata=ObjectMeta(name="bob-reads-pods"),
            subjects=[RBACSubject(kind="User", name="bob")],
            role_ref=RoleRef(kind="ClusterRole", name="pod-reader"),
        ))
        server = APIServer(
            store=store, authorizer=authz,
            tokens={"bob-token": "bob", "admin-token": "admin"},
        ).start()
        try:
            store.create_pod(MakePod().name("w").uid("u-w").obj())
            bob = RestClient(server.url, token="bob-token")
            assert bob.get("Pod", "w") is not None   # pods: granted
            code, _ = bob._request(
                "GET", "/api/v1/namespaces/default/pods/w/log")
            assert code == 403                       # pods/log: not
            # granting pods/log unlocks it (404: no kubelet registered,
            # but the request passed authorization)
            store.add_cluster_role(ClusterRole(
                metadata=ObjectMeta(name="log-reader"),
                rules=[PolicyRule(verbs=["get"],
                                  resources=["pods/log"])],
            ))
            store.add_cluster_role_binding(ClusterRoleBinding(
                metadata=ObjectMeta(name="bob-reads-logs"),
                subjects=[RBACSubject(kind="User", name="bob")],
                role_ref=RoleRef(kind="ClusterRole", name="log-reader"),
            ))
            code, _ = bob._request(
                "GET", "/api/v1/namespaces/default/pods/w/log")
            assert code == 404
        finally:
            server.shutdown_server()

    def test_rbac_objects_have_rest_routes(self):
        store, server = self._serve()
        try:
            admin = RestClient(server.url, token="admin-token")
            roles, _ = admin.list("ClusterRole")
            assert any(r.metadata.name == "system:kube-scheduler"
                       for r in roles)
            admin.create(Role(
                metadata=ObjectMeta(name="r1", namespace="default"),
                rules=[PolicyRule(verbs=["get"], resources=["pods"])],
            ))
            got = admin.get("Role", "r1")
            assert got.rules[0].verbs == ["get"]
        finally:
            server.shutdown_server()


class TestKubectlCanI:
    def test_can_i_through_cluster(self):
        cluster = Cluster.up(nodes=1)
        try:
            sched_client = cluster.client(
                cluster.component_tokens["kube-scheduler"])
            out = io.StringIO()
            rc = run_command(["auth", "can-i", "create", "bindings"],
                             client=sched_client, out=out)
            assert rc == 0 and out.getvalue().strip() == "yes"
            out = io.StringIO()
            rc = run_command(["auth", "can-i", "delete", "nodes"],
                             client=sched_client, out=out)
            assert rc == 1 and out.getvalue().strip() == "no"
            # the default porcelain client is cluster-admin
            out = io.StringIO()
            rc = run_command(["auth", "can-i", "delete", "nodes"],
                             client=cluster.client(), out=out)
            assert rc == 0 and out.getvalue().strip() == "yes"
        finally:
            cluster.down()
