"""Metrics registry + event recorder hot-path semantics."""

from kubernetes_tpu.metrics.registry import Histogram


class TestHistogramBulk:
    def test_observe_many_matches_observe(self):
        a = Histogram("h_a", "", ("result",))
        b = Histogram("h_b", "", ("result",))
        values = [0.0005, 0.003, 0.05, 0.7, 3.0, 30.0, 100.0]
        for v in values:
            a.observe(v, "x")
        b.observe_many(values, "x")
        assert a.count("x") == b.count("x") == len(values)
        assert abs(a.sum("x") - b.sum("x")) < 1e-12
        for q in (0.5, 0.9, 0.99):
            assert a.quantile(q, "x") == b.quantile(q, "x")

    def test_observe_many_empty_is_noop(self):
        h = Histogram("h_c", "")
        h.observe_many([])
        assert h.count() == 0


class TestHistogramEdgeCases:
    """``bucket_counts``/``quantile`` corners — the diag e2e segment
    and the SLO evaluator's windowed-delta math both sit on these
    accessors, so the degenerate shapes must be pinned down."""

    BUCKETS = (0.1, 1.0, 5.0)

    def _h(self) -> Histogram:
        return Histogram("h_edge", "", buckets=self.BUCKETS)

    def test_empty_histogram(self):
        h = self._h()
        assert h.bucket_counts() == []
        assert h.count() == 0 and h.sum() == 0.0
        for q in (0.0, 0.5, 0.99, 1.0):
            assert h.quantile(q) == 0.0
        # an unobserved labelled series is just as empty
        hl = Histogram("h_edge_l", "", ("k",), buckets=self.BUCKETS)
        assert hl.bucket_counts("never") == []
        assert hl.quantile(0.99, "never") == 0.0

    def test_single_sample(self):
        h = self._h()
        h.observe(0.5)
        assert h.bucket_counts() == [0, 1, 0, 0]
        assert h.count() == 1 and h.sum() == 0.5
        # every quantile interpolates inside the one occupied bucket
        # (0.1, 1.0]: q of a single sample spans the bucket linearly
        assert h.quantile(0.5) == 0.1 + 0.9 * 0.5
        assert h.quantile(1.0) == 1.0

    def test_everything_in_overflow_bucket(self):
        h = self._h()
        h.observe_many([9.0, 50.0, 1e6])
        assert h.bucket_counts() == [0, 0, 0, 3]
        # +Inf has no upper edge to interpolate toward: clamp to the
        # largest finite edge (prometheus histogram_quantile semantics)
        for q in (0.5, 0.99):
            assert h.quantile(q) == self.BUCKETS[-1]

    def test_exact_bucket_boundary_counts_le(self):
        h = self._h()
        h.observe(1.0)   # exactly on an edge: le="1.0" bucket, not 5.0
        assert h.bucket_counts() == [0, 1, 0, 0]

    def test_interpolation_at_exact_boundaries(self):
        h = self._h()
        h.observe_many([1.0] * 4)
        # q=1.0 lands exactly on the occupied bucket's upper edge
        assert h.quantile(1.0) == 1.0
        # q=0.5 interpolates halfway through (0.1, 1.0]
        assert h.quantile(0.5) == 0.1 + 0.9 * 0.5
        # with the first bucket occupied, interpolation anchors at 0.0
        h2 = self._h()
        h2.observe_many([0.05] * 2)
        assert h2.quantile(1.0) == 0.1
        assert h2.quantile(0.5) == 0.05

    def test_quantile_skips_empty_leading_buckets(self):
        h = self._h()
        h.observe_many([3.0] * 10)      # only the (1.0, 5.0] bucket
        assert h.bucket_counts() == [0, 0, 10, 0]
        assert h.quantile(0.0001) >= 1.0
        assert h.quantile(0.99) <= 5.0


class TestFabricMetrics:
    def test_retry_fault_degraded_counters_register_and_expose(self):
        from kubernetes_tpu.metrics.fabric_metrics import FabricMetrics
        from kubernetes_tpu.metrics.registry import MetricsRegistry

        reg = MetricsRegistry()
        fm = FabricMetrics(reg)
        fm.client_retries_total.inc("GET", "transport")
        fm.client_retries_total.inc("GET", "transport")
        fm.client_retries_total.inc("POST", "http_429")
        fm.faults_injected_total.inc("reset", "pods")
        fm.degraded_mode_seconds.inc(amount=1.5)
        fm.degraded_mode.set(1.0)
        assert fm.client_retries_total.get("GET", "transport") == 2
        assert fm.faults_injected_total.get("reset", "pods") == 1
        assert fm.degraded_mode_seconds.get() == 1.5
        text = reg.expose()
        assert 'client_retries_total{verb="GET",reason="transport"} 2' \
            in text
        assert 'faults_injected_total{fault="reset",resource="pods"} 1' \
            in text
        assert "degraded_mode_seconds 1.5" in text
        assert "degraded_mode 1.0" in text

    def test_second_instance_shares_series(self):
        """Server gate + N clients in one process must share counters,
        not clobber each other's registrations."""
        from kubernetes_tpu.metrics.fabric_metrics import FabricMetrics
        from kubernetes_tpu.metrics.registry import MetricsRegistry

        reg = MetricsRegistry()
        a = FabricMetrics(reg)
        a.client_retries_total.inc("GET", "transport")
        b = FabricMetrics(reg)
        assert b.client_retries_total is a.client_retries_total
        b.client_retries_total.inc("GET", "transport")
        assert a.client_retries_total.get("GET", "transport") == 2

    def test_default_registry_singleton(self):
        from kubernetes_tpu.metrics import default_registry
        from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

        fm = fabric_metrics()
        assert fm is fabric_metrics()
        assert default_registry().get("client_retries_total") \
            is fm.client_retries_total


class TestLazyEvents:
    def test_eventf_defers_formatting_to_flush(self):
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.client.events import EventRecorder
        from kubernetes_tpu.testing import MakePod

        store = ClusterStore()
        rec = EventRecorder(store, "test")
        pod = MakePod().name("p").uid("u").obj()
        rec.eventf(pod, "Normal", "Scheduled",
                   "Successfully assigned %s/%s to %s",
                   pod.namespace, pod.name, "n1")
        # formatting has not happened yet (queue holds fmt + args)
        assert not store.list_events()
        rec.flush_now()
        evs = store.list_events()
        assert len(evs) == 1
        assert evs[0].message == "Successfully assigned default/p to n1"
        assert evs[0].involved_object.name == "p"

    def test_plain_event_still_correlates(self):
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.client.events import EventRecorder
        from kubernetes_tpu.testing import MakePod

        store = ClusterStore()
        rec = EventRecorder(store, "test")
        pod = MakePod().name("p").uid("u").obj()
        for _ in range(3):
            rec.event(pod, "Warning", "FailedScheduling", "0/5 nodes")
        rec.flush_now()
        evs = store.list_events()
        assert len(evs) == 1
        assert evs[0].count == 3


class TestPreemptionScreen:
    def test_candidates_ranked_and_screened(self):
        from kubernetes_tpu.scheduler.preemption_screen import build_screen
        from kubernetes_tpu.scheduler.snapshot import new_snapshot
        from kubernetes_tpu.testing import MakeNode, MakePod

        nodes = [
            MakeNode().name(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"})
            .obj()
            for i in range(4)
        ]
        # n0: one 3-cpu victim (prio 1); n1: three 1-cpu victims (prio 1);
        # n2: high-priority resident only (no victims); n3: empty but
        # won't need preemption (screen requires victims)
        pods = [
            MakePod().name("v0").uid("v0").node("n0").priority(1)
            .req({"cpu": "3"}).obj(),
            *[MakePod().name(f"v1{j}").uid(f"v1{j}").node("n1").priority(1)
              .req({"cpu": "1"}).obj() for j in range(3)],
            MakePod().name("hi").uid("hi").node("n2").priority(1000)
            .req({"cpu": "3"}).obj(),
        ]
        snap = new_snapshot(pods, nodes)
        screen = build_screen(snap)
        preemptor = MakePod().name("p").uid("p").priority(100)
        pod = preemptor.req({"cpu": "3"}).obj()
        hints = screen.candidates_for(pod, k=4)
        # n2's resident outranks the preemptor -> not a candidate;
        # n3 has no victims -> excluded; n0 (1 victim) ranks before
        # n1 (needs 2+ of its 3 victims)
        assert "n2" not in hints and "n3" not in hints
        assert hints[0] == "n0"
        assert set(hints) == {"n0", "n1"}
        # rotation spreads identical preemptors over distinct heads
        r1 = screen.candidates_for(pod, k=1, rotation=1)
        assert r1 and r1[0] != hints[0]
        # a priority-0 preemptor has no one below it
        zero = MakePod().name("z").uid("z").priority(0).req({"cpu": "1"}).obj()
        assert screen.candidates_for(zero) == []

    def test_static_mask_prunes(self):
        import numpy as np

        from kubernetes_tpu.scheduler.preemption_screen import build_screen
        from kubernetes_tpu.scheduler.snapshot import new_snapshot
        from kubernetes_tpu.testing import MakeNode, MakePod

        nodes = [
            MakeNode().name(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"})
            .obj()
            for i in range(2)
        ]
        pods = [
            MakePod().name(f"v{i}").uid(f"v{i}").node(f"n{i}").priority(0)
            .req({"cpu": "3"}).obj()
            for i in range(2)
        ]
        screen = build_screen(new_snapshot(pods, nodes))
        pod = MakePod().name("p").uid("p").priority(10).req({"cpu": "3"}).obj()
        mask = np.array([False, True])
        hints = screen.candidates_for(pod, static_mask=mask)
        assert hints == ["n1"]
