"""Partitioned control plane: sharded store/watch fabric, partition-aware
clients, and multi-replica scheduling.

Covers the differential guard (partitions=1 ≡ bare ClusterStore: same
event sequences, RVs, kind_seq values), cross-partition watch semantics
(per-partition RV monotonicity under concurrent writers, torn-resume via
the composite cursor, stalled-watcher isolation), the bind-time capacity
ledger + commit-time capacity probe that let concurrent scheduler
replicas resolve conflicts optimistically, the partition-aware REST
client, and the tier-1 mini-scale cell (2 partitions × 2 replicas ×
~200 hollow nodes — zero lost pods, zero double-binds)."""

import threading
import time

import pytest

from kubernetes_tpu.apiserver.partition import (
    CapacityConflictError,
    CompositeCursor,
    PartitionedStore,
    partition_for,
    partitions_for,
)
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.testing import MakeNode, MakePod


def _node(name, cpu="4", memory="8Gi", pods="110"):
    return MakeNode().name(name).capacity(
        {"cpu": cpu, "memory": memory, "pods": pods}).obj()


def _pod(name, ns="default", uid=None, cpu="100m", memory="50Mi"):
    p = MakePod().name(name).uid(uid or f"u-{ns}-{name}").req(
        {"cpu": cpu, "memory": memory}).obj()
    p.metadata.namespace = ns
    return p


# ---------------------------------------------------------------------------
# routing


class TestRouting:
    def test_deterministic_and_in_range(self):
        for n in (1, 2, 4, 7):
            for ns in ("default", "a", "b", "scale-3"):
                p1 = partition_for("Pod", ns, None, n)
                p2 = partition_for("Pod", ns, "ignored", n)
                assert p1 == p2 and 0 <= p1 < n
        # cluster-scoped sharded kinds key by name
        assert partition_for("Node", None, "n1", 4) == \
            partition_for("Node", "anything", "n1", 4)

    def test_non_sharded_kinds_pin_to_partition_zero(self):
        for kind in ("Service", "Lease", "Event", "ConfigMap",
                     "ClusterRole", "PersistentVolume"):
            assert partition_for(kind, "ns9", "x", 8) == 0
            assert partitions_for(kind, 8) == [0]

    def test_namespace_scoped_query_touches_one_partition(self):
        assert len(partitions_for("Pod", 8, namespace="ns1")) == 1
        assert partitions_for("Pod", 8) == list(range(8))
        assert partitions_for("Node", 8) == list(range(8))

    def test_sharded_kinds_actually_spread(self):
        parts = {partition_for("Pod", f"ns{i}", None, 4)
                 for i in range(64)}
        assert parts == {0, 1, 2, 3}
        parts = {partition_for("Node", None, f"n{i}", 4)
                 for i in range(64)}
        assert parts == {0, 1, 2, 3}


# ---------------------------------------------------------------------------
# differential guard: partitions=1 ≡ ClusterStore


def _mutation_script(store):
    """A representative mutation sequence exercising typed pods/nodes,
    bulk verbs, generic objects, status patches, and deletes. Returns
    the recorded event log (type, kind, key, rv)."""
    from kubernetes_tpu.api.types import Service, ObjectMeta

    log = []
    store.watch(lambda e: log.append(
        (e.type, e.kind, e.obj.metadata.name,
         int(e.obj.metadata.resource_version or 0))))
    store.add_node(_node("n1"))
    store.add_node(_node("n2"))
    store.create_pod(_pod("a1", "nsa"))
    store.create_pods([_pod(f"b{i}", "nsb") for i in range(4)])
    store.bind("nsa", "a1", "u-nsa-a1", "n1")
    store.bind_many([("nsb", f"b{i}", f"u-nsb-b{i}", "n2")
                     for i in range(4)])
    store.set_pod_phase("nsa", "a1", "Running", pod_ip="10.0.0.1")
    store.add_service(Service(metadata=ObjectMeta(name="s1",
                                                  namespace="nsa")))
    store.create_object("ConfigMap", __import__(
        "kubernetes_tpu.api.types", fromlist=["ConfigMap"]).ConfigMap(
            metadata=ObjectMeta(name="cm1", namespace="nsa")))
    store.delete_pod("nsb", "b0")
    store.delete_node("n2")
    return log


class TestDifferentialGuard:
    def test_partitions_1_identical_to_cluster_store(self):
        plain = ClusterStore()
        sharded = PartitionedStore(1)
        log_plain = _mutation_script(plain)
        log_sharded = _mutation_script(sharded)
        # identical event sequences INCLUDING resourceVersions
        assert log_plain == log_sharded
        # identical kind_seq values and final RV
        for kind in ("Pod", "Node", "Service", "ConfigMap"):
            assert plain.kind_seq(kind) == sharded.kind_seq(kind), kind
        assert plain.current_rv() == sharded.current_rv()
        # identical surviving object RVs
        rvs_plain = sorted(p.metadata.resource_version
                           for p in plain.list_pods())
        rvs_sharded = sorted(p.metadata.resource_version
                             for p in sharded.list_pods())
        assert rvs_plain == rvs_sharded

    def test_partitions_3_same_event_set_and_final_state(self):
        plain = ClusterStore()
        sharded = PartitionedStore(3)
        log_plain = _mutation_script(plain)
        log_sharded = _mutation_script(sharded)
        # cross-partition interleaving may reorder, but the SET of
        # (type, kind, name) transitions is identical, and per-object
        # event order is preserved (each object lives in one partition)
        assert sorted(e[:3] for e in log_plain) \
            == sorted(e[:3] for e in log_sharded)
        by_obj = {}
        for e in log_sharded:
            by_obj.setdefault((e[1], e[2]), []).append(e[0])
        assert by_obj[("Pod", "a1")] == ["ADDED", "MODIFIED", "MODIFIED"]
        # final states agree
        assert {p.full_name() for p in plain.list_pods()} \
            == {p.full_name() for p in sharded.list_pods()}
        assert {n.name for n in plain.list_nodes()} \
            == {n.name for n in sharded.list_nodes()}
        # RVs are globally unique across partitions
        rvs = [e[3] for e in log_sharded]
        assert len(set(rvs)) == len(rvs)

    def test_generic_surface_routes_consistently(self):
        ps = PartitionedStore(4)
        pod = _pod("g1", "nsg")
        ps.create_object("Pod", pod)
        # typed and generic reads agree wherever the object hashed
        assert ps.get_pod("nsg", "g1") is not None
        assert ps.get_object("Pod", "nsg", "g1") is not None
        assert len(ps.list_objects("Pod")) == 1
        objs, rv = ps.list_objects_with_rv("Pod")
        assert len(objs) == 1 and rv >= 1
        # finalizer flow through the router
        assert ps.add_finalizer("Pod", "nsg", "g1", "t/fin")
        assert ps.delete_object("Pod", "nsg", "g1")
        assert ps.get_pod("nsg", "g1") is not None   # marked, not gone
        assert ps.remove_finalizer("Pod", "nsg", "g1", "t/fin")
        assert ps.get_pod("nsg", "g1") is None


class TestPerPartitionWal:
    def test_wal_segments_restore_and_rv_never_regresses(self, tmp_path):
        """Each partition owns its WAL segment (<dir>/p<k>/); a
        restored store replays every partition and the shared RV
        allocator advances past every committed revision — a recovered
        control plane must never re-issue an RV (the PR 1 watchdog's
        invariant, held across the shard boundary)."""
        d = str(tmp_path)
        ps = PartitionedStore(2)
        ps.attach_wal(d, async_serialize=False)
        for i in range(6):
            ps.create_pod(_pod(f"w{i}", f"wns{i % 2}"))
        high = ps.current_rv()
        ps.stop()
        ps2 = PartitionedStore(2)
        ps2.attach_wal(d, restore=True, async_serialize=False)
        assert len(ps2.list_pods()) == 6
        ps2.create_pod(_pod("fresh", "wns0"))
        assert int(ps2.get_pod("wns0", "fresh")
                   .metadata.resource_version) > high
        ps2.stop()


# ---------------------------------------------------------------------------
# composite cursor: list+watch resume across partitions


class TestCompositeCursor:
    def test_encode_parse_covers(self):
        c = CompositeCursor((5, 9, 2))
        assert CompositeCursor.parse(c.encode()) == c
        assert c.covers(CompositeCursor((5, 8, 2)))
        assert not c.covers(CompositeCursor((6, 8, 2)))
        assert CompositeCursor((7,)).encode() == "7"

    def test_resume_replays_only_post_cursor_events(self):
        ps = PartitionedStore(3)
        ps.enable_resume()
        for i in range(6):
            ps.create_pod(_pod(f"pre{i}", f"ns{i % 3}"))
        objs, cursor = ps.list_with_cursor("Pod")
        assert len(objs) == 6
        for i in range(6):
            ps.create_pod(_pod(f"post{i}", f"ns{i % 3}"))
        got = []
        handle = ps.watch_from_cursor(
            cursor, lambda rv, e: got.append(e.obj.metadata.name))
        # replay is synchronous: exactly the post-cursor writes arrive,
        # none of the pre-cursor ones
        assert sorted(got) == sorted(f"post{i}" for i in range(6))
        # live events still stream after the replay seam
        ps.create_pod(_pod("live0", "ns0"))
        assert "live0" in got
        handle.stop()

    def test_torn_resume_compacted_partition_relists_alone(self):
        from kubernetes_tpu.apiserver.watchcache import (
            TooOldResourceVersion,
        )

        ps = PartitionedStore(2)
        ps.enable_resume()
        # find two namespaces on distinct partitions
        ns_by_part = {}
        i = 0
        while len(ns_by_part) < 2:
            ns_by_part.setdefault(
                partition_for("Pod", f"t{i}", None, 2), f"t{i}")
            i += 1
        ns0, ns1 = ns_by_part[0], ns_by_part[1]
        ps.create_pod(_pod("seed0", ns0))
        ps.create_pod(_pod("seed1", ns1))
        _objs, cursor = ps.list_with_cursor("Pod")
        # partition 0's log advances far past the cursor, then compacts
        for i in range(40):
            ps.create_pod(_pod(f"churn{i}", ns0))
        ps._watch_caches[0].compact(keep_last=2)
        # resuming the whole cursor fails loudly (partition 0 too old)
        with pytest.raises(TooOldResourceVersion):
            ps.watch_from_cursor(cursor, lambda rv, e: None)
        # ...but the torn partition relists ALONE: partition 1's
        # component is still live and replays exactly its delta
        ps.create_pod(_pod("after1", ns1))
        got = []
        h = ps._watch_caches[1].watch_from(
            cursor.component(1),
            lambda rv, e: got.append(e.obj.metadata.name))
        assert got == ["after1"]
        h.stop()


# ---------------------------------------------------------------------------
# cross-partition watch semantics


class TestWatchSemantics:
    def test_per_partition_rv_monotonic_under_concurrent_writers(self):
        ps = PartitionedStore(3)
        # one recorder per PARTITION (the per-partition stream is what
        # promises monotonicity; the merged stream does not)
        logs = [[] for _ in range(3)]
        for i, part in enumerate(ps.parts):
            part.watch(lambda e, log=logs[i]: log.append(
                int(e.obj.metadata.resource_version or 0)))
        namespaces = [f"w{i}" for i in range(9)]
        errors = []

        def writer(ns):
            try:
                for i in range(30):
                    ps.create_pod(_pod(f"p{i}", ns))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(ns,))
                   for ns in namespaces]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        total = 0
        for log in logs:
            assert log == sorted(log), "partition stream RV regressed"
            total += len(log)
        assert total == 9 * 30
        # global uniqueness across partitions (shared RV allocator)
        all_rvs = [rv for log in logs for rv in log]
        assert len(set(all_rvs)) == len(all_rvs)

    def test_stalled_watcher_on_one_partition_does_not_delay_other(self):
        ps = PartitionedStore(2, async_dispatch=True)
        ns_by_part = {}
        i = 0
        while len(ns_by_part) < 2:
            ns_by_part.setdefault(
                partition_for("Pod", f"s{i}", None, 2), f"s{i}")
            i += 1
        stall = threading.Event()
        delivered = []

        def sink(e):
            ns = e.obj.metadata.namespace
            delivered.append((ns, time.monotonic()))
            if partition_for("Pod", ns, None, 2) == 0:
                stall.wait(5.0)   # wedge partition 0's dispatch thread

        ps.watch(sink)
        t0 = time.monotonic()
        ps.create_pod(_pod("slow", ns_by_part[0]))   # wedges dispatcher 0
        time.sleep(0.05)
        ps.create_pod(_pod("fast", ns_by_part[1]))   # must not wait
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            if any(ns == ns_by_part[1] for ns, _ in delivered):
                break
            time.sleep(0.01)
        fast = [ts for ns, ts in delivered if ns == ns_by_part[1]]
        assert fast, "partition-1 delivery stalled behind partition 0"
        assert fast[0] - t0 < 1.0
        stall.set()
        ps.drain()
        ps.stop()


# ---------------------------------------------------------------------------
# bind-time capacity ledger + commit-time capacity probe


class TestCapacityGuards:
    def test_bind_ledger_rejects_oversubscription(self):
        ps = PartitionedStore(2, capacity_guard=True)
        ps.add_node(_node("tight", cpu="1"))
        ps.create_pod(_pod("w1", "default", cpu="600m"))
        ps.create_pod(_pod("w2", "default", cpu="600m"))
        ps.bind("default", "w1", "u-default-w1", "tight")
        with pytest.raises(CapacityConflictError):
            ps.bind("default", "w2", "u-default-w2", "tight")
        # the loser's capacity was never leaked: a right-sized pod fits
        ps.create_pod(_pod("w3", "default", cpu="300m"))
        ps.bind("default", "w3", "u-default-w3", "tight")
        # bulk path returns the conflict positionally
        ps.create_pod(_pod("w4", "default", cpu="600m"))
        errs = ps.bind_many([("default", "w4", "u-default-w4", "tight")])
        assert isinstance(errs[0], CapacityConflictError)

    def test_ledger_releases_on_pod_delete(self):
        ps = PartitionedStore(1, capacity_guard=True)
        ps.add_node(_node("n", cpu="1"))
        ps.create_pod(_pod("a", cpu="800m"))
        ps.bind("default", "a", "u-default-a", "n")
        ps.delete_pod("default", "a")
        ps.create_pod(_pod("b", cpu="800m"))
        ps.bind("default", "b", "u-default-b", "n")   # fits again

    def test_cache_commit_fits_is_cumulative(self):
        from kubernetes_tpu.scheduler.cache import SchedulerCache

        cache = SchedulerCache()
        cache.add_node(_node("n1", cpu="1"))
        p1, p2 = _pod("c1", cpu="600m"), _pod("c2", cpu="600m")
        verdicts = cache.commit_fits([(p1, "n1"), (p2, "n1")])
        assert verdicts == [None, "capacity"]
        # unknown nodes are not judged here (commit_target_flags owns
        # node existence)
        assert cache.commit_fits([(p1, "ghost")]) == [None]


# ---------------------------------------------------------------------------
# replica sharding


class TestReplicaSharding:
    def test_pod_shard_partition_is_complete_and_disjoint(self):
        from kubernetes_tpu.scheduler.replicas import pod_shard_fn

        owners = [pod_shard_fn(i, 3) for i in range(3)]
        for k in range(60):
            pod = _pod(f"p{k}", uid=f"uid-{k}")
            assert sum(1 for own in owners if own(pod)) == 1

    def test_node_shard_partition_is_complete_and_disjoint(self):
        from kubernetes_tpu.scheduler.replicas import node_shard_fn

        owners = [node_shard_fn(i, 4) for i in range(4)]
        for k in range(60):
            assert sum(1 for own in owners if own(f"n{k}")) == 1

    def test_install_replica_sharding_wiring(self):
        from kubernetes_tpu.scheduler.replicas import (
            ReplicaSpec,
            install_replica_sharding,
        )
        from kubernetes_tpu.scheduler.scheduler import Scheduler

        store = ClusterStore()
        sched = Scheduler.create(store)
        install_replica_sharding(sched, ReplicaSpec(
            index=0, count=2, shard_pods=True, shard_nodes=False))
        assert sched.pod_shard is not None
        assert sched.node_shard is None
        assert sched.commit_capacity_guard    # sharing nodes => guarded
        sched2 = Scheduler.create(store)
        install_replica_sharding(sched2, ReplicaSpec(
            index=1, count=2, shard_pods=True, shard_nodes=True))
        assert sched2.node_shard is not None
        assert not sched2.commit_capacity_guard   # disjoint pools

    def test_event_handlers_respect_shards(self):
        from kubernetes_tpu.scheduler.replicas import (
            node_shard_fn,
            pod_shard_fn,
        )
        from kubernetes_tpu.scheduler.scheduler import Scheduler

        store = ClusterStore()
        sched = Scheduler.create(store)
        sched.pod_shard = pod_shard_fn(0, 2)
        sched.node_shard = node_shard_fn(0, 2)
        handlers = sched.event_handlers
        # pending-pod ownership follows the pod hash
        owned = [p for p in (_pod(f"e{k}", uid=f"eu{k}")
                             for k in range(20))
                 if handlers.responsible_for(p)]
        assert 0 < len(owned) < 20
        # assigned pods are cached regardless of ownership
        bound = _pod("bound-far", uid="bf")
        bound.spec.node_name = "n-any"
        handlers._handle_pod(type("E", (), {
            "type": "ADDED", "kind": "Pod", "obj": bound,
            "old_obj": None, "ts": 0.0})())
        assert sched.cache.pod_count() == 1
        # node events filter by pool
        assert 0 < sum(1 for k in range(20)
                       if handlers.caches_node(f"n{k}")) < 20


# ---------------------------------------------------------------------------
# the tier-1 mini-scale cell + conflict chaos cell


class TestMiniScale:
    def test_two_partitions_two_replicas_200_hollow_nodes(self):
        """The CI-fast 10×-shape cell: 2 store partitions (async
        per-partition watch dispatch) × 2 scheduler replicas (pod-hash
        queues, disjoint node pools) × 200 hollow nodes. Invariants:
        zero lost pods, zero double-binds, partitions balanced, every
        partition and replica registry federated."""
        from kubernetes_tpu.harness.scale import run_scale_arm_inproc

        arm = run_scale_arm_inproc(
            nodes=200, pods=500, partitions=2, replicas=2,
            use_batch=False, node_cpu=16, wait_timeout=120.0)
        assert arm["lost_pods"] == 0
        assert arm["double_binds"] == 0
        assert arm["bound"] == 500
        assert arm["partition_balance"] and arm["partition_balance"] > 0.3
        # observability wire-up: federation covers every partition AND
        # every replica (≥ partitions + replicas instances)
        fed = [i for i in arm["federation_instances"]
               if i.startswith(("partition-", "scheduler-"))]
        assert len(fed) >= 2 + 2, arm["federation_instances"]

    def test_conflict_cell_resolves_every_collision(self):
        """Replicas with overlapping responsibility racing over a tight
        cluster: conflicts MUST occur (a quiet cell proves nothing) and
        every one must resolve through the stale-commit guard path —
        zero lost pods, zero double-binds, no oversubscription."""
        from kubernetes_tpu.harness.scale import run_conflict_cell
        from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

        before = sum(v for _, _, v in fabric_metrics()
                     .stale_binds_rejected_total.collect())
        cell = run_conflict_cell()
        after = sum(v for _, _, v in fabric_metrics()
                    .stale_binds_rejected_total.collect())
        assert cell["ok"], cell
        assert cell["conflicts_total"] > 0
        assert cell["lost_pods"] == 0
        assert cell["double_binds"] == 0
        assert after > before   # the conflicts landed on the PR 3 series


# ---------------------------------------------------------------------------
# partition-aware REST client over real partition servers


class TestPartitionAwareClient:
    def _spin_up(self, parts=2):
        from kubernetes_tpu.apiserver.rest import APIServer

        servers = [APIServer(store=ClusterStore(),
                             partition=(i, parts)).start()
                   for i in range(parts)]
        return servers, [s.url for s in servers]

    def test_routing_matches_server_side_truth(self):
        from kubernetes_tpu.client.restcluster import RestClusterClient

        servers, urls = self._spin_up(2)
        client = RestClusterClient(urls[0], partition_urls=urls,
                                   watch_kinds=("Pod",))
        try:
            pods = [_pod(f"r{i}", f"rns{i % 5}") for i in range(20)]
            assert client.create_objects_bulk("Pod", pods) == 20
            nodes = [_node(f"rn{i}") for i in range(8)]
            assert client.create_objects_bulk("Node", nodes) == 8
            # every object landed in exactly the partition the shared
            # routing function names — and ONLY there
            for i, server in enumerate(servers):
                for p in server.store.list_pods():
                    assert partition_for("Pod", p.namespace, None, 2) == i
                for n in server.store.list_nodes():
                    assert partition_for("Node", None, n.name, 2) == i
            # fan-in reads see the union
            assert len(client.list_pods()) == 20
            assert len(client.list_nodes()) == 8
            assert client.get_pod("rns1", "r1") is not None
            # bulk bind splits by partition; positional result intact
            errs = client.bind_many([
                (p.namespace, p.metadata.name, p.metadata.uid, "rn0")
                for p in pods])
            assert errs == [None] * 20
            assert all(p.spec.node_name == "rn0"
                       for p in client.list_pods())
            # the per-(kind,partition) RV watchdog saw no regressions
            assert client.rv_regressions == []
        finally:
            client._stop_watches()
            client._drop_conn()
            for s in servers:
                s.shutdown_server()

    def test_watch_streams_merge_across_partitions(self):
        from kubernetes_tpu.apiserver.store import ADDED
        from kubernetes_tpu.client.restcluster import RestClusterClient

        servers, urls = self._spin_up(2)
        client = RestClusterClient(urls[0], partition_urls=urls,
                                   watch_kinds=("Pod", "Node"))
        got = []
        try:
            client.watch(lambda e: got.append(e),
                         batch_fn=lambda evs: got.extend(evs))
            # one stream per (kind, partition): 2 kinds × 2 partitions
            assert len(client._watch_threads) == 4
            time.sleep(0.4)
            pods = [_pod(f"w{i}", f"wns{i}") for i in range(8)]
            client.create_objects_bulk("Pod", pods)
            client.create_objects_bulk("Node",
                                       [_node(f"wn{i}")
                                        for i in range(4)])
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                adds = [e for e in got if e.type == ADDED]
                if len(adds) >= 12:
                    break
                time.sleep(0.05)
            names = {e.obj.metadata.name for e in got
                     if e.type == ADDED}
            assert {f"w{i}" for i in range(8)} <= names
            assert {f"wn{i}" for i in range(4)} <= names
        finally:
            client._stop_watches()
            client._drop_conn()
            for s in servers:
                s.shutdown_server()

    def test_informer_factory_merges_partition_streams(self):
        from kubernetes_tpu.client import SharedInformerFactory
        from kubernetes_tpu.client.restcluster import RestClusterClient

        servers, urls = self._spin_up(2)
        client = RestClusterClient(urls[0], partition_urls=urls,
                                   watch_kinds=("Pod", "Node"))
        factory = SharedInformerFactory(client)
        pod_lister = factory.lister_for("Pod")
        svc_lister = factory.lister_for("Service")   # generic fallback
        try:
            client.create_objects_bulk(
                "Pod", [_pod(f"inf{i}", f"ins{i}") for i in range(6)])
            factory.start()
            assert factory.wait_for_cache_sync()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if len(pod_lister.list()) >= 6:
                    break
                time.sleep(0.05)
            assert len(pod_lister.list()) == 6
            assert svc_lister.list() == []
            # live events from BOTH partition streams land in one index
            client.create_objects_bulk(
                "Pod", [_pod(f"live{i}", f"ins{i}") for i in range(4)])
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if len(pod_lister.list()) >= 10:
                    break
                time.sleep(0.05)
            assert len(pod_lister.list()) == 10
        finally:
            factory.stop()
            client._stop_watches()
            client._drop_conn()
            for s in servers:
                s.shutdown_server()

    def test_partition_topology_check_catches_misroute(self):
        from kubernetes_tpu.client.restcluster import RestClusterClient

        servers, urls = self._spin_up(2)
        try:
            client = RestClusterClient(urls[0], partition_urls=urls)
            for i in range(2):
                code, topo = client._request(
                    "GET", "/api/v1/partitiontopology", partition=i)
                assert code == 200
                assert topo == {"partition": i, "partitions": 2}
            client.check_partition_topology()   # correct wiring: quiet
            client._drop_conn()
            # shuffled URLs must fail loudly, not read half-empty shards
            bad = RestClusterClient(urls[1],
                                    partition_urls=[urls[1], urls[0]])
            with pytest.raises(RuntimeError, match="misconfigured"):
                bad.check_partition_topology()
            bad._drop_conn()
        finally:
            for s in servers:
                s.shutdown_server()


# ---------------------------------------------------------------------------
# observability wire-up: diag segment + perf_report family


class TestShardsDiagSegment:
    def test_round_trip(self):
        from kubernetes_tpu.harness import diagfmt

        seg = diagfmt.format_shards({
            "partitions": 4, "replicas": 2, "conflicts": 17,
            "capacity_rejects": 3, "balance": 0.876,
            "watch_streams": 36})
        line = diagfmt.format_diag([seg, "chunk=1024"])
        parsed = diagfmt.parse_diag(line)
        assert parsed["shards"]["partitions"] == 4
        assert parsed["shards"]["replicas"] == 2
        assert parsed["shards"]["conflicts"] == 17
        assert parsed["shards"]["capacity_rejects"] == 3
        assert abs(parsed["shards"]["balance"] - 0.88) < 0.01
        assert parsed["shards"]["watch_streams"] == 36
        assert parsed["chunk"] == 1024

    def test_empty_info_prints_nothing(self):
        from kubernetes_tpu.harness import diagfmt

        assert diagfmt.format_shards({}) == ""


class TestPerfReportScaleFamily:
    def _round(self, row) -> dict:
        return {"round": 9, "path": "x", "rc": 0,
                "rows": [dict(row, _diags=[])]}

    def test_flags_ab_and_invariant_failures(self):
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "perf_report", os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                "tools", "perf_report.py"))
        pr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pr)
        good = {
            "metric": "pods_scheduled_per_sec[Scale10x 50000nodes/"
                      "500000pods, partitioned fabric 4p x 2r]",
            "value": 4000.0, "unit": "pods/s",
            "ab": {"partitioned_pods_per_sec": 4000.0,
                   "single_partition_pods_per_sec": 2500.0,
                   "speedup": 1.6, "sharding_pays": True},
            "invariants": {"lost_pods": 0, "double_binds": 0},
            "conflict_cell": {"conflicts_total": 12, "ok": True},
        }
        assert pr.scale_ab_flags([self._round(good)]) == []
        bad_ab = dict(good, ab=dict(good["ab"], sharding_pays=False))
        flags = pr.scale_ab_flags([self._round(bad_ab)])
        assert len(flags) == 1 and "single-partition" in \
            flags[0]["problems"][0]
        bad_inv = dict(good, invariants={"lost_pods": 3,
                                         "double_binds": 0})
        assert pr.scale_ab_flags([self._round(bad_inv)])
        quiet_cell = dict(good, conflict_cell={"conflicts_total": 0,
                                               "ok": False})
        assert pr.scale_ab_flags([self._round(quiet_cell)])
        # the scale row also rides the ordinary throughput series
        series = pr.build_series([self._round(good)])
        assert any("Scale10x" in m for m in series)


# ---------------------------------------------------------------------------
# the full 10× shape over the REAL fabric (slow: spawns P apiservers +
# creator children and runs both A/B arms + the conflict cell)


@pytest.mark.slow
@pytest.mark.chaos
class TestScale10xRow:
    def test_row_at_moderate_scale_over_rest(self):
        from kubernetes_tpu.harness.scale import run_scale10x_row

        row = run_scale10x_row(
            nodes=300, pods=1200, partitions=2, replicas=2,
            use_batch=False, qps=None, node_cpu=16,
            wait_timeout=600.0)
        assert row["invariants"]["lost_pods"] == 0
        assert row["invariants"]["double_binds"] == 0
        assert row["conflict_cell"]["ok"]
        assert row["conflict_cell"]["conflicts_total"] > 0
        assert row["ab"]["partitioned_pods_per_sec"] > 0
        assert row["ab"]["single_partition_pods_per_sec"] > 0
        # federation covered every partition server + replica registry
        fed = [i for i in row["federation_instances"]
               if i.startswith(("apiserver-p", "scheduler-"))]
        assert len(fed) >= 2 + 2
        # the SLO engine evaluated the watch-delivery objective
        assert "watch_delivery" in (row["freshness"].get("slo") or {})
