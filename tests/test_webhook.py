"""Admission webhook extension point (VERDICT r2 #8; reference
``staging/src/k8s.io/apiserver/pkg/admission/plugin/webhook/mutating/
dispatcher.go:75``): out-of-process mutating/validating admission
dispatched over HTTP from the in-process chain."""

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes_tpu.api.types import (
    MutatingWebhookConfiguration,
    ObjectMeta,
    ValidatingWebhookConfiguration,
    Webhook,
    WebhookRule,
)
from kubernetes_tpu.apiserver.rest import APIServer, RestClient
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.apiserver.webhook import apply_json_patch
from kubernetes_tpu.testing import MakePod


class _Hook(BaseHTTPRequestHandler):
    """In-process webhook endpoint. Routes:
    /label     — mutating: adds metadata.labels.injected=yes via patch
    /deny-bad  — validating: denies pods labelled bad=true
    """

    reviews = []

    def log_message(self, fmt, *args):
        pass

    def do_POST(self):
        length = int(self.headers.get("Content-Length") or 0)
        review = json.loads(self.rfile.read(length) or b"{}")
        _Hook.reviews.append((self.path, review))
        req = review.get("request") or {}
        resp = {"uid": req.get("uid"), "allowed": True}
        if self.path == "/label":
            patch = [{"op": "add", "path": "/metadata/labels/injected",
                      "value": "yes"}]
            resp["patch"] = base64.b64encode(
                json.dumps(patch).encode()).decode()
            resp["patchType"] = "JSONPatch"
        elif self.path == "/deny-bad":
            labels = ((req.get("object") or {}).get("metadata") or {}) \
                .get("labels") or {}
            if labels.get("bad") == "true":
                resp = {"uid": req.get("uid"), "allowed": False,
                        "status": {"message": "bad pods are not welcome"}}
        body = json.dumps({
            "kind": "AdmissionReview",
            "apiVersion": "admission.k8s.io/v1",
            "response": resp,
        }).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def hook_server():
    _Hook.reviews = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Hook)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


@pytest.fixture()
def api():
    store = ClusterStore()
    server = APIServer(store=store).start()
    yield store, server, RestClient(server.url)
    server.shutdown_server()


def _mutating_cfg(url, resources=("pods",), policy="Fail"):
    return MutatingWebhookConfiguration(
        metadata=ObjectMeta(name="mutate-pods"),
        webhooks=[Webhook(
            name="label.example.com", url=url,
            rules=[WebhookRule(operations=["CREATE"],
                               resources=list(resources))],
            failure_policy=policy,
        )],
    )


class TestMutatingWebhook:
    def test_pod_mutated_at_create(self, hook_server, api):
        store, server, client = api
        client.create(_mutating_cfg(hook_server + "/label"))
        client.create(MakePod().name("p1").req({"cpu": "1"}).obj())
        pod = store.get_pod("default", "p1")
        assert pod.metadata.labels.get("injected") == "yes"
        # the review carried the operation and object
        path, review = _Hook.reviews[-1]
        assert path == "/label"
        assert review["request"]["operation"] == "CREATE"
        assert review["request"]["object"]["metadata"]["name"] == "p1"

    def test_rules_scope_dispatch(self, hook_server, api):
        store, server, client = api
        client.create(_mutating_cfg(hook_server + "/label",
                                    resources=("deployments",)))
        client.create(MakePod().name("p1").req({"cpu": "1"}).obj())
        assert store.get_pod("default", "p1").metadata.labels.get(
            "injected") is None

    def test_failure_policy(self, api):
        store, server, client = api
        # unreachable hook, Fail: create rejected
        client.create(_mutating_cfg("http://127.0.0.1:1/label"))
        with pytest.raises(PermissionError):
            client.create(MakePod().name("p1").req({"cpu": "1"}).obj())
        assert store.get_pod("default", "p1") is None
        client.delete("MutatingWebhookConfiguration", "mutate-pods",
                      namespace=None)
        # unreachable hook, Ignore: create proceeds unmutated
        client.create(_mutating_cfg("http://127.0.0.1:1/label",
                                    policy="Ignore"))
        client.create(MakePod().name("p2").req({"cpu": "1"}).obj())
        assert store.get_pod("default", "p2") is not None


class TestValidatingWebhook:
    def test_denied_create_is_rejected(self, hook_server, api):
        store, server, client = api
        client.create(ValidatingWebhookConfiguration(
            metadata=ObjectMeta(name="deny-bad"),
            webhooks=[Webhook(
                name="deny.example.com", url=hook_server + "/deny-bad",
                rules=[WebhookRule(operations=["CREATE"],
                                   resources=["pods"])],
            )],
        ))
        ok = MakePod().name("good").req({"cpu": "1"}).obj()
        client.create(ok)
        bad = MakePod().name("bad").label("bad", "true") \
            .req({"cpu": "1"}).obj()
        with pytest.raises(PermissionError) as e:
            client.create(bad)
        assert "not welcome" in str(e.value)
        assert store.get_pod("default", "bad") is None
        assert store.get_pod("default", "good") is not None


class TestJsonPatch:
    def test_rfc6902_subset(self):
        doc = {"metadata": {"labels": {"a": "1"}},
               "spec": {"containers": [{"name": "c1"}]}}
        out = apply_json_patch(doc, [
            {"op": "add", "path": "/metadata/labels/b", "value": "2"},
            {"op": "replace", "path": "/metadata/labels/a", "value": "9"},
            {"op": "remove", "path": "/spec/containers/0"},
            {"op": "add", "path": "/spec/containers/-",
             "value": {"name": "c2"}},
            {"op": "add", "path": "/metadata/annotations/x~1y",
             "value": "z"},
        ])
        assert out["metadata"]["labels"] == {"a": "9", "b": "2"}
        assert out["spec"]["containers"] == [{"name": "c2"}]
        assert out["metadata"]["annotations"] == {"x/y": "z"}


class TestWebhooksComposeWithCRDs:
    def test_custom_resource_admission(self, hook_server, api):
        """The reference's two extension mechanisms compose: a webhook
        intercepts CREATEs of a CRD-registered kind (rules match the
        custom plural) and mutates/validates its instances."""
        from kubernetes_tpu.api.types import (
            CRDNames, CustomObject, CustomResourceDefinition,
        )

        store, server, client = api
        client.create(CustomResourceDefinition(
            metadata=ObjectMeta(name="widgets.example.com"),
            group="example.com",
            names=CRDNames(plural="widgets", kind="Widget"),
        ))
        client.create(MutatingWebhookConfiguration(
            metadata=ObjectMeta(name="label-widgets"),
            webhooks=[Webhook(
                name="label.example.com", url=hook_server + "/label",
                rules=[WebhookRule(operations=["CREATE"],
                                   resources=["widgets"])],
            )],
        ))
        client.create(ValidatingWebhookConfiguration(
            metadata=ObjectMeta(name="deny-bad-widgets"),
            webhooks=[Webhook(
                name="deny.example.com", url=hook_server + "/deny-bad",
                rules=[WebhookRule(operations=["CREATE"],
                                   resources=["widgets"])],
            )],
        ))
        created = client.create(CustomObject(
            kind="Widget",
            metadata=ObjectMeta(name="w1", namespace="default"),
            spec={"size": 1},
        ))
        # mutating webhook patched the custom instance
        assert created.metadata.labels.get("injected") == "yes"
        # validating webhook rejects bad instances
        bad = CustomObject(
            kind="Widget",
            metadata=ObjectMeta(name="w2", namespace="default",
                                labels={"bad": "true"}),
        )
        with pytest.raises(PermissionError):
            client.create(bad)
        assert store.get_object("Widget", "default", "w2") is None


class TestSubresourceRuleMatching:
    """A validating rule naming "pods" must NOT intercept kubelet
    status writes; "pods/status" is its own vocabulary entry
    (reference rule-matching in admission/plugin/webhook/rules)."""

    def _deny_all_cfg(self, url, resources):
        return ValidatingWebhookConfiguration(
            metadata=ObjectMeta(name=f"deny-{'-'.join(resources).replace('/', '-')}"),
            webhooks=[Webhook(
                name="deny.example.com", url=url + "/deny-bad",
                rules=[WebhookRule(operations=["*"],
                                   resources=list(resources))],
            )],
        )

    def test_pods_rule_does_not_block_status_writes(self, hook_server, api):
        store, server, client = api
        pod = MakePod().name("w1").label("bad", "true").uid("u-w1").obj()
        store.create_pod(pod)  # store-direct: no admission at create
        client.create(self._deny_all_cfg(hook_server, ["pods"]))
        # status write sails past the "pods" rule
        client.update_pod_status("default", "w1", "Running")
        assert store.get_pod("default", "w1").status.phase == "Running"
        # a "pods/status" rule DOES gate it
        client.create(self._deny_all_cfg(hook_server, ["pods/status"]))
        with pytest.raises(PermissionError):
            client.update_pod_status("default", "w1", "Failed")
