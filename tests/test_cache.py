"""Cache/snapshot/node_tree tests (modeled on the reference's
``internal/cache/cache_test.go`` strategy: direct state transitions +
incremental-snapshot coherence checks)."""

import pytest

from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.node_tree import NodeTree
from kubernetes_tpu.scheduler.snapshot import Snapshot, new_snapshot
from kubernetes_tpu.testing import MakeNode, MakePod


def make_node(name, zone=None, cpu="4", mem="8Gi"):
    w = MakeNode().name(name).capacity({"cpu": cpu, "memory": mem})
    if zone:
        w.label("topology.kubernetes.io/zone", zone)
    return w.obj()


class TestNodeTree:
    def test_zone_interleave(self):
        t = NodeTree()
        for name, zone in [
            ("a1", "za"), ("a2", "za"), ("a3", "za"),
            ("b1", "zb"), ("c1", "zc"),
        ]:
            t.add_node(make_node(name, zone))
        assert t.list() == ["a1", "b1", "c1", "a2", "a3"]
        assert t.num_nodes == 5

    def test_remove(self):
        t = NodeTree()
        n = make_node("x", "z1")
        t.add_node(n)
        assert t.remove_node(n)
        assert t.num_nodes == 0
        assert t.list() == []


class TestCache:
    def test_assume_confirm_lifecycle(self):
        c = SchedulerCache()
        c.add_node(make_node("n1"))
        pod = MakePod().name("p").uid("u1").req({"cpu": "1"}).node("n1").obj()
        c.assume_pod(pod)
        assert c.is_assumed_pod(pod)
        assert c.pod_count() == 1
        c.add_pod(pod)  # informer confirms
        assert not c.is_assumed_pod(pod)
        assert c.pod_count() == 1
        c.remove_pod(pod)
        assert c.pod_count() == 0

    def test_forget(self):
        c = SchedulerCache()
        c.add_node(make_node("n1"))
        pod = MakePod().name("p").uid("u1").node("n1").obj()
        c.assume_pod(pod)
        c.forget_pod(pod)
        assert c.pod_count() == 0
        with pytest.raises(ValueError):
            c.forget_pod(pod)

    def test_assumed_expiry(self):
        now = [100.0]
        c = SchedulerCache(ttl=30.0, now=lambda: now[0])
        c.add_node(make_node("n1"))
        pod = MakePod().name("p").uid("u1").node("n1").obj()
        c.assume_pod(pod)
        c.finish_binding(pod)
        c.cleanup_expired_assumed_pods(now=105.0)
        assert c.pod_count() == 1  # not yet expired
        c.cleanup_expired_assumed_pods(now=131.0)
        assert c.pod_count() == 0  # expired: assume undone

    def test_expiry_only_after_binding_finished(self):
        c = SchedulerCache(ttl=30.0, now=lambda: 0.0)
        c.add_node(make_node("n1"))
        pod = MakePod().name("p").uid("u1").node("n1").obj()
        c.assume_pod(pod)
        c.cleanup_expired_assumed_pods(now=10_000.0)
        assert c.pod_count() == 1  # no FinishBinding -> never expires

    def test_incremental_snapshot(self):
        c = SchedulerCache()
        snap = Snapshot()
        for i in range(3):
            c.add_node(make_node(f"n{i}"))
        c.update_snapshot(snap)
        assert snap.num_nodes() == 3
        gen1 = snap.generation

        pod = MakePod().name("p").uid("u1").req({"cpu": "500m"}).node("n1").obj()
        c.add_pod(pod)
        c.update_snapshot(snap)
        assert snap.generation > gen1
        assert snap.get("n1").requested.milli_cpu == 500
        # unchanged nodes keep identity (no gratuitous clone churn check:
        # at least the data stays correct)
        assert snap.get("n0").requested.milli_cpu == 0

        c.remove_node(make_node("n2"))
        c.update_snapshot(snap)
        assert snap.num_nodes() == 2
        assert snap.get("n2") is None

    def test_snapshot_affinity_lists(self):
        c = SchedulerCache()
        snap = Snapshot()
        c.add_node(make_node("n1"))
        c.update_snapshot(snap)
        assert snap.have_pods_with_affinity_list() == []
        pod = (
            MakePod().name("p").uid("u1").node("n1")
            .pod_anti_affinity("app", ["web"], "zone").obj()
        )
        c.add_pod(pod)
        c.update_snapshot(snap)
        assert len(snap.have_pods_with_affinity_list()) == 1
        assert len(snap.have_pods_with_required_anti_affinity_list()) == 1

    def test_update_pod(self):
        c = SchedulerCache()
        c.add_node(make_node("n1"))
        old = MakePod().name("p").uid("u1").req({"cpu": "1"}).node("n1").obj()
        c.add_pod(old)
        new = MakePod().name("p").uid("u1").req({"cpu": "2"}).node("n1").obj()
        c.update_pod(old, new)
        snap = Snapshot()
        c.update_snapshot(snap)
        assert snap.get("n1").requested.milli_cpu == 2000

    def test_image_states(self):
        c = SchedulerCache()
        c.add_node(MakeNode().name("n1").image("img:v1", 1000).obj())
        c.add_node(MakeNode().name("n2").image("img:v1", 1000).obj())
        snap = Snapshot()
        c.update_snapshot(snap)
        assert snap.get("n1").image_states["img:v1"].num_nodes == 2


class TestNewSnapshot:
    def test_direct_construction(self):
        nodes = [make_node("n1"), make_node("n2")]
        pods = [MakePod().name("p1").uid("u1").req({"cpu": "1"}).node("n1").obj()]
        s = new_snapshot(pods, nodes)
        assert s.num_nodes() == 2
        assert s.get("n1").requested.milli_cpu == 1000
        assert len(s.pods()) == 1
