"""Scheduling-queue tests with a fake clock (reference
``scheduling_queue_test.go`` patterns: priority ordering, backoff movement,
moveRequestCycle race rule, affinity-triggered wakeups)."""

from kubernetes_tpu.scheduler.queue import SchedulingQueue
from kubernetes_tpu.scheduler.types import QueuedPodInfo
from kubernetes_tpu.testing import MakePod
from kubernetes_tpu.utils.clock import FakeClock


def qpod(name, priority=0, uid=None):
    return MakePod().name(name).uid(uid or f"uid-{name}").priority(priority).obj()


class TestPriorityOrdering:
    def test_pop_highest_priority_first(self):
        q = SchedulingQueue(clock=FakeClock())
        q.add(qpod("low", 1))
        q.add(qpod("high", 10))
        q.add(qpod("mid", 5))
        assert q.pop().pod.name == "high"
        assert q.pop().pod.name == "mid"
        assert q.pop().pod.name == "low"

    def test_fifo_tiebreak(self):
        clock = FakeClock()
        q = SchedulingQueue(clock=clock)
        q.add(qpod("first", 5))
        clock.step(1)
        q.add(qpod("second", 5))
        assert q.pop().pod.name == "first"

    def test_pop_increments_cycle_and_attempts(self):
        q = SchedulingQueue(clock=FakeClock())
        q.add(qpod("p"))
        qpi = q.pop()
        assert qpi.attempts == 1
        assert q.scheduling_cycle == 1


class TestUnschedulableAndBackoff:
    def test_unschedulable_then_move_event(self):
        clock = FakeClock(start=1000.0)
        q = SchedulingQueue(clock=clock)
        q.add(qpod("p"))
        qpi = q.pop()
        q.add_unschedulable_if_not_present(qpi, q.scheduling_cycle)
        assert q.num_unschedulable() == 1
        assert q.pop(timeout=0.01) is None

        clock.step(100)  # backoff long since complete
        q.move_all_to_active_or_backoff_queue("NodeAdd")
        assert q.num_unschedulable() == 0
        assert q.pop().pod.name == "p"

    def test_move_goes_to_backoff_when_backoff_incomplete(self):
        clock = FakeClock(start=1000.0)
        q = SchedulingQueue(clock=clock)
        q.add(qpod("p"))
        qpi = q.pop()
        q.add_unschedulable_if_not_present(qpi, q.scheduling_cycle)
        q.move_all_to_active_or_backoff_queue("NodeAdd")
        assert q.num_backoff() == 1  # 1 attempt -> 1s backoff, not yet elapsed
        clock.step(2.0)
        q.flush_backoff_completed()
        assert q.num_active() == 1

    def test_move_request_cycle_race(self):
        """A move event during this pod's scheduling cycle means the failed
        pod must go to backoff, not unschedulable (scheduling_queue.go:317)."""
        clock = FakeClock(start=1000.0)
        q = SchedulingQueue(clock=clock)
        q.add(qpod("p"))
        qpi = q.pop()
        cycle = q.scheduling_cycle
        q.move_all_to_active_or_backoff_queue("NodeAdd")  # concurrent event
        q.add_unschedulable_if_not_present(qpi, cycle)
        assert q.num_unschedulable() == 0
        assert q.num_backoff() == 1

    def test_backoff_duration_doubles_and_caps(self):
        clock = FakeClock(start=0.0)
        q = SchedulingQueue(clock=clock)
        qpi = QueuedPodInfo(qpod("p"), timestamp=0.0)
        qpi.attempts = 1
        assert q._backoff_duration(qpi) == 1.0
        qpi.attempts = 3
        assert q._backoff_duration(qpi) == 4.0
        qpi.attempts = 10
        assert q._backoff_duration(qpi) == 10.0  # capped

    def test_flush_unschedulable_left_over(self):
        clock = FakeClock(start=0.0)
        q = SchedulingQueue(clock=clock)
        q.add(qpod("p"))
        qpi = q.pop()
        q.add_unschedulable_if_not_present(qpi, q.scheduling_cycle)
        clock.step(30)
        q.flush_unschedulable_left_over()
        assert q.num_unschedulable() == 1  # < 60s old
        clock.step(31)
        q.flush_unschedulable_left_over()
        assert q.num_unschedulable() == 0


class TestAffinityWakeup:
    def test_assigned_pod_added_moves_matching(self):
        clock = FakeClock(start=1000.0)
        q = SchedulingQueue(clock=clock)
        waiting = (
            MakePod().name("w").uid("uw")
            .pod_affinity("app", ["web"], "zone").obj()
        )
        other = MakePod().name("o").uid("uo").obj()
        for p in (waiting, other):
            q.add(p)
            qpi = q.pop()
            q.add_unschedulable_if_not_present(qpi, q.scheduling_cycle)
        assert q.num_unschedulable() == 2

        clock.step(100)
        assigned = MakePod().name("a").uid("ua").label("app", "web").node("n1").obj()
        q.assigned_pod_added(assigned)
        assert q.num_unschedulable() == 1  # only the affinity-matching pod moved
        assert q.pop().pod.name == "w"


class TestNominator:
    def test_nominate_and_delete(self):
        q = SchedulingQueue(clock=FakeClock())
        pod = qpod("p")
        q.add_nominated_pod(pod, "n1")
        assert [pi.pod.name for pi in q.nominated_pods_for_node("n1")] == ["p"]
        q.delete_nominated_pod_if_exists(pod)
        assert q.nominated_pods_for_node("n1") == []

    def test_update_preserves_nomination(self):
        q = SchedulingQueue(clock=FakeClock())
        pod = qpod("p")
        q.add_nominated_pod(pod, "n1")
        newer = qpod("p")
        newer.metadata.uid = pod.metadata.uid
        q.update_nominated_pod(pod, newer)
        assert [pi.pod.name for pi in q.nominated_pods_for_node("n1")] == ["p"]


class TestPendingHint:
    """The streaming scheduler's non-blocking drain hint: size +
    max-priority peek without popping, consistent with what pop_batch
    would then drain."""

    def test_empty_queue(self):
        q = SchedulingQueue(clock=FakeClock())
        assert q.pending_hint() == (0, None)

    def test_hint_matches_next_pop(self):
        q = SchedulingQueue(clock=FakeClock())
        q.add(qpod("low", 1))
        q.add(qpod("high", 10))
        q.add(qpod("mid", 5))
        n, prio = q.pending_hint()
        assert n == 3
        assert prio == 10
        items, _cycle = q.pop_batch(10)
        assert items[0].pod.priority() == prio
        assert len(items) == n
        # the hint consumed nothing: no cycles, no attempts
        assert all(i.attempts == 1 for i in items)

    def test_hint_does_not_consume_cycles(self):
        q = SchedulingQueue(clock=FakeClock())
        q.add(qpod("p"))
        before = q.scheduling_cycle
        for _ in range(5):
            q.pending_hint()
        assert q.scheduling_cycle == before

    def test_hint_under_concurrent_adds(self):
        """Hints taken while writers stream adds are advisory but
        never wrong about the quiet state: every mid-stream hint size
        is within [0, total], and after the writers join, the hint
        agrees exactly with a full drain."""
        import threading

        q = SchedulingQueue(clock=FakeClock())
        total = 300
        writers = [
            threading.Thread(target=lambda lo=lo: [
                q.add(qpod(f"c{lo}-{i}", priority=(lo + i) % 7,
                           uid=f"cu{lo}-{i}"))
                for i in range(100)
            ])
            for lo in range(3)
        ]
        hints = []
        for w in writers:
            w.start()
        while any(w.is_alive() for w in writers):
            hints.append(q.pending_hint())
        for w in writers:
            w.join()
        assert all(0 <= n <= total for n, _ in hints)
        n, prio = q.pending_hint()
        assert n == total
        assert prio == 6
        items, _ = q.pop_batch(total)
        assert len(items) == total
        assert items[0].pod.priority() == prio
        assert q.pending_hint() == (0, None)


class TestDeleteAndUpdate:
    def test_delete_everywhere(self):
        q = SchedulingQueue(clock=FakeClock())
        p = qpod("p")
        q.add(p)
        q.delete(p)
        assert q.pop(timeout=0.01) is None

    def test_update_unknown_adds(self):
        q = SchedulingQueue(clock=FakeClock())
        p = qpod("p")
        q.update(None, p)
        assert q.pop().pod.name == "p"
