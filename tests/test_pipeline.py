"""Streaming-scheduler pipeline tests (ISSUE 14): the differential
guard between the double-buffered loop and the ``KTPU_PIPELINE=off``
barrier arm, the stage-handoff contract, the overlap telemetry, and
the tier-1 sustained-arrival mini-cell.

The differential guard is the PR's hardest promise: over identical
seeded event sequences — including a gang workload and a mid-run
node-death drift — the pipelined loop and the serialized arm must
produce a BIT-IDENTICAL bound set (same pods → same nodes). Both arms
run with ``adaptive_chunk=False`` and the same ``max_batch`` so the
drains partition identically; everything else (incremental mirror,
state carry, tie-breaks) must line up by construction.
"""

from __future__ import annotations

import time

import pytest

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config.feature_gates import FeatureGates
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.sidecar import TPUBatchScheduler, attach_batch_scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


def _make_sched(store, pipeline, max_batch=32):
    sched = Scheduler.create(
        store, feature_gates=FeatureGates({"TPUBatchScheduler": True}),
        provider="GangSchedulingProvider")
    bs = attach_batch_scheduler(sched, max_batch=max_batch,
                                adaptive_chunk=False, pipeline=pipeline)
    sched.start()
    return sched, bs


def _pump(sched, bs, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sched.queue.flush_backoff_completed()
        if bs.run_batch(pop_timeout=0.0):
            continue
        if sched.queue.pending_active_count() == 0 and \
                bs._pending is None:
            break
        time.sleep(0.01)
    bs.flush()
    assert sched.wait_for_inflight_bindings()


def _bound_set(store):
    return sorted((p.metadata.name, p.spec.node_name)
                  for p in store.list_pods())


def _run_event_sequence(pipeline: bool, waves, nodes=12, node_cpu="8",
                        kill_node_after=None, max_batch=32):
    """Drive one arm through a seeded event sequence: each wave is a
    list of pod builders, pumped to quiescence before the next;
    ``kill_node_after`` deletes that node name after the given wave
    index (the mid-run drift — both arms see it at the same quiesce
    point)."""
    store = ClusterStore()
    for i in range(nodes):
        store.add_node(MakeNode().name(f"n{i}")
                       .capacity({"cpu": node_cpu, "memory": "16Gi"})
                       .obj())
    sched, bs = _make_sched(store, pipeline, max_batch=max_batch)
    try:
        for wi, wave in enumerate(waves):
            store.create_pods([mk() for mk in wave])
            _pump(sched, bs)
            if kill_node_after is not None and \
                    wi == kill_node_after[0]:
                store.delete_node(kill_node_after[1])
        return _bound_set(store)
    finally:
        sched.stop()
        import gc

        gc.collect()   # don't leave a deferred-GC pause for later tests


def _plain_waves(n_waves=3, per_wave=40, cpu="1", offset=0):
    return [
        [
            (lambda w=w, i=i: MakePod().name(f"w{w}-p{i}")
             .uid(f"u{w}-{i}").req({"cpu": cpu}).obj())
            for i in range(per_wave)
        ]
        for w in range(offset, offset + n_waves)
    ]


def _gang_wave(w, gangs=3, size=4, cpu="2"):
    out = []
    for g in range(gangs):
        for m in range(size):
            out.append(
                lambda w=w, g=g, m=m: MakePod()
                .name(f"w{w}-g{g}-m{m}").uid(f"gu{w}-{g}-{m}")
                .priority(10).req({"cpu": cpu})
                .label("pod-group.scheduling.k8s.io/name", f"gang-{w}-{g}")
                .label("pod-group.scheduling.k8s.io/min-available",
                       str(size))
                .obj())
    return out


class TestDifferentialGuard:
    def test_contended_waves_bit_identical(self):
        """Capacity-contended waves (more pods than fit): the two arms
        must agree on exactly WHICH pods bound and WHERE."""
        waves = _plain_waves(3, 40)   # 120 x 1cpu vs 96 cores
        a = _run_event_sequence(True, waves)
        b = _run_event_sequence(False, waves)
        assert a == b
        assert sum(1 for _, n in a if n) == 96   # capacity exactly

    def test_gang_workload_bit_identical(self):
        """Gangs (Permit-parked, async binding cycles) interleaved
        with plain pods — the arms must still agree pod-for-pod."""
        waves = [
            _plain_waves(1, 20)[0],
            _gang_wave(1, gangs=3, size=4),
            _plain_waves(1, 10, offset=2)[0]
            + _gang_wave(2, gangs=2, size=4),
        ]
        a = _run_event_sequence(True, waves)
        b = _run_event_sequence(False, waves)
        assert a == b
        # the gangs actually landed (atomically) in both arms
        for w, g, size in ((1, 0, 4), (1, 1, 4), (1, 2, 4),
                           (2, 0, 4), (2, 1, 4)):
            members = [n for (name, n) in a
                       if name.startswith(f"w{w}-g{g}-") and n]
            assert len(members) in (0, size), (w, g, members)

    def test_mid_run_node_death_bit_identical(self):
        """A node deleted mid-sequence (after wave 0's quiesce): the
        node-SET epoch bump forces both arms through the drift
        re-encode, and the remaining waves must still land
        identically — with nothing placed on the dead node."""
        waves = _plain_waves(3, 30)
        a = _run_event_sequence(True, waves, kill_node_after=(0, "n3"))
        b = _run_event_sequence(False, waves, kill_node_after=(0, "n3"))
        assert a == b
        # post-death waves never bound onto the deleted node
        for name, node in a:
            if node == "n3":
                assert name.startswith("w0-"), \
                    f"{name} bound to the dead node after its deletion"

    def test_mid_flight_node_death_loses_nothing(self):
        """Drift WHILE a batch is in flight (pipelined arm only — the
        barrier arm has no in-flight window): dispatch a solve, kill a
        node before its commit cycle, keep pumping. The mirror guard
        must discard the suspect batch and re-solve; every pod still
        binds, none onto the dead node."""
        store = ClusterStore()
        for i in range(8):
            store.add_node(MakeNode().name(f"n{i}")
                           .capacity({"cpu": "8", "memory": "16Gi"})
                           .obj())
        sched, bs = _make_sched(store, pipeline=True, max_batch=16)
        try:
            store.create_pods([
                MakePod().name(f"p{i}").uid(f"u{i}")
                .req({"cpu": "1"}).obj()
                for i in range(48)
            ])
            # one cycle: dispatches a solve and (first call) holds it
            bs.run_batch(pop_timeout=0.1)
            store.delete_node("n2")   # drift while in flight
            _pump(sched, bs)
            pods = store.list_pods()
            assert all(p.spec.node_name for p in pods)
            assert len(pods) == 48
            assert not any(p.spec.node_name == "n2" for p in pods)
        finally:
            sched.stop()


class TestKillSwitch:
    def test_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("KTPU_PIPELINE", "off")
        store = ClusterStore()
        sched = Scheduler.create(
            store,
            feature_gates=FeatureGates({"TPUBatchScheduler": True}))
        bs = attach_batch_scheduler(sched)
        assert bs.pipeline_enabled is False
        assert bs.pipeline_info() is None
        monkeypatch.setenv("KTPU_PIPELINE", "on")
        assert TPUBatchScheduler(sched).pipeline_enabled is True
        monkeypatch.delenv("KTPU_PIPELINE")
        assert TPUBatchScheduler(sched).pipeline_enabled is True

    def test_serialized_arm_never_holds_a_batch(self):
        """The barrier arm commits every solve in the same call:
        ``_pending`` must never survive a ``run_batch`` return, and
        ``flush`` is a no-op."""
        store = ClusterStore()
        for i in range(4):
            store.add_node(MakeNode().name(f"n{i}")
                           .capacity({"cpu": "8", "memory": "16Gi"})
                           .obj())
        sched, bs = _make_sched(store, pipeline=False, max_batch=8)
        try:
            store.create_pods([
                MakePod().name(f"p{i}").uid(f"u{i}")
                .req({"cpu": "500m"}).obj()
                for i in range(30)
            ])
            while bs.run_batch(pop_timeout=0.0):
                assert bs._pending is None
            assert bs.flush() == 0
            sched.wait_for_inflight_bindings()
            assert all(p.spec.node_name for p in store.list_pods())
        finally:
            sched.stop()


class TestStageHandoff:
    def test_carry_never_reencoded_between_chained_solves(self):
        """The donated-carry contract: once the session has rebuilt,
        back-to-back pipelined solves chain on the device-resident
        state carry — ``prepare``/``prepare_state_only`` must NOT run
        again (re-encoding a carry a donating backend already consumed
        would corrupt the mirror)."""
        store = ClusterStore()
        for i in range(8):
            store.add_node(MakeNode().name(f"n{i}")
                           .capacity({"cpu": "16", "memory": "32Gi"})
                           .obj())
        sched, bs = _make_sched(store, pipeline=True, max_batch=16)
        try:
            # settle the first rebuild
            store.create_pods([MakePod().name("seed").uid("useed")
                               .req({"cpu": "100m"}).obj()])
            _pump(sched, bs)
            active = bs.session._active
            calls = []
            orig_prepare = active.prepare

            def counting_prepare(cluster, batch):
                calls.append("prepare")
                return orig_prepare(cluster, batch)

            active.prepare = counting_prepare
            if hasattr(active, "prepare_state_only"):
                orig_so = active.prepare_state_only

                def counting_so(cluster, batch):
                    calls.append("state_only")
                    return orig_so(cluster, batch)

                active.prepare_state_only = counting_so
            store.create_pods([
                MakePod().name(f"p{i}").uid(f"u{i}")
                .req({"cpu": "200m"}).obj()
                for i in range(64)
            ])
            hits_before = bs.session.incremental_hits
            _pump(sched, bs)
            assert bs.session.incremental_hits > hits_before
            assert calls == [], \
                f"pipelined solves re-encoded the carry: {calls}"
        finally:
            sched.stop()

    def test_depth_tracked_under_backlog(self):
        store = ClusterStore()
        for i in range(8):
            store.add_node(MakeNode().name(f"n{i}")
                           .capacity({"cpu": "16", "memory": "32Gi"})
                           .obj())
        sched, bs = _make_sched(store, pipeline=True, max_batch=16)
        try:
            store.create_pods([
                MakePod().name(f"p{i}").uid(f"u{i}")
                .req({"cpu": "100m"}).obj()
                for i in range(64)
            ])
            _pump(sched, bs)
            # 64 pods through a 16-pad loop: at least solve N + commit
            # N-1 were in flight together at some point
            assert bs.pipeline_depth_max >= 2
        finally:
            sched.stop()


class TestSustainedMiniCell:
    """Satellite 6: the tier-1 sustained-arrival cell — open-loop
    arrivals through the replay engine at compressed scale, asserting
    the pipeline genuinely overlaps and the staleness SLO stays green,
    inside the fast-suite time budget."""

    def test_overlap_occurs_and_staleness_green(self):
        from kubernetes_tpu.harness.sustained import run_sustained_cell

        cell = run_sustained_cell(pods=400, qps=400.0, max_batch=64,
                                  wait_timeout=90.0)
        assert cell["lost"] == 0
        assert cell["ever_bound"] == cell["injected"] == 400
        # the pipeline actually overlapped host work with in-flight
        # device time — the tentpole's measurable claim
        assert cell["overlapped_cycles"] > 0
        assert cell["overlap_share"] > 0.0
        # depth ≥ 2 under a guaranteed backlog is pinned by
        # TestStageHandoff; open-loop trickle timing only guarantees
        # the pipeline was on
        assert cell["pipeline"]["depth"] >= 1
        # the deeper in-flight window never let the solve run stale:
        # PR 8's staleness SLO verdict holds under open-loop arrivals
        assert cell["staleness_verdict"] in (None, "ok")
        assert cell["p99_arrival_to_bind_ms"] < 2000

    def test_barrier_arm_reports_no_overlap(self):
        """The same cell with KTPU_PIPELINE=off: eager solves open no
        in-flight window, so overlap telemetry must read zero — the
        A/B that proves overlap_share measures the pipeline and not an
        artifact."""
        from kubernetes_tpu.harness.sustained import run_sustained_cell

        cell = run_sustained_cell(pods=200, qps=400.0, max_batch=64,
                                  pipeline=False, wait_timeout=90.0)
        assert cell["lost"] == 0
        assert cell["overlapped_cycles"] == 0
        assert cell["overlap_share"] == 0.0
        assert cell["pipeline"] is None


class TestOverlapTelemetry:
    def test_note_block_computes_overlap(self):
        from kubernetes_tpu.observability.devprof import DevProfiler

        p = DevProfiler(enabled=True, use_listener=False)
        rec = p.begin_cycle(cycle=1, pad=64, real=32)
        p.phase("dispatch", 0.01)
        p.end_cycle(rec, pending_block=True)
        t_dispatch_end = rec.dispatch_end
        # host work happens here (the pipeline's overlap window)
        p.note_block(rec, 0.05, 128,
                     start_mono=t_dispatch_end + 0.2)
        assert rec["overlap_s"] == pytest.approx(0.2)
        s = p.summary()
        assert s["overlapped_cycles"] == 1
        assert s["overlap_s"] == pytest.approx(0.2, abs=1e-4)
        assert s["overlap_share"] == pytest.approx(0.2 / 0.25, abs=1e-3)

    def test_eager_cycles_excluded_from_overlap_share(self):
        from kubernetes_tpu.observability.devprof import DevProfiler

        p = DevProfiler(enabled=True, use_listener=False)
        # one eager cycle: block recorded inline, no in-flight window
        rec = p.begin_cycle(cycle=1, pad=64, real=32)
        p.phase("block", 1.0)
        p.end_cycle(rec)
        # one lazy cycle that fully overlapped
        rec2 = p.begin_cycle(cycle=2, pad=64, real=32)
        p.end_cycle(rec2, pending_block=True)
        p.note_block(rec2, 0.0, 0,
                     start_mono=rec2.dispatch_end + 0.5)
        s = p.summary()
        assert s["overlapped_cycles"] == 1
        # the eager cycle's 1.0s block must not dilute the share
        assert s["overlap_share"] == pytest.approx(1.0)

    def test_overlap_rides_jsonl_and_stream_summary(self, tmp_path):
        from kubernetes_tpu.observability.devprof import DevProfiler
        from tools.perf_report import summarize_telemetry

        p = DevProfiler(enabled=True, use_listener=False,
                        telemetry_dir=str(tmp_path))
        rec = p.begin_cycle(cycle=1, pad=64, real=32)
        p.end_cycle(rec, pending_block=True)
        p.note_block(rec, 0.1, 0, start_mono=rec.dispatch_end + 0.3)
        p.close()
        stream = summarize_telemetry(str(tmp_path))
        assert stream["overlapped_cycles"] == 1
        assert stream["overlap_s"] == pytest.approx(0.3, abs=1e-4)
        assert stream["overlap_share"] == pytest.approx(0.75, abs=1e-3)
        live = p.summary()
        assert stream["overlap_share"] == pytest.approx(
            live["overlap_share"], abs=1e-3)
