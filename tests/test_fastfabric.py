"""Fast REST fabric: bulk hot-path verbs, pipelined watch delivery, and
codec/connection overhead elimination (ISSUE 5).

Covers the contracts the perf work must not bend:

- bulk-verb round-trips over the binary codec (create/bind/status as
  ``{Kind}List`` requests) cross-checked against store truth;
- coalesced-watch framing: batched event chunks decode, a frame split
  mid-event is detected as torn (relist), cached event bytes are shared;
- the per-object and bulk paths produce IDENTICAL store mutation
  sequences (events, order, resource versions);
- token-bucket rate equivalence: a bulk request of N objects charges
  the same budget as N singles (the documented RestClusterClient
  contract), so the perf win cannot come from laundering client QPS;
- bench emission order: the REST row prints immediately before the
  headline (the driver tail-captures stdout) and parses with the
  fabric-overhead ratio;
- gang batches no longer churn the solver session (WAIT-parked pods
  count through the commit mutation ledger).
"""

from __future__ import annotations

import io
import json
import time

import pytest

from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.api.types import (
    Container,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
)
from kubernetes_tpu.apiserver import codec
from kubernetes_tpu.apiserver.rest import APIServer
from kubernetes_tpu.apiserver.store import ClusterStore, Event
from kubernetes_tpu.client.restcluster import RestClusterClient
from kubernetes_tpu.testing import MakeNode, MakePod


def _pod(name: str, uid: str = "") -> Pod:
    return Pod(
        metadata=ObjectMeta(name=name, namespace="default",
                            uid=uid or f"uid-{name}"),
        spec=PodSpec(containers=[Container(
            name="c",
            resources=ResourceRequirements(
                requests={"cpu": parse_quantity("100m")}),
        )]),
    )


def _serve(store_factory=ClusterStore):
    server = APIServer(store=store_factory()).start()
    return server.store, server


# ---------------------------------------------------------------------------
# bulk-verb round-trip over the binary codec


class TestBulkVerbRoundTrip:
    def test_create_bind_status_bulk_binary_cross_checked(self):
        store, server = _serve()
        client = RestClusterClient(server.url, binary=True)
        try:
            node = MakeNode().name("n1").capacity(
                {"cpu": "64", "memory": "256Gi", "pods": "500"}).obj()
            code, resp = client._request(
                "POST", "/api/v1/nodes",
                {"kind": "NodeList", "items": [node]}, charge=1)
            assert code == 201 and not resp.get("failures")

            pods = [_pod(f"p{i}") for i in range(40)]
            code, resp = client._request(
                "POST", "/api/v1/namespaces/default/pods",
                {"kind": "PodList", "items": pods}, charge=len(pods))
            assert code == 201
            assert resp.get("created") == 40 and not resp.get("failures")

            errors = client.bind_many([
                ("default", p.metadata.name, p.metadata.uid, "n1")
                for p in pods
            ])
            assert errors == [None] * 40

            updates = [{"namespace": "default", "name": p.metadata.name,
                        "status": {"phase": "Running",
                                   "podIP": f"10.0.0.{i}"}}
                       for i, p in enumerate(pods)]
            errs = client.write_pod_statuses(updates)
            assert errs == [None] * 40

            # store truth: every pod bound to n1, Running, IP stamped,
            # resourceVersions strictly increasing across the flow
            live = {p.metadata.name: p for p in store.list_pods()}
            assert len(live) == 40
            for i, p in enumerate(pods):
                got = live[p.metadata.name]
                assert got.spec.node_name == "n1"
                assert got.status.phase == "Running"
                assert got.status.pod_ip == f"10.0.0.{i}"
        finally:
            client._drop_conn()
            server.shutdown_server()

    def test_bulk_status_reports_positional_failures(self):
        store, server = _serve()
        client = RestClusterClient(server.url, binary=True)
        try:
            store.create_pod(_pod("exists"))
            errs = client.write_pod_statuses([
                {"namespace": "default", "name": "exists",
                 "status": {"phase": "Running"}},
                {"namespace": "default", "name": "ghost",
                 "status": {"phase": "Running"}},
            ])
            # 404s are None (pod deleted under us — single-PUT no-op
            # semantics); the live pod applied
            assert errs == [None, None]
            assert store.get_pod("default", "exists").status.phase \
                == "Running"
            assert store.get_pod("default", "ghost") is None
        finally:
            client._drop_conn()
            server.shutdown_server()

    def test_bulk_status_conditions_and_nomination(self):
        store, server = _serve()
        client = RestClusterClient(server.url, binary=True)
        try:
            store.create_pod(_pod("p1"))
            errs = client.write_pod_statuses([
                {"namespace": "default", "name": "p1", "status": {
                    "conditions": [{"type": "PodScheduled",
                                    "status": "False",
                                    "reason": "Unschedulable",
                                    "message": "no fit"}],
                    "nominatedNodeName": "n9",
                }},
            ])
            assert errs == [None]
            pod = store.get_pod("default", "p1")
            conds = {c.type: c for c in pod.status.conditions}
            assert conds["PodScheduled"].reason == "Unschedulable"
            assert pod.status.nominated_node_name == "n9"
        finally:
            client._drop_conn()
            server.shutdown_server()


# ---------------------------------------------------------------------------
# coalesced watch framing


class TestCoalescedWatchFraming:
    def test_batched_chunks_decode_and_carry_old(self):
        store, server = _serve()
        client = RestClusterClient(server.url, binary=True,
                                   watch_kinds=("Pod",))
        batches = []
        try:
            handle = client.watch(lambda e: None,
                                  batch_fn=batches.append)
            time.sleep(0.3)   # initial list + stream up
            pods = [_pod(f"w{i}") for i in range(64)]
            store.create_pods(pods)
            store.bind_many([
                ("default", p.metadata.name, p.metadata.uid, "n1")
                for p in pods
            ])
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                seen = [e for b in batches for e in b]
                if len(seen) >= 128:
                    break
                time.sleep(0.05)
            seen = [e for b in batches for e in b]
            adds = [e for e in seen if e.type == "ADDED"]
            mods = [e for e in seen if e.type == "MODIFIED"]
            assert len(adds) == 64 and len(mods) == 64
            # coalescing actually happened: fewer chunks than events
            assert len(batches) < len(seen)
            # old_obj rides along (bind-transition detection keys on it)
            assert all(m.old_obj is not None
                       and not m.old_obj.spec.node_name for m in mods)
            handle.stop()
        finally:
            client._drop_conn()
            server.shutdown_server()

    def test_event_bytes_cached_across_watchers(self):
        from kubernetes_tpu.apiserver.rest import _cached_event_bytes

        pod = _pod("c1")
        event = Event("ADDED", "Pod", pod)
        b1 = _cached_event_bytes(event)
        b2 = _cached_event_bytes(event)
        assert b1 is b2   # second watcher reuses the first encode
        t, obj, old, ts = codec.decode(b1)
        assert t == "ADDED" and obj.metadata.name == "c1" and old is None
        # the commit stamp rides the cached encoding (freshness SLI);
        # an un-dispatched event carries the 0.0 sentinel
        assert ts == 0.0

    def test_frame_split_mid_event_reads_as_torn(self):
        events = [codec.encode(("ADDED", _pod(f"t{i}"), None))
                  for i in range(8)]
        wire = codec.frame(events)
        # a complete frame decodes whole
        batch = codec.read_frame(io.BytesIO(wire))
        assert [codec.decode(b)[1].metadata.name for b in batch] \
            == [f"t{i}" for i in range(8)]
        # cut mid-event (inside the pickled body): torn -> None, the
        # client's relist trigger — no partial batch is ever delivered
        for cut in (2, codec.FRAME_LEN_BYTES + 10, len(wire) - 3):
            assert codec.read_frame(io.BytesIO(wire[:cut])) is None

    def test_json_watchers_coalesce_but_still_parse_by_line(self):
        store, server = _serve()
        from kubernetes_tpu.apiserver.rest import RestClient

        client = RestClient(server.url)
        got = []
        try:
            handle = client.watch("Pod", 0, lambda t, o: got.append((t, o)))
            time.sleep(0.3)
            store.create_pods([_pod(f"j{i}") for i in range(16)])
            deadline = time.monotonic() + 5.0
            while len(got) < 16 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert len(got) == 16
            assert {o.metadata.name for _, o in got} \
                == {f"j{i}" for i in range(16)}
            handle.stop()
        finally:
            server.shutdown_server()


class TestListCache:
    def test_cached_list_refreshes_when_rv_compacts_out(self):
        """A quiet kind's cached list rv must not outlive the watch
        log: serving it after compaction would strand the reflector in
        a relist→410 loop (its watch from the stale rv can never
        attach)."""
        from kubernetes_tpu.apiserver.watchcache import (
            TooOldResourceVersion,
        )

        store, server = _serve()
        try:
            store.add_node(MakeNode().name("n1").capacity(
                {"cpu": "4", "memory": "8Gi"}).obj())
            body1 = server.cached_list_binary("Node", None)
            rv1 = codec.decode(body1)["resourceVersion"]
            # hit while valid: byte-identical cached body
            assert server.cached_list_binary("Node", None) is body1
            # other-kind churn advances the log, then compaction drops
            # everything at or below the Node list's rv
            store.create_pods([_pod(f"churn{i}") for i in range(50)])
            server.watch_cache.compact(keep_last=10)
            assert server.watch_cache.oldest_rv() > rv1
            body2 = server.cached_list_binary("Node", None)
            rv2 = codec.decode(body2)["resourceVersion"]
            assert rv2 > rv1
            # the refreshed rv can open a watch; the stale one cannot
            h = server.watch_cache.watch_from(rv2, lambda rv, e: None)
            h.stop()
            with pytest.raises(TooOldResourceVersion):
                server.watch_cache.watch_from(rv1, lambda rv, e: None)
        finally:
            server.shutdown_server()


# ---------------------------------------------------------------------------
# the fastfabric matrix over the PARTITIONED store (differential guard
# satellite of the sharded-control-plane PR): with partitions=1 the
# sharded store must be behaviorally identical to a bare ClusterStore,
# and with partitions>1 every bulk-verb/watch/list-cache semantic above
# must hold unchanged through the same REST surface.


class TestPartitionedFabricMatrix:
    @pytest.mark.parametrize("parts", [1, 3])
    def test_fabric_matrix_over_partitioned_store(self, parts,
                                                  monkeypatch):
        import sys as _sys

        from kubernetes_tpu.apiserver.partition import PartitionedStore

        mod = _sys.modules[__name__]
        monkeypatch.setattr(
            mod, "_serve",
            lambda: _orig_serve(lambda: PartitionedStore(parts)))
        TestBulkVerbRoundTrip(
        ).test_create_bind_status_bulk_binary_cross_checked()
        TestBulkVerbRoundTrip(
        ).test_bulk_status_reports_positional_failures()
        TestCoalescedWatchFraming(
        ).test_batched_chunks_decode_and_carry_old()
        TestListCache().test_cached_list_refreshes_when_rv_compacts_out()


_orig_serve = _serve


# ---------------------------------------------------------------------------
# per-object vs bulk: identical store mutation sequences


class TestMutationSequenceInvariant:
    @staticmethod
    def _record(store):
        log = []
        store.watch(lambda e: log.append(
            (e.type, e.kind, e.obj.metadata.name,
             e.obj.metadata.resource_version)))
        return log

    def test_create_and_bind_sequences_match(self):
        single, bulk = ClusterStore(), ClusterStore()
        log_s, log_b = self._record(single), self._record(bulk)

        pods_s = [_pod(f"p{i}") for i in range(12)]
        pods_b = [_pod(f"p{i}") for i in range(12)]
        for p in pods_s:
            single.create_pod(p)
        bulk.create_pods(pods_b)
        for p in pods_s:
            single.bind("default", p.metadata.name, p.metadata.uid, "n1")
        bulk.bind_many([
            ("default", p.metadata.name, p.metadata.uid, "n1")
            for p in pods_b
        ])
        assert log_s == log_b

    def test_status_sequences_match_over_rest(self):
        # two servers: one takes per-object PUTs, one the bulk verb —
        # watchers must observe identical event sequences and the
        # stores identical final state
        store_s, server_s = _serve()
        store_b, server_b = _serve()
        log_s, log_b = self._record(store_s), self._record(store_b)
        cs = RestClusterClient(server_s.url, binary=True)
        cb = RestClusterClient(server_b.url, binary=True)
        try:
            for store in (store_s, store_b):
                store.create_pods([_pod(f"p{i}") for i in range(6)])
            for i in range(6):
                cs._put_status("default", f"p{i}",
                               {"phase": "Running",
                                "nominatedNodeName": "n3"})
            cb.write_pod_statuses([
                {"namespace": "default", "name": f"p{i}",
                 "status": {"phase": "Running",
                            "nominatedNodeName": "n3"}}
                for i in range(6)
            ])
            assert log_s == log_b
            for i in range(6):
                ps = store_s.get_pod("default", f"p{i}")
                pb = store_b.get_pod("default", f"p{i}")
                assert ps.status.phase == pb.status.phase == "Running"
                assert ps.status.nominated_node_name \
                    == pb.status.nominated_node_name == "n3"
        finally:
            cs._drop_conn()
            cb._drop_conn()
            server_s.shutdown_server()
            server_b.shutdown_server()


# ---------------------------------------------------------------------------
# token-bucket rate equivalence


class _RecordingLimiter:
    def __init__(self):
        self.charges = []

    def charge(self, n: float = 1.0) -> None:
        self.charges.append(n)


class TestRateEquivalence:
    def test_bulk_verbs_charge_per_object(self):
        store, server = _serve()
        client = RestClusterClient(server.url, binary=True)
        limiter = _RecordingLimiter()
        client.limiter = limiter
        try:
            node = MakeNode().name("n1").capacity(
                {"cpu": "64", "memory": "256Gi", "pods": "500"}).obj()
            store.add_node(node)
            pods = [_pod(f"r{i}") for i in range(17)]
            client.create_objects_bulk("Pod", pods)
            client.bind_many([
                ("default", p.metadata.name, p.metadata.uid, "n1")
                for p in pods
            ])
            client.write_pod_statuses([
                {"namespace": "default", "name": p.metadata.name,
                 "status": {"phase": "Running"}} for p in pods
            ])
            # 3 bulk requests, each charging exactly N — the budget N
            # singles would pay (the documented contract; batching must
            # never launder rate)
            assert limiter.charges == [17.0, 17.0, 17.0] \
                or limiter.charges == [17, 17, 17]
        finally:
            client._drop_conn()
            server.shutdown_server()

    def test_batched_status_scope_charges_per_item(self):
        store, server = _serve()
        client = RestClusterClient(server.url, binary=True)
        limiter = _RecordingLimiter()
        client.limiter = limiter
        try:
            store.create_pods([_pod(f"s{i}") for i in range(9)])
            with client.batched_status_writes():
                for i in range(9):
                    client.set_nominated_node_name("default", f"s{i}",
                                                   "n1")
            assert limiter.charges == [9.0] or limiter.charges == [9]
            for i in range(9):
                assert store.get_pod(
                    "default", f"s{i}").status.nominated_node_name == "n1"
        finally:
            client._drop_conn()
            server.shutdown_server()

    def test_token_bucket_blocks_same_for_bulk_and_singles(self):
        from kubernetes_tpu.client.restcluster import TokenBucket

        # deterministic accounting check on the bucket itself: after
        # any charge pattern totalling N from a full bucket, the token
        # deficit is identical
        b1 = TokenBucket(qps=1000.0, burst=50.0)
        b2 = TokenBucket(qps=1000.0, burst=50.0)
        b1.charge(30)
        for _ in range(30):
            b2.charge(1)
        assert b1._tokens == pytest.approx(b2._tokens, abs=1.5)


# ---------------------------------------------------------------------------
# bench emission order + REST-row parse smoke (tier-1 regression guard)


class TestBenchRowOrder:
    def test_rest_row_prints_immediately_before_headline(self, capsys,
                                                         monkeypatch):
        import bench

        def fake_run_one(key, name, nodes, init_pods, measure_pods,
                         serial_rate, repeat=1):
            return {"metric": f"pods_scheduled_per_sec[{name} {key}]",
                    "value": 1000.0, "unit": "pods/s",
                    "vs_baseline": 10.0}

        def fake_run_rest_one(nodes, measure_pods, serial_rate, qps,
                              repeat=1):
            return {"metric":
                    "pods_scheduled_per_sec[SchedulingBasic REST fabric]",
                    "value": 4500.0, "unit": "pods/s",
                    "vs_baseline": 70.0, "p99_latency_ms": 900,
                    "store_direct_pods_per_sec": 7500.0,
                    "fabric_overhead_ratio": 0.6}

        def fake_run_qos_one(nodes, measure_pods, serial_rate, qps,
                             tenants=3, solo_baseline=None):
            # the default matrix hands the REST row's numbers over as
            # the solo baseline (same configuration, no third run)
            assert solo_baseline is not None
            assert solo_baseline["pods_per_sec"] == 4500.0
            return {"metric": "noisy_tenant_qos[SchedulingBasic]",
                    "value": 3000.0, "unit": "pods/s",
                    "vs_baseline": 48.0, "p99_ratio_vs_solo": 1.3,
                    "qos_ok": True}

        def fake_run_scale10x_one(serial_rate, qps, quick=False):
            return {"metric": "pods_scheduled_per_sec[Scale10x "
                              "400nodes/2000pods, partitioned fabric "
                              "2p x 2r]",
                    "value": 2000.0, "unit": "pods/s",
                    "vs_baseline": 32.0,
                    "ab": {"partitioned_pods_per_sec": 2000.0,
                           "single_partition_pods_per_sec": 1500.0,
                           "speedup": 1.33, "sharding_pays": True},
                    "invariants": {"lost_pods": 0, "double_binds": 0},
                    "conflict_cell": {"conflicts_total": 9, "ok": True}}

        monkeypatch.setattr(bench, "run_one", fake_run_one)
        monkeypatch.setattr(bench, "run_rest_one", fake_run_rest_one)
        monkeypatch.setattr(bench, "run_qos_one", fake_run_qos_one)
        monkeypatch.setattr(bench, "run_scale10x_one",
                            fake_run_scale10x_one)
        monkeypatch.setattr(bench.sys, "argv",
                            ["bench.py", "--skip-serial"])
        bench.main()
        lines = [ln for ln in capsys.readouterr().out.splitlines()
                 if ln.strip().startswith("{")]
        rows = [json.loads(ln) for ln in lines]
        idx_rest = next(i for i, r in enumerate(rows)
                        if "REST fabric" in r["metric"])
        idx_headline = len(rows) - 1
        # the driver tail-captures stdout: the REST row must be the
        # second-to-last JSON line, right before the headline — and the
        # noisy-tenant QoS row rides right before the REST row
        assert idx_rest == idx_headline - 1
        assert "REST fabric" not in rows[idx_headline]["metric"]
        idx_qos = next(i for i, r in enumerate(rows)
                       if "noisy_tenant_qos" in r["metric"])
        assert idx_qos == idx_rest - 1
        assert rows[idx_qos]["qos_ok"] is True
        # the 10×-tier partitioned-control-plane row rides right
        # before the QoS/REST/headline tail with its A/B intact
        idx_scale = next(i for i, r in enumerate(rows)
                         if "Scale10x" in r["metric"])
        assert idx_scale == idx_qos - 1
        assert rows[idx_scale]["ab"]["sharding_pays"] is True
        assert rows[idx_scale]["conflict_cell"]["ok"] is True
        # smoke: the REST row parses with its required fields
        rest = rows[idx_rest]
        assert rest["value"] > 0 and rest["unit"] == "pods/s"
        assert rest["fabric_overhead_ratio"] > 0
        assert rest["store_direct_pods_per_sec"] > 0

    def test_matrix_row_order_contract(self):
        import bench

        order = bench.matrix_row_order()
        assert order[-1] == "headline"
        assert order[-2] == "rest"
        assert order[-3] == "qos"
        assert order[-4] == "scale10x"
        order_all = bench.matrix_row_order(include_extra=True)
        assert order_all[-4:] == ["scale10x", "qos", "rest", "headline"]
        assert set(bench.EXTRA_MATRIX) < set(order_all)


# ---------------------------------------------------------------------------
# gang batches must not churn the solver session


class TestGangSessionStability:
    def test_wait_parked_gang_pods_keep_session_valid(self):
        """A batch whose gang members park at Permit (WAIT) assumes
        them without committing them; the commit mutation ledger must
        count those assumes or every gang batch reads as mirror drift
        (the r5 state-only-rebuild-per-batch churn)."""
        from kubernetes_tpu.config.feature_gates import FeatureGates
        from kubernetes_tpu.scheduler.framework.plugins.coscheduling import (  # noqa: E501
            GROUP_NAME_LABEL,
            MIN_AVAILABLE_LABEL,
        )
        from kubernetes_tpu.scheduler.scheduler import Scheduler
        from kubernetes_tpu.sidecar import attach_batch_scheduler

        store = ClusterStore()
        for i in range(8):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "16", "memory": "64Gi", "pods": "110"})
                .obj())
        sched = Scheduler.create(
            store, feature_gates=FeatureGates({"TPUBatchScheduler": True}),
            provider="GangSchedulingProvider")
        bs = attach_batch_scheduler(sched, max_batch=64)
        sched.start()
        try:
            # two full gangs — every member fits; members park at
            # Permit until their gang completes within the same batch
            pods = []
            for g in range(2):
                for m in range(10):
                    pods.append(
                        MakePod().name(f"g{g}-m{m}").uid(f"g{g}-m{m}")
                        .req({"cpu": "100m"})
                        .labels({GROUP_NAME_LABEL: f"gang-{g}",
                                 MIN_AVAILABLE_LABEL: "10"})
                        .obj())
            store.create_pods(pods)
            deadline = time.monotonic() + 30.0
            bound = 0
            while time.monotonic() < deadline and bound < 20:
                bs.run_batch(pop_timeout=0.05)
                bound = sum(1 for p in store.list_pods()
                            if p.spec.node_name)
            assert bound == 20
            bs.flush()
            sched.wait_for_inflight_bindings(timeout=10.0)
            # the solve that placed the gangs must not have poisoned
            # the session: WAIT-parked assumes are sanctioned mutations
            assert bs.session.mirror_current(), (
                "gang WAIT assumes invalidated the session "
                f"(rebuilds={bs.session.rebuilds}, "
                f"state_only={bs.session.state_only_rebuilds})")
        finally:
            sched.stop()
