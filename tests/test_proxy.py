"""Dataplane ring: IP allocator, proxier rule sync, routing, affinity,
and the full Service→Endpoints→Proxier pipeline with the endpoints
controller."""

import time

import pytest

from kubernetes_tpu.api.types import (
    EndpointAddress,
    Endpoints,
    RUNNING,
    Service,
    ServicePort,
)
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.proxy import IPAllocator, IPAllocatorFull, Proxier
from kubernetes_tpu.testing import MakePod


def _svc(name, selector, port=80, target=8080, ip="10.96.0.10",
         affinity="None", ns="default"):
    s = Service(selector=selector,
                ports=[ServicePort(name="http", port=port, target_port=target)],
                cluster_ip=ip, session_affinity=affinity)
    s.metadata.name = name
    s.metadata.namespace = ns
    return s


def _ep(name, ips, port=8080, ns="default"):
    e = Endpoints(addresses=[EndpointAddress(ip=ip, target_pod=f"{ns}/p{i}")
                             for i, ip in enumerate(ips)],
                  ports=[ServicePort(name="http", port=port)])
    e.metadata.name = name
    e.metadata.namespace = ns
    return e


def test_ip_allocator_allocate_reserve_release():
    alloc = IPAllocator("10.96.0.0/29")  # 8 addrs → 5 usable
    ips = {alloc.allocate() for _ in range(5)}
    assert len(ips) == 5
    with pytest.raises(IPAllocatorFull):
        alloc.allocate()
    ip = ips.pop()
    alloc.release(ip)
    assert alloc.allocate() == ip
    assert not alloc.reserve(ip)  # already used again
    alloc.release(ip)
    assert alloc.reserve(ip)


def test_proxier_builds_rules_and_round_robins():
    store = ClusterStore()
    store.add_service(_svc("web", {"app": "web"}))
    store.upsert_endpoints(_ep("web", ["10.88.0.2", "10.88.0.3"]))
    proxier = Proxier(store).start()

    rules = proxier.rules()
    assert len(rules) == 1
    assert rules[0].backends == ["10.88.0.2:8080", "10.88.0.3:8080"]

    picks = [proxier.route("10.96.0.10", 80) for _ in range(4)]
    assert picks == ["10.88.0.2:8080", "10.88.0.3:8080",
                     "10.88.0.2:8080", "10.88.0.3:8080"]
    proxier.stop()


def test_proxier_no_endpoints_rejects():
    store = ClusterStore()
    store.add_service(_svc("lonely", {"app": "x"}))
    proxier = Proxier(store).start()
    assert proxier.route("10.96.0.10", 80) is None
    proxier.stop()


def test_proxier_session_affinity():
    store = ClusterStore()
    store.add_service(_svc("web", {"app": "web"}, affinity="ClientIP"))
    store.upsert_endpoints(_ep("web", ["10.88.0.2", "10.88.0.3"]))
    proxier = Proxier(store).start()
    first = proxier.route("10.96.0.10", 80, client_ip="1.2.3.4")
    for _ in range(5):
        assert proxier.route("10.96.0.10", 80, client_ip="1.2.3.4") == first
    other = proxier.route("10.96.0.10", 80, client_ip="5.6.7.8")
    # the second client stays sticky too, independent of the first
    assert proxier.route("10.96.0.10", 80, client_ip="5.6.7.8") == other
    proxier.stop()


def test_proxier_reacts_to_endpoint_changes():
    store = ClusterStore()
    store.add_service(_svc("web", {"app": "web"}))
    store.upsert_endpoints(_ep("web", ["10.88.0.2"]))
    proxier = Proxier(store).start()
    assert proxier.route("10.96.0.10", 80) == "10.88.0.2:8080"
    before = proxier.syncs
    # backend set changes → next route sees the new endpoints
    store.upsert_endpoints(_ep("web", ["10.88.0.9"]))
    assert proxier.route("10.96.0.10", 80) == "10.88.0.9:8080"
    assert proxier.syncs == before + 1
    # service deleted → VIP gone
    store.delete_service("default", "web")
    assert proxier.route("10.96.0.10", 80) is None
    proxier.stop()


def test_service_to_proxier_pipeline_with_endpoints_controller():
    """Full path: bound+running pods → endpoints controller materializes
    Endpoints → proxier routes to pod IPs (the cluster-networking loop the
    reference closes across kcm + kube-proxy)."""
    from kubernetes_tpu.client import SharedInformerFactory
    from kubernetes_tpu.controllers.endpoints import EndpointsController

    store = ClusterStore()
    factory = SharedInformerFactory(store)
    ctrl = EndpointsController(store, factory)
    factory.start()
    ctrl.run()
    try:
        store.add_service(_svc("web", {"app": "web"}, target=9000))
        for i, ip in enumerate(["10.88.0.2", "10.88.0.3"]):
            pod = MakePod().name(f"w{i}").uid(f"uw{i}").label("app", "web").obj()
            store.create_pod(pod)
            store.bind("default", f"w{i}", pod.uid, "n1")
            store.set_pod_phase("default", f"w{i}", RUNNING, pod_ip=ip)
        proxier = Proxier(store).start()
        deadline = time.time() + 5
        backends = set()
        while time.time() < deadline:
            b = proxier.route("10.96.0.10", 80)
            if b:
                backends.add(b)
            if len(backends) == 2:
                break
            time.sleep(0.05)
        assert backends == {"10.88.0.2:9000", "10.88.0.3:9000"}
        proxier.stop()
    finally:
        ctrl.stop()
        factory.stop()


def test_rest_assigns_cluster_ip():
    from kubernetes_tpu.apiserver.rest import APIServer, RestClient

    srv = APIServer().start()
    try:
        client = RestClient(srv.url)
        svc = _svc("auto", {"app": "a"}, ip="")
        created = client.create(svc)
        assert created.cluster_ip.startswith("10.96.")
        # explicit IP is reserved; duplicate explicit IP is rejected
        svc2 = _svc("manual", {"app": "b"}, ip="10.96.1.1")
        assert client.create(svc2).cluster_ip == "10.96.1.1"
        svc3 = _svc("dup", {"app": "c"}, ip="10.96.1.1")
        with pytest.raises(PermissionError):
            client.create(svc3)
        # delete releases the VIP for reuse
        client.delete("Service", "manual")
        svc4 = _svc("again", {"app": "d"}, ip="10.96.1.1")
        assert client.create(svc4).cluster_ip == "10.96.1.1"
    finally:
        srv.shutdown_server()


class TestIptablesRender:
    def test_ruleset_shape_and_stability(self):
        from kubernetes_tpu.proxy.proxier import Rule, render_iptables

        rules = [
            Rule(service="default/web", cluster_ip="10.0.0.10", port=80,
                 protocol="TCP",
                 backends=["10.244.0.5:8080", "10.244.1.7:8080",
                           "10.244.2.9:8080"]),
            Rule(service="default/db", cluster_ip="10.0.0.11", port=5432,
                 protocol="TCP", backends=[],
                 session_affinity="ClientIP"),
        ]
        text = render_iptables(rules)
        assert text.startswith("*nat\n")
        assert text.rstrip().endswith("COMMIT")
        # no-endpoints REJECT lives in the filter table, never nat
        nat_section = text.split("*filter")[0]
        assert "REJECT" not in nat_section
        # one KUBE-SVC chain per VIP:port WITH endpoints (the
        # endpointless service only gets a filter-table REJECT),
        # one KUBE-SEP per backend
        assert text.count(":KUBE-SVC-") == 1
        assert text.count(":KUBE-SEP-") == 3
        # probability fan-out: 1/3 then 1/2 then unconditional
        assert "--probability 0.33333" in text
        assert "--probability 0.50000" in text
        # DNAT per backend
        assert text.count("-j DNAT") == 3
        assert "--to-destination 10.244.0.5:8080" in text
        # endpointless service REJECTs
        assert '"default/db has no endpoints" -j REJECT' in text
        # byte-stable for the same table
        assert render_iptables(rules) == text

    def test_affinity_uses_recent_match(self):
        from kubernetes_tpu.proxy.proxier import Rule, render_iptables

        text = render_iptables([
            Rule(service="default/sticky", cluster_ip="10.0.0.12", port=443,
                 protocol="TCP", backends=["10.244.0.2:8443"],
                 session_affinity="ClientIP"),
        ])
        assert "-m recent" in text and "--rcheck" in text
        assert "--set" in text
        # sticky return traffic jumps to the remembered SEP chain, not
        # RETURN (which would exit without any DNAT)
        assert "-j RETURN" not in text
        import re
        m = re.search(r"--rcheck --seconds \d+ --reap -j (KUBE-SEP-\w+)", text)
        assert m, text


class TestVirtualDataplane:
    """The rendered iptables-restore artifact EXECUTED (VERDICT r2
    missing #7): load the exact render_iptables output into the
    netfilter-semantics dataplane and route synthetic connections."""

    @staticmethod
    def _rules():
        from kubernetes_tpu.proxy.proxier import Rule

        return [
            Rule(service="default/web", cluster_ip="10.96.0.10", port=80,
                 protocol="TCP",
                 backends=["10.244.0.5:8080", "10.244.0.6:8080",
                           "10.244.0.7:8080"]),
            Rule(service="default/empty", cluster_ip="10.96.0.20",
                 port=443, protocol="TCP", backends=[]),
            Rule(service="default/sticky", cluster_ip="10.96.0.30",
                 port=5432, protocol="TCP",
                 backends=["10.244.1.1:5432", "10.244.1.2:5432"],
                 session_affinity="ClientIP"),
        ]

    def _plane(self, seed=7, clock=None):
        import random

        from kubernetes_tpu.proxy.dataplane import VirtualDataplane
        from kubernetes_tpu.proxy.proxier import render_iptables

        kw = {"rng": random.Random(seed)}
        if clock is not None:
            kw["clock"] = clock
        plane = VirtualDataplane(**kw)
        plane.load(render_iptables(self._rules()))
        return plane

    def test_vip_dnats_to_backends_with_spread(self):
        plane = self._plane()
        hits = {}
        for i in range(600):
            out = plane.route("10.96.0.10", 80, src_ip=f"10.0.0.{i}")
            assert out is not None and out.endswith(":8080")
            hits[out] = hits.get(out, 0) + 1
        # all three backends serve, statistic-random spread roughly even
        assert len(hits) == 3, hits
        assert all(c > 120 for c in hits.values()), hits

    def test_non_service_traffic_falls_through(self):
        plane = self._plane()
        assert plane.route("8.8.8.8", 53) is None
        assert plane.route("10.96.0.10", 8080) is None  # wrong port

    def test_no_endpoints_rejected_via_filter_table(self):
        plane = self._plane()
        assert plane.route("10.96.0.20", 443, src_ip="10.0.0.1") is None

    def test_client_ip_affinity_via_recent_match(self):
        now = [0.0]
        plane = self._plane(clock=lambda: now[0])
        first = plane.route("10.96.0.30", 5432, src_ip="10.0.0.9")
        assert first is not None
        # the same client sticks across many connections
        for _ in range(20):
            assert plane.route("10.96.0.30", 5432,
                               src_ip="10.0.0.9") == first
        # ...but after the 3h window the recent entry reaps
        now[0] += 10801.0
        outs = {plane.route("10.96.0.30", 5432, src_ip="10.0.0.9")
                for _ in range(20)}
        assert len(outs) >= 1  # re-balanced (sticky again afterwards)
        again = plane.route("10.96.0.30", 5432, src_ip="10.0.0.9")
        for _ in range(10):
            assert plane.route("10.96.0.30", 5432,
                               src_ip="10.0.0.9") == again

    def test_reload_replaces_rules_atomically(self):
        from kubernetes_tpu.proxy.proxier import Rule, render_iptables

        plane = self._plane()
        assert plane.route("10.96.0.10", 80, src_ip="a") is not None
        plane.load(render_iptables([
            Rule(service="default/web", cluster_ip="10.96.0.10", port=80,
                 protocol="TCP", backends=["10.244.9.9:9999"]),
        ]))
        assert plane.route("10.96.0.10", 80, src_ip="a") == \
            "10.244.9.9:9999"
        assert plane.route("10.96.0.30", 5432, src_ip="a") is None


# ---------------------------------------------------------------------------
# ipvs mode (reference pkg/proxy/ipvs/proxier.go:342 +
# graceful_termination.go)


class TestIpvsProxier:
    def _cluster(self, scheduler="rr", affinity="None"):
        from kubernetes_tpu.proxy import IpvsProxier

        store = ClusterStore()
        store.add_service(_svc("web", {"app": "web"}, affinity=affinity))
        store.upsert_endpoints(_ep("web", ["10.1.0.1", "10.1.0.2",
                                        "10.1.0.3"]))
        p = IpvsProxier(store, scheduler=scheduler).start()
        return store, p

    def test_round_robin_over_real_servers(self):
        store, p = self._cluster()
        try:
            got = [p.route("10.96.0.10", 80) for _ in range(6)]
            assert got == ["10.1.0.1:8080", "10.1.0.2:8080",
                           "10.1.0.3:8080"] * 2
            # virtual server table reads like ipvsadm -L
            vs = p.virtual_servers()[0]
            assert vs.scheduler == "rr" and len(vs.reals) == 3
        finally:
            p.stop()

    def test_least_connection_scheduling(self):
        store, p = self._cluster(scheduler="lc")
        try:
            # two long-lived connections pin .1 and .2; lc must send
            # the next connections to the least-loaded real server
            c1 = p.connect("10.96.0.10", 80)
            c2 = p.connect("10.96.0.10", 80)
            assert {c1.backend, c2.backend} == \
                {"10.1.0.1:8080", "10.1.0.2:8080"}
            c3 = p.connect("10.96.0.10", 80)
            assert c3.backend == "10.1.0.3:8080"
            c3.close()
            c1.close()
            # .1 and .3 now idle; .2 still busy — next goes to .1
            assert p.connect("10.96.0.10", 80).backend == "10.1.0.1:8080"
        finally:
            p.stop()

    def test_client_ip_persistence(self):
        store, p = self._cluster(affinity="ClientIP")
        try:
            first = p.route("10.96.0.10", 80, client_ip="172.16.0.9")
            for _ in range(5):
                assert p.route("10.96.0.10", 80,
                               client_ip="172.16.0.9") == first
            # a different client advances the scheduler independently
            other = p.route("10.96.0.10", 80, client_ip="172.16.0.10")
            assert other != first or len(
                p.virtual_servers()[0].reals) == 1
        finally:
            p.stop()

    def test_graceful_termination_drains_connections(self):
        store, p = self._cluster()
        try:
            conns = [p.connect("10.96.0.10", 80) for _ in range(3)]
            victim = "10.1.0.3:8080"
            held = next(c for c in conns if c.backend == victim)
            # endpoint vanishes: real server drains instead of dying
            store.upsert_endpoints(_ep("web", ["10.1.0.1", "10.1.0.2"]))
            time.sleep(0.05)
            p.sync()
            vs = p.virtual_servers()[0]
            assert vs.reals[victim].weight == 0, "no graceful drain"
            # new traffic skips the draining server...
            assert all(
                p.route("10.96.0.10", 80) != victim for _ in range(6)
            )
            # ...and the entry disappears once the last connection closes
            held.close()
            vs = p.virtual_servers()[0]
            assert victim not in vs.reals
        finally:
            p.stop()

    def test_no_real_servers_rejects(self):
        from kubernetes_tpu.proxy import IpvsProxier

        store = ClusterStore()
        store.add_service(_svc("lonely", {"app": "x"}, ip="10.96.0.77"))
        p = IpvsProxier(store).start()
        try:
            assert p.route("10.96.0.77", 80) is None
            assert p.connect("10.96.0.77", 80) is None
        finally:
            p.stop()
