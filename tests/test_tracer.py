"""Observability layer: span recorder, flight recorder, Perfetto export,
admin endpoints, causal-trace stitching, and the trace_report tool."""

import json
import time
import urllib.request

import pytest

from kubernetes_tpu.metrics.registry import MetricsRegistry
from kubernetes_tpu.observability import Tracer, get_tracer


@pytest.fixture
def global_tracer():
    """The process-wide tracer, reset around each test that touches it."""
    t = get_tracer()
    saved = (t.enabled, t.sample_rate, t.seed, t.retain_s, t._dump_dir)
    t.clear()
    t._last_dump_mono.clear()
    t.last_dump_path = None
    t.configure(enabled=True, sample_rate=1.0)
    yield t
    (t.enabled, t.sample_rate, t.seed, t.retain_s, t._dump_dir) = saved
    t.clear()


def _http(url, method="GET", body=None):
    req = urllib.request.Request(url, method=method,
                                 data=json.dumps(body).encode()
                                 if body is not None else None)
    if body is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


class TestSpanRecorder:
    def test_span_nesting_and_ordering(self):
        t = Tracer(component="test", sample_rate=1.0,
                   registry=MetricsRegistry())
        with t.span("outer", trace="pod-1", kind="cycle") as outer:
            with t.span("inner") as inner:
                time.sleep(0.001)
            assert inner.parent_id == outer.span_id
            # children inherit the trace id from the enclosing span
            assert inner.trace == "pod-1"
        recs = list(t._ring)
        # inner closes (and records) before outer
        names = [r[0] for r in recs]
        assert names == ["inner", "outer"]
        inner_rec = recs[0]
        outer_rec = recs[1]
        assert inner_rec[6] == outer_rec[5]       # parent linkage
        assert inner_rec[3] <= outer_rec[3]       # nested duration
        assert outer_rec[8] == {"kind": "cycle"}  # attrs carried

    def test_explicit_record_and_event(self):
        t = Tracer(component="test", sample_rate=1.0,
                   registry=MetricsRegistry())
        now = time.monotonic()
        t.record("queue.wait", now - 0.25, now, trace="pod-2", attempts=1)
        t.event("rest.ingest", trace="pod-2")
        spans = [r for r in t._ring if r[1] == "X"]
        assert len(spans) == 1
        assert abs(spans[0][3] - 0.25) < 0.01
        events = [r for r in t._ring if r[1] == "i"]
        assert events[0][0] == "rest.ingest"

    def test_ring_eviction_under_overflow(self):
        t = Tracer(component="test", sample_rate=1.0, max_events=10,
                   registry=MetricsRegistry())
        for i in range(25):
            t.event(f"e{i}")
        assert len(t) == 10
        names = [r[0] for r in t._ring]
        assert names == [f"e{i}" for i in range(15, 25)]  # oldest evicted

    def test_sampling_deterministic_with_fixed_seed(self):
        uids = [f"uid-{i}" for i in range(500)]
        a = Tracer(component="a", sample_rate=0.25, seed=7,
                   registry=MetricsRegistry())
        b = Tracer(component="b", sample_rate=0.25, seed=7,
                   registry=MetricsRegistry())
        decisions_a = [a.sampled(u) for u in uids]
        decisions_b = [b.sampled(u) for u in uids]
        assert decisions_a == decisions_b     # no shared state needed
        frac = sum(decisions_a) / len(uids)
        assert 0.15 < frac < 0.35             # roughly the configured rate
        c = Tracer(component="c", sample_rate=0.25, seed=8,
                   registry=MetricsRegistry())
        assert [c.sampled(u) for u in uids] != decisions_a
        # edge rates
        assert Tracer(sample_rate=1.0,
                      registry=MetricsRegistry()).sampled("x")
        assert not Tracer(sample_rate=0.0,
                          registry=MetricsRegistry()).sampled("x")

    def test_disabled_tracer_records_nothing(self):
        t = Tracer(component="test", enabled=False,
                   registry=MetricsRegistry())
        t.event("e")
        t.record("s", time.monotonic() - 0.1)
        with t.span("x"):
            pass
        assert len(t) == 0
        assert not t.sampled("uid")

    def test_phase_stats_from_ring(self):
        t = Tracer(component="test", sample_rate=1.0,
                   registry=MetricsRegistry())
        now = time.monotonic()
        for dur in (0.010, 0.020, 0.030):
            t.record("solve.device", now - dur, now)
        stats = t.phase_stats()
        assert stats["solve.device"]["count"] == 3
        assert abs(stats["solve.device"]["total_s"] - 0.060) < 0.005
        assert abs(stats["solve.device"]["p50_s"] - 0.020) < 0.005

    def test_phase_histogram_exported_via_registry(self):
        reg = MetricsRegistry()
        t = Tracer(component="test", sample_rate=1.0, registry=reg)
        now = time.monotonic()
        t.record("solve.encode", now - 0.05, now)
        text = reg.expose()
        assert "schedtrace_phase_duration_seconds" in text
        assert 'phase="solve.encode"' in text


class TestPerfettoExport:
    def test_schema_validity(self):
        t = Tracer(component="test", sample_rate=1.0,
                   registry=MetricsRegistry())
        with t.span("cycle", trace="pod-3"):
            t.event("mark", trace="pod-3")
        doc = json.loads(json.dumps(t.export_perfetto()))
        events = doc["traceEvents"]
        assert events, "export produced no events"
        for ev in events:
            for field in ("ph", "ts", "pid", "tid"):
                assert field in ev, f"missing {field} in {ev}"
        xs = [e for e in events if e["ph"] == "X"]
        assert xs and all("dur" in e and e["dur"] >= 0 for e in xs)
        metas = [e for e in events if e["ph"] == "M"]
        assert any(e["name"] == "process_name" for e in metas)
        assert doc["otherData"]["component"] == "test"

    def test_retention_window_filters_old_spans(self):
        t = Tracer(component="test", sample_rate=1.0,
                   registry=MetricsRegistry())
        now = time.monotonic()
        t.record("old", now - 100.0, now - 99.0)
        t.record("new", now - 0.1, now)
        names = [e["name"] for e in
                 t.export_perfetto(window_s=60.0)["traceEvents"]]
        assert "new" in names and "old" not in names
        # explicit wide window keeps everything
        names = [e["name"] for e in
                 t.export_perfetto(window_s=1000.0)["traceEvents"]]
        assert "old" in names

    def test_dump_writes_valid_json(self, tmp_path):
        t = Tracer(component="test", sample_rate=1.0,
                   registry=MetricsRegistry(), dump_dir=str(tmp_path))
        t.event("e")
        path = t.dump(reason="unit")
        assert path is not None and path.startswith(str(tmp_path))
        with open(path) as f:
            doc = json.load(f)
        assert doc["otherData"]["reason"] == "unit"
        assert t.last_dump_path == path


class TestTraceCompatShim:
    def test_out_of_order_steps_get_chronological_deltas(self, caplog,
                                                         global_tracer):
        from kubernetes_tpu.utils.trace import Trace

        tr = Trace("Reorder", pod="default/p")
        # helper code stamped its step BEFORE the caller stamped an
        # earlier moment: append order is not chronological
        tr.steps.append((tr.start + 0.050, "late step"))
        tr.steps.append((tr.start + 0.010, "early step"))
        with caplog.at_level("INFO", logger="kubernetes_tpu.trace"):
            tr.log_if_long(0.0)
        text = caplog.text
        assert text.index("early step") < text.index("late step")
        assert "+-" not in text            # no negative deltas
        # the shim folded the trace onto the flight recorder
        assert any(r[0] == "trace.Reorder" for r in global_tracer._ring)

    def test_under_threshold_does_not_log_but_records(self, caplog,
                                                      global_tracer):
        from kubernetes_tpu.utils.trace import Trace

        with caplog.at_level("INFO", logger="kubernetes_tpu.trace"):
            with Trace("Quiet") as tr:
                tr.step("s")
                tr.log_if_long(10.0)
        assert "Quiet" not in caplog.text
        assert any(r[0] == "trace.Quiet" for r in global_tracer._ring)


class TestAdminEndpoints:
    def test_both_admin_routes_exempt_from_lanes_and_faults(
            self, global_tracer):
        from kubernetes_tpu.apiserver.rest import APIServer
        from kubernetes_tpu.apiserver.store import ClusterStore

        # legacy lane path: exhausting the semaphores directly is the
        # cheapest way to prove lane exemption (APF-path exemption has
        # its own saturation test in test_flowcontrol.py)
        server = APIServer(store=ClusterStore(),
                           max_readonly_inflight=1,
                           max_mutating_inflight=1,
                           flow_control=None).start()
        try:
            url = server.url
            # exhaust both lanes: ordinary traffic now answers 429 ...
            assert server.readonly_lane.acquire(blocking=False)
            assert server.mutating_lane.acquire(blocking=False)
            code, _ = _http(f"{url}/api/v1/pods")
            assert code == 429
            # ... while BOTH admin routes bypass the lanes
            code, _ = _http(f"{url}/debug/faults")
            assert code == 200
            code, doc = _http(f"{url}/debug/trace")
            assert code == 200 and "traceEvents" in doc
            code, _ = _http(f"{url}/debug/faults", method="POST",
                            body={"seed": 1, "rules": [
                                {"fault": "error", "verb": "GET",
                                 "resource": "*", "probability": 1.0,
                                 "code": 503}]})
            assert code == 200
            server.readonly_lane.release()
            server.mutating_lane.release()
            # fault armed: ordinary GETs now eat injected 503s ...
            code, _ = _http(f"{url}/api/v1/pods")
            assert code == 503
            # ... while BOTH admin routes stay fault-exempt
            code, _ = _http(f"{url}/debug/faults")
            assert code == 200
            code, _ = _http(f"{url}/debug/trace")
            assert code == 200
            # clear via DELETE still reachable under the armed gate
            code, _ = _http(f"{url}/debug/faults", method="DELETE")
            assert code == 200
        finally:
            server.shutdown_server()

    def test_trace_endpoint_dump_and_clear(self, global_tracer):
        from kubernetes_tpu.apiserver.rest import APIServer
        from kubernetes_tpu.apiserver.store import ClusterStore

        server = APIServer(store=ClusterStore()).start()
        try:
            global_tracer.event("probe-event", trace="u1")
            code, doc = _http(f"{server.url}/debug/trace")
            assert code == 200
            names = [e["name"] for e in doc["traceEvents"]]
            assert "probe-event" in names
            code, _ = _http(f"{server.url}/debug/trace", method="DELETE")
            assert code == 200
            # cleared — only the DELETE request's own span may remain
            # (it closes, and records, after the handler ran)
            assert not any(r[0] == "probe-event"
                           for r in global_tracer._ring)
            code, _ = _http(f"{server.url}/debug/trace?window=bogus")
            assert code == 400
            # PATCH routes through the admin registry: 405, not a 404
            # from resource routing
            code, _ = _http(f"{server.url}/debug/trace", method="PATCH",
                            body={})
            assert code == 405
            code, _ = _http(f"{server.url}/debug/faults", method="PATCH",
                            body={})
            assert code == 405
            # disabled tracer: an explicit 404, never a 200 empty dump
            global_tracer.configure(enabled=False)
            code, _ = _http(f"{server.url}/debug/trace")
            assert code == 404
            global_tracer.configure(enabled=True)
        finally:
            server.shutdown_server()


class TestCausalStitching:
    def test_rest_queue_solve_bind_stitch_over_debug_trace(
            self, global_tracer):
        """The acceptance path: a pod created over REST, scheduled by
        the batch path, must show up in /debug/trace with spans that
        stitch REST ingest → queue wait → solve → bind by pod uid."""
        from kubernetes_tpu.apiserver.rest import APIServer, RestClient
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.config.feature_gates import FeatureGates
        from kubernetes_tpu.scheduler.scheduler import Scheduler
        from kubernetes_tpu.sidecar import attach_batch_scheduler
        from kubernetes_tpu.testing import MakeNode, MakePod

        store = ClusterStore()
        server = APIServer(store=store).start()
        sched = Scheduler.create(
            store, feature_gates=FeatureGates({"TPUBatchScheduler": True}))
        bs = attach_batch_scheduler(sched, max_batch=64)
        sched.start()
        try:
            client = RestClient(server.url)
            client.create(MakeNode().name("n1")
                          .capacity({"cpu": "8", "memory": "16Gi"}).obj())
            pod = MakePod().name("traced").uid("traced-uid") \
                .req({"cpu": "1"}).obj()
            client.create(pod)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                bs.run_batch(pop_timeout=0.05)
                live = store.get_pod("default", "traced")
                if live is not None and live.spec.node_name:
                    break
            else:
                pytest.fail("pod never bound")
            bs.flush()
            code, doc = _http(f"{server.url}/debug/trace")
            assert code == 200
            events = doc["traceEvents"]
            for ev in events:
                for field in ("ph", "ts", "pid", "tid"):
                    assert field in ev
            mine = [e for e in events
                    if (e.get("args") or {}).get("trace") == "traced-uid"]
            names = {e["name"] for e in mine}
            assert "rest.ingest" in names     # REST ingestion
            assert "queue.wait" in names      # queueing
            assert "sched.bind" in names      # commit/bind e2e
            all_names = {e["name"] for e in events}
            # per-cycle solver phase spans from the same recorder
            assert any(n.startswith("solve.") for n in all_names), all_names
            # and the span-derived histogram reached /metrics
            with urllib.request.urlopen(f"{server.url}/metrics",
                                        timeout=10) as resp:
                metrics_text = resp.read().decode()
            assert "schedtrace_phase_duration_seconds" in metrics_text
        finally:
            sched.stop()
            server.shutdown_server()


@pytest.mark.chaos
class TestDegradedModeDump:
    def test_flight_recorder_dump_on_degraded_entry(self, tmp_path,
                                                    global_tracer):
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.scheduler.scheduler import Scheduler

        global_tracer._dump_dir = str(tmp_path)
        global_tracer.event("pre-outage-span", trace="u1")
        sched = Scheduler.create(ClusterStore())
        try:
            # the circuit breaker's listener path: an injected outage
            sched.set_degraded(True)
            path = global_tracer.last_dump_path
            assert path is not None and path.startswith(str(tmp_path))
            with open(path) as f:
                doc = json.load(f)
            assert doc["otherData"]["reason"] == "degraded"
            names = [e["name"] for e in doc["traceEvents"]]
            assert "pre-outage-span" in names
            sched.set_degraded(False)
        finally:
            sched.stop()


class TestTraceReportTool:
    def test_report_on_synthetic_dump(self, tmp_path):
        import sys
        sys.path.insert(0, "tools")
        try:
            import trace_report
        finally:
            sys.path.pop(0)
        t = Tracer(component="test", sample_rate=1.0,
                   registry=MetricsRegistry(), dump_dir=str(tmp_path))
        now = time.monotonic()
        t.event("rest.ingest", trace="pod-slow")
        t.record("queue.wait", now - 0.5, now - 0.1, trace="pod-slow")
        t.record("sched.bind", now - 0.1, now, trace="pod-slow",
                 node="n1", pod="default/slow")
        t.record("queue.wait", now - 0.05, now - 0.04, trace="pod-fast")
        t.record("solve.device", now - 0.2, now - 0.15)
        path = t.dump(reason="unit")
        out = trace_report.report(path)
        assert "per-phase latency breakdown" in out
        assert "queue.wait" in out and "solve.device" in out
        # slowest pod first, with its span tree and node
        slow_idx = out.index("pod-slow")
        fast_idx = out.index("pod-fast")
        assert slow_idx < fast_idx
        assert "n1" in out
        # malformed dumps fail loudly (the smoke check's purpose)
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"traceEvents": [{"name": "x"}]}))
        with pytest.raises(ValueError):
            trace_report.report(str(bad))

    @pytest.mark.slow
    def test_smoke_on_bench_path_dump(self, tmp_path, global_tracer):
        """The slow-marker bench path: run a small batch workload, dump
        the flight recorder, and push the dump through trace_report —
        a dump-format regression fails here, not in a postmortem."""
        import subprocess
        import sys

        from kubernetes_tpu.harness import make_workload, run_workload

        ops = make_workload("SchedulingBasic", nodes=20, init_pods=0,
                            measure_pods=40)
        result = run_workload("SchedulingBasic/trace-smoke", ops,
                              use_batch=True, wait_timeout=120)
        assert result.pods_per_second > 0
        path = global_tracer.dump(
            path=str(tmp_path / "bench-dump.json"), reason="bench-smoke")
        assert path is not None
        proc = subprocess.run(
            [sys.executable, "tools/trace_report.py", path, "--top", "3"],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert "per-phase latency breakdown" in proc.stdout
        assert "solve." in proc.stdout
        assert "slowest pods" in proc.stdout
