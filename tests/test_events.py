"""Events API end-to-end: EventRecorder aggregation, the scheduler's
FailedScheduling / Scheduled / Preempted recording sites (reference
``pkg/scheduler/scheduler.go:331,423``, ``default_preemption.go:698``),
TTL pruning, and the kubectl surface."""

import time

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client.events import EventRecorder
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


def drain_serial(sched, rounds=200):
    for _ in range(rounds):
        sched.queue.flush_backoff_completed()
        if not sched.schedule_one(pop_timeout=0.0):
            break
    sched.wait_for_inflight_bindings()
    sched.recorder.flush_now()


class TestEventRecorder:
    def test_aggregation_and_fields(self):
        store = ClusterStore()
        pod = MakePod().name("p").uid("u1").obj()
        rec = EventRecorder(store, "test-component")
        for _ in range(3):
            rec.event(pod, "Warning", "FailedScheduling", "0/5 nodes fit")
        rec.event(pod, "Normal", "Scheduled", "assigned")
        rec.flush_now()
        events = store.list_events()
        assert len(events) == 2
        agg = next(e for e in events if e.reason == "FailedScheduling")
        assert agg.count == 3
        assert agg.type == "Warning"
        assert agg.involved_object.name == "p"
        assert agg.involved_object.uid == "u1"
        assert agg.source_component == "test-component"
        assert agg.last_timestamp >= agg.first_timestamp

    def test_queue_overflow_drops_not_blocks(self):
        store = ClusterStore()
        pod = MakePod().name("p").obj()
        rec = EventRecorder(store, "c", queue_cap=10)
        for i in range(25):
            rec.event(pod, "Normal", "R", f"m{i}")  # distinct: no agg
        assert rec.dropped == 15
        rec.flush_now()
        assert len(store.list_events()) == 10

    def test_ttl_prune(self):
        store = ClusterStore()
        store.event_ttl = 10.0
        pod = MakePod().name("p").obj()
        rec = EventRecorder(store, "c")
        rec.event(pod, "Normal", "R", "m")
        rec.flush_now()
        assert len(store.list_events()) == 1
        assert store.prune_expired_events(now=time.time() + 11) == 1
        assert store.list_events() == []


class TestSchedulerEventSites:
    def test_scheduled_and_failed_events(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n1")
                       .capacity({"cpu": "4", "memory": "8Gi"}).obj())
        sched = Scheduler.create(store)
        sched.start()
        store.create_pod(MakePod().name("ok").uid("u-ok")
                         .req({"cpu": "1"}).obj())
        store.create_pod(MakePod().name("toobig").uid("u-big")
                         .req({"cpu": "64"}).obj())
        drain_serial(sched)
        sched.stop()

        reasons = {
            (e.involved_object.name, e.reason, e.type)
            for e in store.list_events()
        }
        assert ("ok", "Scheduled", "Normal") in reasons
        assert ("toobig", "FailedScheduling", "Warning") in reasons
        sch = next(e for e in store.list_events() if e.reason == "Scheduled")
        assert "default/ok" in sch.message and "n1" in sch.message

    def test_preempted_event_on_victim(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n1")
                       .capacity({"cpu": "4", "memory": "8Gi"}).obj())
        sched = Scheduler.create(store)
        sched.start()
        store.create_pod(MakePod().name("victim").uid("u-v")
                         .priority(0).req({"cpu": "4"}).obj())
        drain_serial(sched)
        store.create_pod(MakePod().name("vip").uid("u-hi")
                         .priority(1000).req({"cpu": "4"}).obj())
        # first cycle fails + preempts; victim delete frees capacity
        drain_serial(sched)
        time.sleep(1.1)  # backoff for the retried vip
        drain_serial(sched)
        sched.stop()

        evs = store.list_events()
        preempted = [e for e in evs if e.reason == "Preempted"]
        assert preempted, [e.reason for e in evs]
        assert preempted[0].involved_object.name == "victim"
        assert "default/vip" in preempted[0].message
        # and the vip eventually scheduled
        assert store.get_pod("default", "vip").spec.node_name == "n1"


class TestKubectlEvents:
    def test_get_events_table(self):
        import io

        from kubernetes_tpu.apiserver.rest import APIServer, RestClient
        from kubernetes_tpu.cli.kubectl import Kubectl

        store = ClusterStore()
        pod = MakePod().name("p").obj()
        rec = EventRecorder(store, "scheduler")
        rec.event(pod, "Warning", "FailedScheduling", "0/1 nodes")
        rec.flush_now()
        server = APIServer(store).start()
        try:
            out = io.StringIO()
            k = Kubectl(RestClient(server.url), out=out, err=out)
            assert k.get("events", None, "default", False, None) == 0
            text = out.getvalue()
            assert "FailedScheduling" in text
            assert "pod/p" in text
        finally:
            server.shutdown()
