"""Batch-path integration tests: the TPUBatchScheduler gate, commit
pipeline, and clean fallback to the serial path."""

import time

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config.feature_gates import FeatureGates
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.sidecar import attach_batch_scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


def make_batch_scheduler(store, validate=False, gate=True):
    sched = Scheduler.create(
        store, feature_gates=FeatureGates({"TPUBatchScheduler": gate})
    )
    bs = attach_batch_scheduler(sched, validate=validate)
    sched.start()
    return sched, bs


def drain_batches(sched, bs, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sched.queue.flush_backoff_completed()
        if bs.run_batch(pop_timeout=0.0):
            continue
        if sched.queue.num_active() == 0 and sched.queue.num_backoff() == 0:
            break
        time.sleep(0.05)
    assert sched.wait_for_inflight_bindings()


class TestGate:
    def test_gate_off_returns_none(self):
        sched = Scheduler.create(ClusterStore())
        assert attach_batch_scheduler(sched) is None
        assert sched.batch_scheduler is None


class TestBatchScheduling:
    def test_batch_binds_all(self):
        store = ClusterStore()
        for i in range(10):
            store.add_node(
                MakeNode().name(f"n{i}").capacity({"cpu": "8", "memory": "16Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store)
        for i in range(40):
            store.create_pod(MakePod().name(f"p{i}").req({"cpu": "1"}).obj())
        drain_batches(sched, bs)
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 40
        # capacity respected: 8 cpu per node, 1 cpu pods -> max 8/node
        per_node = {}
        for p in bound:
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(c <= 8 for c in per_node.values())
        sched.stop()

    def test_batch_respects_spread(self):
        store = ClusterStore()
        for z in ("za", "zb", "zc"):
            for i in range(2):
                store.add_node(
                    MakeNode().name(f"{z}-{i}")
                    .label("topology.kubernetes.io/zone", z)
                    .capacity({"cpu": "16", "memory": "32Gi"}).obj()
                )
        sched, bs = make_batch_scheduler(store, validate=True)
        for i in range(9):
            store.create_pod(
                MakePod().name(f"s{i}").label("app", "web").req({"cpu": "1"})
                .spread_constraint(
                    1, "topology.kubernetes.io/zone", "DoNotSchedule",
                    {"app": "web"},
                ).obj()
            )
        drain_batches(sched, bs)
        zones = {}
        for p in store.list_pods():
            assert p.spec.node_name, f"{p.name} not bound"
            z = p.spec.node_name.split("-")[0]
            zones[z] = zones.get(z, 0) + 1
        assert all(c == 3 for c in zones.values()), zones
        sched.stop()

    def test_unschedulable_falls_back_with_status(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n0").capacity({"cpu": "1", "memory": "2Gi"}).obj())
        sched, bs = make_batch_scheduler(store)
        store.create_pod(MakePod().name("big").req({"cpu": "64"}).obj())
        bs.run_batch(pop_timeout=0.1)
        assert sched.wait_for_inflight_bindings()
        pod = store.get_pod("default", "big")
        conds = {c.type: c for c in pod.status.conditions}
        assert "Insufficient cpu" in conds["PodScheduled"].message
        assert sched.queue.num_unschedulable() == 1
        sched.stop()

    def test_pvc_pod_takes_serial_path(self):
        from kubernetes_tpu.api.types import (
            PersistentVolume,
            PersistentVolumeClaim,
            ObjectMeta,
            StorageClass,
        )
        from kubernetes_tpu.api.resource import parse_quantity

        store = ClusterStore()
        store.add_node(MakeNode().name("n0").capacity({"cpu": "8", "memory": "16Gi"}).obj())
        store.add_storage_class(
            StorageClass(metadata=ObjectMeta(name="fast"), provisioner="x",
                         volume_binding_mode="WaitForFirstConsumer")
        )
        store.add_pv(PersistentVolume(
            metadata=ObjectMeta(name="pv1"),
            capacity={"storage": parse_quantity("10Gi")},
            storage_class_name="fast",
        ))
        store.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name="claim", namespace="default"),
            storage_class_name="fast",
            requests={"storage": parse_quantity("5Gi")},
        ))
        sched, bs = make_batch_scheduler(store)
        store.create_pod(MakePod().name("p").req({"cpu": "1"}).pvc("claim").obj())
        drain_batches(sched, bs)
        assert store.get_pod("default", "p").spec.node_name == "n0"
        # volume got bound through Reserve/PreBind
        assert store.get_pvc("default", "claim").volume_name == "pv1"
        sched.stop()

    def test_preemption_via_fallback(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n0").capacity({"cpu": "2", "memory": "4Gi"}).obj())
        sched, bs = make_batch_scheduler(store)
        store.create_pod(MakePod().name("victim").priority(1).req({"cpu": "2"}).obj())
        drain_batches(sched, bs)
        store.create_pod(MakePod().name("vip").priority(100).req({"cpu": "2"}).obj())
        drain_batches(sched, bs)
        assert store.get_pod("default", "victim") is None
        assert store.get_pod("default", "vip").spec.node_name == "n0"
        sched.stop()

    def test_mixed_batch_and_serial(self):
        store = ClusterStore()
        for i in range(4):
            store.add_node(
                MakeNode().name(f"n{i}").capacity({"cpu": "8", "memory": "16Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store)
        for i in range(10):
            store.create_pod(MakePod().name(f"b{i}").req({"cpu": "500m"}).obj())
        # host-port pod must take the serial path
        store.create_pod(MakePod().name("hp").req({"cpu": "500m"}).host_port(8080).obj())
        drain_batches(sched, bs)
        assert all(p.spec.node_name for p in store.list_pods())
        sched.stop()


class TestWarmup:
    def test_warmup_without_samples_compiles(self, caplog):
        """warmup() with no sample pods must encode+solve cleanly (not
        swallow an exception and silently leave the solver cold)."""
        store = ClusterStore()
        store.add_node(
            MakeNode().name("n0").capacity({"cpu": "8", "memory": "16Gi"}).obj()
        )
        sched, bs = make_batch_scheduler(store)
        import logging

        with caplog.at_level(logging.ERROR, logger="kubernetes_tpu.sidecar"):
            spent = bs.warmup()
        assert spent > 0.0
        assert "warmup failed" not in caplog.text
        sched.stop()

    def test_warmup_with_workload_samples(self):
        store = ClusterStore()
        for i in range(4):
            store.add_node(
                MakeNode().name(f"n{i}")
                .label("topology.kubernetes.io/zone", f"z{i % 2}")
                .capacity({"cpu": "8", "memory": "16Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store)
        sample = (
            MakePod().name("tmpl").uid("tmpl-u").label("app", "w")
            .req({"cpu": "1"})
            .spread_constraint(
                1, "topology.kubernetes.io/zone", "DoNotSchedule", {"app": "w"}
            ).obj()
        )
        assert bs.warmup(sample_pods=[sample]) > 0.0
        sched.stop()
