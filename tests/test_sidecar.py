"""Batch-path integration tests: the TPUBatchScheduler gate, commit
pipeline, and clean fallback to the serial path."""

import time

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config.feature_gates import FeatureGates
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.sidecar import attach_batch_scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


def make_batch_scheduler(store, validate=False, gate=True):
    sched = Scheduler.create(
        store, feature_gates=FeatureGates({"TPUBatchScheduler": gate})
    )
    bs = attach_batch_scheduler(sched, validate=validate)
    sched.start()
    return sched, bs


def drain_batches(sched, bs, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sched.queue.flush_backoff_completed()
        if bs.run_batch(pop_timeout=0.0):
            continue
        if sched.queue.num_active() == 0 and sched.queue.num_backoff() == 0:
            break
        time.sleep(0.05)
    assert sched.wait_for_inflight_bindings()


class TestGate:
    def test_gate_off_returns_none(self):
        sched = Scheduler.create(ClusterStore())
        assert attach_batch_scheduler(sched) is None
        assert sched.batch_scheduler is None


class TestBatchScheduling:
    def test_batch_binds_all(self):
        store = ClusterStore()
        for i in range(10):
            store.add_node(
                MakeNode().name(f"n{i}").capacity({"cpu": "8", "memory": "16Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store)
        for i in range(40):
            store.create_pod(MakePod().name(f"p{i}").req({"cpu": "1"}).obj())
        drain_batches(sched, bs)
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 40
        # capacity respected: 8 cpu per node, 1 cpu pods -> max 8/node
        per_node = {}
        for p in bound:
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(c <= 8 for c in per_node.values())
        sched.stop()

    def test_batch_respects_spread(self):
        store = ClusterStore()
        for z in ("za", "zb", "zc"):
            for i in range(2):
                store.add_node(
                    MakeNode().name(f"{z}-{i}")
                    .label("topology.kubernetes.io/zone", z)
                    .capacity({"cpu": "16", "memory": "32Gi"}).obj()
                )
        sched, bs = make_batch_scheduler(store, validate=True)
        for i in range(9):
            store.create_pod(
                MakePod().name(f"s{i}").label("app", "web").req({"cpu": "1"})
                .spread_constraint(
                    1, "topology.kubernetes.io/zone", "DoNotSchedule",
                    {"app": "web"},
                ).obj()
            )
        drain_batches(sched, bs)
        zones = {}
        for p in store.list_pods():
            assert p.spec.node_name, f"{p.name} not bound"
            z = p.spec.node_name.split("-")[0]
            zones[z] = zones.get(z, 0) + 1
        assert all(c == 3 for c in zones.values()), zones
        sched.stop()

    def test_unschedulable_falls_back_with_status(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n0").capacity({"cpu": "1", "memory": "2Gi"}).obj())
        sched, bs = make_batch_scheduler(store)
        store.create_pod(MakePod().name("big").req({"cpu": "64"}).obj())
        bs.run_batch(pop_timeout=0.1)
        assert sched.wait_for_inflight_bindings()
        pod = store.get_pod("default", "big")
        conds = {c.type: c for c in pod.status.conditions}
        assert "Insufficient cpu" in conds["PodScheduled"].message
        assert sched.queue.num_unschedulable() == 1
        sched.stop()

    def test_unbound_pvc_pod_takes_serial_path(self):
        from kubernetes_tpu.api.types import (
            PersistentVolume,
            PersistentVolumeClaim,
            ObjectMeta,
            StorageClass,
        )
        from kubernetes_tpu.api.resource import parse_quantity

        store = ClusterStore()
        store.add_node(MakeNode().name("n0").capacity({"cpu": "8", "memory": "16Gi"}).obj())
        store.add_storage_class(
            StorageClass(metadata=ObjectMeta(name="fast"), provisioner="x",
                         volume_binding_mode="WaitForFirstConsumer")
        )
        store.add_pv(PersistentVolume(
            metadata=ObjectMeta(name="pv1"),
            capacity={"storage": parse_quantity("10Gi")},
            storage_class_name="fast",
        ))
        store.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name="claim", namespace="default"),
            storage_class_name="fast",
            requests={"storage": parse_quantity("5Gi")},
        ))
        sched, bs = make_batch_scheduler(store)
        store.create_pod(MakePod().name("p").req({"cpu": "1"}).pvc("claim").obj())
        drain_batches(sched, bs)
        assert store.get_pod("default", "p").spec.node_name == "n0"
        # volume got bound through Reserve/PreBind
        assert store.get_pvc("default", "claim").volume_name == "pv1"
        sched.stop()

    def test_preemption_via_fallback(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n0").capacity({"cpu": "2", "memory": "4Gi"}).obj())
        sched, bs = make_batch_scheduler(store)
        store.create_pod(MakePod().name("victim").priority(1).req({"cpu": "2"}).obj())
        drain_batches(sched, bs)
        store.create_pod(MakePod().name("vip").priority(100).req({"cpu": "2"}).obj())
        drain_batches(sched, bs)
        assert store.get_pod("default", "victim") is None
        assert store.get_pod("default", "vip").spec.node_name == "n0"
        sched.stop()

    def test_mixed_batch_and_serial(self):
        store = ClusterStore()
        for i in range(4):
            store.add_node(
                MakeNode().name(f"n{i}").capacity({"cpu": "8", "memory": "16Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store)
        for i in range(10):
            store.create_pod(MakePod().name(f"b{i}").req({"cpu": "500m"}).obj())
        # host-port pod must take the serial path
        store.create_pod(MakePod().name("hp").req({"cpu": "500m"}).host_port(8080).obj())
        drain_batches(sched, bs)
        assert all(p.spec.node_name for p in store.list_pods())
        sched.stop()


class TestBatchVolumes:
    """Round-3 volume tensorization (VERDICT r2 #1): bound-PVC pods ride
    the DEVICE path — PV node-affinity/zone constraints fold into the
    static profile masks and CSI attach limits become resource columns
    enforced by the in-batch capacity re-masking. Reference semantics:
    ``volumebinding/volume_binding.go:82-269``, ``volumezone/
    volume_zone.go``, ``nodevolumelimits/csi.go``."""

    @staticmethod
    def _bound_pair(store, claim, pv, driver="", zone=None, affinity=None):
        from kubernetes_tpu.api.resource import parse_quantity
        from kubernetes_tpu.api.types import (
            ObjectMeta, PersistentVolume, PersistentVolumeClaim,
            StorageClass,
        )

        if store.get_storage_class("sc") is None:
            store.add_storage_class(StorageClass(
                metadata=ObjectMeta(name="sc"), provisioner="x",
                volume_binding_mode="Immediate",
            ))
        labels = {"topology.kubernetes.io/zone": zone} if zone else {}
        store.add_pv(PersistentVolume(
            metadata=ObjectMeta(name=pv, labels=labels),
            capacity={"storage": parse_quantity("1Gi")},
            storage_class_name="sc",
            claim_ref=f"default/{claim}",
            phase="Bound",
            node_affinity=affinity,
            csi_driver=driver,
        ))
        store.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name=claim, namespace="default"),
            storage_class_name="sc",
            requests={"storage": parse_quantity("1Gi")},
            volume_name=pv,
            phase="Bound",
        ))

    @staticmethod
    def _csi_node(store, node_name, driver, limit):
        from kubernetes_tpu.api.types import (
            CSINode, CSINodeDriver, ObjectMeta,
        )

        store.add_csi_node(CSINode(
            metadata=ObjectMeta(name=node_name),
            drivers=[CSINodeDriver(name=driver, node_id=node_name,
                                   allocatable_count=limit)],
        ))

    def test_bound_pvc_pods_stay_on_batch_path(self):
        """No serial fallback for bound claims — the whole point of the
        round-3 change (SchedulingCSIPVs at 42 pods/s was the one family
        the Go reference beat)."""
        store = ClusterStore()
        for i in range(4):
            store.add_node(MakeNode().name(f"n{i}")
                           .capacity({"cpu": "8", "memory": "16Gi"}).obj())
            self._csi_node(store, f"n{i}", "csi.x", 39)
        for i in range(12):
            self._bound_pair(store, f"c{i}", f"pv{i}", driver="csi.x")
        sched, bs = make_batch_scheduler(store)
        serial = []
        orig = sched.schedule_pod_serial
        sched.schedule_pod_serial = (
            lambda fwk, qpi: (serial.append(qpi), orig(fwk, qpi))[1]
        )
        for i in range(12):
            store.create_pod(
                MakePod().name(f"p{i}").req({"cpu": "1"}).pvc(f"c{i}").obj()
            )
        drain_batches(sched, bs)
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 12
        assert not serial, (
            f"{len(serial)} bound-PVC pods fell back to the serial path"
        )
        sched.stop()

    def test_pv_zone_mask_constrains_placement(self):
        store = ClusterStore()
        for i, zone in enumerate(["z0", "z0", "z1"]):
            store.add_node(MakeNode().name(f"n{i}")
                           .label("topology.kubernetes.io/zone", zone)
                           .capacity({"cpu": "8", "memory": "16Gi"}).obj())
        for i in range(4):
            self._bound_pair(store, f"c{i}", f"pv{i}", zone="z1")
        sched, bs = make_batch_scheduler(store)
        for i in range(4):
            store.create_pod(
                MakePod().name(f"p{i}").req({"cpu": "1"}).pvc(f"c{i}").obj()
            )
        drain_batches(sched, bs)
        for i in range(4):
            assert store.get_pod("default", f"p{i}").spec.node_name == "n2"
        sched.stop()

    def test_pv_node_affinity_mask(self):
        from kubernetes_tpu.api.types import (
            NodeSelector, NodeSelectorRequirement, NodeSelectorTerm,
        )

        store = ClusterStore()
        for i in range(3):
            store.add_node(MakeNode().name(f"n{i}").label("disk", f"d{i}")
                           .capacity({"cpu": "8", "memory": "16Gi"}).obj())
        aff = NodeSelector(node_selector_terms=[NodeSelectorTerm(
            match_expressions=[NodeSelectorRequirement(
                key="disk", operator="In", values=["d1"])],
        )])
        self._bound_pair(store, "c0", "pv0", affinity=aff)
        sched, bs = make_batch_scheduler(store)
        store.create_pod(
            MakePod().name("p0").req({"cpu": "1"}).pvc("c0").obj()
        )
        drain_batches(sched, bs)
        assert store.get_pod("default", "p0").spec.node_name == "n1"
        sched.stop()

    def test_csi_attach_limits_enforced_in_batch(self):
        """One batch of 5 attach pods against 2 nodes × limit 2: exactly
        4 bind — the in-batch carry must decrement attach budgets pod by
        pod, not just check the pre-batch counts."""
        store = ClusterStore()
        for i in range(2):
            store.add_node(MakeNode().name(f"n{i}")
                           .capacity({"cpu": "64", "memory": "64Gi"}).obj())
            self._csi_node(store, f"n{i}", "csi.x", 2)
        for i in range(5):
            self._bound_pair(store, f"c{i}", f"pv{i}", driver="csi.x")
        sched, bs = make_batch_scheduler(store)
        for i in range(5):
            store.create_pod(
                MakePod().name(f"p{i}").req({"cpu": "1"}).pvc(f"c{i}").obj()
            )
        drain_batches(sched, bs)
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 4, f"bound {len(bound)} of 5 (limits 2×2)"
        per_node = {}
        for p in bound:
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(c <= 2 for c in per_node.values()), per_node
        sched.stop()

    def test_shared_volume_rides_serial_path(self):
        """Two pods sharing one bound RWO claim (legal: RWO is per-node,
        not per-pod): the additive attach-column model would double-count
        the share, so the SECOND user must fall back to the serial path
        (csi.go counts len(in_use | wanted) — set semantics)."""
        store = ClusterStore()
        store.add_node(MakeNode().name("n0")
                       .capacity({"cpu": "64", "memory": "64Gi"}).obj())
        self._csi_node(store, "n0", "csi.x", 1)
        self._bound_pair(store, "c0", "pv0", driver="csi.x")
        sched, bs = make_batch_scheduler(store)
        serial = []
        orig = sched.schedule_pod_serial
        sched.schedule_pod_serial = (
            lambda fwk, qpi: (serial.append(qpi.pod.metadata.name),
                              orig(fwk, qpi))[1]
        )
        for i in range(2):
            store.create_pod(
                MakePod().name(f"p{i}").req({"cpu": "1"}).pvc("c0").obj()
            )
        drain_batches(sched, bs)
        bound = [p for p in store.list_pods() if p.spec.node_name]
        # host semantics: the shared volume counts ONCE -> both pods fit
        # on n0 despite the limit of 1
        assert len(bound) == 2, [p.metadata.name for p in bound]
        assert serial, "second share user should have taken the serial path"
        sched.stop()

    def test_host_only_contract(self):
        """is_host_only: bound RWO claims are expressible; unbound,
        shared-access, dangling-PV, and inline cloud-disk volumes are
        not."""
        from kubernetes_tpu.api.resource import parse_quantity
        from kubernetes_tpu.api.types import (
            ObjectMeta, PersistentVolumeClaim, Volume,
        )
        from kubernetes_tpu.ops.encode import is_host_only

        store = ClusterStore()
        self._bound_pair(store, "bound", "pv-b")
        store.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name="unbound", namespace="default"),
            storage_class_name="sc",
            requests={"storage": parse_quantity("1Gi")},
        ))
        store.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name="shared", namespace="default"),
            access_modes=["ReadWriteMany"],
            volume_name="pv-b",
            phase="Bound",
        ))

        def pod(claim=None, inline=None):
            p = MakePod().name("x").req({"cpu": "1"})
            if claim:
                p = p.pvc(claim)
            obj = p.obj()
            if inline:
                obj.spec.volumes.append(inline)
            return obj

        assert not is_host_only(pod("bound"), store)
        assert is_host_only(pod("bound"))            # no client → conservative
        assert is_host_only(pod("unbound"), store)
        # a shared claim on a non-CSI PV consumes no attach budget:
        # expressible (static PV affinity masks only)
        assert not is_host_only(pod("shared"), store)
        # a CSI-attached shared claim batches via the per-volume attach
        # planes (round 5; csi.go set semantics carried in solver
        # state) — but only ONE plane reference per pod per step, so a
        # pod with TWO shared CSI volumes keeps the host path
        self._bound_pair(store, "shared-csi", "pv-csi", driver="csi.x")
        store.get_pvc("default", "shared-csi").access_modes = [
            "ReadWriteMany"]
        assert not is_host_only(pod("shared-csi"), store)
        self._bound_pair(store, "shared-csi2", "pv-csi2", driver="csi.x")
        store.get_pvc("default", "shared-csi2").access_modes = [
            "ReadWriteMany"]
        double = pod("shared-csi")
        double.spec.volumes.append(
            Volume(name="d2", persistent_volume_claim="shared-csi2"))
        assert is_host_only(double, store)
        assert is_host_only(pod("missing"), store)
        assert is_host_only(
            pod(inline=Volume(name="d", gce_persistent_disk="pd-1")), store
        )


    def test_wfc_claims_batch_with_commit_time_binding(self):
        """Node-independent WaitForFirstConsumer claims ride the BATCH
        path; the sidecar pops a real PV per claim at commit (the
        Reserve/PreBind moment). Pool depletion without a provisioner
        routes the overflow pods to the serial path for their real
        unschedulable status."""
        from kubernetes_tpu.api.resource import parse_quantity
        from kubernetes_tpu.api.types import (
            ObjectMeta, PersistentVolume, PersistentVolumeClaim,
            StorageClass,
        )
        from kubernetes_tpu.ops.encode import is_host_only

        store = ClusterStore()
        for i in range(3):
            store.add_node(MakeNode().name(f"n{i}")
                           .capacity({"cpu": "16", "memory": "32Gi"}).obj())
        # provisioner-less WFC class with a 4-PV affinity-free pool
        store.add_storage_class(StorageClass(
            metadata=ObjectMeta(name="wfc-sc"), provisioner="",
            volume_binding_mode="WaitForFirstConsumer",
        ))
        for i in range(4):
            store.add_pv(PersistentVolume(
                metadata=ObjectMeta(name=f"wfc-pv-{i}"),
                capacity={"storage": parse_quantity("1Gi")},
                storage_class_name="wfc-sc",
                phase="Available",
            ))
        pods = []
        for i in range(6):
            store.add_pvc(PersistentVolumeClaim(
                metadata=ObjectMeta(name=f"wfc-c{i}", namespace="default"),
                storage_class_name="wfc-sc",
                requests={"storage": parse_quantity("1Gi")},
            ))
            p = MakePod().name(f"wp{i}").uid(f"wpu{i}") \
                .req({"cpu": "100m"}).pvc(f"wfc-c{i}").obj()
            pods.append(p)
        # expressible on the batch path
        assert not is_host_only(pods[0], store)
        sched, bs = make_batch_scheduler(store)
        try:
            for p in pods:
                store.create_pod(p)
            drain_batches(sched, bs)
            bound = [p for p in store.list_pods() if p.spec.node_name]
            assert len(bound) == 4, "pool of 4 PVs binds exactly 4 pods"
            # every scheduled pod's claim got a REAL PV at commit
            for p in bound:
                pvc = store.get_pvc("default",
                                    p.spec.volumes[0].persistent_volume_claim)
                assert pvc.volume_name, "claim left unbound after commit"
                assert store.get_pv(pvc.volume_name).claim_ref == \
                    f"default/{pvc.name}"
            # the two overflow pods took the serial path and pend with
            # the real bind-conflict status
            pending = [p for p in store.list_pods() if not p.spec.node_name]
            assert len(pending) == 2
        finally:
            sched.stop()

    def test_wfc_with_node_affinity_stays_serial(self):
        """A WFC pool containing ANY node-affine PV is node-dependent:
        the per-node match machinery is required, so the claim stays on
        the serial path."""
        from kubernetes_tpu.api.resource import parse_quantity
        from kubernetes_tpu.api.types import (
            NodeSelector, NodeSelectorRequirement, NodeSelectorTerm,
            ObjectMeta, PersistentVolume, PersistentVolumeClaim,
            StorageClass,
        )
        from kubernetes_tpu.ops.encode import is_host_only

        store = ClusterStore()
        store.add_storage_class(StorageClass(
            metadata=ObjectMeta(name="zonal-sc"), provisioner="",
            volume_binding_mode="WaitForFirstConsumer",
        ))
        affinity = NodeSelector(node_selector_terms=[NodeSelectorTerm(
            match_expressions=[NodeSelectorRequirement(
                key="zone", operator="In", values=["z1"])],
        )])
        store.add_pv(PersistentVolume(
            metadata=ObjectMeta(name="zonal-pv"),
            capacity={"storage": parse_quantity("1Gi")},
            storage_class_name="zonal-sc",
            phase="Available",
            node_affinity=affinity,
        ))
        store.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name="zonal-c", namespace="default"),
            storage_class_name="zonal-sc",
            requests={"storage": parse_quantity("1Gi")},
        ))
        pod = MakePod().name("zp").uid("zpu").pvc("zonal-c").obj()
        assert is_host_only(pod, store)


class TestBatchPreemption:
    """The mass-decline victim planner (VERDICT r2 #3): semantics it
    must share with the serial PostFilter path."""

    @staticmethod
    def _full_cluster(store, nodes=3):
        for i in range(nodes):
            store.add_node(MakeNode().name(f"n{i}")
                           .capacity({"cpu": "4", "memory": "8Gi"}).obj())
        fillers = [
            MakePod().name(f"low{i}").uid(f"lu{i}").priority(0)
            .req({"cpu": "3"}).obj()
            for i in range(nodes)
        ]
        return fillers

    def test_preemption_policy_never_is_respected(self):
        """A mass-decline batch of preemptionPolicy=Never pods must not
        evict anyone (PodEligibleToPreemptOthers,
        default_preemption.go:246) — shared gate with the serial path."""
        store = ClusterStore()
        fillers = self._full_cluster(store, nodes=3)
        sched, bs = make_batch_scheduler(store)
        # force the mass-decline branch for small batches
        bs.DECLINED_SERIAL_LIMIT = 0
        store.create_pods(fillers)
        drain_batches(sched, bs)
        assert all(p.spec.node_name for p in store.list_pods())
        never = []
        for i in range(40):
            p = MakePod().name(f"hi{i}").uid(f"hu{i}").priority(100) \
                .req({"cpu": "3"}).obj()
            p.spec.preemption_policy = "Never"
            never.append(p)
        store.create_pods(never)
        drain_batches(sched, bs)
        # no filler was evicted; no Never pod bound
        assert sum(1 for p in store.list_pods()
                   if p.metadata.name.startswith("low")) == 3
        assert not any(
            p.spec.node_name for p in store.list_pods()
            if p.metadata.name.startswith("hi")
        )
        sched.stop()

    def test_planner_never_proposes_pdb_covered_victims(self):
        """One planned batch must not burn a PodDisruptionBudget: any
        PDB-COVERED pod is excluded from planning outright (the exact
        dry-run path owns violation counting)."""
        from kubernetes_tpu.api.labels import LabelSelector
        from kubernetes_tpu.api.types import ObjectMeta, PodDisruptionBudget
        from kubernetes_tpu.scheduler.preemption_screen import (
            build_victim_planner,
        )
        from kubernetes_tpu.scheduler.snapshot import Snapshot
        from kubernetes_tpu.scheduler.types import NodeInfo

        node = MakeNode().name("n0").capacity(
            {"cpu": "4", "memory": "8Gi"}).obj()
        ni = NodeInfo()
        ni.set_node(node)
        protected = MakePod().name("guard").uid("gu").priority(0) \
            .label("app", "guarded").req({"cpu": "3"}).obj()
        ni.add_pod(protected)
        pdb = PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb", namespace="default"),
            label_selector=LabelSelector(match_labels={"app": "guarded"}),
        )
        # budget LEFT — coverage alone excludes
        pdb.status.disruptions_allowed = 5

        class Snap:
            def list(self):
                return [ni]

        planner = build_victim_planner(Snap(), pdbs=[pdb])
        preemptor = MakePod().name("hi").uid("hu").priority(100) \
            .req({"cpu": "3"}).obj()
        assert planner.plan_group(preemptor, 1) == []


class TestWarmup:
    def test_warmup_without_samples_compiles(self, caplog):
        """warmup() with no sample pods must encode+solve cleanly (not
        swallow an exception and silently leave the solver cold)."""
        store = ClusterStore()
        store.add_node(
            MakeNode().name("n0").capacity({"cpu": "8", "memory": "16Gi"}).obj()
        )
        sched, bs = make_batch_scheduler(store)
        import logging

        with caplog.at_level(logging.ERROR, logger="kubernetes_tpu.sidecar"):
            spent = bs.warmup()
        assert spent > 0.0
        assert "warmup failed" not in caplog.text
        sched.stop()

    def test_warmup_with_workload_samples(self):
        store = ClusterStore()
        for i in range(4):
            store.add_node(
                MakeNode().name(f"n{i}")
                .label("topology.kubernetes.io/zone", f"z{i % 2}")
                .capacity({"cpu": "8", "memory": "16Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store)
        sample = (
            MakePod().name("tmpl").uid("tmpl-u").label("app", "w")
            .req({"cpu": "1"})
            .spread_constraint(
                1, "topology.kubernetes.io/zone", "DoNotSchedule", {"app": "w"}
            ).obj()
        )
        assert bs.warmup(sample_pods=[sample]) > 0.0
        sched.stop()
