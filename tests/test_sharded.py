"""Sharded-solver tests on the 8-device virtual CPU mesh: results must
match the single-device scan solver exactly."""

import numpy as np
import pytest

import jax

from kubernetes_tpu.ops import BatchEncoder, solve_scan
from kubernetes_tpu.parallel import make_mesh, solve_scan_sharded
from kubernetes_tpu.scheduler.snapshot import new_snapshot
from kubernetes_tpu.testing import MakeNode, MakePod

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual CPU mesh"
)


def encode(nodes, existing, pods):
    snap = new_snapshot(existing, nodes)
    enc = BatchEncoder(snap, pad_nodes=128)
    return enc.encode(pods)


class TestShardedMatchesSingle:
    def test_basic_fit(self):
        nodes = [
            MakeNode().name(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"}).obj()
            for i in range(20)
        ]
        pods = [
            MakePod().name(f"p{i}").uid(f"pu{i}").req({"cpu": "2"}).obj()
            for i in range(30)
        ]
        cluster, batch = encode(nodes, [], pods)
        single = solve_scan(cluster, batch)
        mesh = make_mesh(8, batch_axis=2)
        sharded, feasible_counts = solve_scan_sharded(cluster, batch, mesh)
        np.testing.assert_array_equal(single, sharded)
        # every real pod saw at least one statically feasible node
        assert all(feasible_counts[: len(pods)] > 0)

    def test_spread_and_affinity(self):
        nodes = [
            MakeNode().name(f"n{i}")
            .label("topology.kubernetes.io/zone", f"z{i % 4}")
            .capacity({"cpu": "16", "memory": "32Gi"}).obj()
            for i in range(16)
        ]
        pods = []
        for i in range(24):
            w = (
                MakePod().name(f"p{i}").uid(f"pu{i}").label("app", "w")
                .req({"cpu": "1"})
            )
            if i % 3 == 0:
                w.spread_constraint(
                    1, "topology.kubernetes.io/zone", "DoNotSchedule",
                    {"app": "w"},
                )
            elif i % 3 == 1:
                w.pod_anti_affinity("app", ["w"], "kubernetes.io/hostname")
            pods.append(w.obj())
        cluster, batch = encode(nodes, [], pods)
        single = solve_scan(cluster, batch)
        mesh = make_mesh(8, batch_axis=1)
        sharded, _ = solve_scan_sharded(cluster, batch, mesh)
        np.testing.assert_array_equal(single, sharded)
