"""Chaos ring (VERDICT r3 #10; reference
``test/e2e/chaosmonkey/chaosmonkey.go:35``): randomized component kills
MID-WORKLOAD — the scheduler leader, the controller manager, and
finally the whole control plane over the WAL-backed store — with
invariant checks after quiescence:

- **no lost pods**: every pod created (directly or via ReplicaSet)
  exists and is bound;
- **no double-bind / oversubscription**: every bound pod's node exists,
  and per-node summed cpu requests stay within allocatable — the
  invariant two racing schedulers would break;
- **durability**: a WAL restore after the full-control-plane crash
  reproduces the live pod->node assignment exactly.

Each seed drives a different interleaving of kills and pod arrivals;
the suite runs 5 seeds (the reference's chaosmonkey runs its Tests
concurrently with the disruption; here the workload stream plays that
role).
"""

import random
import time

import pytest

from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.apiserver.wal import attach_wal, restore_store
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod

NODES = 20
NODE_CPU = 16          # cores per node
POD_CPU_MILLI = 500    # per pod -> 32 pods/node, 640 cluster capacity
TOTAL_PODS = 120


class _Ring:
    """One chaos run's moving parts."""

    def __init__(self, tmp_path, seed: int):
        self.rng = random.Random(seed)
        self.dir = str(tmp_path)
        self.store = ClusterStore()
        self.wal = attach_wal(self.store, self.dir)
        for i in range(NODES):
            self.store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": str(NODE_CPU), "memory": "64Gi",
                           "pods": "110"}).obj()
            )
        self.scheds = []
        self.electors = []
        self._sched_seq = 0
        self.cm = None
        self.start_controllers()
        self.add_scheduler()
        self.add_scheduler()

    # -- components ----------------------------------------------------
    def add_scheduler(self) -> None:
        s = Scheduler.create(self.store)
        e = s.run_with_leader_election(
            identity=f"sched-{self._sched_seq}",
            lease_duration=0.6, renew_deadline=0.45, retry_period=0.05,
        )
        self._sched_seq += 1
        self.scheds.append(s)
        self.electors.append(e)

    def kill_leader(self) -> None:
        """Stop whichever instance currently holds the lease and spawn
        a replacement (the chaosmonkey 'kill the active master')."""
        for i, e in enumerate(self.electors):
            if e.is_leader:
                self.scheds.pop(i).stop()
                self.electors.pop(i)
                self.add_scheduler()
                return
        # no leader this instant (mid-failover): kill any instance
        if self.scheds:
            self.scheds.pop(0).stop()
            self.electors.pop(0)
            self.add_scheduler()

    def start_controllers(self) -> None:
        self.cm = ControllerManager(
            self.store, controllers=["replicaset", "podgc"]
        )
        self.cm.start()

    def restart_controllers(self) -> None:
        self.cm.stop()
        self.start_controllers()

    def stop_all(self) -> None:
        for s in self.scheds:
            s.stop()
        self.scheds = []
        self.electors = []
        if self.cm is not None:
            self.cm.stop()
            self.cm = None

    # -- workload ------------------------------------------------------
    def create_pods(self, start: int, count: int) -> None:
        for i in range(start, start + count):
            self.store.create_pod(
                MakePod().name(f"w{i}").uid(f"wu{i}")
                .req({"cpu": f"{POD_CPU_MILLI}m"}).obj()
            )

    def wait_all_bound(self, expect: int, timeout: float = 60.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            pods = self.store.list_pods()
            if len(pods) >= expect and all(
                    p.spec.node_name for p in pods):
                return
            time.sleep(0.05)
        pods = self.store.list_pods()
        unbound = [p.metadata.name for p in pods if not p.spec.node_name]
        raise AssertionError(
            f"{len(pods)}/{expect} pods, unbound after chaos: "
            f"{unbound[:10]}"
        )


def _check_invariants(store: ClusterStore) -> None:
    nodes = {n.name: n for n in store.list_nodes()}
    used: dict = {}
    for p in store.list_pods():
        assert p.spec.node_name, f"pod {p.metadata.name} lost its binding"
        assert p.spec.node_name in nodes, (
            f"pod {p.metadata.name} bound to missing node "
            f"{p.spec.node_name!r}"
        )
        used[p.spec.node_name] = used.get(p.spec.node_name, 0) + sum(
            int(c.resources.requests["cpu"].milli_value())
            for c in p.spec.containers if "cpu" in c.resources.requests
        )
    for name, milli in used.items():
        alloc = int(nodes[name].status.allocatable["cpu"].milli_value())
        assert milli <= alloc, (
            f"node {name} oversubscribed: {milli}m > {alloc}m — "
            f"a double-bind slipped through the chaos"
        )


@pytest.mark.parametrize("seed", [11, 23, 37, 41, 53])
def test_chaos_ring_survives_component_kills(tmp_path, seed):
    ring = _Ring(tmp_path, seed)
    try:
        created = 0
        chunks = 6
        per_chunk = TOTAL_PODS // chunks
        for c in range(chunks):
            ring.create_pods(created, per_chunk)
            created += per_chunk
            # a random kill lands between every arrival wave
            action = ring.rng.choice(
                ["kill_leader", "restart_controllers", "none"]
            )
            if action == "kill_leader":
                ring.kill_leader()
            elif action == "restart_controllers":
                ring.restart_controllers()
            time.sleep(ring.rng.uniform(0.0, 0.15))
        ring.wait_all_bound(expect=created)
        _check_invariants(ring.store)

        # finale: the whole control plane dies over the WAL-backed
        # store; the restored world must equal the live one
        live = {
            p.uid: p.spec.node_name for p in ring.store.list_pods()
        }
        ring.stop_all()
        ring.wal.close()
        restored = restore_store(ring.dir)
        got = {p.uid: p.spec.node_name for p in restored.list_pods()}
        assert got == live, "WAL restore diverged from the live store"
        _check_invariants(restored)

        # the restored store schedules NEW work (recovery is not
        # read-only): fresh control plane, fresh pods
        sched = Scheduler.create(restored)
        sched.run()
        try:
            for i in range(8):
                restored.create_pod(
                    MakePod().name(f"post-{i}").uid(f"pu{i}")
                    .req({"cpu": "250m"}).obj()
                )
            deadline = time.time() + 20
            while time.time() < deadline and any(
                not p.spec.node_name for p in restored.list_pods()
            ):
                time.sleep(0.05)
            assert all(p.spec.node_name for p in restored.list_pods())
        finally:
            sched.stop()
    finally:
        ring.stop_all()
        try:
            ring.wal.close()
        except Exception:  # noqa: BLE001 — already closed in the happy path
            pass
