"""Cluster autoscaler: node groups, solver-simulated scale-up,
drain-based scale-down (ISSUE 4).

Layers under test, bottom-up: the group/provisioner surface, the
virtual-column what-if solve (batched vs the per-pod serial oracle —
the differential acceptance bar), the expander strategies, the
reconcile loop (trigger → cooldown → max-size caps), the PDB-respecting
drain pipeline, and the end-to-end elastic story (burst beyond
capacity → scale up → all bind → idle → drain back toward min with
zero lost pods). Satellites: ClusterAutoscalerProvider actually scoring
with MostAllocated, the shared pending-burst generator, the HPA →
autoscaler hand-off, and the churn-integration run (slow marker).
"""

import time

import pytest

from kubernetes_tpu.api.labels import LabelSelector
from kubernetes_tpu.api.types import (
    PodCondition,
    PodDisruptionBudget,
    SUCCEEDED,
    shallow_copy,
)
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.autoscaler import (
    ClusterAutoscaler,
    NODE_GROUP_LABEL,
    NodeGroup,
    NodeGroupRegistry,
    SAFE_TO_EVICT_ANNOTATION,
    SimulatedProvisioner,
    plan_scale_up,
    pods_fit_elsewhere,
)
from kubernetes_tpu.client.informers import SharedInformerFactory
from kubernetes_tpu.harness.burst import make_burst_pods, run_pending_burst
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod

ZONE = "topology.kubernetes.io/zone"


def _wait(cond, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            raise AssertionError(f"timeout waiting for {msg}")
        time.sleep(0.02)


def _full_node(store, name, cpu="4", used="3800m", uid=None):
    """A node plus a bound pod leaving no meaningful headroom."""
    store.add_node(MakeNode().name(name)
                   .capacity({"cpu": cpu, "memory": "8Gi"}).obj())
    store.create_pod(
        MakePod().name(f"filler-{name}").uid(uid or f"fu-{name}")
        .req({"cpu": used}).node(name).obj())


def _mark_unschedulable(store, pod):
    store.patch_pod_condition(
        pod.namespace, pod.metadata.name,
        PodCondition("PodScheduled", "False", "Unschedulable", "test"))


def _mk_ca(store, registry, **knobs):
    ca = ClusterAutoscaler(store, SharedInformerFactory(store),
                           registry=registry)
    for k, v in knobs.items():
        setattr(ca, k, v)
    return ca


# ---------------------------------------------------------------------------
# node groups + provisioner


class TestNodeGroups:
    def test_template_carries_identity_capacity_and_taints(self):
        from kubernetes_tpu.api.types import Taint

        g = NodeGroup("ng-a", cpu="8", memory="16Gi",
                      labels={ZONE: "z-a"},
                      taints=[Taint("dedicated", "batch", "NoSchedule")],
                      min_size=1, max_size=4)
        node = g.node_template(3)
        assert node.name == "ng-a-3"
        assert node.metadata.labels[NODE_GROUP_LABEL] == "ng-a"
        assert node.metadata.labels["kubernetes.io/hostname"] == "ng-a-3"
        assert node.metadata.labels[ZONE] == "z-a"
        assert int(node.status.allocatable["cpu"].milli_value()) == 8000
        assert node.spec.taints[0].key == "dedicated"
        reg = NodeGroupRegistry([g])
        assert reg.get("ng-a") is g
        assert reg.group_of(node) == "ng-a"
        assert reg.group_of(MakeNode().name("plain").obj()) is None

    def test_provisioner_creates_real_nodes_after_boot_latency(self):
        store = ClusterStore()
        reg = NodeGroupRegistry()
        g = reg.add(NodeGroup("ng-b", cpu="2", boot_latency=0.15))
        prov = SimulatedProvisioner(store, reg)
        prov.start()
        try:
            names = prov.provision(g, 2)
            assert prov.group_size("ng-b") == 2      # booting counts
            assert prov.live_count("ng-b") == 0
            assert len(prov.booting_templates("ng-b")) == 2
            _wait(lambda: prov.live_count("ng-b") == 2,
                  msg="nodes registered after boot latency")
            got = {n.name for n in store.list_nodes()}
            assert set(names) <= got
            prov.deprovision(names[0])
            assert prov.live_count("ng-b") == 1
        finally:
            prov.stop()

    def test_provisioner_skips_existing_static_indices(self):
        store = ClusterStore()
        reg = NodeGroupRegistry()
        g = reg.add(NodeGroup("ng-c", cpu="2"))
        store.add_node(g.node_template(5))   # static member, index 5
        prov = SimulatedProvisioner(store, reg)
        names = prov.provision(g, 2)         # boot 0: synchronous
        assert names == ["ng-c-6", "ng-c-7"]
        assert prov.group_size("ng-c") == 3


# ---------------------------------------------------------------------------
# the what-if solve (virtual columns)


class TestWhatIf:
    def _pending(self, n, cpu="500m"):
        return [MakePod().name(f"p{i}").uid(f"pu{i}")
                .req({"cpu": cpu, "memory": "500Mi"}).obj()
                for i in range(n)]

    def test_prefers_existing_capacity_no_scale_up(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n0")
                       .capacity({"cpu": "8", "memory": "16Gi"}).obj())
        g = NodeGroup("ng", cpu="8")
        plan = plan_scale_up(store.list_nodes(), [], self._pending(4),
                             [(g, 8)])
        assert plan.solves == 1
        assert plan.chosen is None          # pods fit the real node

    def test_counts_needed_nodes_by_bin_packing(self):
        store = ClusterStore()
        _full_node(store, "n0")
        g = NodeGroup("ng", cpu="2", memory="4Gi")
        plan = plan_scale_up(
            store.list_nodes(),
            [p for p in store.list_pods()], self._pending(10), [(g, 20)])
        # 10 x 500m onto 2-cpu nodes: 4+4+2 -> 3 nodes, not 10
        assert plan.chosen is not None
        assert plan.chosen.nodes_needed == 3
        assert plan.chosen.pods_on_new == 10

    def test_headroom_caps_virtual_columns(self):
        store = ClusterStore()
        _full_node(store, "n0")
        g = NodeGroup("ng", cpu="2", max_size=1)
        plan = plan_scale_up(store.list_nodes(),
                             [p for p in store.list_pods()],
                             self._pending(10), [(g, 1)])
        assert plan.chosen.nodes_needed == 1   # only 1 column offered
        assert plan.chosen.pods_on_new == 4    # 4 x 500m fit 2 cpu

    def test_respects_template_constraints(self):
        """A group whose template a pod's nodeSelector rejects gets no
        placements — template taints/labels flow through the same host
        plugin code as real nodes."""
        store = ClusterStore()
        _full_node(store, "n0")
        pending = [MakePod().name(f"z{i}").uid(f"zu{i}")
                   .req({"cpu": "500m"})
                   .node_selector({ZONE: "z-a"}).obj() for i in range(4)]
        g_a = NodeGroup("ng-za", cpu="4", labels={ZONE: "z-a"})
        g_b = NodeGroup("ng-zb", cpu="4", labels={ZONE: "z-b"})
        plan = plan_scale_up(store.list_nodes(),
                             [p for p in store.list_pods()],
                             pending, [(g_a, 4), (g_b, 4)])
        assert plan.chosen.group == "ng-za"
        assert [o.group for o in plan.options] == ["ng-za"]

    def test_expanders_least_waste_vs_priority(self):
        store = ClusterStore()
        _full_node(store, "n0")
        bound = [p for p in store.list_pods()]
        pending = self._pending(10)
        g_small = NodeGroup("ng-small", cpu="2", memory="4Gi", priority=0)
        g_big = NodeGroup("ng-big", cpu="16", memory="32Gi", priority=9)
        groups = [(g_small, 20), (g_big, 20)]
        lw = plan_scale_up(store.list_nodes(), bound, pending, groups,
                           expander="least-waste")
        pr = plan_scale_up(store.list_nodes(), bound, pending, groups,
                           expander="priority")
        assert lw.chosen.group == "ng-small"   # tighter fit
        assert pr.chosen.group == "ng-big"     # higher priority
        assert pr.chosen.nodes_needed == 1

    def test_upcoming_nodes_prevent_double_buy(self):
        """Capacity already booting absorbs pending demand: the what-if
        must not re-buy nodes the provisioner is still spinning up."""
        store = ClusterStore()
        _full_node(store, "n0")
        g = NodeGroup("ng", cpu="8", memory="16Gi")
        upcoming = [g.node_template("boot-0")]
        plan = plan_scale_up(store.list_nodes(),
                             [p for p in store.list_pods()],
                             self._pending(8), [(g, 20)],
                             upcoming=upcoming)
        assert plan.chosen is None   # all 8 x 500m ride the upcoming node

    def test_fit_elsewhere_disabled_column(self):
        store = ClusterStore()
        for name in ("m0", "m1"):
            store.add_node(MakeNode().name(name)
                           .capacity({"cpu": "4", "memory": "8Gi"}).obj())
        pods_m0 = [MakePod().name(f"d{i}").uid(f"du{i}")
                   .req({"cpu": "1"}).node("m0").obj() for i in range(2)]
        for p in pods_m0:
            store.create_pod(p)
        assert pods_fit_elsewhere(store.list_nodes(), store.list_pods(),
                                  "m0", pods_m0)
        # fill m1: now m0's pods have nowhere to go
        store.create_pod(MakePod().name("big").uid("bigu")
                         .req({"cpu": "3800m"}).node("m1").obj())
        assert not pods_fit_elsewhere(
            store.list_nodes(), store.list_pods(), "m0", pods_m0)


# ---------------------------------------------------------------------------
# differential: batched virtual-column solve vs serial per-pod oracle


class TestDifferential:
    @pytest.mark.parametrize("seed", [7, 21, 42, 1337])
    def test_batched_agrees_with_serial_oracle(self, seed):
        """Acceptance bar: on randomized clusters/bursts the batched
        estimator and a per-pod serial simulation must choose the same
        group and node count, under both expanders."""
        import random

        rng = random.Random(seed)
        nodes = []
        bound = []
        for i in range(rng.randint(6, 10)):
            cpu = rng.choice([2, 4, 8])
            nodes.append(
                MakeNode().name(f"rn{i}")
                .label(ZONE, f"z{i % 2}")
                .capacity({"cpu": str(cpu), "memory": "16Gi"}).obj())
            # fill 60-100% of each node
            fill = int(cpu * 1000 * rng.uniform(0.6, 1.0))
            bound.append(
                MakePod().name(f"rf{i}").uid(f"rfu{i}")
                .req({"cpu": f"{fill}m"}).node(f"rn{i}").obj())
        pending = []
        for i in range(rng.randint(10, 22)):
            w = MakePod().name(f"rp{i}").uid(f"rpu{i}").req(
                {"cpu": f"{rng.choice([250, 500, 1000])}m",
                 "memory": "256Mi"})
            if rng.random() < 0.3:
                w.node_selector({ZONE: f"z{rng.randint(0, 1)}"})
            pending.append(w.obj())
        groups = []
        for j, cpu in enumerate(rng.sample([2, 4, 8, 16], k=2)):
            groups.append((NodeGroup(
                f"rg{j}", cpu=str(cpu), memory="16Gi",
                labels={ZONE: f"z{j % 2}"},
                priority=rng.randint(0, 5)), 16))
        for expander in ("least-waste", "priority"):
            batched = plan_scale_up(nodes, bound, pending, groups,
                                    expander=expander)
            serial = plan_scale_up(nodes, bound, pending, groups,
                                   expander=expander, serial=True)
            if batched.chosen is None:
                assert serial.chosen is None, (expander, serial.chosen)
            else:
                assert serial.chosen is not None, (expander, batched.chosen)
                assert batched.chosen.group == serial.chosen.group
                assert batched.chosen.nodes_needed == \
                    serial.chosen.nodes_needed
                assert batched.chosen.pods_on_new == \
                    serial.chosen.pods_on_new


# ---------------------------------------------------------------------------
# the control loop


class TestControlLoop:
    def test_scale_up_decision_is_batched_not_per_pod(self, monkeypatch):
        """The decision path issues ONE solve per candidate group —
        independent of pending-set size — through the virtual-column
        solver, never a per-pod loop."""
        from kubernetes_tpu.autoscaler import simulator as sim
        from kubernetes_tpu.ops import solver as solver_mod

        batched_calls = []
        serial_calls = []
        real = solver_mod.solve_whatif
        monkeypatch.setattr(
            sim, "solve_whatif",
            lambda *a, **kw: batched_calls.append(1) or real(*a, **kw))
        monkeypatch.setattr(
            sim, "_serial_whatif",
            lambda *a, **kw: serial_calls.append(1) or (_ for _ in ()).throw(
                AssertionError("serial oracle used on the decision path")))

        store = ClusterStore()
        _full_node(store, "n0")
        for i in range(40):
            pod = MakePod().name(f"q{i}").uid(f"qu{i}") \
                .req({"cpu": "500m"}).obj()
            store.create_pod(pod)
            _mark_unschedulable(store, pod)
        reg = NodeGroupRegistry([NodeGroup("ga", cpu="4", max_size=30),
                                 NodeGroup("gb", cpu="8", max_size=30)])
        ca = _mk_ca(store, reg, scale_up_cooldown=0.0)
        ca.reconcile_once()
        assert len(batched_calls) == 2      # one per group, not per pod
        assert not serial_calls
        assert ca.whatif_solves == 2
        assert ca.scale_up_events == 1

    def test_reconcile_scales_up_within_bounds_and_cooldown(self):
        store = ClusterStore()
        _full_node(store, "n0")
        pods = []
        for i in range(12):
            pod = MakePod().name(f"w{i}").uid(f"wu{i}") \
                .req({"cpu": "500m"}).obj()
            store.create_pod(pod)
            _mark_unschedulable(store, pod)
            pods.append(pod)
        reg = NodeGroupRegistry([NodeGroup("gc", cpu="2", max_size=2)])
        ca = _mk_ca(store, reg, scale_up_cooldown=30.0)
        ca.reconcile_once()
        # 12 x 500m want 3 nodes; max_size caps the group at 2
        assert ca.provisioner.group_size("gc") == 2
        assert ca.metrics.pending_unschedulable.get() == 12.0
        # cooldown: a second pass buys nothing even though pods pend
        ca.reconcile_once()
        assert ca.provisioner.group_size("gc") == 2
        # bind everything -> pending drains -> time-to-capacity observed
        before = ca.metrics.time_to_capacity_seconds.count()
        names = {n.name for n in store.list_nodes()}
        target = sorted(names - {"n0"})[0]
        for pod in pods:
            store.bind(pod.namespace, pod.metadata.name, pod.uid, target)
        ca.reconcile_once()
        assert ca.metrics.pending_unschedulable.get() == 0.0
        assert ca.metrics.time_to_capacity_seconds.count() == before + 1

    def test_queue_introspection_is_the_trigger(self):
        """With a scheduler queue attached, its unschedulableQ is the
        trigger surface (no store heuristics)."""
        from kubernetes_tpu.scheduler.queue import SchedulingQueue
        from kubernetes_tpu.scheduler.types import QueuedPodInfo

        q = SchedulingQueue()
        pod = MakePod().name("uq").uid("uqu").req({"cpu": "1"}).obj()
        q.add(pod)
        qpi = q.pop()
        q.add_unschedulable_if_not_present(qpi, q.scheduling_cycle)
        assert [p.metadata.name for p in q.unschedulable_pods()] == ["uq"]
        assert isinstance(qpi, QueuedPodInfo)

        store = ClusterStore()
        _full_node(store, "n0")
        reg = NodeGroupRegistry([NodeGroup("gq", cpu="2", max_size=4)])
        ca = _mk_ca(store, reg, scale_up_cooldown=0.0)
        ca.queue_introspect = q
        ca.reconcile_once()
        assert ca.provisioner.group_size("gq") == 1

    def test_scale_down_drains_with_pdb_and_deletes(self):
        """Cordon -> PDB-respecting eviction -> deletion: a PDB with
        zero budget blocks the drain; raising the budget releases it."""
        store = ClusterStore()
        reg = NodeGroupRegistry(
            [NodeGroup("gd", cpu="4", memory="8Gi", min_size=1,
                       max_size=5)])
        g = reg.get("gd")
        for i in range(3):
            store.add_node(g.node_template(i))
        # one small annotated pod on gd-0; gd-1 busy; gd-2 holds the
        # PDB-protected app pod's sibling so healthy count is 2
        low = MakePod().name("low").uid("lowu").label("app", "db") \
            .req({"cpu": "250m"}).node("gd-0").obj()
        low.metadata.annotations[SAFE_TO_EVICT_ANNOTATION] = "true"
        store.create_pod(low)
        sib = MakePod().name("sib").uid("sibu").label("app", "db") \
            .req({"cpu": "250m"}).node("gd-2").obj()
        sib.metadata.annotations[SAFE_TO_EVICT_ANNOTATION] = "true"
        store.create_pod(sib)
        store.create_pod(MakePod().name("busy").uid("busyu")
                         .req({"cpu": "3500m"}).node("gd-1").obj())
        pdb = PodDisruptionBudget(
            label_selector=LabelSelector(match_labels={"app": "db"}),
            min_available=2)
        pdb.metadata.name = "db-pdb"
        pdb.status.disruptions_allowed = 0     # blocked
        store.add_pdb(pdb)

        ca = _mk_ca(store, reg, scale_down_unneeded_time=0.0,
                    max_concurrent_drains=1,
                    scale_down_utilization_threshold=0.5)
        ca.reconcile_once()                     # picks ONE candidate
        assert len(ca._draining) == 1
        drained_name = next(iter(ca._draining))
        assert store.get_node(drained_name).spec.unschedulable
        if drained_name == "gd-0":
            # PDB budget 0: the pod survives every pass
            ca.reconcile_once()
            assert store.get_pod("default", "low") is not None
            # raise the budget (the disruption controller's job)
            upd = shallow_copy(pdb)
            upd.metadata = shallow_copy(pdb.metadata)
            upd.status = type(pdb.status)(disruptions_allowed=1,
                                          current_healthy=2,
                                          desired_healthy=2,
                                          expected_pods=2)
            store.update_object("PodDisruptionBudget", upd)
            ca.reconcile_once()                 # evicts
            assert store.get_pod("default", "low") is None
        _wait(lambda: (ca.reconcile_once(),
                       store.get_node(drained_name) is None)[1],
              timeout=5.0, msg="drained node deleted")
        assert ca.scale_down_events >= 1
        assert ca.metrics.scaledowns_total.get("gd") >= 1.0
        # busy and the min-size floor survive
        assert store.get_node("gd-1") is not None
        assert len(store.list_nodes()) >= 1

    def test_scale_down_refuses_unowned_unannotated_pods(self):
        store = ClusterStore()
        reg = NodeGroupRegistry([NodeGroup("ge", cpu="4", min_size=0,
                                           max_size=5)])
        g = reg.get("ge")
        for i in range(2):
            store.add_node(g.node_template(i))
        store.create_pod(MakePod().name("bare").uid("bareu")
                         .req({"cpu": "100m"}).node("ge-0").obj())
        ca = _mk_ca(store, reg, scale_down_unneeded_time=0.0)
        for _ in range(3):
            ca.reconcile_once()
        # ge-0 holds a bare pod nothing would recreate: never drained;
        # ge-1 is empty and goes
        assert store.get_node("ge-0") is not None
        assert store.get_pod("default", "bare") is not None
        _wait(lambda: (ca.reconcile_once(),
                       store.get_node("ge-1") is None)[1],
              timeout=5.0, msg="empty node deleted")

    def test_leader_election_single_brain(self):
        """Two autoscalers, one lease: only the leader provisions."""
        store = ClusterStore()
        _full_node(store, "n0")
        for i in range(2):
            pod = MakePod().name(f"le{i}").uid(f"leu{i}") \
                .req({"cpu": "500m"}).obj()
            store.create_pod(pod)
            _mark_unschedulable(store, pod)
        mk = lambda: _mk_ca(  # noqa: E731 — two identical instances
            store, NodeGroupRegistry([NodeGroup("gl", cpu="2",
                                                max_size=4)]),
            scale_up_cooldown=0.0, RESYNC_SECONDS=0.05,
            scale_down_enabled=False)
        ca1, ca2 = mk(), mk()
        try:
            ca1.run_with_leader_election(
                identity="ca-1", lease_duration=1.0,
                renew_deadline=0.6, retry_period=0.1)
            _wait(lambda: ca1.elector.is_leader, msg="ca-1 leads")
            ca2.run_with_leader_election(
                identity="ca-2", lease_duration=1.0,
                renew_deadline=0.6, retry_period=0.1)
            _wait(lambda: len(store.list_nodes()) == 2,
                  msg="leader provisions one node")
            time.sleep(0.6)   # a double-brain would buy more
            assert not ca2.elector.is_leader
            assert len(store.list_nodes()) == 2
        finally:
            ca1.stop()
            ca2.stop()


# ---------------------------------------------------------------------------
# satellites


class TestClusterAutoscalerProvider:
    def _run_one(self, provider):
        store = ClusterStore()
        for name in ("pa", "pb"):
            store.add_node(MakeNode().name(name)
                           .capacity({"cpu": "10", "memory": "10Gi"}).obj())
        # pa is 60% full; pb empty
        store.create_pod(MakePod().name("base").uid("baseu")
                         .req({"cpu": "6", "memory": "6Gi"})
                         .node("pa").obj())
        sched = Scheduler.create(store, provider=provider)
        try:
            sched.start()
            store.create_pod(MakePod().name("probe").uid("probeu")
                             .req({"cpu": "1", "memory": "1Gi"}).obj())
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                sched.queue.flush_backoff_completed()
                if not sched.schedule_one(pop_timeout=0.05):
                    pod = store.get_pod("default", "probe")
                    if pod is not None and pod.spec.node_name:
                        break
            assert sched.wait_for_inflight_bindings()
            return sched, store.get_pod("default", "probe").spec.node_name
        finally:
            sched.stop()

    def test_profile_swaps_least_for_most_allocated(self):
        store = ClusterStore()
        sched = Scheduler.create(store, provider="ClusterAutoscalerProvider")
        try:
            fwk = next(iter(sched.profiles.values()))
            score = fwk.list_plugins()["score"]
            assert "NodeResourcesMostAllocated" in score
            assert "NodeResourcesLeastAllocated" not in score
        finally:
            sched.stop()

    def test_bin_packs_vs_default_spreading(self):
        """The profile must CHANGE BEHAVIOR: MostAllocated packs onto
        the fuller node, the default LeastAllocated spreads away from
        it — same cluster, same pod."""
        _, packed = self._run_one("ClusterAutoscalerProvider")
        assert packed == "pa"
        _, spread = self._run_one("DefaultProvider")
        assert spread == "pb"


class TestBurstGenerator:
    def test_shapes_names_uids_annotations(self):
        pods = make_burst_pods(3, cpu_milli=250, name_prefix="bb-",
                               uid_prefix="bbu-", offset=5,
                               labels={"app": "bb"}, safe_to_evict=True)
        assert [p.metadata.name for p in pods] == ["bb-5", "bb-6", "bb-7"]
        assert pods[0].metadata.uid == "bbu-5"
        assert pods[0].metadata.labels["app"] == "bb"
        assert pods[0].metadata.annotations[SAFE_TO_EVICT_ANNOTATION] \
            == "true"
        from kubernetes_tpu.scheduler.types import (
            compute_pod_resource_request,
        )

        assert compute_pod_resource_request(pods[0]).milli_cpu == 250

    def test_reports_time_to_all_bound(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("bn0")
                       .capacity({"cpu": "4", "memory": "8Gi"}).obj())
        sched = Scheduler.create(store)
        try:
            sched.run()
            res = run_pending_burst(store, 5, timeout=20.0,
                                    cpu_milli=250, name_prefix="tb-",
                                    uid_prefix="tbu-")
            assert res.ok and res.bound == 5
            assert res.time_to_all_bound > 0
            assert res.pods_per_second > 0
        finally:
            sched.stop()

    def test_timeout_reports_unbound(self):
        store = ClusterStore()   # no nodes, no scheduler
        res = run_pending_burst(store, 2, timeout=0.2,
                                name_prefix="to-", uid_prefix="tou-")
        assert not res.ok
        assert res.bound == 0
        assert res.time_to_all_bound is None


class TestHPAHandoff:
    def test_hpa_scales_past_capacity_autoscaler_adds_nodes(self):
        """HPA scales a Deployment beyond node capacity -> replicas go
        unschedulable -> the autoscaler buys a group node -> every
        replica binds."""
        from kubernetes_tpu.api.types import (
            Deployment,
            HorizontalPodAutoscaler,
        )
        from kubernetes_tpu.controllers import ControllerManager
        from kubernetes_tpu.controllers.horizontalpodautoscaler import (
            USAGE_ANNOTATION,
        )

        store = ClusterStore()
        store.add_node(MakeNode().name("static-0")
                       .capacity({"cpu": "2", "memory": "8Gi"}).obj())
        cm = ControllerManager(store, controllers=[
            "deployment", "replicaset", "horizontalpodautoscaler"])
        cm.get("horizontalpodautoscaler").RESYNC_SECONDS = 0.2
        reg = NodeGroupRegistry([NodeGroup(
            "ng-hpa", cpu="2", memory="8Gi", min_size=0, max_size=3)])
        sched = Scheduler.create(store)
        ca = _mk_ca(store, reg, RESYNC_SECONDS=0.05,
                    scale_up_cooldown=0.3, scale_down_enabled=False)
        ca.queue_introspect = sched.queue
        try:
            cm.start()
            sched.run()
            ca.run()
            d = Deployment(
                selector=LabelSelector(match_labels={"app": "web"}),
                replicas=2,
                template={
                    "metadata": {"labels": {"app": "web"}},
                    "spec": {"containers": [
                        {"name": "c",
                         "resources": {"requests": {"cpu": "1000m"}}}
                    ]},
                })
            d.metadata.name = "web"
            store.add_deployment(d)
            _wait(lambda: sum(
                1 for p in store.list_pods() if p.spec.node_name) == 2,
                msg="2 replicas bound on the static node")

            def annotate(usage: str) -> None:
                for p in store.list_pods():
                    if p.metadata.labels.get("app") != "web":
                        continue
                    cur = store.get_pod(p.namespace, p.metadata.name)
                    if cur is None or \
                            cur.metadata.annotations.get(
                                USAGE_ANNOTATION) == usage:
                        continue
                    up = shallow_copy(cur)
                    up.metadata = shallow_copy(cur.metadata)
                    up.metadata.annotations = dict(cur.metadata.annotations)
                    up.metadata.annotations[USAGE_ANNOTATION] = usage
                    store.update_pod(up)

            hpa = HorizontalPodAutoscaler(
                scale_target_ref={"kind": "Deployment", "name": "web"},
                min_replicas=2, max_replicas=4,
                target_cpu_utilization_percentage=50)
            hpa.metadata.name = "web-hpa"
            store.add_hpa(hpa)
            annotate("1000")   # 100% vs 50% target -> scale toward 4
            _wait(lambda: (annotate("1000"),
                           store.get_deployment("default", "web")
                           .replicas == 4)[1],
                  timeout=20.0, msg="HPA scaled 2 -> 4")
            # the hand-off: 2 new replicas exceed static capacity, the
            # autoscaler must buy capacity and every replica must bind
            _wait(lambda: sum(
                1 for p in store.list_pods()
                if p.metadata.labels.get("app") == "web"
                and p.spec.node_name) == 4,
                timeout=30.0, msg="all 4 replicas bound after scale-up")
            assert ca.scale_up_events >= 1
            assert ca.provisioner.live_count("ng-hpa") >= 1
        finally:
            ca.stop()
            sched.stop()
            cm.stop()


# ---------------------------------------------------------------------------
# the end-to-end elastic story (acceptance)


class TestEndToEndElastic:
    def test_burst_scale_up_all_bind_then_drain_back(self):
        """Cluster at 2 nodes, burst 40 pods that cannot fit ->
        autoscaler scales the group within min/max -> ALL pods bind ->
        the workload shrinks -> idle nodes are drained (PDB honored,
        evicted pods rescued and re-bound: zero lost) back toward min
        size."""
        from kubernetes_tpu.controllers import ControllerManager
        from kubernetes_tpu.harness.chaos_nodes import PodRescuer

        store = ClusterStore()
        reg = NodeGroupRegistry([NodeGroup(
            "ng-e2e", cpu="4", memory="8Gi", min_size=2, max_size=12,
            boot_latency=0.05)])
        g = reg.get("ng-e2e")
        for i in range(2):
            store.add_node(g.node_template(i))
        # disruption controller maintains the PDB state the drain reads
        cm = ControllerManager(store, controllers=["disruption"])
        sched = Scheduler.create(store)
        ca = _mk_ca(store, reg, RESYNC_SECONDS=0.05,
                    scale_up_cooldown=0.3,
                    scale_down_unneeded_time=0.4,
                    scale_down_utilization_threshold=0.35,
                    max_concurrent_drains=1)
        ca.queue_introspect = sched.queue
        rescuer = PodRescuer(store, store, name_prefix="eb-")
        pdb = PodDisruptionBudget(
            label_selector=LabelSelector(match_labels={"app": "eb"}),
            min_available=2)
        pdb.metadata.name = "eb-pdb"
        store.add_pdb(pdb)
        try:
            cm.start()
            sched.run()
            ca.run()
            rescuer.start()
            # ---- phase A: burst beyond capacity, scale up, all bind
            res = run_pending_burst(
                store, 40, timeout=60.0, cpu_milli=500,
                name_prefix="eb-", uid_prefix="ebu-",
                labels={"app": "eb"}, safe_to_evict=True)
            assert res.ok, f"only {res.bound}/40 bound"
            peak = ca.provisioner.live_count("ng-e2e")
            assert 5 <= peak <= 12          # needed 5, capped at 12
            assert ca.scale_up_events >= 1
            assert ca.whatif_solves >= 1    # the batched decision path
            assert ca.metrics.time_to_capacity_seconds.count() >= 1
            # ---- phase B: workload completes down to 8 pods, spread
            # across the scaled-up nodes so draining REQUIRES eviction
            survivor_ids = [5 * i for i in range(8)]
            survivors = [f"eb-{i}" for i in survivor_ids]
            for i in range(40):
                if i in survivor_ids:
                    continue
                cur = store.get_pod("default", f"eb-{i}")
                up = shallow_copy(cur)
                up.metadata = shallow_copy(cur.metadata)
                up.status = type(cur.status)(phase=SUCCEEDED)
                store.update_pod(up)            # terminal: rescuer skips
                store.delete_pod("default", f"eb-{i}")
            # idle nodes drain back toward min; evicted survivors are
            # rescued (fresh uid, same name) and re-bind elsewhere
            _wait(lambda: ca.provisioner.live_count("ng-e2e") <= 3,
                  timeout=45.0, msg="scale-down toward min size")
            _wait(lambda: all(
                any(p.metadata.name == n and p.spec.node_name
                    for p in store.list_pods()) for n in survivors),
                timeout=30.0, msg="every surviving pod re-bound")
            assert rescuer.recreate_failures == 0
            live = ca.provisioner.live_count("ng-e2e")
            assert live >= reg.get("ng-e2e").min_size
            assert ca.scale_down_events >= 1
            assert ca.metrics.scaledowns_total.get("ng-e2e") >= 1.0
            # zero lost: every survivor bound exactly once, on a live node
            live_nodes = {n.name for n in store.list_nodes()}
            for name in survivors:
                pod = store.get_pod("default", name)
                assert pod is not None and pod.spec.node_name in live_nodes
        finally:
            rescuer.stop()
            ca.stop()
            sched.stop()
            cm.stop()


# ---------------------------------------------------------------------------
# churn integration (slow): killer profile with the autoscaler on


@pytest.mark.slow
@pytest.mark.chaos
class TestChurnIntegration:
    def test_killer_churn_with_autoscaler_replaces_dead_capacity(self):
        """chaos_nodes killer profile with the autoscaler enabled: the
        PR 3 invariants (no binds to dead nodes, zero lost pods, cache
        convergence) must hold, AND dead capacity is replaced — the
        workload needs ~9 of 10 nodes, the killer profile buries up to
        3, so binding everything requires autoscaled replacements."""
        from kubernetes_tpu.harness.chaos_nodes import run_chaos_nodes

        result = run_chaos_nodes(
            seed=29, nodes=10, pods=70, node_cpu=4, waves=4,
            churn_profile="killer", autoscale=True,
            wait_timeout=180.0)
        assert result["ok"], result
        assert result["stats"]["autoscaler_nodes_added"] >= 1, result
