"""API machinery tests: Quantity parsing and label-selector matching."""

import pytest

from kubernetes_tpu.api.labels import (
    LabelSelector,
    Requirement,
    Selector,
    parse_selector,
    selector_from_label_selector,
)
from kubernetes_tpu.api.resource import Quantity, parse_quantity
from kubernetes_tpu.api.types import Pod, Taint, Toleration
from kubernetes_tpu.testing import MakeNode, MakePod


class TestQuantity:
    @pytest.mark.parametrize(
        "s,milli",
        [
            ("100m", 100),
            ("1", 1000),
            ("1.5", 1500),
            ("0.1", 100),
            (".5", 500),
            ("2", 2000),
            ("0", 0),
        ],
    )
    def test_milli_value(self, s, milli):
        assert parse_quantity(s).milli_value() == milli

    @pytest.mark.parametrize(
        "s,value",
        [
            ("128Mi", 128 * 2**20),
            ("1Gi", 2**30),
            ("1G", 10**9),
            ("500k", 500_000),
            ("1e3", 1000),
            ("1.5Ki", 1536),
            ("64", 64),
        ],
    )
    def test_value(self, s, value):
        assert parse_quantity(s).value() == value

    def test_value_rounds_up(self):
        # 100m of a countable resource is 1 unit (reference Value() ceils)
        assert parse_quantity("100m").value() == 1
        assert parse_quantity("1m").milli_value() == 1

    def test_arithmetic_and_ordering(self):
        a, b = parse_quantity("1"), parse_quantity("500m")
        assert (a + b).milli_value() == 1500
        assert (a - b).milli_value() == 500
        assert b < a
        assert parse_quantity("1Gi") == Quantity.from_value(2**30)

    @pytest.mark.parametrize("bad", ["", "abc", "1x", "--1", "1.2.3", "Mi"])
    def test_invalid(self, bad):
        with pytest.raises(ValueError):
            parse_quantity(bad)

    def test_int_float_passthrough(self):
        assert parse_quantity(4).value() == 4
        assert parse_quantity(0.25).milli_value() == 250


class TestSelectors:
    def test_from_map(self):
        s = Selector.from_map({"app": "web"})
        assert s.matches({"app": "web", "tier": "fe"})
        assert not s.matches({"app": "db"})
        assert not s.matches({})

    def test_empty_matches_everything_nil_matches_nothing(self):
        assert Selector.everything().matches({"a": "b"})
        assert Selector.everything().matches({})
        assert not Selector.nothing().matches({})
        assert selector_from_label_selector(None).matches({}) is False
        # empty LabelSelector matches everything (reference semantics)
        assert selector_from_label_selector(LabelSelector()).matches({})

    @pytest.mark.parametrize(
        "op,values,labels,want",
        [
            ("In", ("a", "b"), {"k": "a"}, True),
            ("In", ("a", "b"), {"k": "c"}, False),
            ("In", ("a",), {}, False),
            ("NotIn", ("a",), {"k": "b"}, True),
            ("NotIn", ("a",), {"k": "a"}, False),
            ("NotIn", ("a",), {}, False),  # key absent -> NotIn fails (k8s semantics)
            ("Exists", (), {"k": "x"}, True),
            ("Exists", (), {}, False),
            ("DoesNotExist", (), {}, True),
            ("DoesNotExist", (), {"k": "x"}, False),
            ("Gt", ("5",), {"k": "7"}, True),
            ("Gt", ("5",), {"k": "3"}, False),
            ("Lt", ("5",), {"k": "3"}, True),
            ("Gt", ("5",), {"k": "abc"}, False),
        ],
    )
    def test_requirement_ops(self, op, values, labels, want):
        assert Requirement("k", op, values).matches(labels) is want

    def test_parse_selector(self):
        s = parse_selector("app=web, tier in (fe, be), !legacy, env!=dev")
        assert s.matches({"app": "web", "tier": "fe", "env": "prod"})
        assert not s.matches({"app": "web", "tier": "fe", "legacy": "1", "env": "prod"})
        assert not s.matches({"app": "web", "tier": "mid", "env": "prod"})
        assert not s.matches({"app": "web", "tier": "fe", "env": "dev"})


class TestTolerations:
    def test_tolerates(self):
        taint = Taint("gpu", "true", "NoSchedule")
        assert Toleration(key="gpu", operator="Equal", value="true").tolerates(taint)
        assert Toleration(key="gpu", operator="Exists").tolerates(taint)
        assert Toleration(operator="Exists").tolerates(taint)  # empty key matches all
        assert not Toleration(key="gpu", operator="Equal", value="false").tolerates(taint)
        assert not Toleration(
            key="gpu", operator="Equal", value="true", effect="NoExecute"
        ).tolerates(taint)


class TestWrappersAndFromDict:
    def test_pod_wrapper(self):
        p = (
            MakePod()
            .name("p1")
            .namespace("ns")
            .label("app", "web")
            .req({"cpu": "500m", "memory": "1Gi"})
            .priority(10)
            .obj()
        )
        assert p.full_name() == "ns/p1"
        assert p.priority() == 10
        assert p.spec.containers[0].resources.requests["cpu"].milli_value() == 500

    def test_node_wrapper(self):
        n = MakeNode().name("n1").capacity({"cpu": "4", "memory": "8Gi"}).obj()
        assert n.status.allocatable["cpu"].milli_value() == 4000
        assert n.metadata.labels["kubernetes.io/hostname"] == "n1"

    def test_pod_from_dict(self):
        p = Pod.from_dict(
            {
                "metadata": {"name": "x", "labels": {"a": "b"}},
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "resources": {"requests": {"cpu": "250m", "memory": "64Mi"}},
                            "ports": [{"containerPort": 80, "hostPort": 8080}],
                        }
                    ],
                    "nodeSelector": {"disk": "ssd"},
                    "priority": 5,
                    "tolerations": [{"key": "k", "operator": "Exists"}],
                    "topologySpreadConstraints": [
                        {
                            "maxSkew": 2,
                            "topologyKey": "zone",
                            "whenUnsatisfiable": "DoNotSchedule",
                            "labelSelector": {"matchLabels": {"a": "b"}},
                        }
                    ],
                },
            }
        )
        assert p.spec.containers[0].ports[0].host_port == 8080
        assert p.spec.topology_spread_constraints[0].max_skew == 2
        assert p.priority() == 5


# ---------------------------------------------------------------------------
# versioned API machinery (runtime.Scheme analog — VERDICT r2 missing #5)


class TestVersionedScheme:
    def test_v2_decode_converts_and_defaults(self):
        from kubernetes_tpu.api.scheme import SCHEME_V

        body = {
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {
                "scaleTargetRef": {"kind": "Deployment", "name": "web"},
                "maxReplicas": 10,
                "metrics": [{
                    "type": "Resource",
                    "resource": {
                        "name": "cpu",
                        "target": {"type": "Utilization",
                                   "averageUtilization": 60},
                    },
                }],
            },
        }
        hpa = SCHEME_V.decode(body, "HorizontalPodAutoscaler",
                              "autoscaling/v2")
        assert hpa.target_cpu_utilization_percentage == 60
        assert hpa.max_replicas == 10
        assert hpa.min_replicas == 1  # v2 defaulting
        assert hpa.scale_target_ref == {"kind": "Deployment",
                                        "name": "web"}

    def test_roundtrip_through_both_versions(self):
        from kubernetes_tpu.api.scheme import SCHEME_V
        from kubernetes_tpu.api.types import (
            HorizontalPodAutoscaler, ObjectMeta,
        )

        hpa = HorizontalPodAutoscaler(
            metadata=ObjectMeta(name="api", namespace="default"),
            scale_target_ref={"kind": "Deployment", "name": "api"},
            min_replicas=2, max_replicas=8,
            target_cpu_utilization_percentage=70,
        )
        v2 = SCHEME_V.encode(hpa, "autoscaling/v2")
        assert v2["apiVersion"] == "autoscaling/v2"
        assert v2["spec"]["metrics"][0]["resource"]["target"][
            "averageUtilization"] == 70
        back = SCHEME_V.decode(v2, "HorizontalPodAutoscaler",
                               "autoscaling/v2")
        assert back.target_cpu_utilization_percentage == 70
        assert back.min_replicas == 2
        v1 = SCHEME_V.encode(hpa, "autoscaling/v1")
        assert v1["targetCpuUtilizationPercentage"] == 70

    def test_group_routes_served_over_http(self):
        """The REST layer serves /apis/autoscaling/v2 alongside the
        legacy hub route, converting per request — one stored object,
        two wire shapes (InstallLegacyAPI vs InstallAPIs)."""
        import json as _json

        from kubernetes_tpu.apiserver.rest import APIServer, RestClient
        from kubernetes_tpu.apiserver.store import ClusterStore

        store = ClusterStore()
        server = APIServer(store=store).start()
        try:
            client = RestClient(server.url)
            v2_body = {
                "kind": "HorizontalPodAutoscaler",
                "apiVersion": "autoscaling/v2",
                "metadata": {"name": "web", "namespace": "default"},
                "spec": {
                    "scaleTargetRef": {"kind": "Deployment",
                                       "name": "web"},
                    "maxReplicas": 6,
                    "metrics": [{
                        "type": "Resource",
                        "resource": {
                            "name": "cpu",
                            "target": {"type": "Utilization",
                                       "averageUtilization": 55},
                        },
                    }],
                },
            }
            code, payload = client._request(
                "POST",
                "/apis/autoscaling/v2/namespaces/default/"
                "horizontalpodautoscalers",
                v2_body,
            )
            assert code == 201, payload
            assert payload["spec"]["metrics"][0]["resource"]["target"][
                "averageUtilization"] == 55
            # the SAME object through the legacy hub route is flat v1
            code, flat = client._request(
                "GET",
                "/api/v1/namespaces/default/horizontalpodautoscalers/web",
            )
            assert code == 200
            assert flat["targetCpuUtilizationPercentage"] == 55
            # and through the v1 group route
            code, v2read = client._request(
                "GET",
                "/apis/autoscaling/v2/namespaces/default/"
                "horizontalpodautoscalers/web",
            )
            assert code == 200
            assert v2read["apiVersion"] == "autoscaling/v2"
            assert "metrics" in v2read["spec"]
            # unknown group/version: 404
            code, _ = client._request(
                "GET", "/apis/nope/v9/horizontalpodautoscalers")
            assert code == 404
        finally:
            server.shutdown_server()


class TestBatchPolicySpokes:
    """VERDICT r3 #8: two more versioned spokes (batch/v1beta1 CronJob,
    policy/v1beta1 PodDisruptionBudget) with nested reference wire
    shapes, the unconvertible-field error path, a hub<->spoke
    round-trip fuzz over every registered kind, and one watch stream
    per version serving the same store concurrently."""

    def test_cronjob_v1beta1_nested_shape_round_trips(self):
        from kubernetes_tpu.api.scheme import SCHEME_V

        body = {
            "metadata": {"name": "backup", "namespace": "default"},
            "spec": {
                "schedule": "*/10 * * * *",
                "startingDeadlineSeconds": 120,
                "jobTemplate": {"spec": {
                    "completions": 2, "parallelism": 2,
                    "template": {"spec": {"containers": []}},
                }},
            },
        }
        cj = SCHEME_V.decode(body, "CronJob", "batch/v1beta1")
        assert cj.schedule == "*/10 * * * *"
        assert cj.completions == 2 and cj.parallelism == 2
        assert cj.starting_deadline_seconds == 120
        assert cj.concurrency_policy == "Allow"  # v1beta1 defaulting
        assert cj.suspend is False
        out = SCHEME_V.encode(cj, "batch/v1beta1")
        assert out["apiVersion"] == "batch/v1beta1"
        assert out["spec"]["jobTemplate"]["spec"]["completions"] == 2
        assert out["spec"]["successfulJobsHistoryLimit"] == 3

    def test_cronjob_unconvertible_field_rejected(self):
        import pytest

        from kubernetes_tpu.api.scheme import SCHEME_V, UnconvertibleError

        body = {
            "metadata": {"name": "x", "namespace": "default"},
            "spec": {"schedule": "* * * * *",
                     "successfulJobsHistoryLimit": 7},
        }
        with pytest.raises(UnconvertibleError):
            SCHEME_V.decode(body, "CronJob", "batch/v1beta1")
        # ...and over HTTP it is the client's 400, not a silent drop
        from kubernetes_tpu.apiserver.rest import APIServer, RestClient
        from kubernetes_tpu.apiserver.store import ClusterStore

        server = APIServer(store=ClusterStore()).start()
        try:
            client = RestClient(server.url)
            code, payload = client._request(
                "POST",
                "/apis/batch/v1beta1/namespaces/default/cronjobs",
                dict(body, kind="CronJob", apiVersion="batch/v1beta1"),
            )
            assert code == 400
            assert "successfulJobsHistoryLimit" in payload.get(
                "message", "")
        finally:
            server.shutdown_server()

    def test_pdb_v1beta1_nested_shape(self):
        from kubernetes_tpu.api.scheme import SCHEME_V

        body = {
            "metadata": {"name": "pdb", "namespace": "default"},
            "spec": {
                "minAvailable": "50%",
                "selector": {"matchLabels": {"app": "web"}},
            },
        }
        pdb = SCHEME_V.decode(body, "PodDisruptionBudget",
                              "policy/v1beta1")
        assert pdb.min_available == "50%"
        assert pdb.label_selector is not None
        out = SCHEME_V.encode(pdb, "policy/v1beta1")
        assert out["spec"]["minAvailable"] == "50%"
        assert out["spec"]["selector"]["matchLabels"] == {"app": "web"}
        assert "minAvailable" not in out  # nested, not flat

    def test_roundtrip_fuzz_all_registered_kinds(self):
        """Hub -> spoke -> hub must be the identity for every
        registered (version, kind) over randomized objects (reference
        roundtrip_test.go fuzzing)."""
        import random

        from kubernetes_tpu.api.scheme import SCHEME_V
        from kubernetes_tpu.api.serialization import to_wire
        from kubernetes_tpu.api.types import (
            CronJob, HorizontalPodAutoscaler, ObjectMeta,
            PodDisruptionBudget,
        )

        rng = random.Random(20260730)

        def rand_meta(i):
            return ObjectMeta(name=f"obj-{i}", namespace="default")

        def rand_hpa(i):
            return HorizontalPodAutoscaler(
                metadata=rand_meta(i),
                scale_target_ref={"kind": "Deployment",
                                  "name": f"d{i}"},
                min_replicas=rng.randint(1, 5),
                max_replicas=rng.randint(5, 50),
                target_cpu_utilization_percentage=rng.randint(1, 99),
            )

        def rand_cronjob(i):
            return CronJob(
                metadata=rand_meta(i),
                schedule=f"*/{rng.randint(1, 59)} * * * *",
                suspend=rng.random() < 0.5,
                completions=rng.randint(1, 5),
                parallelism=rng.randint(1, 5),
                starting_deadline_seconds=(
                    float(rng.randint(10, 600))
                    if rng.random() < 0.5 else None),
                concurrency_policy=rng.choice(
                    ["Allow", "Forbid", "Replace"]),
                job_template={"spec": {"containers": [
                    {"name": "c", "image": f"img-{i}"}]}},
            )

        def rand_pdb(i):
            pdb = PodDisruptionBudget(metadata=rand_meta(i))
            if rng.random() < 0.5:
                pdb.min_available = rng.choice(
                    [rng.randint(1, 5), f"{rng.randint(1, 99)}%"])
            else:
                pdb.max_unavailable = rng.choice(
                    [rng.randint(1, 5), f"{rng.randint(1, 99)}%"])
            return pdb

        makers = {
            "HorizontalPodAutoscaler": rand_hpa,
            "CronJob": rand_cronjob,
            "PodDisruptionBudget": rand_pdb,
        }
        versions = sorted({v for (v, _k) in SCHEME_V._spokes})
        assert len(versions) >= 4  # autoscaling x2, batch, policy
        checked = 0
        for version in versions:
            for kind in SCHEME_V.kinds_for(version):
                maker = makers[kind]
                for i in range(25):
                    obj = maker(i)
                    hub = to_wire(obj)
                    spoke = SCHEME_V.encode(obj, version)
                    back = SCHEME_V.decode(spoke, kind, version)
                    assert to_wire(back) == hub, (
                        f"{version}/{kind} object {i} did not "
                        f"round-trip"
                    )
                    checked += 1
        assert checked >= 100

    def test_concurrent_watch_streams_one_per_version(self):
        """One store, one object stream, two watch connections — each
        serving ITS version's wire shape (versioned-codec contract on
        the watch path, weak #5)."""
        import threading

        from kubernetes_tpu.apiserver.rest import APIServer, RestClient
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.api.types import (
            ObjectMeta, PodDisruptionBudget,
        )

        store = ClusterStore()
        server = APIServer(store=store).start()
        try:
            client = RestClient(server.url)
            frames = {"v1": [], "v1beta1": []}
            seen = {"v1": threading.Event(),
                    "v1beta1": threading.Event()}

            def watcher(path, key):
                import json as _json
                import urllib.request

                req = urllib.request.Request(server.url + path)
                with urllib.request.urlopen(req, timeout=10) as resp:
                    for line in resp:
                        frames[key].append(_json.loads(line))
                        seen[key].set()
                        return

            t1 = threading.Thread(
                target=watcher,
                args=("/api/v1/namespaces/default/"
                      "poddisruptionbudgets?watch=1", "v1"),
                daemon=True)
            t2 = threading.Thread(
                target=watcher,
                args=("/apis/policy/v1beta1/namespaces/default/"
                      "poddisruptionbudgets?watch=1", "v1beta1"),
                daemon=True)
            t1.start(); t2.start()
            import time as _time

            _time.sleep(0.3)  # both streams connected
            client.create(PodDisruptionBudget(
                metadata=ObjectMeta(name="pdb1", namespace="default"),
                min_available=2,
            ))
            assert seen["v1"].wait(5) and seen["v1beta1"].wait(5)
            flat = frames["v1"][0]["object"]
            nested = frames["v1beta1"][0]["object"]
            assert flat["minAvailable"] == 2 and "spec" not in flat
            assert nested["spec"]["minAvailable"] == 2
            assert nested["apiVersion"] == "policy/v1beta1"
            assert "minAvailable" not in nested
        finally:
            server.shutdown_server()
