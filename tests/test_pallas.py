"""Pallas kernel differential tests (interpret mode — CPU-safe).

The kernel must produce EXACTLY the scan solver's assignments (same
serial-equivalent semantics) on mixed workloads: resource fit, hard
topology spread, and (anti-)affinity, including intra-batch interaction.
"""

import dataclasses

import numpy as np
import pytest

from kubernetes_tpu.ops import pallas_solver as ps
from kubernetes_tpu.ops.encode import BatchEncoder
from kubernetes_tpu.ops.solver import SolverParams, pack_podin, solve_scan
from kubernetes_tpu.scheduler.snapshot import new_snapshot
from kubernetes_tpu.testing import MakeNode, MakePod


def _problem(n_nodes=12, n_pods=16, mixed=True):
    nodes = [
        MakeNode().name(f"n{i}")
        .label("topology.kubernetes.io/zone", f"z{i % 3}")
        .capacity({"cpu": "8", "memory": "16Gi"}).obj()
        for i in range(n_nodes)
    ]
    pods = []
    for i in range(n_pods):
        w = MakePod().name(f"p{i}").uid(f"pu{i}").label("app", "w").req(
            {"cpu": "500m", "memory": "256Mi"})
        if mixed and i % 3 == 0:
            w.spread_constraint(2, "topology.kubernetes.io/zone",
                                "DoNotSchedule", {"app": "w"})
        elif mixed and i % 3 == 1:
            w.pod_anti_affinity("app", ["w"], "kubernetes.io/hostname")
        pods.append(w.obj())
    snap = new_snapshot([], nodes)
    enc = BatchEncoder(snap, pad_nodes=128)
    return enc.encode(pods, pad_pods=32)


@pytest.mark.parametrize("mixed", [False, True])
def test_kernel_matches_scan(mixed):
    cluster, batch = _problem(mixed=mixed)
    ref = solve_scan(cluster, batch, SolverParams())
    pstatic, pstate = ps.prepare(cluster, batch)
    ints, floats = pack_podin(batch)
    backend = ps.PallasBackend(interpret=True)
    got, _ = backend.solve(SolverParams(), pstatic, pstate, ints, floats)
    np.testing.assert_array_equal(ref, got)


def test_kernel_state_carry_across_batches():
    """Two sequential 8-pod batches through the kernel must equal one
    16-pod scan: the carried PState (capacity + topology counts) is the
    cross-batch contract."""
    cluster, batch = _problem(n_pods=16)
    ref = solve_scan(cluster, batch, SolverParams())

    pstatic, pstate = ps.prepare(cluster, batch)
    backend = ps.PallasBackend(interpret=True)
    outs = []
    pods_all = batch.pods
    for half in (slice(0, 8), slice(8, 16)):
        sub = dataclasses.replace(
            batch,
            pods=pods_all[half],
            num_real_pods=8,
            requests=np.vstack([batch.requests[half],
                                np.zeros((8, batch.requests.shape[1]),
                                         np.int32)]),
            nonzero_requests=np.vstack([batch.nonzero_requests[half],
                                        np.zeros((8, 2), np.int32)]),
            profile_idx=np.concatenate([batch.profile_idx[half],
                                        np.zeros(8, np.int32)]),
            inexpressible=np.concatenate([batch.inexpressible[half],
                                          np.zeros(8, bool)]),
            pod_sc=np.vstack([batch.pod_sc[half],
                              np.zeros((8, batch.pod_sc.shape[1]), bool)]),
            pod_sc_match=np.vstack(
                [batch.pod_sc_match[half],
                 np.zeros((8, batch.pod_sc_match.shape[1]), bool)]),
            match_by=np.vstack([batch.match_by[half],
                                np.zeros((8, batch.match_by.shape[1]),
                                         bool)]),
            own_aff=np.vstack([batch.own_aff[half],
                               np.zeros((8, batch.own_aff.shape[1]), bool)]),
            own_anti=np.vstack([batch.own_anti[half],
                                np.zeros((8, batch.own_anti.shape[1]),
                                         bool)]),
            pref_weight=np.vstack(
                [batch.pref_weight[half],
                 np.zeros((8, batch.pref_weight.shape[1]), np.float32)]),
        )
        ints, floats = pack_podin(sub)
        got, pstate = backend.solve(SolverParams(), pstatic, pstate,
                                    ints, floats)
        outs.extend(got[:8].tolist())
    np.testing.assert_array_equal(ref[:16], outs)


@pytest.mark.parametrize("mixed", [False, True])
def test_xla_planes_backend_matches_scan(mixed):
    """The gather-free planes scan (wide-constraint fallback) must also
    match the legacy scan exactly."""
    cluster, batch = _problem(mixed=mixed)
    ref = solve_scan(cluster, batch, SolverParams())
    backend = ps.XlaPlanesBackend()
    pstatic, pstate = backend.prepare(cluster, batch)
    ints, floats = pack_podin(batch)
    got, _ = backend.solve(SolverParams(), pstatic, pstate, ints, floats)
    np.testing.assert_array_equal(ref, got)
