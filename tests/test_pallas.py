"""Pallas kernel differential tests (interpret mode — CPU-safe).

The kernel must produce EXACTLY the scan solver's assignments (same
serial-equivalent semantics) on mixed workloads: resource fit, hard
topology spread, and (anti-)affinity, including intra-batch interaction.
"""

import dataclasses

import numpy as np
import pytest

from kubernetes_tpu.ops import pallas_solver as ps
from kubernetes_tpu.ops.encode import BatchEncoder
from kubernetes_tpu.ops.solver import SolverParams, pack_podin, solve_scan
from kubernetes_tpu.scheduler.snapshot import new_snapshot
from kubernetes_tpu.testing import MakeNode, MakePod


def _problem(n_nodes=12, n_pods=16, mixed=True):
    nodes = [
        MakeNode().name(f"n{i}")
        .label("topology.kubernetes.io/zone", f"z{i % 3}")
        .capacity({"cpu": "8", "memory": "16Gi"}).obj()
        for i in range(n_nodes)
    ]
    pods = []
    for i in range(n_pods):
        w = MakePod().name(f"p{i}").uid(f"pu{i}").label("app", "w").req(
            {"cpu": "500m", "memory": "256Mi"})
        if mixed and i % 3 == 0:
            w.spread_constraint(2, "topology.kubernetes.io/zone",
                                "DoNotSchedule", {"app": "w"})
        elif mixed and i % 3 == 1:
            w.pod_anti_affinity("app", ["w"], "kubernetes.io/hostname")
        pods.append(w.obj())
    snap = new_snapshot([], nodes)
    enc = BatchEncoder(snap, pad_nodes=128)
    return enc.encode(pods, pad_pods=32)


@pytest.mark.parametrize("mixed", [False, True])
def test_kernel_matches_scan(mixed):
    cluster, batch = _problem(mixed=mixed)
    ref = solve_scan(cluster, batch, SolverParams())
    pstatic, pstate = ps.prepare(cluster, batch)
    ints, floats = pack_podin(batch)
    backend = ps.PallasBackend(interpret=True)
    got, _ = backend.solve(SolverParams(), pstatic, pstate, ints, floats)
    np.testing.assert_array_equal(ref, got)


def test_kernel_state_carry_across_batches():
    """Two sequential 8-pod batches through the kernel must equal one
    16-pod scan: the carried PState (capacity + topology counts) is the
    cross-batch contract."""
    cluster, batch = _problem(n_pods=16)
    ref = solve_scan(cluster, batch, SolverParams())

    pstatic, pstate = ps.prepare(cluster, batch)
    backend = ps.PallasBackend(interpret=True)
    outs = []
    pods_all = batch.pods
    for half in (slice(0, 8), slice(8, 16)):
        sub = dataclasses.replace(
            batch,
            pods=pods_all[half],
            num_real_pods=8,
            requests=np.vstack([batch.requests[half],
                                np.zeros((8, batch.requests.shape[1]),
                                         np.int32)]),
            nonzero_requests=np.vstack([batch.nonzero_requests[half],
                                        np.zeros((8, 2), np.int32)]),
            profile_idx=np.concatenate([batch.profile_idx[half],
                                        np.zeros(8, np.int32)]),
            inexpressible=np.concatenate([batch.inexpressible[half],
                                          np.zeros(8, bool)]),
            pod_sc=np.vstack([batch.pod_sc[half],
                              np.zeros((8, batch.pod_sc.shape[1]), bool)]),
            pod_sc_match=np.vstack(
                [batch.pod_sc_match[half],
                 np.zeros((8, batch.pod_sc_match.shape[1]), bool)]),
            match_by=np.vstack([batch.match_by[half],
                                np.zeros((8, batch.match_by.shape[1]),
                                         bool)]),
            own_aff=np.vstack([batch.own_aff[half],
                               np.zeros((8, batch.own_aff.shape[1]), bool)]),
            own_anti=np.vstack([batch.own_anti[half],
                                np.zeros((8, batch.own_anti.shape[1]),
                                         bool)]),
            pref_weight=np.vstack(
                [batch.pref_weight[half],
                 np.zeros((8, batch.pref_weight.shape[1]), np.float32)]),
        )
        ints, floats = pack_podin(sub)
        got, pstate = backend.solve(SolverParams(), pstatic, pstate,
                                    ints, floats)
        outs.extend(got[:8].tolist())
    np.testing.assert_array_equal(ref[:16], outs)


@pytest.mark.parametrize("mixed", [False, True])
def test_xla_planes_backend_matches_scan(mixed):
    """The gather-free planes scan (wide-constraint fallback) must also
    match the legacy scan exactly."""
    cluster, batch = _problem(mixed=mixed)
    ref = solve_scan(cluster, batch, SolverParams())
    backend = ps.XlaPlanesBackend()
    pstatic, pstate = backend.prepare(cluster, batch)
    ints, floats = pack_podin(batch)
    got, _ = backend.solve(SolverParams(), pstatic, pstate, ints, floats)
    np.testing.assert_array_equal(ref, got)


def _wide_term_problem(n_nodes=16, n_pods=48, groups=20, preferred=False):
    """Config-4-shaped workload: many anti-affinity groups (T >=
    SPARSE_MIN_T tracked terms), each pod referencing exactly one."""
    nodes = [
        MakeNode().name(f"n{i}")
        .label("topology.kubernetes.io/zone", f"z{i % 4}")
        .capacity({"cpu": "64", "memory": "64Gi"}).obj()
        for i in range(n_nodes)
    ]
    pods = []
    for i in range(n_pods):
        g = f"g{i % groups}"
        w = (MakePod().name(f"p{i}").uid(f"pu{i}").label("grp", g)
             .req({"cpu": "100m", "memory": "64Mi"}))
        if preferred and i % 2 == 0:
            w.preferred_pod_affinity(3, "grp", [g],
                                     "topology.kubernetes.io/zone")
        else:
            w.pod_anti_affinity("grp", [g], "kubernetes.io/hostname")
        pods.append(w.obj())
    snap = new_snapshot([], nodes)
    enc = BatchEncoder(snap, pad_nodes=128)
    return enc.encode(pods, pad_pods=64)


@pytest.mark.parametrize("preferred", [False, True])
def test_sparse_term_slots_match_scan(preferred):
    """The sparse term-slot scan (wide-T fast path) must match the
    legacy scan exactly — same assignments, same carried state."""
    cluster, batch = _wide_term_problem(preferred=preferred)
    t = batch.term_counts.shape[0]
    assert t >= ps.SPARSE_MIN_T, f"problem too narrow (T={t}) to hit sparse"
    ref = solve_scan(cluster, batch, SolverParams())
    backend = ps.XlaPlanesBackend()
    pstatic, pstate = backend.prepare(cluster, batch)
    ints, floats = pack_podin(batch)
    # make sure the sparse packer actually applies to this problem
    assert ps.pack_sparse_slots(ints, floats, pstatic.r, pstatic.sc,
                                t) is not None
    got, state = backend.solve(SolverParams(), pstatic, pstate, ints, floats)
    np.testing.assert_array_equal(ref, got)
    # carried state must equal the dense path's carried state
    pstatic2, pstate2 = backend.prepare(cluster, batch)
    dense_planes, _ = ps._xla_planes_solve(
        SolverParams(), pstatic2.r, pstatic2.sc, pstatic2.t, pstatic2.u,
        pstatic2.v, pstatic2.sc_meta, pstatic2.ints, pstatic2.f32s,
        pstate2.planes, ints, floats,
    )
    np.testing.assert_array_equal(np.asarray(dense_planes),
                                  np.asarray(state.planes))


def test_sparse_overflow_falls_back_dense():
    """A batch containing a pod that references more than SPARSE_K terms
    must solve END-TO-END on the dense path (pack_sparse_slots declines,
    solve_lazy falls through) and still match the legacy scan."""
    nodes = [
        MakeNode().name(f"n{i}")
        .capacity({"cpu": "64", "memory": "64Gi"}).obj()
        for i in range(8)
    ]
    pods = []
    for i in range(24):
        w = (MakePod().name(f"p{i}").uid(f"pu{i}")
             .label("grp", f"g{i % 16}").req({"cpu": "100m"}))
        if i == 0:
            # one pod owning SPARSE_K+1 distinct anti-affinity terms
            for j in range(ps.SPARSE_K + 1):
                w.label(f"multi{j}", "x")
                w.pod_anti_affinity(f"multi{j}", ["x"],
                                    "kubernetes.io/hostname")
        else:
            w.pod_anti_affinity("grp", [f"g{i % 16}"],
                                "kubernetes.io/hostname")
        pods.append(w.obj())
    snap = new_snapshot([], nodes)
    enc = BatchEncoder(snap, pad_nodes=128)
    cluster, batch = enc.encode(pods, pad_pods=32)
    t = batch.term_counts.shape[0]
    assert t >= ps.SPARSE_MIN_T
    ints, floats = pack_podin(batch)
    r, sc = cluster.allocatable.shape[1], batch.sc_counts.shape[0]
    assert ps.pack_sparse_slots(ints, floats, r, sc, t) is None
    ref = solve_scan(cluster, batch, SolverParams())
    backend = ps.XlaPlanesBackend()
    pstatic, pstate = backend.prepare(cluster, batch)
    got, _ = backend.solve(SolverParams(), pstatic, pstate, ints, floats)
    np.testing.assert_array_equal(ref, got)
