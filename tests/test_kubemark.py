"""Kubemark ring: hollow nodes under the real scheduler — the full pod
lifecycle (create → schedule → bind → kubelet runs → Running) without any
real machines, plus node-lifecycle health integration."""

import time

from kubernetes_tpu.api.types import RUNNING
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.kubelet.devicemanager import TPU_RESOURCE
from kubernetes_tpu.kubemark import HollowCluster
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing import MakePod


def wait_for(cond, timeout=15.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def test_hollow_cluster_runs_pods_end_to_end():
    store = ClusterStore()
    cluster = HollowCluster(store)
    cluster.start_nodes(5, capacity={"cpu": "8", "memory": "16Gi"})
    sched = Scheduler.create(store)
    sched.run()
    try:
        for i in range(20):
            store.create_pod(
                MakePod().name(f"p{i}").uid(f"u{i}").req({"cpu": "500m"}).obj()
            )
        assert wait_for(
            lambda: all(
                p.status.phase == RUNNING and p.spec.node_name
                for p in store.list_pods()
            )
        ), [(p.name, p.spec.node_name, p.status.phase) for p in store.list_pods()]
        # pods spread across hollow nodes, each with a real pod IP
        nodes_used = {p.spec.node_name for p in store.list_pods()}
        assert len(nodes_used) >= 3
        assert all(p.status.pod_ip for p in store.list_pods())
    finally:
        sched.stop()
        cluster.stop()


def test_hollow_nodes_expose_tpu_capacity_and_run_tpu_pods():
    store = ClusterStore()
    cluster = HollowCluster(store)
    cluster.start_nodes(2, tpu_chips=4)
    sched = Scheduler.create(store)
    sched.run()
    try:
        node = store.get_node("hollow-0")
        assert node.status.capacity[TPU_RESOURCE].value() == 4
        store.create_pod(
            MakePod().name("train").uid("ut").req(
                {"cpu": "1", TPU_RESOURCE: "4"}
            ).obj()
        )
        assert wait_for(
            lambda: store.get_pod("default", "train").status.phase == RUNNING
        )
        node_name = store.get_pod("default", "train").spec.node_name
        hollow = next(n for n in cluster.nodes if n.name == node_name)
        uid = store.get_pod("default", "train").uid
        assert len(hollow.kubelet.devices.devices_of(uid)[TPU_RESOURCE]) == 4
    finally:
        sched.stop()
        cluster.stop()


def test_hollow_heartbeats_keep_nodelifecycle_quiet():
    from kubernetes_tpu.client import SharedInformerFactory
    from kubernetes_tpu.controllers.nodelifecycle import (
        NodeLifecycleController,
        UNREACHABLE_TAINT,
    )

    store = ClusterStore()
    factory = SharedInformerFactory(store)
    nlc = NodeLifecycleController(store, factory)
    nlc.monitor_interval = 0.1
    nlc.grace_period = 1.0
    nlc.eviction_grace = 0.5
    cluster = HollowCluster(store, heartbeat_fn=nlc.heartbeat)
    cluster.start_nodes(3)
    factory.start()
    nlc.run()
    try:
        time.sleep(1.5)  # several grace periods with live heartbeats
        for node in store.list_nodes():
            assert not any(t.key == UNREACHABLE_TAINT for t in node.spec.taints)
        # kill one hollow node → it gets tainted, the others stay clean
        dead = cluster.nodes[0]
        dead.kubelet.stop()
        assert wait_for(
            lambda: any(
                t.key == UNREACHABLE_TAINT
                for t in store.get_node(dead.name).spec.taints
            ),
            timeout=5,
        )
        for node in store.list_nodes():
            if node.name != dead.name:
                assert not any(t.key == UNREACHABLE_TAINT for t in node.spec.taints)
    finally:
        nlc.stop()
        factory.stop()
        cluster.stop()


def test_hollow_node_over_rest_fabric_runs_pods():
    """Kubemark over the REAL fabric (partitioned-control-plane
    satellite): a HollowNode given a RestClusterClient registers its
    node, renews its heartbeat lease through the lease verb, watches
    pods, and drives one to Running — authn, APF, and the watch fabric
    all exercised like a real kubelet (the store-direct path above
    stays the fast default)."""
    from kubernetes_tpu.apiserver.rbac import provision_bootstrap_policy
    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.client.restcluster import RestClusterClient
    from kubernetes_tpu.kubemark import HollowNode

    store = ClusterStore()
    authz = provision_bootstrap_policy(store)
    authz.add_user_to_group("kubemark", "system:masters")
    server = APIServer(store=store, authorizer=authz,
                       tokens={"hollow-tok": "kubemark"}).start()
    client = RestClusterClient(server.url, token="hollow-tok",
                               watch_kinds=("Pod",))
    hollow = HollowNode(client, "hollow-rest-0",
                        capacity={"cpu": "8", "memory": "16Gi"})
    sched = Scheduler.create(store)
    sched.run()
    hollow.start()
    try:
        # no proxier over REST (no in-process rule-table seam)
        assert hollow.proxier is None
        assert wait_for(lambda: store.get_node("hollow-rest-0")
                        is not None)
        # heartbeat lease renewed through POST .../leases/{n}/acquire
        assert wait_for(lambda: store.lease_holder("node-hollow-rest-0")
                        == "hollow-rest-0")
        store.create_pod(
            MakePod().name("hp").uid("hu").req({"cpu": "200m"}).obj())
        assert wait_for(lambda: (
            (p := store.get_pod("default", "hp")) is not None
            and p.spec.node_name == "hollow-rest-0"
            and p.status.phase == RUNNING and p.status.pod_ip))
        # the fabric actually served the kubelet: the APF admission
        # path is live (masters-group identities ride the exempt level,
        # which is never charged — so assert the controller classified
        # traffic rather than a charged-seat count) and authn resolved
        # the bearer token (an unauthenticated request would have 401d
        # long before the pod ever ran)
        assert server.flowcontrol is not None
    finally:
        sched.stop()
        hollow.stop()
        client._stop_watches()
        client._drop_conn()
        server.shutdown_server()


def test_hollow_fleet_bulk_registration_and_shared_heartbeats():
    """HollowFleet: the 10×-tier kubemark shape — N Node objects bulk-
    registered, ONE thread renewing every lease in rotating slices."""
    from kubernetes_tpu.kubemark import HollowFleet

    store = ClusterStore()
    fleet = HollowFleet(store, interval=30.0, beats_per_tick=5)
    names = fleet.register(12, cpu="16", name_prefix="fl")
    assert len(store.list_nodes()) == 12
    assert all(store.get_node(n) is not None for n in names)
    # three slices cover more nodes than one (rotation advances)
    beaten = fleet.beat_slice() + fleet.beat_slice() + fleet.beat_slice()
    assert beaten == 15
    holders = [n for n in names if store.lease_holder(f"node-{n}") == n]
    assert len(holders) >= 12   # 15 beats over 12 nodes wraps around
    fleet.stop()
