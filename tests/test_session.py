"""SolverSession tests: device-resident state carry, incremental
pod-side-only encoding, and mutation-seq invalidation.

The correctness bar is differential: many small incremental batches must
produce the same bindings (validity + constraint satisfaction) as one cold
full-encode solve per batch, and any external cache mutation must force a
rebuild rather than solving against a stale device mirror.
"""

import time

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config.feature_gates import FeatureGates
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.sidecar import attach_batch_scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


def make_batch_scheduler(store, max_batch=8, validate=False):
    sched = Scheduler.create(
        store, feature_gates=FeatureGates({"TPUBatchScheduler": True})
    )
    bs = attach_batch_scheduler(sched, max_batch=max_batch, validate=validate)
    sched.start()
    return sched, bs


def drain(sched, bs, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sched.queue.flush_backoff_completed()
        if bs.run_batch(pop_timeout=0.0):
            continue
        if sched.queue.num_active() == 0 and sched.queue.num_backoff() == 0:
            break
        time.sleep(0.02)
    assert sched.wait_for_inflight_bindings()


class TestIncrementalSession:
    def test_many_small_batches_use_incremental_path(self):
        store = ClusterStore()
        for i in range(6):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "16", "memory": "32Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store, max_batch=8)
        for i in range(64):
            store.create_pod(MakePod().name(f"p{i}").req({"cpu": "1"}).obj())
        drain(sched, bs)
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 64
        # 64 pods at max_batch=8 → ≥8 batches; all but the first should
        # ride the device-resident incremental path
        assert bs.session.rebuilds >= 1
        assert bs.session.incremental_hits >= 6
        # capacity respected across the carried state
        per_node = {}
        for p in bound:
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(c <= 16 for c in per_node.values())
        sched.stop()

    def test_incremental_respects_spread_across_batches(self):
        store = ClusterStore()
        for i in range(9):
            store.add_node(
                MakeNode().name(f"n{i}")
                .label("topology.kubernetes.io/zone", f"z{i % 3}")
                .capacity({"cpu": "64", "memory": "64Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store, max_batch=6)
        for i in range(30):
            store.create_pod(
                MakePod().name(f"p{i}").uid(f"u{i}")
                .label("app", "web").req({"cpu": "100m"})
                .spread_constraint(
                    1, "topology.kubernetes.io/zone", "DoNotSchedule",
                    {"app": "web"},
                ).obj()
            )
        drain(sched, bs)
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 30
        zone_of = {}
        for n in store.list_nodes():
            zone_of[n.name] = n.metadata.labels["topology.kubernetes.io/zone"]
        counts = {}
        for p in bound:
            z = zone_of[p.spec.node_name]
            counts[z] = counts.get(z, 0) + 1
        # maxSkew=1 must hold across batch boundaries (carried counts)
        assert max(counts.values()) - min(counts.values()) <= 1
        assert bs.session.incremental_hits >= 2
        sched.stop()

    def test_external_mutation_invalidates_session(self):
        store = ClusterStore()
        for i in range(4):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "8", "memory": "16Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store, max_batch=4)
        for i in range(8):
            store.create_pod(MakePod().name(f"a{i}").req({"cpu": "1"}).obj())
        drain(sched, bs)
        rebuilds_before = bs.session.rebuilds
        # external change: a new node appears via the watch path
        store.add_node(
            MakeNode().name("late").capacity({"cpu": "8", "memory": "16Gi"}).obj()
        )
        for i in range(8):
            store.create_pod(MakePod().name(f"b{i}").req({"cpu": "1"}).obj())
        drain(sched, bs)
        assert bs.session.rebuilds > rebuilds_before
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 16
        # the late node is actually usable (mirror rebuilt, not stale)
        assert any(p.spec.node_name == "late" for p in bound)
        sched.stop()

    def test_serial_fallback_poisons_session(self):
        """A batch containing a host-only pod (PVC) triggers the serial
        path; if the serial path binds it the mirror must rebuild."""
        store = ClusterStore()
        for i in range(3):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "8", "memory": "16Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store, max_batch=8)
        for i in range(6):
            store.create_pod(MakePod().name(f"p{i}").req({"cpu": "1"}).obj())
        store.create_pod(
            MakePod().name("pvc-pod").req({"cpu": "1"}).pvc("claim-1").obj()
        )
        drain(sched, bs)
        # all plain pods bound; pvc pod went serial (may bind or stay
        # pending depending on volume plugins — either way the session
        # must have noticed the serial traffic)
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len([p for p in bound if p.metadata.name.startswith("p")]) >= 6
        # next batch must rebuild (session was invalidated by serial bind)
        rebuilds = bs.session.rebuilds
        for i in range(4):
            store.create_pod(MakePod().name(f"q{i}").req({"cpu": "1"}).obj())
        drain(sched, bs)
        assert len([p for p in store.list_pods() if p.spec.node_name]) >= 10
        if any(p.metadata.name == "pvc-pod" and p.spec.node_name
               for p in store.list_pods()):
            assert bs.session.rebuilds > rebuilds
        sched.stop()

    def test_validate_mode_matches_host_filters(self):
        """validate=True re-checks every device assignment with the host
        filter chain; carried state must never produce a host-rejected
        placement in a plain workload."""
        store = ClusterStore()
        for i in range(5):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "4", "memory": "8Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store, max_batch=5, validate=True)
        for i in range(20):
            store.create_pod(MakePod().name(f"p{i}").req({"cpu": "1"}).obj())
        drain(sched, bs)
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 20
        sched.stop()

    def test_wide_term_space_falls_back_to_legacy_backend(self):
        """More tracked anti-affinity terms than padded nodes exceeds the
        planes layout's totals plane; the solve chain must demote to the
        legacy backend and still schedule everything."""
        store = ClusterStore()
        for i in range(8):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "64", "memory": "64Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store, max_batch=256)
        # 140 distinct groups > 128 padded nodes
        for i in range(140):
            store.create_pod(
                MakePod().name(f"p{i}").uid(f"u{i}")
                .label("g", f"g{i}").req({"cpu": "100m"})
                .pod_anti_affinity("g", [f"g{i}"], "kubernetes.io/hostname")
                .obj()
            )
        drain(sched, bs, timeout=120)
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 140
        assert bs.session._active.name == "xla-legacy"
        sched.stop()


class TestPipelinedBatches:
    def test_stale_pending_is_resolved_not_serialized(self):
        """A held batch whose mirror diverges (external node add between
        its solve and commit) must be re-solved against a fresh snapshot
        and still bind everything correctly."""
        store = ClusterStore()
        for i in range(4):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "8", "memory": "16Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store, max_batch=8)
        for i in range(24):
            store.create_pod(MakePod().name(f"p{i}").req({"cpu": "1"}).obj())
        # first cycle: solves 8, holds them pending (queue still has 16)
        bs.run_batch(pop_timeout=0.1)
        assert bs._pending is not None
        # external mutation while the batch is in flight
        store.add_node(
            MakeNode().name("late").capacity({"cpu": "8", "memory": "16Gi"}).obj()
        )
        drain(sched, bs)
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 24
        per_node = {}
        for p in bound:
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(c <= 8 for c in per_node.values())
        sched.stop()

    def test_rebuild_mid_pipeline_commits_in_flight_batch_first(self):
        """A second wave introducing a NEW constraint space while a batch
        is pending forces a rebuild; the in-flight batch must commit
        before the rebuild so the fresh snapshot includes it (no
        double-placement / overcommit)."""
        store = ClusterStore()
        for i in range(6):
            store.add_node(
                MakeNode().name(f"n{i}")
                .label("topology.kubernetes.io/zone", f"z{i % 3}")
                .capacity({"cpu": "4", "memory": "8Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store, max_batch=8)
        # wave 1: plain pods (fills the pipeline)
        for i in range(16):
            store.create_pod(MakePod().name(f"a{i}").req({"cpu": "1"}).obj())
        bs.run_batch(pop_timeout=0.1)    # solve 8, hold pending
        # wave 2: spread-constrained pods -> new tracked constraint ->
        # encode space mismatch -> rebuild path
        for i in range(12):
            store.create_pod(
                MakePod().name(f"s{i}").uid(f"su{i}")
                .label("app", "web").req({"cpu": "500m"})
                .spread_constraint(1, "topology.kubernetes.io/zone",
                                   "DoNotSchedule", {"app": "web"}).obj()
            )
        drain(sched, bs)
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 28
        # capacity must hold INCLUDING the batch that was in flight at
        # rebuild time (4 cpu/node: 4x1cpu 'a' pods or mixes)
        cpu_on = {}
        for p in bound:
            m = 1000 if p.metadata.name.startswith("a") else 500
            cpu_on[p.spec.node_name] = cpu_on.get(p.spec.node_name, 0) + m
        assert all(v <= 4000 for v in cpu_on.values()), cpu_on
        # spread invariant for wave 2
        zone_of = {n.name: n.metadata.labels["topology.kubernetes.io/zone"]
                   for n in store.list_nodes()}
        counts = {}
        for p in bound:
            if p.metadata.name.startswith("s"):
                z = zone_of[p.spec.node_name]
                counts[z] = counts.get(z, 0) + 1
        assert max(counts.values()) - min(counts.values()) <= 1
        sched.stop()


class TestCarriedBatchRepartition:
    def test_pod_deleted_in_flight_is_dropped_on_carry(self):
        """ADVICE r1: a pod deleted while its solved batch was in flight
        must be dropped when the discarded batch's pods are carried over,
        not re-committed from a stale QueuedPodInfo."""
        store = ClusterStore()
        for i in range(4):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "8", "memory": "16Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store, max_batch=8)
        for i in range(16):
            store.create_pod(MakePod().name(f"p{i}").uid(f"u{i}")
                             .req({"cpu": "1"}).obj())
        bs.run_batch(pop_timeout=0.1)      # solve 8, hold pending
        assert bs._pending is not None
        victim = bs._pending["batchable"][0][0].pod
        store.delete_pod(victim.namespace, victim.name)
        # external cache mutation -> mirror diverges -> batch discarded,
        # pods carried over through the fresh partition
        store.add_node(
            MakeNode().name("late").capacity({"cpu": "8", "memory": "16Gi"}).obj()
        )
        drain(sched, bs)
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 15
        assert victim.metadata.name not in {p.metadata.name for p in bound}
        sched.stop()


class _FlakyBackend:
    """Delegating backend that fails its first N prepare() calls —
    models a transient TPU-tunnel error during rebuild."""

    name = "flaky"

    def __init__(self, inner, fail_times):
        self.inner = inner
        self.fails_left = fail_times
        self.attempts = 0

    def prepare(self, cluster, batch):
        self.attempts += 1
        if self.fails_left > 0:
            self.fails_left -= 1
            raise RuntimeError("transient tunnel flake")
        return self.inner.prepare(cluster, batch)

    def solve(self, *a):
        return self.inner.solve(*a)

    def solve_lazy(self, *a):
        return self.inner.solve_lazy(*a)

    def materialize(self, h):
        return self.inner.materialize(h)


class TestDemotionRetry:
    def test_transient_failure_does_not_demote_forever(self):
        """ADVICE r1: a backend demoted by a (possibly transient) error
        must be retried after DEMOTION_RETRY_REBUILDS successful rebuilds
        instead of staying demoted for the session's lifetime."""
        from kubernetes_tpu.ops.pallas_solver import XlaPlanesBackend
        from kubernetes_tpu.ops.session import DEMOTION_RETRY_REBUILDS

        store = ClusterStore()
        for i in range(4):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "64", "memory": "64Gi"}).obj()
            )
        sched, bs = make_batch_scheduler(store, max_batch=4)
        flaky = _FlakyBackend(XlaPlanesBackend(), fail_times=1)
        bs.session.backend = flaky
        bs.session._preferred = flaky

        n = 0

        def pump_one_rebuild():
            nonlocal n
            bs.session.invalidate()          # force a rebuild next batch
            for i in range(4):
                store.create_pod(MakePod().name(f"w{n}-{i}").uid(f"wu{n}-{i}")
                                 .req({"cpu": "100m"}).obj())
            n += 1
            drain(sched, bs)

        pump_one_rebuild()                   # rebuild 1: flaky fails, demoted
        assert bs.session.backend.name != "flaky"
        for _ in range(DEMOTION_RETRY_REBUILDS):
            pump_one_rebuild()               # cooldown ticks down
        # preferred backend retried and (flake over) sticks
        assert bs.session.backend is flaky
        assert bs.session._active is flaky
        assert flaky.attempts >= 2
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 4 * (1 + DEMOTION_RETRY_REBUILDS)
        sched.stop()
