"""REST apiserver ring: codec, watch cache, admission, HTTP CRUD + watch.

Mirrors the reference's integration-test ring (SURVEY.md section 4 ring 2):
a real in-process apiserver, real HTTP, no kubelets.
"""

import threading
import time

import pytest

from kubernetes_tpu.api.serialization import from_wire, roundtrip_equal, to_wire
from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.apiserver.admission import (
    AdmissionChain,
    AdmissionError,
    AdmissionRequest,
    CREATE,
    DefaultTolerationSeconds,
    LimitRanger,
    NamespaceLifecycle,
    PodPriorityResolver,
)
from kubernetes_tpu.apiserver.rest import APIServer, RestClient
from kubernetes_tpu.apiserver.store import ClusterStore, ConflictError
from kubernetes_tpu.apiserver.watchcache import TooOldResourceVersion, WatchCache
from kubernetes_tpu.testing import MakeNode, MakePod


# ---------------------------------------------------------------------------
# codec


def test_codec_roundtrip_pod_with_affinity():
    pod = (
        MakePod().name("p").uid("u1").req({"cpu": "250m", "memory": "64Mi"})
        .label("app", "web")
        .pod_anti_affinity("app", ["web"], "kubernetes.io/hostname")
        .spread_constraint(1, "zone", "DoNotSchedule", {"app": "web"})
        .obj()
    )
    assert roundtrip_equal(pod)
    back = from_wire(to_wire(pod))
    assert back.uid == "u1"
    assert back.spec.containers[0].resources.requests["cpu"].milli_value() == 250
    assert back.spec.topology_spread_constraints[0].max_skew == 1


def test_codec_roundtrip_node():
    node = (
        MakeNode().name("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": "110"})
        .label("topology.kubernetes.io/zone", "z1").obj()
    )
    assert roundtrip_equal(node)
    back = from_wire(to_wire(node))
    assert back.status.allocatable["memory"].value() == 8 * 2**30


# ---------------------------------------------------------------------------
# watch cache


def test_watchcache_replay_from_rv():
    store = ClusterStore()
    cache = WatchCache(store)
    store.create_pod(MakePod().name("a").obj())
    p_b = store.create_pod(MakePod().name("b").obj())
    rv_after_b = int(p_b.metadata.resource_version)
    store.create_pod(MakePod().name("c").obj())

    seen = []
    handle = cache.watch_from(rv_after_b, lambda rv, e: seen.append(e.obj.name))
    assert seen == ["c"]  # only events after rv(b) replayed
    store.create_pod(MakePod().name("d").obj())
    assert seen == ["c", "d"]  # live event delivered
    handle.stop()
    store.create_pod(MakePod().name("e").obj())
    assert "e" not in seen


def test_watchcache_too_old_rv_after_compaction():
    store = ClusterStore()
    cache = WatchCache(store)
    for i in range(10):
        store.create_pod(MakePod().name(f"p{i}").obj())
    cache.compact(keep_last=2)
    with pytest.raises(TooOldResourceVersion):
        cache.watch_from(0, lambda rv, e: None)
    # watching from the newest rv still works
    cache.watch_from(cache.latest_rv(), lambda rv, e: None)


def test_delete_bumps_resource_version():
    store = ClusterStore()
    cache = WatchCache(store)
    p = store.create_pod(MakePod().name("a").obj())
    rv_created = int(p.metadata.resource_version)
    events = []
    cache.watch_from(rv_created, lambda rv, e: events.append((rv, e.type)))
    store.delete_pod("default", "a")
    assert events and events[-1][1] == "DELETED"
    assert events[-1][0] > rv_created


# ---------------------------------------------------------------------------
# admission


def test_admission_default_tolerations_and_requests():
    chain = AdmissionChain(
        [DefaultTolerationSeconds(), LimitRanger({"cpu": "100m", "memory": "200Mi"})]
    )
    pod = MakePod().name("p").container().obj()
    chain.run(AdmissionRequest(CREATE, "Pod", "default", pod))
    keys = {t.key for t in pod.spec.tolerations}
    assert "node.kubernetes.io/not-ready" in keys
    assert "node.kubernetes.io/unreachable" in keys
    assert pod.spec.containers[0].resources.requests["cpu"].milli_value() == 100


def test_admission_namespace_lifecycle_rejects_terminating():
    chain = AdmissionChain([NamespaceLifecycle({"default": "Active", "dying": "Terminating"})])
    ok = MakePod().name("p").obj()
    chain.run(AdmissionRequest(CREATE, "Pod", "default", ok))
    bad = MakePod().name("q").namespace("dying").obj()
    with pytest.raises(AdmissionError):
        chain.run(AdmissionRequest(CREATE, "Pod", "dying", bad))


def test_admission_priority_class_resolution():
    chain = AdmissionChain([PodPriorityResolver({"high": 1000})])
    pod = MakePod().name("p").obj()
    pod.spec.priority_class_name = "high"
    chain.run(AdmissionRequest(CREATE, "Pod", "default", pod))
    assert pod.spec.priority == 1000
    bad = MakePod().name("q").obj()
    bad.spec.priority_class_name = "nonexistent"
    with pytest.raises(AdmissionError):
        chain.run(AdmissionRequest(CREATE, "Pod", "default", bad))


# ---------------------------------------------------------------------------
# HTTP server end-to-end


@pytest.fixture()
def server():
    srv = APIServer().start()
    yield srv
    srv.shutdown_server()


def test_rest_crud_and_binding(server):
    client = RestClient(server.url)
    assert client.healthz()

    node = client.create(MakeNode().name("n1").capacity({"cpu": "4", "memory": "8Gi"}).obj())
    assert node.metadata.resource_version != ""

    pod = client.create(MakePod().name("web").uid("u-web").req({"cpu": "100m"}).obj())
    assert pod.spec.node_name == ""
    # admission chain ran on the REST path
    assert any(t.key == "node.kubernetes.io/not-ready" for t in pod.spec.tolerations)

    # bind via the Binding subresource, observe nodeName on read-back
    client.bind("default", "web", "u-web", "n1")
    bound = client.get("Pod", "web")
    assert bound.spec.node_name == "n1"
    # double-bind conflicts
    with pytest.raises(ConflictError):
        client.bind("default", "web", "u-web", "n2")

    pods, rv = client.list("Pod")
    assert [p.name for p in pods] == ["web"] and rv > 0

    client.update_pod_status("default", "web", "Running", pod_ip="10.0.0.5")
    assert client.get("Pod", "web").status.phase == "Running"

    assert client.delete("Pod", "web")
    assert client.get("Pod", "web") is None
    assert not client.delete("Pod", "web")


def test_rest_update_conflict_on_stale_rv(server):
    client = RestClient(server.url)
    client.create(MakeNode().name("n1").obj())
    n1 = client.get("Node", "n1")
    n1b = client.get("Node", "n1")
    n1.metadata.labels["a"] = "1"
    client.update(n1)
    n1b.metadata.labels["b"] = "2"
    with pytest.raises(ConflictError):
        client.update(n1b)  # stale resourceVersion


def test_rest_watch_stream_replays_and_streams(server):
    client = RestClient(server.url)
    client.create(MakePod().name("p0").obj())
    _, rv0 = client.list("Pod")

    got = []
    done = threading.Event()

    def on_event(etype, obj):
        got.append((etype, obj.name))
        if len(got) >= 2:
            done.set()

    handle = client.watch("Pod", 0, on_event)  # rv=0 → replay everything
    client.create(MakePod().name("p1").obj())
    assert done.wait(5), f"watch frames: {got}"
    assert ("ADDED", "p0") in got and ("ADDED", "p1") in got
    handle.stop()

    # watch from the list RV sees only the new pod
    got2 = []
    done2 = threading.Event()
    handle2 = client.watch(
        "Pod", rv0, lambda t, o: (got2.append((t, o.name)), done2.set())
    )
    # p1's create happened after rv0 — replayed; nothing else required
    assert done2.wait(5)
    assert got2[0] == ("ADDED", "p1")
    handle2.stop()


def test_rest_authz_denies(server):
    server.authorizer = lambda user, verb, kind, ns: verb != "delete"
    client = RestClient(server.url)
    client.create(MakeNode().name("n1").obj())
    # a 403 raises (it must never read as a routine not-found miss)
    with pytest.raises(PermissionError):
        client.delete("Node", "n1")
    assert client.get("Node", "n1") is not None


def test_rest_feeds_informers_over_http(server):
    """The reflector contract: list+watch over real HTTP drives handlers."""
    client = RestClient(server.url)
    client.create(MakePod().name("seed").obj())

    adds = []
    synced = threading.Event()
    objs, rv = client.list("Pod")
    for o in objs:
        adds.append(o.name)
    handle = client.watch(
        "Pod", rv, lambda t, o: (adds.append(o.name), synced.set())
    )
    client.create(MakePod().name("late").obj())
    assert synced.wait(5)
    assert adds == ["seed", "late"]
    handle.stop()


class TestMutateObject:
    def test_cas_retries_on_concurrent_writer(self):
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.testing import MakeNode

        store = ClusterStore()
        store.add_node(MakeNode().name("n1").capacity({"cpu": "4"}).obj())
        calls = {"n": 0}

        def mutate(n):
            calls["n"] += 1
            if calls["n"] == 1:
                # interleave a concurrent write between read and CAS:
                # the first attempt must conflict and retry
                other = store.get_node("n1")
                from kubernetes_tpu.api.types import shallow_copy
                up = shallow_copy(other)
                up.metadata = shallow_copy(other.metadata)
                up.metadata.annotations = dict(other.metadata.annotations)
                up.metadata.annotations["other"] = "写"
                store.update_node(up)
            n.status.volumes_attached = ["pv-1"]
            return True

        store.mutate_object("Node", "", "n1", mutate)
        node = store.get_node("n1")
        assert calls["n"] == 2  # first attempt conflicted
        assert node.status.volumes_attached == ["pv-1"]
        assert node.metadata.annotations.get("other") == "写"  # preserved

    def test_mutate_abort_writes_nothing(self):
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.testing import MakeNode

        store = ClusterStore()
        store.add_node(MakeNode().name("n1").capacity({"cpu": "4"}).obj())
        rv = store.get_node("n1").metadata.resource_version
        assert store.mutate_object("Node", "", "n1",
                                   lambda n: False) is None
        assert store.get_node("n1").metadata.resource_version == rv


class TestAdmissionBreadth:
    """Opt-in in-tree plugins (reference plugin/pkg/admission/
    {alwayspullimages,eventratelimit,podnodeselector}) — available and
    tested, not default-enabled, matching upstream's default plugin
    set."""

    def test_always_pull_images(self):
        from kubernetes_tpu.apiserver.admission import (
            AdmissionChain, AdmissionRequest, AlwaysPullImages, CREATE,
        )
        from kubernetes_tpu.testing import MakePod

        chain = AdmissionChain([AlwaysPullImages()])
        pod = MakePod().name("p").container(image="private/app").obj()
        pod.spec.containers[0].image_pull_policy = "IfNotPresent"
        chain.run(AdmissionRequest(CREATE, "Pod", "default", pod))
        assert pod.spec.containers[0].image_pull_policy == "Always"

    def test_event_rate_limit(self):
        import pytest as _pytest

        from kubernetes_tpu.api.types import Event as ApiEvent
        from kubernetes_tpu.apiserver.admission import (
            AdmissionError, AdmissionRequest, CREATE, EventRateLimit,
        )

        limiter = EventRateLimit(qps=0.0, burst=3)
        ev = ApiEvent()
        for _ in range(3):
            limiter.validate(AdmissionRequest(
                CREATE, "Event", "flood", ev))
        with _pytest.raises(AdmissionError):
            limiter.validate(AdmissionRequest(
                CREATE, "Event", "flood", ev))
        # other namespaces keep their own bucket
        limiter.validate(AdmissionRequest(CREATE, "Event", "calm", ev))

    def test_pod_node_selector_merge_and_conflict(self):
        import pytest as _pytest

        from kubernetes_tpu.api.types import Namespace, ObjectMeta
        from kubernetes_tpu.apiserver.admission import (
            AdmissionError, AdmissionRequest, CREATE, PodNodeSelector,
        )
        from kubernetes_tpu.testing import MakePod

        store = ClusterStore()
        store.add_namespace(Namespace(metadata=ObjectMeta(
            name="tenant-a",
            annotations={"scheduler.alpha.kubernetes.io/node-selector":
                         "pool=gold, region=us"},
        )))
        plugin = PodNodeSelector(store)
        pod = MakePod().name("p").namespace("tenant-a").obj()
        plugin.admit(AdmissionRequest(CREATE, "Pod", "tenant-a", pod))
        assert pod.spec.node_selector == {"pool": "gold", "region": "us"}
        # conflicting own selector: rejected
        bad = MakePod().name("q").namespace("tenant-a").obj()
        bad.spec.node_selector["pool"] = "silver"
        with _pytest.raises(AdmissionError):
            plugin.admit(AdmissionRequest(CREATE, "Pod", "tenant-a", bad))

    def test_default_storage_class_assignment(self):
        """DefaultStorageClass (default-enabled upstream): a PVC naming
        no class gets the newest default-annotated class."""
        from kubernetes_tpu.api.resource import parse_quantity
        from kubernetes_tpu.api.types import (
            ObjectMeta, PersistentVolumeClaim, StorageClass,
        )
        from kubernetes_tpu.apiserver.rest import APIServer, RestClient

        store = ClusterStore()
        ann = {"storageclass.kubernetes.io/is-default-class": "true"}
        old = StorageClass(metadata=ObjectMeta(name="old-default",
                                               annotations=dict(ann)),
                           provisioner="x")
        old.metadata.creation_timestamp = 100.0
        new = StorageClass(metadata=ObjectMeta(name="new-default",
                                               annotations=dict(ann)),
                           provisioner="x")
        new.metadata.creation_timestamp = 200.0
        plain = StorageClass(metadata=ObjectMeta(name="plain"),
                             provisioner="x")
        for sc in (old, new, plain):
            store.add_storage_class(sc)
        server = APIServer(store=store).start()
        try:
            client = RestClient(server.url)
            client.create(PersistentVolumeClaim(
                metadata=ObjectMeta(name="classless", namespace="default"),
                requests={"storage": parse_quantity("1Gi")},
            ))
            got = store.get_pvc("default", "classless")
            assert got.storage_class_name == "new-default"
            # an explicit class is never overridden
            client.create(PersistentVolumeClaim(
                metadata=ObjectMeta(name="classed", namespace="default"),
                storage_class_name="plain",
                requests={"storage": parse_quantity("1Gi")},
            ))
            assert store.get_pvc(
                "default", "classed").storage_class_name == "plain"
        finally:
            server.shutdown_server()

    def test_discovery_endpoints(self):
        """/api, /apis, /api/v1, /apis/<g>/<v> serve the discovery
        documents kubectl/client-go RESTMappers consume — including
        live CRD registrations."""
        from kubernetes_tpu.api.types import (
            CRDNames, CustomResourceDefinition, ObjectMeta,
        )
        from kubernetes_tpu.apiserver.rest import APIServer, RestClient

        store = ClusterStore()
        server = APIServer(store=store).start()
        try:
            client = RestClient(server.url)
            code, versions = client._request("GET", "/api")
            assert code == 200 and versions["versions"] == ["v1"]
            code, groups = client._request("GET", "/apis")
            assert code == 200
            names = {g["name"] for g in groups["groups"]}
            assert {"autoscaling", "batch", "policy"} <= names
            auto = next(g for g in groups["groups"]
                        if g["name"] == "autoscaling")
            assert auto["preferredVersion"]["version"] == "v2"
            code, core = client._request("GET", "/api/v1")
            by_name = {r["name"]: r for r in core["resources"]}
            assert by_name["pods"]["namespaced"] is True
            assert by_name["nodes"]["namespaced"] is False
            # CRD registration appears in discovery immediately
            client.create(CustomResourceDefinition(
                metadata=ObjectMeta(name="policies.example.com"),
                names=CRDNames(plural="policies", kind="Policy"),
            ))
            code, core = client._request("GET", "/api/v1")
            assert any(r["name"] == "policies"
                       for r in core["resources"])
            code, batch = client._request("GET", "/apis/batch/v1beta1")
            assert code == 200 and batch["resources"][0]["name"] == \
                "cronjobs"
            code, _ = client._request("GET", "/apis/nope/v9")
            assert code == 404
        finally:
            server.shutdown_server()

    def test_priority_class_api_resolution(self):
        """PriorityClass API objects drive the Priority admission
        plugin (reference plugin/pkg/admission/priority): named class
        resolves, globalDefault applies to classless pods, system
        built-ins always exist."""
        from kubernetes_tpu.api.types import ObjectMeta, PriorityClass
        from kubernetes_tpu.apiserver.rest import APIServer, RestClient
        from kubernetes_tpu.testing import MakePod

        store = ClusterStore()
        server = APIServer(store=store).start()
        try:
            client = RestClient(server.url)
            client.create(PriorityClass(
                metadata=ObjectMeta(name="high"), value=1000))
            client.create(PriorityClass(
                metadata=ObjectMeta(name="workhorse"), value=50,
                global_default=True))
            p1 = MakePod().name("p1").obj()
            p1.spec.priority_class_name = "high"
            client.create(p1)
            assert store.get_pod("default", "p1").spec.priority == 1000
            # classless pod inherits the global default
            client.create(MakePod().name("p2").obj())
            got = store.get_pod("default", "p2")
            assert got.spec.priority == 50
            assert got.spec.priority_class_name == "workhorse"
            # system built-in resolves without any object
            p3 = MakePod().name("p3").obj()
            p3.spec.priority_class_name = "system-cluster-critical"
            client.create(p3)
            assert store.get_pod(
                "default", "p3").spec.priority == 2000000000
            # unknown class still rejects
            bad = MakePod().name("p4").obj()
            bad.spec.priority_class_name = "nope"
            import pytest as _pytest

            with _pytest.raises(PermissionError):
                client.create(bad)
        finally:
            server.shutdown_server()

    def test_leases_are_observable(self):
        """coordination.k8s.io view: leader-election/heartbeat leases
        list through the API (kubectl get leases parity)."""
        from kubernetes_tpu.apiserver.rest import APIServer, RestClient

        store = ClusterStore()
        store.try_acquire_or_renew("kube-scheduler", "sched-a",
                                   100.0, 15.0)
        server = APIServer(store=store).start()
        try:
            client = RestClient(server.url)
            leases, _ = client.list("Lease")
            by_name = {ls.metadata.name: ls for ls in leases}
            assert by_name["kube-scheduler"].holder_identity == "sched-a"
            assert by_name["kube-scheduler"].lease_duration_seconds == 15.0
        finally:
            server.shutdown_server()

    def test_patch_merge_and_json(self):
        """PATCH: RFC 7386 merge (nulls delete, dicts merge) and RFC
        6902 json-patch, CAS'd on the read revision, through
        admission."""
        from kubernetes_tpu.apiserver.rest import APIServer, RestClient
        from kubernetes_tpu.testing import MakePod

        store = ClusterStore()
        server = APIServer(store=store).start()
        try:
            client = RestClient(server.url)
            pod = MakePod().name("web").uid("u-web") \
                .label("app", "web").label("tier", "x").obj()
            client.create(pod)
            # merge patch: set one label, delete another
            got = client.patch("Pod", "web", {
                "metadata": {"labels": {"env": "prod", "tier": None}},
            })
            assert got.metadata.labels.get("env") == "prod"
            assert "tier" not in got.metadata.labels
            assert got.metadata.labels.get("app") == "web"  # merged
            # json patch
            got = client.patch("Pod", "web", [
                {"op": "replace", "path": "/metadata/labels/env",
                 "value": "staging"},
            ], patch_type="json")
            assert got.metadata.labels["env"] == "staging"
            live = store.get_pod("default", "web")
            assert live.metadata.labels["env"] == "staging"
            # identity immutable
            got = client.patch("Pod", "web",
                               {"metadata": {"name": "evil"}})
            assert got.metadata.name == "web"
        finally:
            server.shutdown_server()

    def test_patch_respects_versioned_routes(self):
        """A patch against a group route applies to THAT version's wire
        shape (nested v1beta1 spec), not the hub."""
        import urllib.request
        import json as _json

        from kubernetes_tpu.api.types import CronJob, ObjectMeta
        from kubernetes_tpu.apiserver.rest import APIServer
        from kubernetes_tpu.apiserver.store import ClusterStore

        store = ClusterStore()
        server = APIServer(store=store).start()
        try:
            store.create_object("CronJob", CronJob(
                metadata=ObjectMeta(name="backup", namespace="default"),
                schedule="* * * * *",
            ))
            req = urllib.request.Request(
                server.url + "/apis/batch/v1beta1/namespaces/default/"
                             "cronjobs/backup",
                data=_json.dumps(
                    {"spec": {"schedule": "*/10 * * * *"}}).encode(),
                method="PATCH",
                headers={"Content-Type": "application/merge-patch+json"},
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                payload = _json.loads(resp.read())
            assert payload["spec"]["schedule"] == "*/10 * * * *"
            assert store.get_object(
                "CronJob", "default", "backup").schedule == "*/10 * * * *"
        finally:
            server.shutdown_server()

    def test_patch_hardening(self):
        """Scalar bodies 400; uid/creationTimestamp pinned; Service
        clusterIP immutable; RFC 6902 test/move/copy + strict errors."""
        import pytest as _pytest

        from kubernetes_tpu.api.types import Service, ServicePort
        from kubernetes_tpu.apiserver.rest import APIServer, RestClient
        from kubernetes_tpu.testing import MakePod

        store = ClusterStore()
        server = APIServer(store=store).start()
        try:
            client = RestClient(server.url)
            pod = MakePod().name("p").uid("u-p").label("a", "1").obj()
            client.create(pod)
            # scalar merge body -> 400, not a dropped connection
            code, _ = client._request(
                "PATCH", "/api/v1/namespaces/default/pods/p", 5,
                content_type="application/merge-patch+json")
            assert code == 400
            # metadata null cannot regenerate identity
            got = client.patch("Pod", "p", {"metadata": None})
            assert got.metadata.uid == "u-p"
            got = client.patch("Pod", "p", {"metadata": {"uid": "evil"}})
            assert got.metadata.uid == "u-p"
            # metadata:null wiped the labels (correct RFC semantics,
            # identity pinned); restore them for the json-patch leg
            client.patch("Pod", "p", {"metadata": {"labels": {"a": "1"}}})
            # Service clusterIP immutable via PATCH like PUT
            svc = Service(cluster_ip="10.96.0.9",
                          ports=[ServicePort(name="http", port=80)])
            svc.metadata.name = "svc"
            svc.metadata.namespace = "default"
            client.create(svc)
            with _pytest.raises(PermissionError):
                client.patch("Service", "svc", {"clusterIp": "10.96.0.77"})
            # RFC 6902: test guards, strict replace
            with _pytest.raises(RuntimeError):
                client.patch("Pod", "p", [
                    {"op": "test", "path": "/metadata/labels/a",
                     "value": "WRONG"},
                    {"op": "replace", "path": "/metadata/labels/a",
                     "value": "2"},
                ], patch_type="json")
            assert store.get_pod(
                "default", "p").metadata.labels["a"] == "1"
            with _pytest.raises(RuntimeError):
                client.patch("Pod", "p", [
                    {"op": "replace", "path": "/metadata/labels/nope",
                     "value": "x"},
                ], patch_type="json")
            got = client.patch("Pod", "p", [
                {"op": "test", "path": "/metadata/labels/a",
                 "value": "1"},
                {"op": "copy", "from": "/metadata/labels/a",
                 "path": "/metadata/labels/b"},
                {"op": "move", "from": "/metadata/labels/b",
                 "path": "/metadata/labels/c"},
            ], patch_type="json")
            assert got.metadata.labels.get("c") == "1"
            assert "b" not in got.metadata.labels
        finally:
            server.shutdown_server()
