"""Native C++ planes-solver tests: build, exact differential equality
against the scan solver, and state carry across batches.

Skipped when no C++ toolchain is available (the runtime then falls back
to the JAX backends — the clean-degradation contract)."""

import numpy as np
import pytest

from kubernetes_tpu.ops.encode import BatchEncoder
from kubernetes_tpu.ops.solver import SolverParams, pack_podin, solve_scan
from kubernetes_tpu.ops import native_backend
from kubernetes_tpu.scheduler.snapshot import new_snapshot
from kubernetes_tpu.testing import MakeNode, MakePod

pytestmark = pytest.mark.skipif(
    not native_backend.available(), reason="no native toolchain"
)


def _problem(n_nodes=12, n_pods=16, heterogeneous=True):
    nodes = [
        MakeNode().name(f"n{i}")
        .label("topology.kubernetes.io/zone", f"z{i % 3}")
        .capacity({
            "cpu": str(4 + (i % 5 if heterogeneous else 0)),
            "memory": f"{8 + (i % 7 if heterogeneous else 0)}Gi",
        }).obj()
        for i in range(n_nodes)
    ]
    pods = []
    for i in range(n_pods):
        w = MakePod().name(f"p{i}").uid(f"pu{i}").label("app", "w").req(
            {"cpu": "500m", "memory": "256Mi"})
        if i % 3 == 0:
            w.spread_constraint(2, "topology.kubernetes.io/zone",
                                "DoNotSchedule", {"app": "w"})
        elif i % 3 == 1:
            w.pod_anti_affinity("app", ["w"], "kubernetes.io/hostname")
        pods.append(w.obj())
    snap = new_snapshot([], nodes)
    return BatchEncoder(snap, pad_nodes=128).encode(pods, pad_pods=32)


@pytest.mark.parametrize("heterogeneous", [False, True])
def test_cpp_matches_scan(heterogeneous):
    cluster, batch = _problem(heterogeneous=heterogeneous)
    ref = solve_scan(cluster, batch, SolverParams())
    be = native_backend.CppBackend()
    pstatic, pstate = be.prepare(cluster, batch)
    ints, floats = pack_podin(batch)
    got, _ = be.solve(SolverParams(), pstatic, pstate, ints, floats)
    np.testing.assert_array_equal(ref, got)


def test_cpp_state_carry():
    """Solving the same batch twice against carried state must keep
    consuming capacity (not reset): second round lands on the
    least-loaded remaining nodes, and capacity is never exceeded."""
    cluster, batch = _problem(n_nodes=4, n_pods=8)
    be = native_backend.CppBackend()
    pstatic, pstate = be.prepare(cluster, batch)
    ints, floats = pack_podin(batch)
    a1, pstate = be.solve(SolverParams(), pstatic, pstate, ints, floats)
    a2, pstate = be.solve(SolverParams(), pstatic, pstate, ints, floats)
    # compare against one 16-pod scan solve (the serial-equivalent truth)
    import dataclasses

    double = dataclasses.replace(
        batch,
        pods=batch.pods + batch.pods,
        num_real_pods=16,
        requests=np.vstack([batch.requests[:8], batch.requests[:8],
                            np.zeros((16, batch.requests.shape[1]),
                                     np.int32)]),
        nonzero_requests=np.vstack(
            [batch.nonzero_requests[:8], batch.nonzero_requests[:8],
             np.zeros((16, 2), np.int32)]),
        profile_idx=np.concatenate(
            [batch.profile_idx[:8], batch.profile_idx[:8],
             np.zeros(16, np.int32)]),
        inexpressible=np.concatenate(
            [batch.inexpressible[:8], batch.inexpressible[:8],
             np.zeros(16, bool)]),
        pod_sc=np.vstack([batch.pod_sc[:8], batch.pod_sc[:8],
                          np.zeros((16, batch.pod_sc.shape[1]), bool)]),
        pod_sc_match=np.vstack(
            [batch.pod_sc_match[:8], batch.pod_sc_match[:8],
             np.zeros((16, batch.pod_sc_match.shape[1]), bool)]),
        match_by=np.vstack([batch.match_by[:8], batch.match_by[:8],
                            np.zeros((16, batch.match_by.shape[1]),
                                     bool)]),
        own_aff=np.vstack([batch.own_aff[:8], batch.own_aff[:8],
                           np.zeros((16, batch.own_aff.shape[1]), bool)]),
        own_anti=np.vstack([batch.own_anti[:8], batch.own_anti[:8],
                            np.zeros((16, batch.own_anti.shape[1]),
                                     bool)]),
        pref_weight=np.vstack(
            [batch.pref_weight[:8], batch.pref_weight[:8],
             np.zeros((16, batch.pref_weight.shape[1]), np.float32)]),
    )
    ref = solve_scan(cluster, double, SolverParams())
    np.testing.assert_array_equal(
        ref[:16], np.concatenate([a1[:8], a2[:8]])
    )


def test_cpp_matches_planes_scan_on_shared_volumes():
    """sv epochs: the C++ mirror carries the same per-volume attach
    planes as the XLA planes scan — identical assignments end to end,
    including in-batch attachment reuse (round 5)."""
    from kubernetes_tpu.api.types import (
        CSINode,
        CSINodeDriver,
        ObjectMeta,
        PersistentVolume,
        PersistentVolumeClaim,
        Volume,
    )
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.ops.encode import BatchEncoder
    from kubernetes_tpu.ops.pallas_solver import XlaPlanesBackend

    store = ClusterStore()
    for i in range(4):
        store.add_node(MakeNode().name(f"n{i}")
                       .capacity({"cpu": "32", "memory": "64Gi"}).obj())
        store.add_csi_node(CSINode(
            metadata=ObjectMeta(name=f"n{i}"),
            drivers=[CSINodeDriver(name="csi.x", allocatable_count=2)]))
    for c in range(3):
        store.add_pv(PersistentVolume(
            metadata=ObjectMeta(name=f"pv{c}"),
            access_modes=["ReadWriteMany"], csi_driver="csi.x",
            claim_ref=f"default/claim{c}", phase="Bound"))
        store.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name=f"claim{c}", namespace="default"),
            access_modes=["ReadWriteMany"], volume_name=f"pv{c}"))
    pods = []
    for i in range(24):
        p = MakePod().name(f"p{i}").uid(f"u{i}").req(
            {"cpu": "100m"}).obj()
        p.spec.volumes = [Volume(
            name="d", persistent_volume_claim=f"claim{i % 3}")]
        pods.append(p)

    snap = new_snapshot([], store.list_nodes())
    enc = BatchEncoder(snap, pad_nodes=128, client=store)
    cluster, batch = enc.encode(pods, pad_pods=32)
    assert cluster.sv_attached is not None   # sv epoch
    ints, floats = pack_podin(batch)

    ref_be = XlaPlanesBackend()
    ps, st = ref_be.prepare(cluster, batch)
    ref, _ = ref_be.solve(SolverParams(), ps, st, ints, floats)

    be = native_backend.CppBackend()
    pstatic, pstate = be.prepare(cluster, batch)
    got, _ = be.solve(SolverParams(), pstatic, pstate, ints, floats)
    np.testing.assert_array_equal(np.asarray(ref), got)
    # attach-limit invariant on the native result: per node, distinct
    # volumes <= 2
    per_node = {}
    for bi, a in enumerate(got[:24]):
        assert a >= 0
        per_node.setdefault(int(a), set()).add(bi % 3)
    for node, vols in per_node.items():
        assert len(vols) <= 2, (node, vols)
