"""Config-compat tests: legacy Policy translation (factory.go:207-296 +
legacy_registry.go), v1beta1 validation depth, /metrics/resources."""

import json
import urllib.request

import pytest

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config.policy import PolicyError, load_policy, policy_to_config
from kubernetes_tpu.config.types import KubeSchedulerConfiguration
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


class TestPolicyTranslation:
    def test_policy_predicates_and_priorities_map_to_plugins(self):
        cfg = load_policy(json.dumps({
            "kind": "Policy", "apiVersion": "v1",
            "predicates": [
                {"name": "PodFitsResources"},
                {"name": "PodFitsHostPorts"},
                {"name": "MatchNodeSelector"},
            ],
            "priorities": [
                {"name": "LeastRequestedPriority", "weight": 2},
                {"name": "BalancedResourceAllocation", "weight": 1},
            ],
        }))
        sched = Scheduler.create(ClusterStore(), config=cfg)
        fwk = sched.profiles["default-scheduler"]
        plugins = fwk.list_plugins()
        assert "NodeResourcesFit" in plugins["filter"]
        assert "NodePorts" in plugins["filter"]
        assert "NodeAffinity" in plugins["filter"]
        # NOT the provider defaults: policy replaces them
        assert "PodTopologySpread" not in plugins["filter"]
        assert set(plugins["score"]) == {
            "NodeResourcesLeastAllocated",
            "NodeResourcesBalancedAllocation",
        }
        # mandatory wiring survives
        assert plugins["queue_sort"] == ["PrioritySort"]
        assert plugins["bind"] == ["DefaultBinder"]
        assert plugins["post_filter"] == ["DefaultPreemption"]

    def test_policy_score_weight_carries(self):
        cfg = policy_to_config({
            "predicates": [{"name": "PodFitsResources"}],
            "priorities": [{"name": "LeastRequestedPriority", "weight": 5}],
        })
        prof = cfg.profiles[0]
        entry = next(e for e in prof.plugins.score.enabled
                     if e.name == "NodeResourcesLeastAllocated")
        assert entry.weight == 5

    def test_policy_nil_lists_use_defaults(self):
        cfg = policy_to_config({})
        prof = cfg.profiles[0]
        filters = {e.name for e in prof.plugins.filter.enabled}
        assert "NodeResourcesFit" in filters
        assert "InterPodAffinity" in filters
        scores = {e.name for e in prof.plugins.score.enabled}
        assert "NodeResourcesLeastAllocated" in scores

    def test_policy_end_to_end_schedules(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n1")
                       .capacity({"cpu": "4", "memory": "8Gi"}).obj())
        cfg = policy_to_config({
            "predicates": [{"name": "PodFitsResources"}],
            "priorities": [{"name": "LeastRequestedPriority", "weight": 1}],
        })
        sched = Scheduler.create(store, config=cfg)
        sched.start()
        store.create_pod(MakePod().name("p").uid("u").req({"cpu": "1"}).obj())
        for _ in range(20):
            sched.queue.flush_backoff_completed()
            if not sched.schedule_one(pop_timeout=0.0):
                break
        sched.wait_for_inflight_bindings()
        sched.stop()
        assert store.get_pod("default", "p").spec.node_name == "n1"

    def test_policy_errors(self):
        with pytest.raises(PolicyError):
            policy_to_config({"predicates": [{"name": "NoSuchPredicate"}]})
        with pytest.raises(PolicyError):
            policy_to_config({"hardPodAffinitySymmetricWeight": 1000})
        with pytest.raises(PolicyError):
            load_policy("{not json")


class TestValidationDepth:
    def test_score_weight_bounds(self):
        cfg = KubeSchedulerConfiguration.from_dict({
            "profiles": [{
                "schedulerName": "default-scheduler",
                "plugins": {"score": {"enabled": [
                    {"name": "NodeResourcesLeastAllocated", "weight": 500},
                ]}},
            }],
        })
        assert any("not in [0,100]" in e for e in cfg.validate())

    def test_single_binder_extender(self):
        cfg = KubeSchedulerConfiguration.from_dict({
            "extenders": [
                {"urlPrefix": "http://a", "bindVerb": "bind"},
                {"urlPrefix": "http://b", "bindVerb": "bind"},
            ],
        })
        assert any("one extender" in e for e in cfg.validate())

    def test_empty_url_prefix(self):
        cfg = KubeSchedulerConfiguration.from_dict({
            "extenders": [{"bindVerb": "bind"}],
        })
        assert any("urlPrefix" in e for e in cfg.validate())


class TestMetricsResources:
    def test_endpoint_exposes_pod_requests(self):
        from kubernetes_tpu.apiserver.rest import APIServer

        store = ClusterStore()
        store.create_pod(
            MakePod().name("p1").uid("u1").node("n1")
            .req({"cpu": "500m", "memory": "256Mi"}).obj())
        server = APIServer(store).start()
        try:
            with urllib.request.urlopen(
                server.url + "/metrics/resources"
            ) as resp:
                text = resp.read().decode()
        finally:
            server.shutdown()
        assert "kube_pod_resource_request" in text
        assert 'pod="p1"' in text and 'resource="cpu"' in text
        assert 'unit="cores"} 0.5' in text
        assert 'resource="memory"' in text


class TestPolicyWeightSemantics:
    def test_same_plugin_weights_accumulate(self):
        """Two legacy priorities mapping to one plugin sum their weights
        (createFromConfig accumulates: SelectorSpreadPriority +
        ServiceSpreadingPriority -> one SelectorSpread entry, weight 5)."""
        cfg = policy_to_config({
            "priorities": [
                {"name": "SelectorSpreadPriority", "weight": 2},
                {"name": "ServiceSpreadingPriority", "weight": 3},
            ],
        })
        prof = cfg.profiles[0]
        entries = [e for e in prof.plugins.score.enabled
                   if e.name == "SelectorSpread"]
        assert len(entries) == 1
        assert entries[0].weight == 5

    def test_zero_weight_rejected(self):
        with pytest.raises(PolicyError):
            policy_to_config({
                "priorities": [
                    {"name": "LeastRequestedPriority", "weight": 0},
                ],
            })
