"""Elastic control plane: live partition resharding, cursor-preserving
watch handoff, hotspot rebalancing, and partition failover.

Covers the layers ISSUE 15 stacked on the PR 9 partitioned fabric:

- the runtime ``PartitionTopology`` (hash slots, epoch-monotonic
  evolution, spread namespaces, wire round-trip);
- ``PartitionedStore(reshardable=True)`` slice migrations — move /
  split / merge / buy / failover — under the bounded freeze-and-drain
  protocol, with the SILENT adopt/evict placement channel (no watch
  events, RVs preserved, WAL-durable);
- the REST surface: the full topology document at
  ``/api/v1/partitiontopology``, epoch-monotonic installs, the
  freeze/ownership write gate answering 429 + computed Retry-After +
  ``X-Partition-Epoch``, and the ``/debug/partition`` admin ops;
- the ``ReshardCoordinator`` driving real migrations over the wire,
  including rollback when a destination dies mid-copy;
- the elastic client: the per-(kind, partition) RV watchdog and
  reflector state surviving a topology-epoch change (the false-
  regression fix), and the cursor-preserving watch handoff;
- the pure ``plan_rebalance`` decision function (split > move > buy,
  failover first, retire when idle);
- the perf_report ``hotspot`` family gates and the ``reshard[...]``
  diag segment round-trip;
- the tier-1 mini-cell: a live 2→3-partition split at ~200 hollow
  nodes with writes and an informer active THROUGH the migration —
  informer ≡ server truth, zero lost, no relist of unmoved slices.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from kubernetes_tpu.apiserver.partition import (
    NUM_SLOTS,
    PartitionedStore,
    PartitionTopology,
    SliceFrozenError,
    slot_for,
)
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.testing import MakeNode, MakePod


def _node(name, cpu="4", memory="8Gi", pods="110"):
    return MakeNode().name(name).capacity(
        {"cpu": cpu, "memory": memory, "pods": pods}).obj()


def _pod(name, ns="default", uid=None, cpu="100m", memory="50Mi"):
    p = MakePod().name(name).uid(uid or f"u-{ns}-{name}").req(
        {"cpu": cpu, "memory": memory}).obj()
    p.metadata.namespace = ns
    return p


# ---------------------------------------------------------------------------
# the runtime topology


class TestTopology:
    def test_default_layout_and_wire_round_trip(self):
        topo = PartitionTopology.default(3, urls=["http://a", "http://b",
                                                  "http://c"])
        assert topo.epoch == 1 and topo.slots == NUM_SLOTS
        assert set(topo.owner) == {0, 1, 2}
        back = PartitionTopology.from_dict(
            json.loads(json.dumps(topo.to_dict())))
        assert back.owner == topo.owner
        assert back.epoch == topo.epoch
        assert back.spread == topo.spread
        assert back.urls == topo.urls

    def test_evolve_bumps_epoch_and_preserves_original(self):
        topo = PartitionTopology.default(2)
        owner = list(topo.owner)
        owner[0] = 1
        nxt = topo.evolve(owner=owner)
        assert nxt.epoch == topo.epoch + 1
        assert topo.owner[0] == 0 and nxt.owner[0] == 1

    def test_namespace_colocated_until_spread(self):
        # unspread: every pod of a namespace shares one slot
        slots = {slot_for("Pod", "tenant-a", n)
                 for n in ("p1", "p2", "p3", "p4")}
        assert len(slots) == 1
        # spread: the namespace fans per object name
        spread = frozenset({"tenant-a"})
        fanned = {slot_for("Pod", "tenant-a", f"p{i}", spread=spread)
                  for i in range(40)}
        assert len(fanned) > 8
        # other namespaces are untouched by the spread set
        assert slot_for("Pod", "tenant-b", "p1") == \
            slot_for("Pod", "tenant-b", "p1", spread=spread)

    def test_non_sharded_kinds_have_no_slot(self):
        assert slot_for("Service", "ns", "x") is None
        topo = PartitionTopology.default(4)
        assert topo.partition_of("ConfigMap", "ns", "x") == 0
        assert topo.partitions_for("Lease") == [0]

    def test_partitions_for_narrows_unspread_namespace(self):
        topo = PartitionTopology.default(4)
        assert len(topo.partitions_for("Pod", "tenant-a")) == 1
        spread = topo.evolve(spread={"tenant-a"})
        assert spread.partitions_for("Pod", "tenant-a") == \
            sorted(set(spread.owner))


# ---------------------------------------------------------------------------
# reshardable PartitionedStore: migrations under the freeze protocol


def _fill(store, namespaces=("ns-a", "ns-b", "ns-c"), per_ns=6):
    for ns in namespaces:
        for i in range(per_ns):
            store.create_pod(_pod(f"p{i}", ns=ns))
    for i in range(4):
        store.add_node(_node(f"n{i}"))


class TestReshardableStore:
    def test_migrate_slots_moves_objects_preserving_rvs(self):
        store = PartitionedStore(partitions=2, reshardable=True)
        _fill(store)
        topo = store.topology
        slot = topo.slot_of("Pod", "ns-a", None)
        src = topo.owner[slot]
        dest = 1 - src
        before = {(p.namespace, p.metadata.name):
                  p.metadata.resource_version
                  for p in store.list_pods("ns-a")}
        report = store.migrate_slots({slot: dest})
        assert report["moved_objects"] >= len(before)
        assert store.topology.epoch == topo.epoch + 1
        assert store.topology.owner[slot] == dest
        # objects now live on the destination, same RVs
        moved = {(p.namespace, p.metadata.name):
                 p.metadata.resource_version
                 for p in store.parts[dest].list_pods("ns-a")}
        for key, rv in before.items():
            assert moved[key] == rv
        # and evicted from the source
        assert not store.parts[src].list_pods("ns-a")
        # router follows the new layout
        assert store.get_pod("ns-a", "p0") is not None

    def test_migration_is_watch_silent(self):
        store = PartitionedStore(partitions=2, reshardable=True)
        _fill(store)
        events = []
        handle = store.watch(events.append)
        topo = store.topology
        slot = topo.slot_of("Pod", "ns-b", None)
        store.migrate_slots({slot: 1 - topo.owner[slot]})
        store.create_pod(_pod("after", ns="ns-b"))
        assert [e.obj.metadata.name for e in events
                if e.kind == "Pod"] == ["after"]
        handle.stop()

    def test_spread_namespace_fans_hot_tenant(self):
        store = PartitionedStore(partitions=3, reshardable=True)
        for i in range(48):
            store.create_pod(_pod(f"hot{i}", ns="hot"))
        report = store.spread_namespace("hot")
        assert "hot" in store.topology.spread
        assert report["moved_objects"] > 0
        holders = [i for i, part in enumerate(store.parts)
                   if part.list_pods("hot")]
        assert len(holders) > 1
        # no key lost or duplicated across the fan
        seen = {}
        for part in store.parts:
            for p in part.list_pods("hot"):
                assert p.metadata.name not in seen
                seen[p.metadata.name] = True
        assert len(seen) == 48

    def test_retire_partition_drains_to_survivors(self):
        store = PartitionedStore(partitions=3, reshardable=True)
        _fill(store)
        store.retire_partition(2)
        assert 2 in store.topology.retired
        assert not store.topology.slots_of_partition(2)
        assert sum(len(part.list_pods()) for part in store.parts[:2]) \
            == len(store.list_pods())
        with pytest.raises(ValueError):
            store.retire_partition(1), store.retire_partition(0)

    def test_add_partition_then_move_routes_and_watches(self):
        store = PartitionedStore(partitions=2, reshardable=True)
        _fill(store)
        events = []
        handle = store.watch(events.append)
        idx = store.add_partition()
        assert idx == 2 and store.partitions == 3
        topo = store.topology
        slot = topo.slot_of("Pod", "ns-c", None)
        store.migrate_slots({slot: idx})
        # a write routed to the NEW partition reaches the fleet watch
        store.create_pod(_pod("fresh", ns="ns-c"))
        assert store.parts[idx].get_pod("ns-c", "fresh") is not None
        assert "fresh" in [e.obj.metadata.name for e in events
                           if e.kind == "Pod"]
        handle.stop()

    def test_frozen_slot_blocks_writer_until_thaw(self):
        store = PartitionedStore(partitions=2, reshardable=True)
        slot = store.topology.slot_of("Pod", "frozen-ns", None)
        with store._freeze_cond:
            store._frozen[slot] = time.monotonic() + 5.0
        landed = threading.Event()

        def writer():
            store.create_pod(_pod("w", ns="frozen-ns"))
            landed.set()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        assert not landed.wait(0.15), "write went through a frozen slot"
        with store._freeze_cond:
            store._frozen.pop(slot, None)
            store._freeze_cond.notify_all()
        assert landed.wait(2.0)
        t.join(timeout=2.0)

    def test_freeze_extension_past_budget_raises_retryable(self):
        # a waiter sleeps until the freeze deadline it observed; if the
        # migration EXTENDED the freeze meanwhile, the waiter's budget
        # is exhausted and it pushes back with a computed retry-after
        # instead of waiting open-endedly
        store = PartitionedStore(partitions=2, reshardable=True)
        slot = store.topology.slot_of("Pod", "stuck-ns", None)
        with store._freeze_cond:
            store._frozen[slot] = time.monotonic() + 0.2

        def extend():
            time.sleep(0.05)
            with store._freeze_cond:
                store._frozen[slot] = time.monotonic() + 30.0

        threading.Thread(target=extend, daemon=True).start()
        with pytest.raises(SliceFrozenError) as exc:
            store._wait_unfrozen(slot)
        assert exc.value.retry_after > 0
        with store._freeze_cond:
            store._frozen.pop(slot, None)
        # an expired freeze auto-thaws: the backstop for a crashed
        # migration that never unfroze
        with store._freeze_cond:
            store._frozen[slot] = time.monotonic() - 0.01
        store._wait_unfrozen(slot)
        assert slot not in store._frozen

    def test_adopt_never_regresses_a_newer_local_write(self):
        store = ClusterStore()
        store.create_pod(_pod("x", ns="a"))
        store.set_pod_phase("a", "x", "Running")   # bump the live RV
        live = store.get_pod("a", "x")
        stale = _pod("x", ns="a")
        stale.metadata.resource_version = "1"
        assert store.adopt_objects("Pod", [stale]) == 0
        assert store.get_pod("a", "x").metadata.resource_version \
            == live.metadata.resource_version
        # an equal-or-newer adopt lands (the migration's normal case)
        newer = _pod("x", ns="a")
        newer.metadata.resource_version = str(
            int(live.metadata.resource_version) + 5)
        assert store.adopt_objects("Pod", [newer]) == 1

    def test_failover_restores_adopted_slice_from_wal(self, tmp_path):
        store = PartitionedStore(partitions=2, reshardable=True)
        store.attach_wal(str(tmp_path))
        _fill(store)
        topo = store.topology
        slot = topo.slot_of("Pod", "ns-a", None)
        src = topo.owner[slot]
        dest = 1 - src
        store.migrate_slots({slot: dest})
        before = {(p.namespace, p.metadata.name):
                  p.metadata.resource_version
                  for p in store.parts[dest].list_pods()}
        epoch_before = store.topology.epoch
        report = store.restart_partition(dest)
        assert report["restored_objects"] >= len(before)
        # the adopted slice survived the failover; the evicted source
        # copies did NOT resurrect
        after = {(p.namespace, p.metadata.name):
                 p.metadata.resource_version
                 for p in store.parts[dest].list_pods()}
        assert after == before
        assert not store.parts[src].list_pods("ns-a")
        assert store.topology.epoch == epoch_before + 1
        # the restored partition keeps serving through the router
        store.create_pod(_pod("post-failover", ns="ns-a"))
        assert store.get_pod("ns-a", "post-failover") is not None

    def test_reshard_stats_feed(self):
        store = PartitionedStore(partitions=2, reshardable=True)
        _fill(store)
        stats = store.reshard_stats()
        assert stats["epoch"] == 1
        assert len(stats["partitions"]) == 2
        assert sum(stats["slot_writes"].values()) > 0
        assert set(stats["ns_writes"]) == {"ns-a", "ns-b", "ns-c"}


# ---------------------------------------------------------------------------
# REST surface + coordinator over real (in-process) servers


def _spin(n):
    from kubernetes_tpu.apiserver.rest import APIServer

    servers = [APIServer(store=ClusterStore(), partition=(i, n)).start()
               for i in range(n)]
    urls = [s.url for s in servers]
    topo = PartitionTopology.default(n, urls=urls)
    for s in servers:
        s.install_topology(topo)
    return servers, urls


class TestRestSurface:
    def test_topology_document_and_epoch_monotonic_install(self):
        servers, urls = _spin(2)
        try:
            from kubernetes_tpu.client.restcluster import (
                RestClusterClient,
            )

            client = RestClusterClient(urls[0], partition_urls=urls)
            try:
                code, doc = client._request(
                    "GET", "/api/v1/partitiontopology")
                assert code == 200
                assert doc["epoch"] == 1 and len(doc["owner"]) == NUM_SLOTS
                assert doc["urls"] == urls
                # stale install refused; newer accepted
                topo = PartitionTopology.from_dict(doc)
                assert not servers[0].install_topology(topo)
                assert servers[0].install_topology(topo.evolve())
                assert servers[0].partition_topology.epoch == 2
            finally:
                client._drop_conn()
        finally:
            for s in servers:
                s.shutdown_server()

    def test_frozen_and_moved_slices_answer_topology_429(self):
        import http.client as hc

        servers, urls = _spin(2)
        try:
            topo = servers[0].partition_topology
            pod = _pod("gated", ns="gate-ns")
            slot = topo.slot_of("Pod", "gate-ns", None)
            owner = topo.owner[slot]
            host, port = urls[owner].split("://")[1].split(":")

            def post(path, body):
                conn = hc.HTTPConnection(host, int(port), timeout=10)
                try:
                    conn.request("POST", path, json.dumps(body),
                                 {"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    return resp.status, dict(resp.getheaders()), \
                        resp.read()
                finally:
                    conn.close()

            wire = {"kind": "Pod",
                    "metadata": {"name": "gated",
                                 "namespace": "gate-ns"},
                    "spec": {}}
            # freeze the slot on its owner: 429 + computed Retry-After
            # and NO epoch header (frozen = the routing is correct,
            # the only cure is waiting — the epoch header is the
            # re-route signal and rides only MOVED rejections)
            servers[owner].frozen_slots[slot] = \
                (time.monotonic() + 3.0, 3.0)
            code, headers, _ = post(
                "/api/v1/namespaces/gate-ns/pods", wire)
            assert code == 429
            assert float(headers["Retry-After"]) > 0
            assert "X-Partition-Epoch" not in headers
            servers[owner].frozen_slots.clear()
            # move the slot away: the old owner answers 429 + new epoch
            new_owner = [1 - topo.owner[slot] if s == slot else o
                         for s, o in enumerate(topo.owner)]
            moved = topo.evolve(owner=new_owner)
            assert servers[owner].install_topology(moved)
            code, headers, body = post(
                "/api/v1/namespaces/gate-ns/pods", wire)
            assert code == 429
            assert int(headers["X-Partition-Epoch"]) == moved.epoch
            assert b"no longer owns" in body
            del pod
        finally:
            for s in servers:
                s.shutdown_server()

    def test_coordinator_move_and_rollback(self):
        from kubernetes_tpu.apiserver.reshard import (
            ReshardCoordinator,
            ReshardError,
        )
        from kubernetes_tpu.client.restcluster import RestClusterClient

        servers, urls = _spin(2)
        client = RestClusterClient(urls[0], partition_urls=urls)
        try:
            assert client.enable_topology(poll_interval=0)
            for i in range(10):
                client.create_object("Pod", _pod(f"m{i}", ns="mv-ns"))
            coordinator = ReshardCoordinator(client, freeze_eta=3.0,
                                             evict_grace_s=0.0)
            topo = coordinator.fetch_topology()
            slot = topo.slot_of("Pod", "mv-ns", None)
            src = topo.owner[slot]
            report = coordinator.move_slots({slot: 1 - src})
            assert report["moved_objects"] >= 10
            assert coordinator.fetch_topology().epoch == topo.epoch + 1
            assert not servers[src].store.list_pods("mv-ns")
            assert len(servers[1 - src].store.list_pods("mv-ns")) == 10
            # rollback: the destination's adopt fails after the copy —
            # the old topology stands, the source keeps its slice, and
            # nothing is half-routed (a SIGKILLed real process is the
            # chaos suite's job; the injected failure pins the
            # protocol deterministically)
            topo2 = coordinator.fetch_topology()
            slot2 = topo2.slot_of("Pod", "mv2-ns", None)
            src2 = topo2.owner[slot2]
            dest2 = 1 - src2
            for i in range(5):
                client.create_object("Pod", _pod(f"r{i}", ns="mv2-ns"))
            orig_admin = coordinator._admin

            def failing_admin(p, payload, _orig=orig_admin):
                if p == dest2 and payload.get("op") == "adopt":
                    raise ReshardError(
                        "injected: destination unreachable")
                return _orig(p, payload)

            coordinator._admin = failing_admin
            with pytest.raises(ReshardError):
                coordinator.move_slots({slot2: dest2})
            coordinator._admin = orig_admin
            assert coordinator.fetch_topology().epoch == topo2.epoch
            assert len(servers[src2].store.list_pods("mv2-ns")) == 5
            assert not servers[dest2].store.list_pods("mv2-ns")
            # and the thaw happened: a post-rollback write lands
            client.create_object("Pod", _pod("thawed", ns="mv2-ns"))
            assert len(servers[src2].store.list_pods("mv2-ns")) == 6
        finally:
            client._stop_watches()
            client._drop_conn()
            for s in servers:
                s.shutdown_server()


# ---------------------------------------------------------------------------
# elastic client: RV watchdog + reflector state across an epoch change


class TestEpochChangeSurvival:
    def test_rv_watchdog_survives_failover_epoch_bump_mid_watch(self):
        """Satellite: partition 1 'fails over' to a FRESH server whose
        store restarts at low RVs while the client is mid-watch. The
        per-(kind, partition) RV watchdog must reset for exactly that
        index — no false regression — and the stream must keep
        delivering through the seam."""
        from kubernetes_tpu.apiserver.rest import APIServer
        from kubernetes_tpu.client.restcluster import RestClusterClient

        servers, urls = _spin(2)
        fresh = None
        client = RestClusterClient(urls[0], partition_urls=urls,
                                   watch_kinds=("Pod",))
        seen = []
        seen_lock = threading.Lock()

        def on_events(evs):
            with seen_lock:
                seen.extend(e.obj.metadata.name for e in evs)

        try:
            assert client.enable_topology(poll_interval=0.1)
            client.watch(lambda e: on_events([e]), batch_fn=on_events)
            time.sleep(0.3)
            # drive RVs on partition 1's namespaces well past zero
            p1_ns = next(
                ns for ns in (f"ns-{i}" for i in range(50))
                if client._topology.partition_of("Pod", ns, None) == 1)
            for i in range(30):
                client.create_object("Pod", _pod(f"hw{i}", ns=p1_ns))
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                with seen_lock:
                    if len([n for n in seen
                            if n.startswith("hw")]) >= 30:
                        break
                time.sleep(0.05)
            # the per-(kind, partition) high-water marks a reflector's
            # lists would have recorded against the OLD partition 1:
            # the fresh server's RVs restart far below 10_000, so a
            # watchdog that carried this across the epoch change would
            # flag a false regression on the handoff list
            with client._rv_lock:
                client._last_rv[("Pod", 0)] = 7
                client._last_rv[("Pod", 1)] = 10_000
            # failover: fresh server, EMPTY store (RVs restart at 0),
            # topology epoch bump re-points partition 1 mid-watch
            fresh = APIServer(store=ClusterStore(),
                              partition=(1, 2)).start()
            topo = client._topology
            new_urls = [urls[0], fresh.url]
            new_topo = topo.evolve(urls=new_urls)
            servers[0].install_topology(new_topo)
            fresh.install_topology(new_topo)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline \
                    and client.topology_epoch < new_topo.epoch:
                time.sleep(0.05)
            assert client.topology_epoch == new_topo.epoch
            time.sleep(0.4)   # the re-plumb's handoff stream attaches
            # the watchdog did NOT flag the restarted partition's low
            # RVs as a regression, and exactly the CHANGED index was
            # reset — the unchanged partition keeps its real
            # monotonicity promise
            assert client.rv_regressions == []
            with client._rv_lock:
                assert client._last_rv.get(("Pod", 0), 0) >= 7
                assert client._last_rv.get(("Pod", 1), 0) < 10_000
            # and the stream keeps delivering from the new endpoint
            client.create_object("Pod", _pod("post-epoch", ns=p1_ns))
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                with seen_lock:
                    if "post-epoch" in seen:
                        break
                time.sleep(0.05)
            with seen_lock:
                assert "post-epoch" in seen
        finally:
            client._stop_watches()
            client._drop_conn()
            for s in servers:
                s.shutdown_server()
            if fresh is not None:
                fresh.shutdown_server()


# ---------------------------------------------------------------------------
# the pure rebalancing planner


class TestPlanRebalance:
    def _mk(self, partitions=3):
        from kubernetes_tpu.autoscaler.partitions import (
            PartitionGroup,
            RebalancePolicy,
        )

        return (PartitionTopology.default(partitions),
                RebalancePolicy(), PartitionGroup())

    def test_failover_beats_everything(self):
        from kubernetes_tpu.autoscaler.partitions import plan_rebalance

        topo, policy, group = self._mk()
        action = plan_rebalance({0: 9999.0}, {"hot": 9999.0}, topo,
                                dead=[2], policy=policy, group=group)
        assert action == {"op": "failover", "partition": 2}

    def test_dominant_namespace_splits(self):
        from kubernetes_tpu.autoscaler.partitions import plan_rebalance

        topo, policy, group = self._mk()
        hot_slot = topo.slot_of("Pod", "hot", None)
        action = plan_rebalance(
            {hot_slot: 800.0}, {"hot": 780.0, "cold": 20.0}, topo,
            dead=[], policy=policy, group=group)
        assert action == {"op": "split", "namespace": "hot"}

    def test_no_dominant_tenant_moves_hot_slots(self):
        from kubernetes_tpu.autoscaler.partitions import plan_rebalance

        topo, policy, group = self._mk()
        hot = 0
        slots = topo.slots_of_partition(hot)[:6]
        slot_rates = {s: 100.0 for s in slots}
        ns_rates = {f"t{i}": 40.0 for i in range(15)}
        action = plan_rebalance(slot_rates, ns_rates, topo, dead=[],
                                policy=policy, group=group)
        assert action["op"] == "move"
        assert set(action["assignments"]).issubset(set(slots))
        assert all(dest != hot
                   for dest in action["assignments"].values())

    def test_saturated_balanced_fleet_buys(self):
        from kubernetes_tpu.autoscaler.partitions import plan_rebalance

        topo, policy, group = self._mk()
        slot_rates = {s: 60.0 for s in range(topo.slots)}
        action = plan_rebalance(slot_rates, {}, topo, dead=[],
                                policy=policy, group=group)
        assert action == {"op": "buy"}
        # pinned fleet: no buy available
        group.max_partitions = 3
        assert plan_rebalance(slot_rates, {}, topo, dead=[],
                              policy=policy, group=group) is None

    def test_idle_fleet_retires_above_floor(self):
        from kubernetes_tpu.autoscaler.partitions import plan_rebalance

        topo, policy, group = self._mk()
        action = plan_rebalance({0: 1.0}, {}, topo, dead=[],
                                policy=policy, group=group)
        assert action is not None and action["op"] == "retire"
        group.min_partitions = 3
        assert plan_rebalance({0: 1.0}, {}, topo, dead=[],
                              policy=policy, group=group) is None

    def test_quiet_or_balanced_fleet_no_action(self):
        from kubernetes_tpu.autoscaler.partitions import plan_rebalance

        topo, policy, group = self._mk()
        group.min_partitions = 3
        assert plan_rebalance({}, {}, topo, dead=[],
                              policy=policy, group=group) is None
        balanced = {s: 5.0 for s in range(topo.slots)}
        assert plan_rebalance(balanced, {}, topo, dead=[],
                              policy=policy, group=group) is None

    def test_inproc_buy_grows_and_drains(self):
        from kubernetes_tpu.autoscaler.partitions import (
            InprocElasticDriver,
        )

        store = PartitionedStore(partitions=2, reshardable=True)
        _fill(store)
        driver = InprocElasticDriver(store)
        report = driver.apply({"op": "buy"})
        assert report["new_partition"] == 2
        assert store.partitions == 3
        assert store.topology.slots_of_partition(2)


# ---------------------------------------------------------------------------
# diag + perf_report family


class TestReshardDiagAndReport:
    def test_reshard_diag_round_trip(self):
        from kubernetes_tpu.harness import diagfmt

        seg = diagfmt.format_reshard({
            "moves": 3, "frozen_ms": 214.7, "epoch": 5,
            "lost_watches": 0})
        parsed = diagfmt.parse_diag(diagfmt.format_diag([seg]))
        assert parsed["reshard"]["moves"] == 3
        assert parsed["reshard"]["frozen_ms"] == pytest.approx(214.7)
        assert parsed["reshard"]["epoch"] == 5
        assert parsed["reshard"]["lost_watches"] == 0

    def _row(self, tmp_path, **extra):
        import os

        base = {
            "metric": ("hotspot_recovery[3p, one namespace 80% of "
                       "24000 writes, elastic control plane]"),
            "value": 0.91, "unit": "ratio", "recovery_ratio": 0.91,
            "lost_watches": 0, "invariants_ok": True,
            "invariants": {"lost_pods": 0, "duplicated_pods": 0,
                           "lost_watches": 0, "unmoved_relists": 0,
                           "rv_regressions": 0,
                           "rebalancer_acted": True},
        }
        base.update(extra)
        tail = "\n".join([
            "[hotspot] rebalanced arm: split committed",
            "    diag: reshard[moves=1 frozen_ms=812.0 epoch=2 "
            "lost_watches=0]",
            json.dumps(base),
        ])
        doc = {"n": 1, "cmd": "timeout 3600 python bench.py", "rc": 0,
               "tail": tail}
        with open(os.path.join(str(tmp_path), "BENCH_r01.json"),
                  "w") as f:
            json.dump(doc, f)

    def test_green_hotspot_row_passes_strict(self, tmp_path):
        from tools.perf_report import hotspot_flags, load_rounds, main

        self._row(tmp_path)
        assert hotspot_flags(load_rounds(str(tmp_path))) == []
        assert main(["--dir", str(tmp_path), "--strict"]) == 0

    def test_lost_watches_gate_strict(self, tmp_path):
        from tools.perf_report import hotspot_flags, load_rounds, main

        self._row(tmp_path, lost_watches=4)
        (flag,) = hotspot_flags(load_rounds(str(tmp_path)))
        assert "lost_watches=4" in flag["problems"][0]
        assert main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_low_recovery_and_failed_invariants_flagged(self, tmp_path):
        from tools.perf_report import hotspot_flags, load_rounds, main

        self._row(tmp_path, value=0.55, recovery_ratio=0.55,
                  invariants_ok=False,
                  invariants={"lost_pods": 0, "duplicated_pods": 2,
                              "rebalancer_acted": False})
        (flag,) = hotspot_flags(load_rounds(str(tmp_path)))
        probs = " ".join(flag["problems"])
        assert "0.550 < 0.8" in probs
        assert "duplicated_pods" in probs
        assert "rebalancer_acted" in probs
        assert main(["--dir", str(tmp_path), "--strict"]) == 1


# ---------------------------------------------------------------------------
# compressed chaos cells (the full seeded matrix rides
# tools/chaos_matrix.py --suite reshard; sigkill spawns real processes
# and stays behind the slow/chaos markers)


class TestReshardChaosCells:
    def test_midstorm_cell(self):
        from kubernetes_tpu.harness.chaos_reshard import (
            run_reshard_midstorm,
        )

        r = run_reshard_midstorm(11)
        assert r["ok"], r["failure"]
        assert r["stats"]["migrations"] == 3
        assert r["stats"]["moved"] > 0

    def test_rebalance_cell(self):
        from kubernetes_tpu.harness.chaos_reshard import (
            run_reshard_rebalance,
        )

        r = run_reshard_rebalance(11)
        assert r["ok"], r["failure"]
        assert "split" in r["stats"]["actions"]
        assert r["stats"]["hot_partitions"] > 1


@pytest.mark.slow
@pytest.mark.chaos
class TestReshardChaosSigkill:
    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_sigkill_mid_migration(self, seed):
        from kubernetes_tpu.harness.chaos_reshard import (
            run_reshard_sigkill,
        )

        r = run_reshard_sigkill(seed)
        assert r["ok"], r["failure"]
        assert r["stats"]["outcome"]


# ---------------------------------------------------------------------------
# the tier-1 mini-cell: live 2→3 split under writes + informer


class TestReshardMiniCell:
    def test_live_split_zero_loss_no_relist(self):
        from kubernetes_tpu.harness.hotspot import run_reshard_mini_cell

        r = run_reshard_mini_cell()
        assert r["errors"] == []
        assert r["confirmed"] > 0
        # informer ≡ server truth at quiesce: nothing missing, nothing
        # extra, nothing stale — the zero-lost-watch-events bar
        assert r["lost_watches"] == 0, (r["missing"], r["extra"],
                                        r["stale"])
        assert r["informer_pods"] == r["server_pods"] == r["confirmed"]
        assert r["duplicates"] == 0
        assert r["informer_nodes"] == r["nodes"] == 200
        # unmoved slices never relisted through the migration
        assert r["unmoved_relists"] == 0
        assert r["rv_regressions"] == []
        # the split moved real keyspace under a bounded freeze
        assert r["moved_objects"] > 0
        assert 0 < r["frozen_ms"] < 5000
        assert r["epoch"] >= 3
