"""End-to-end scheduler tests (the "integration ring" of the reference:
in-process state server + real scheduler, no kubelets — SURVEY.md section 4
carry-over (b))."""

import time

import pytest

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config.types import (
    KubeSchedulerConfiguration,
    KubeSchedulerProfile,
    PluginEntry,
    Plugins,
    PluginSet,
)
from kubernetes_tpu.scheduler.framework import interface as fw
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


def make_scheduler(store, config=None, **kwargs):
    sched = Scheduler.create(store, config=config, **kwargs)
    sched.start()
    return sched


def drain(sched, timeout=10.0):
    """Run scheduling cycles until active+backoff queues are empty (flushing
    backoff as the wall clock allows), then wait for in-flight bindings."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sched.queue.flush_backoff_completed()
        if sched.schedule_one(pop_timeout=0.0):
            continue
        if sched.queue.num_active() == 0 and sched.queue.num_backoff() == 0:
            break
        time.sleep(0.05)
    else:
        raise AssertionError("scheduler did not drain in time")
    assert sched.wait_for_inflight_bindings()


class TestBasicScheduling:
    def test_single_pod_binds(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n1").capacity({"cpu": "4", "memory": "8Gi"}).obj())
        sched = make_scheduler(store)
        store.create_pod(MakePod().name("p1").req({"cpu": "1"}).obj())
        drain(sched)
        assert store.get_pod("default", "p1").spec.node_name == "n1"
        sched.stop()

    def test_spreads_over_nodes_least_allocated(self):
        store = ClusterStore()
        for i in range(4):
            store.add_node(
                MakeNode().name(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"}).obj()
            )
        sched = make_scheduler(store)
        for i in range(8):
            store.create_pod(MakePod().name(f"p{i}").req({"cpu": "1"}).obj())
        drain(sched)
        placement = {}
        for i in range(8):
            node = store.get_pod("default", f"p{i}").spec.node_name
            placement[node] = placement.get(node, 0) + 1
        # LeastAllocated + BalancedAllocation spread 8 pods over 4 nodes
        assert all(count == 2 for count in placement.values()), placement
        sched.stop()

    def test_unschedulable_pod_stays_pending(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n1").capacity({"cpu": "1", "memory": "1Gi"}).obj())
        sched = make_scheduler(store)
        store.create_pod(MakePod().name("big").req({"cpu": "8"}).obj())
        drain(sched)
        pod = store.get_pod("default", "big")
        assert pod.spec.node_name == ""
        conds = {c.type: c for c in pod.status.conditions}
        assert conds["PodScheduled"].status == "False"
        assert "Insufficient cpu" in conds["PodScheduled"].message
        assert sched.queue.num_unschedulable() == 1
        sched.stop()

    def test_node_add_wakes_unschedulable_pod(self):
        store = ClusterStore()
        sched = make_scheduler(store)
        store.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
        drain(sched)
        assert sched.queue.num_unschedulable() == 1
        store.add_node(MakeNode().name("n1").capacity({"cpu": "4", "memory": "8Gi"}).obj())
        # move event sends it to backoff (1s); wait out the backoff
        deadline = time.time() + 5
        while time.time() < deadline:
            sched.queue.flush_backoff_completed()
            if sched.schedule_one(pop_timeout=0.05):
                break
        assert sched.wait_for_inflight_bindings()
        assert store.get_pod("default", "p").spec.node_name == "n1"
        sched.stop()

    def test_affinity_workload(self):
        store = ClusterStore()
        for zone, names in (("za", ["a1", "a2"]), ("zb", ["b1", "b2"])):
            for n in names:
                store.add_node(
                    MakeNode().name(n)
                    .label("topology.kubernetes.io/zone", zone)
                    .capacity({"cpu": "8", "memory": "16Gi"}).obj()
                )
        sched = make_scheduler(store)
        store.create_pod(
            MakePod().name("db").label("app", "db").req({"cpu": "1"}).obj()
        )
        drain(sched)
        db_node = store.get_pod("default", "db").spec.node_name
        db_zone = store.get_node(db_node).metadata.labels["topology.kubernetes.io/zone"]

        store.create_pod(
            MakePod().name("web").req({"cpu": "1"})
            .pod_affinity("app", ["db"], "topology.kubernetes.io/zone").obj()
        )
        drain(sched)
        web_node = store.get_pod("default", "web").spec.node_name
        web_zone = store.get_node(web_node).metadata.labels["topology.kubernetes.io/zone"]
        assert web_zone == db_zone
        sched.stop()

    def test_anti_affinity_excludes_node(self):
        store = ClusterStore()
        for n in ("n1", "n2"):
            store.add_node(
                MakeNode().name(n).capacity({"cpu": "8", "memory": "16Gi"}).obj()
            )
        sched = make_scheduler(store)
        store.create_pod(
            MakePod().name("a").label("app", "x").req({"cpu": "1"}).obj()
        )
        drain(sched)
        first = store.get_pod("default", "a").spec.node_name
        store.create_pod(
            MakePod().name("b").label("app", "x").req({"cpu": "1"})
            .pod_anti_affinity("app", ["x"], "kubernetes.io/hostname").obj()
        )
        drain(sched)
        second = store.get_pod("default", "b").spec.node_name
        assert second != first
        sched.stop()

    def test_topology_spread_workload(self):
        store = ClusterStore()
        for zone in ("za", "zb", "zc"):
            store.add_node(
                MakeNode().name(f"{zone}-n")
                .label("topology.kubernetes.io/zone", zone)
                .capacity({"cpu": "16", "memory": "32Gi"}).obj()
            )
        sched = make_scheduler(store)
        for i in range(6):
            store.create_pod(
                MakePod().name(f"p{i}").label("app", "spread").req({"cpu": "1"})
                .spread_constraint(
                    1, "topology.kubernetes.io/zone", "DoNotSchedule",
                    {"app": "spread"},
                ).obj()
            )
            drain(sched)  # schedule one-by-one so counts are visible
        zones = {}
        for i in range(6):
            node = store.get_pod("default", f"p{i}").spec.node_name
            zone = store.get_node(node).metadata.labels["topology.kubernetes.io/zone"]
            zones[zone] = zones.get(zone, 0) + 1
        assert all(c == 2 for c in zones.values()), zones
        sched.stop()


class TestPreemption:
    def test_higher_priority_preempts(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n1").capacity({"cpu": "2", "memory": "4Gi"}).obj())
        sched = make_scheduler(store)
        store.create_pod(
            MakePod().name("victim").priority(1).req({"cpu": "2"}).obj()
        )
        drain(sched)
        assert store.get_pod("default", "victim").spec.node_name == "n1"

        store.create_pod(
            MakePod().name("vip").priority(100).req({"cpu": "2"}).obj()
        )
        drain(sched)
        # victim evicted, vip nominated to n1
        assert store.get_pod("default", "victim") is None
        vip = store.get_pod("default", "vip")
        assert vip.status.nominated_node_name == "n1"
        # next cycle schedules vip onto the freed node
        drain(sched)
        assert store.get_pod("default", "vip").spec.node_name == "n1"
        sched.stop()

    def test_preemption_policy_never(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n1").capacity({"cpu": "2", "memory": "4Gi"}).obj())
        sched = make_scheduler(store)
        store.create_pod(MakePod().name("victim").priority(1).req({"cpu": "2"}).obj())
        drain(sched)
        vip = MakePod().name("gentle").priority(100).req({"cpu": "2"}).obj()
        vip.spec.preemption_policy = "Never"
        store.create_pod(vip)
        drain(sched)
        assert store.get_pod("default", "victim") is not None
        assert store.get_pod("default", "gentle").status.nominated_node_name == ""
        sched.stop()


class TestGangScheduling:
    def _gang_pod(self, name, group, min_available):
        return (
            MakePod().name(name)
            .label("pod-group.scheduling.k8s.io/name", group)
            .label("pod-group.scheduling.k8s.io/min-available", str(min_available))
            .req({"cpu": "1"})
            .obj()
        )

    def test_gang_waits_then_binds_together(self):
        store = ClusterStore()
        for i in range(3):
            store.add_node(
                MakeNode().name(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"}).obj()
            )
        profile = KubeSchedulerProfile(
            plugins=Plugins(permit=PluginSet(enabled=[PluginEntry("Coscheduling")])),
        )
        config = KubeSchedulerConfiguration(profiles=[profile])
        sched = make_scheduler(store, config=config)
        store.create_pod(self._gang_pod("g1", "team", 2))
        while sched.schedule_one(pop_timeout=0.0):
            pass
        # first member waits at permit: not bound yet
        assert store.get_pod("default", "g1").spec.node_name == ""
        store.create_pod(self._gang_pod("g2", "team", 2))
        drain(sched)
        assert store.get_pod("default", "g1").spec.node_name != ""
        assert store.get_pod("default", "g2").spec.node_name != ""
        sched.stop()

    def test_queue_sort_coorders_gang_members(self):
        """CoschedulingSort drains a gang's members contiguously even
        when their creation interleaves with another gang's (the
        out-of-tree plugin's queue-sort behavior): interleaving is the
        gang starvation mode."""
        from kubernetes_tpu.scheduler.framework.plugins.coscheduling import (
            CoschedulingSort,
        )
        from kubernetes_tpu.scheduler.types import QueuedPodInfo

        sort = CoschedulingSort()
        qpis = []
        for i in range(3):  # a0 b0 a1 b1 a2 b2 (interleaved arrival)
            for g in ("a", "b"):
                qpi = QueuedPodInfo(self._gang_pod(f"{g}{i}", f"gang-{g}", 3))
                qpi.timestamp = float(len(qpis))
                qpis.append(qpi)
        ordered = sorted(qpis, key=sort.sort_key)
        names = [q.pod.name for q in ordered]
        assert names == ["a0", "a1", "a2", "b0", "b1", "b2"], names

    def test_partial_gang_rejected_together_then_backs_off(self):
        """A partial gang must not squat at Permit: when the first
        member's permit times out, every waiting member is rejected in
        the same instant, and the gang backs off (PreFilter fails fast)
        until the window expires — after which a completed gang binds."""
        from kubernetes_tpu.config.types import PluginConfig

        store = ClusterStore()
        for i in range(4):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "4", "memory": "8Gi"}).obj()
            )
        profile = KubeSchedulerProfile(
            plugin_config=[PluginConfig("Coscheduling", {
                "permitWaitSeconds": 0.3,
                "gangBackoffSeconds": 0.5,
            })],
        )
        config = KubeSchedulerConfiguration(profiles=[profile])
        sched = make_scheduler(store, config=config,
                               provider="GangSchedulingProvider")
        # only 3 of 4 members exist: the gang can never complete
        for i in range(3):
            store.create_pod(self._gang_pod(f"m{i}", "squad", 4))
        while sched.schedule_one(pop_timeout=0.0):
            pass
        t0 = time.monotonic()
        # permit timeout fires for the first member; the plugin must
        # reject the OTHER waiting members immediately (not one timeout
        # each), so all three come back well before 3 x 0.3s
        sched.wait_for_inflight_bindings(timeout=5.0)
        assert time.monotonic() - t0 < 0.9
        assert all(
            not store.get_pod("default", f"m{i}").spec.node_name
            for i in range(3)
        )
        # while backing off, members fail fast at PreFilter
        gang = sched.profiles["default-scheduler"].get_plugin("Coscheduling")
        from kubernetes_tpu.scheduler.framework.cycle_state import CycleState

        st = gang.pre_filter(CycleState(), store.get_pod("default", "m0"))
        assert st is not None and not st.is_success()
        # after the backoff window, the COMPLETED gang binds
        time.sleep(0.6)
        store.create_pod(self._gang_pod("m3", "squad", 4))
        drain(sched, timeout=15.0)
        sched.wait_for_inflight_bindings()
        bound = [store.get_pod("default", f"m{i}").spec.node_name
                 for i in range(4)]
        assert all(bound), bound
        sched.stop()

    def test_gang_sort_prevents_interleaved_gang_deadlock(self):
        """Capacity for one gang only, two gangs' members interleaved:
        with gang-aware sorting one gang admits fully and binds; the
        other stays pending. (With plain FIFO both gangs half-reserve
        and neither can complete until permit timeouts fire.)"""
        store = ClusterStore()
        for i in range(4):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "1", "memory": "8Gi"}).obj()
            )  # 4 one-cpu slots: exactly one 4-pod gang fits
        sched = make_scheduler(store, provider="GangSchedulingProvider")
        for i in range(4):  # interleaved: a0 b0 a1 b1 ...
            store.create_pod(self._gang_pod(f"a{i}", "gang-a", 4))
            store.create_pod(self._gang_pod(f"b{i}", "gang-b", 4))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            sched.queue.flush_backoff_completed()
            if sched.schedule_one(pop_timeout=0.0):
                continue
            a_bound = sum(
                1 for i in range(4)
                if store.get_pod("default", f"a{i}").spec.node_name
            )
            if a_bound == 4:
                break
            time.sleep(0.02)
        sched.wait_for_inflight_bindings()
        a_bound = [store.get_pod("default", f"a{i}").spec.node_name
                   for i in range(4)]
        b_bound = [store.get_pod("default", f"b{i}").spec.node_name
                   for i in range(4)]
        assert all(a_bound), a_bound      # first gang complete
        assert not any(b_bound), b_bound  # second gang untouched
        sched.stop()


class TestMultiProfile:
    def test_second_profile(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n1").capacity({"cpu": "4", "memory": "8Gi"}).obj())
        config = KubeSchedulerConfiguration(
            profiles=[
                KubeSchedulerProfile(scheduler_name="default-scheduler"),
                KubeSchedulerProfile(scheduler_name="custom-scheduler"),
            ]
        )
        sched = make_scheduler(store, config=config)
        store.create_pod(
            MakePod().name("p").scheduler_name("custom-scheduler").req({"cpu": "1"}).obj()
        )
        store.create_pod(
            MakePod().name("q").scheduler_name("other-scheduler").req({"cpu": "1"}).obj()
        )
        drain(sched)
        assert store.get_pod("default", "p").spec.node_name == "n1"
        # not our pod: untouched
        assert store.get_pod("default", "q").spec.node_name == ""
        sched.stop()


class TestSchedulerLeaderElection:
    """HA wiring (reference cmd/kube-scheduler/app/server.go:199-208):
    only the lease holder schedules; a deposed leader stops for good;
    two instances never double-bind."""

    def test_only_leader_schedules_and_failover(self):
        import time as _time

        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.scheduler.scheduler import Scheduler
        from kubernetes_tpu.testing import MakeNode, MakePod

        store = ClusterStore()
        store.add_node(MakeNode().name("n1")
                       .capacity({"cpu": "64", "memory": "64Gi"}).obj())
        a = Scheduler.create(store)
        b = Scheduler.create(store)
        ea = a.run_with_leader_election(
            identity="sched-a", lease_duration=0.5, renew_deadline=0.4,
            retry_period=0.05)
        _time.sleep(0.2)  # a acquires first
        eb = b.run_with_leader_election(
            identity="sched-b", lease_duration=0.5, renew_deadline=0.4,
            retry_period=0.05)
        _time.sleep(0.2)
        assert ea.is_leader and not eb.is_leader

        for i in range(8):
            store.create_pod(MakePod().name(f"w1-{i}").uid(f"w1u{i}")
                             .req({"cpu": "100m"}).obj())
        deadline = _time.time() + 15
        while _time.time() < deadline and any(
            not p.spec.node_name for p in store.list_pods()
        ):
            _time.sleep(0.05)
        assert all(p.spec.node_name for p in store.list_pods())
        # only A attempted/bound them
        assert b.metrics.schedule_attempts.get(
            "scheduled", "default-scheduler") == 0

        # leader dies: lease expires, B takes over; A must not come back.
        # Scheduler.stop() alone must stop the elector too — a stopped
        # scheduler that kept renewing would block failover forever.
        a.stop()
        deadline = _time.time() + 10
        while _time.time() < deadline and not eb.is_leader:
            _time.sleep(0.05)
        assert eb.is_leader
        for i in range(8):
            store.create_pod(MakePod().name(f"w2-{i}").uid(f"w2u{i}")
                             .req({"cpu": "100m"}).obj())
        deadline = _time.time() + 15
        while _time.time() < deadline and any(
            not p.spec.node_name for p in store.list_pods()
        ):
            _time.sleep(0.05)
        assert all(p.spec.node_name for p in store.list_pods())
        assert b.metrics.schedule_attempts.get(
            "scheduled", "default-scheduler") == 8
        b.stop()
        eb.stop()

    def test_lost_lease_is_fatal(self):
        import time as _time

        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.scheduler.scheduler import Scheduler

        store = ClusterStore()
        s = Scheduler.create(store)
        es = s.run_with_leader_election(
            identity="sched-x", lease_duration=0.4, renew_deadline=0.3,
            retry_period=0.05)
        _time.sleep(0.2)
        assert es.is_leader
        # usurp the lease (another instance force-acquires far in the
        # future so renewal fails)
        store.try_acquire_or_renew("kube-scheduler", "usurper",
                                   _time.monotonic() + 3600, 3600)
        deadline = _time.time() + 10
        while _time.time() < deadline and not s.lost_lease:
            _time.sleep(0.05)
        assert s.lost_lease
        assert s._stop.is_set()  # fatal-style stop


class TestGangRecreation:
    def test_recreated_gang_regates_at_permit(self):
        """Deleting a bound gang and resubmitting under the same group
        name must NOT inherit the old arrival count — the new gang's
        first member has to wait for siblings again."""
        store = ClusterStore()
        for i in range(4):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "8", "memory": "8Gi"}).obj()
            )
        sched = make_scheduler(store, provider="GangSchedulingProvider")

        def gang_pod(name, uid):
            return (
                MakePod().name(name).uid(uid)
                .label("pod-group.scheduling.k8s.io/name", "team")
                .label("pod-group.scheduling.k8s.io/min-available", "2")
                .req({"cpu": "1"}).obj()
            )

        store.create_pod(gang_pod("g1", "u1"))
        store.create_pod(gang_pod("g2", "u2"))
        drain(sched)
        sched.wait_for_inflight_bindings()
        assert store.get_pod("default", "g1").spec.node_name
        assert store.get_pod("default", "g2").spec.node_name
        # delete the whole bound gang
        store.delete_pod("default", "g1")
        store.delete_pod("default", "g2")
        time.sleep(0.1)
        # resubmit ONE member of a new gang with the same name: it must
        # wait at Permit (not ride the stale count straight to bind)
        store.create_pod(gang_pod("h1", "u3"))
        while sched.schedule_one(pop_timeout=0.0):
            pass
        assert store.get_pod("default", "h1").spec.node_name == ""
        # second member completes the gang
        store.create_pod(gang_pod("h2", "u4"))
        drain(sched)
        sched.wait_for_inflight_bindings()
        assert store.get_pod("default", "h1").spec.node_name
        assert store.get_pod("default", "h2").spec.node_name
        sched.stop()
