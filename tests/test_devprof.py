"""Device/solver profiling layer (observability/devprof.py): cycle
lifecycle, compile detection (jax.monitoring listener + timing
heuristic), metrics mirroring, the KTPU_TELEMETRY JSONL stream, and the
bench-row ``telemetry`` sub-object guard."""

import json
import os

import pytest

from kubernetes_tpu.observability.devprof import (
    DevProfiler,
    get_devprof,
    set_devprof,
)


@pytest.fixture
def prof():
    """A fresh profiler installed as the process default (the compile
    listener routes through ``get_devprof``), restored afterwards."""
    prev = get_devprof()
    p = DevProfiler(enabled=True, use_listener=False)
    set_devprof(p)
    yield p
    set_devprof(prev)


@pytest.fixture
def fresh_jax_cache(tmp_path):
    """Point the persistent XLA compile cache at an empty dir: a
    compile-event test must actually compile, not deserialize a binary
    cached by an earlier run (cache hits emit no compile event — that
    is devprof's 'actual recompiles' semantics, but here we need one)."""
    import jax

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", str(tmp_path))
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


class TestCycleLifecycle:
    def test_phases_and_bytes_accumulate(self, prof):
        rec = prof.begin_cycle(cycle=7, pad=256, real=100)
        prof.phase("encode", 0.01)
        prof.phase("encode", 0.02)
        prof.phase("dispatch", 0.005)
        prof.phase("block", 0.1)
        prof.add_bytes("h2d", 1000)
        prof.add_bytes("d2h", 64)
        prof.end_cycle(rec)
        (cycle,) = prof.cycles()
        assert cycle["cycle"] == 7
        assert cycle["encode_s"] == pytest.approx(0.03)
        assert cycle["block_s"] == pytest.approx(0.1)
        assert cycle["h2d_bytes"] == 1000 and cycle["d2h_bytes"] == 64

    def test_pending_block_completes_via_note_block(self, prof):
        """Lazy solves materialize cycles later in the commit pipeline:
        the record stays open until the timed materializer reports the
        measured block_until_ready wait."""
        rec = prof.begin_cycle(cycle=1, pad=128, real=128)
        prof.phase("dispatch", 0.002)
        prof.end_cycle(rec, pending_block=True)
        assert prof.cycles() == []          # not complete yet
        prof.note_block(rec, 0.25, d2h_bytes=512)
        (cycle,) = prof.cycles()
        assert cycle["block_s"] == pytest.approx(0.25)
        assert cycle["d2h_bytes"] == 512

    def test_abort_drops_record(self, prof):
        rec = prof.begin_cycle(cycle=1, pad=64, real=10)
        prof.abort(rec)
        assert prof.cycles(include_warming=True) == []
        # a later phase call must not resurrect the aborted record
        prof.phase("encode", 1.0)
        assert prof.cycles(include_warming=True) == []

    def test_disabled_is_noop(self):
        p = DevProfiler(enabled=False, use_listener=False)
        assert p.begin_cycle(cycle=1) is None
        p.phase("encode", 1.0)          # must not raise
        p.end_cycle(None)
        assert p.cycles() == []

    def test_warming_cycles_excluded_from_summary(self, prof):
        rec = prof.begin_cycle(cycle=-1, pad=128, real=8, warming=True)
        prof.phase("block", 5.0)
        prof.end_cycle(rec)
        rec = prof.begin_cycle(cycle=1, pad=128, real=64)
        prof.phase("block", 0.1)
        prof.phase("dispatch", 0.1)
        prof.end_cycle(rec)
        s = prof.summary()
        assert s["cycles"] == 1
        assert s["block_s"] == pytest.approx(0.1)
        assert len(prof.cycles(include_warming=True)) == 2


class TestSummary:
    def _cycle(self, prof, cycle, pad, real, block, dispatch=0.01,
               encode=0.01, rebuild="none"):
        rec = prof.begin_cycle(cycle=cycle, pad=pad, real=real,
                               rebuild=rebuild)
        prof.phase("encode", encode)
        prof.phase("dispatch", dispatch)
        prof.phase("block", block)
        prof.add_bytes("h2d", 100)
        prof.end_cycle(rec)

    def test_wait_share_pad_waste_and_max_cycle(self, prof):
        self._cycle(prof, 1, pad=256, real=128, block=0.08)
        self._cycle(prof, 2, pad=256, real=256, block=1.0,
                    rebuild="full")
        s = prof.summary()
        assert s["cycles"] == 2
        # block dominates: 1.08 of 1.12 total phase seconds
        assert s["device_wait_share"] == pytest.approx(
            1.08 / 1.12, abs=0.01)
        # 384 real rows over 512 padded
        assert s["pad_waste_pct"] == pytest.approx(25.0)
        assert s["max_cycle"]["cycle"] == 2
        assert s["max_cycle"]["rebuild"] == "full"
        assert s["h2d_bytes"] == 200

    def test_max_cycle_phase_attribution(self, prof):
        from kubernetes_tpu.harness.diagfmt import max_cycle_phase

        self._cycle(prof, 1, pad=64, real=64, block=0.5)
        s = prof.summary()
        assert max_cycle_phase(s["max_cycle"]) == "block"
        assert max_cycle_phase({"compiles": 2}) == "compile"

    def test_reset_clears_window(self, prof):
        self._cycle(prof, 1, pad=64, real=64, block=0.1)
        prof.unexpected_compiles = 3
        prof.reset(workload="next-row")
        assert prof.summary()["cycles"] == 0
        assert prof.unexpected_compiles == 0
        assert prof.workload == "next-row"


class TestCompileDetection:
    def test_listener_counts_real_compile_in_cycle(self, prof,
                                                   fresh_jax_cache):
        """A real XLA compilation inside an open cycle lands on that
        cycle's record via the process-wide jax.monitoring listener."""
        import jax
        import jax.numpy as jnp

        p = DevProfiler(enabled=True)   # listener ON
        set_devprof(p)
        if not p.listener_active:
            pytest.skip("jax.monitoring listener unavailable")
        rec = p.begin_cycle(cycle=1, pad=16, real=16)
        jax.jit(lambda x: x * 3.5 + 17.25)(jnp.arange(16.0))
        p.end_cycle(rec)
        (cycle,) = p.cycles()
        assert cycle["compiles"] >= 1
        assert cycle["compile_s"] > 0.0

    def test_background_compiles_counted_separately(self, prof,
                                                    fresh_jax_cache):
        import jax
        import jax.numpy as jnp

        p = DevProfiler(enabled=True)
        set_devprof(p)
        if not p.listener_active:
            pytest.skip("jax.monitoring listener unavailable")
        before = p.background_compiles
        jax.jit(lambda x: x * 2.5 - 3.125)(jnp.arange(8.0))
        assert p.background_compiles > before
        assert p.cycles() == []

    def test_unexpected_compile_increments_metric(self, prof):
        """The forbidden case: a compile inside a MEASURED cycle bumps
        solver_unexpected_compiles_total (and drops a flight dump)."""
        from kubernetes_tpu.metrics.solver_metrics import solver_metrics

        sm = solver_metrics()
        before = sm.unexpected_compiles_total.get()
        prof.listener_active = True     # trust on_compile attribution
        rec = prof.begin_cycle(cycle=9, pad=512, real=400)
        prof.on_compile(1.5)
        prof.end_cycle(rec)
        assert prof.unexpected_compiles == 1
        assert sm.unexpected_compiles_total.get() == before + 1

    def test_compile_after_abort_is_background(self, prof):
        """An aborted cycle (encode fell through, solver raised) must
        not soak up later compile events: they count as background, not
        as compiles of a dead record."""
        prof.listener_active = True
        rec = prof.begin_cycle(cycle=1, pad=64, real=10)
        prof.abort(rec)
        prof.on_compile(1.0)
        assert prof.background_compiles == 1
        assert prof.unexpected_compiles == 0
        assert rec["compiles"] == 0

    def test_warm_compile_goes_to_warm_ledger(self, prof):
        prof.listener_active = True
        rec = prof.begin_cycle(cycle=-1, pad=512, real=8, warming=True)
        prof.on_compile(2.0)
        prof.end_cycle(rec)
        assert prof.warm_compiles == 1
        assert prof.unexpected_compiles == 0

    def test_heuristic_flags_outlier_cycle(self, prof):
        """No listener API: a warmed bucket's 4x + 250ms excursion is
        attributed a suspected compile; ordinary jitter is not."""
        assert not prof.listener_active
        for i in range(3):
            rec = prof.begin_cycle(cycle=i, pad=256, real=256)
            prof.phase("block", 0.1)
            prof.end_cycle(rec)
        rec = prof.begin_cycle(cycle=3, pad=256, real=256)
        prof.phase("block", 0.15)       # jitter: inside the band
        prof.end_cycle(rec)
        assert prof.unexpected_compiles == 0
        rec = prof.begin_cycle(cycle=4, pad=256, real=256)
        prof.phase("block", 2.0)        # 20x + >250ms: compile-shaped
        prof.end_cycle(rec)
        assert prof.unexpected_compiles == 1
        assert prof.cycles()[-1]["compile_suspected"] is True


class TestDonatedBytes:
    """Donated/persistent device buffers must not be counted as
    transfers (sharded-by-default satellite): the tentpole's proof
    metric — ``solver_transfer_bytes_total`` strictly lower with
    donation on — would lie if resident planes were booked as
    re-uploads every cycle."""

    def test_donated_bytes_excluded_from_transfer_totals(self, prof):
        from kubernetes_tpu.metrics.solver_metrics import solver_metrics

        sm = solver_metrics()
        h2d_before = sm.transfer_bytes_total.get("h2d")
        d2h_before = sm.transfer_bytes_total.get("d2h")
        rec = prof.begin_cycle(cycle=1, pad=64, real=64)
        prof.add_bytes("h2d", 1_000)        # the pod stream: a real upload
        prof.add_bytes("donated", 50_000)   # resident donated planes
        prof.end_cycle(rec)
        (cycle,) = prof.cycles()
        # the record keeps the two ledgers apart
        assert cycle["h2d_bytes"] == 1_000
        assert cycle["donated_bytes"] == 50_000
        # the /metrics mirror counts ONLY the real transfer
        assert sm.transfer_bytes_total.get("h2d") == h2d_before + 1_000
        assert sm.transfer_bytes_total.get("d2h") == d2h_before
        # the summary surfaces both, h2d excluding donated
        s = prof.summary()
        assert s["h2d_bytes"] == 1_000
        assert s["donated_bytes"] == 50_000

    def test_legacy_records_without_donated_field_summarize(self, prof):
        """Ring records written before the donated ledger existed (or
        hand-built in tests) must not break the summary."""
        rec = prof.begin_cycle(cycle=1, pad=8, real=8)
        del rec["donated_bytes"]
        prof.phase("block", 0.01)
        prof.end_cycle(rec)
        assert prof.summary()["donated_bytes"] == 0


class TestMetricsMirror:
    def test_completed_cycle_updates_solver_metrics(self, prof):
        from kubernetes_tpu.metrics.solver_metrics import solver_metrics

        sm = solver_metrics()
        wait_before = sm.device_wait_seconds.count()
        h2d_before = sm.transfer_bytes_total.get("h2d")
        rec = prof.begin_cycle(cycle=1, pad=128, real=96)
        prof.phase("block", 0.05)
        prof.add_bytes("h2d", 4096)
        prof.end_cycle(rec)
        assert sm.device_wait_seconds.count() == wait_before + 1
        assert sm.transfer_bytes_total.get("h2d") == h2d_before + 4096
        assert sm.pad_occupancy_ratio.get("128") == pytest.approx(0.75)


class TestTelemetryStream:
    def test_jsonl_one_record_per_cycle(self, tmp_path):
        p = DevProfiler(enabled=True, use_listener=False,
                        telemetry_dir=str(tmp_path))
        for i in range(3):
            rec = p.begin_cycle(cycle=i, pad=64, real=32)
            p.phase("block", 0.01 * (i + 1))
            p.end_cycle(rec)
        p.close()
        files = list(tmp_path.glob("solvercycles-*.jsonl"))
        assert len(files) == 1
        records = [json.loads(ln) for ln in
                   files[0].read_text().splitlines()]
        assert len(records) == 3
        assert [r["cycle"] for r in records] == [0, 1, 2]
        assert records[2]["block_s"] == pytest.approx(0.03)

    def test_env_var_activates_stream(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KTPU_TELEMETRY", str(tmp_path / "t"))
        p = DevProfiler(enabled=True, use_listener=False)
        rec = p.begin_cycle(cycle=1, pad=8, real=8)
        p.end_cycle(rec)
        p.close()
        assert list((tmp_path / "t").glob("solvercycles-*.jsonl"))


class TestSessionIntegration:
    def test_solve_produces_cycle_records(self, prof):
        """The real solve path (session + sidecar over a small store)
        emits one measured record per solve cycle with the phase split,
        transfer bytes and pad occupancy populated — and the summary
        aggregates into the shape every bench row commits."""
        import time

        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.config.feature_gates import FeatureGates
        from kubernetes_tpu.scheduler.scheduler import Scheduler
        from kubernetes_tpu.sidecar import attach_batch_scheduler
        from kubernetes_tpu.testing import MakeNode, MakePod

        store = ClusterStore()
        for i in range(4):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "8", "memory": "32Gi"}).obj())
        sched = Scheduler.create(
            store,
            feature_gates=FeatureGates({"TPUBatchScheduler": True}))
        bs = attach_batch_scheduler(sched, max_batch=32)
        sched.start()
        try:
            for i in range(16):
                store.create_pod(
                    MakePod().name(f"p{i}").req({"cpu": "1"}).obj())
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                sched.queue.flush_backoff_completed()
                if bs.run_batch(pop_timeout=0.0):
                    continue
                if sched.queue.num_active() == 0 \
                        and sched.queue.num_backoff() == 0:
                    break
                time.sleep(0.05)
            assert sched.wait_for_inflight_bindings()
        finally:
            sched.stop()
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 16
        recs = prof.cycles(include_warming=True)
        assert recs, "solve path recorded no devprof cycles"
        solved = [r for r in recs if not r["warming"]]
        assert solved
        for r in solved:
            # every measured cycle shipped pod planes up and carries
            # the dispatch-vs-block split around the solver call
            assert r["h2d_bytes"] > 0
            assert r["dispatch_s"] >= 0.0 and r["block_s"] >= 0.0
            assert r["real"] > 0 and r["pad"] >= r["real"]
        s = prof.summary()
        assert s["cycles"] == len(solved)
        assert s["h2d_bytes"] > 0
        assert "max_cycle" in s
