"""The REST fabric as a measured path (VERDICT r4 missing #1 / next #1,
#7): binary codec negotiation, bulk wire verbs, max-in-flight lanes,
the ClusterStore-shaped REST client driving the real scheduler, and the
multiprocess perf harness. Reference anchors:
``runtime/serializer/protobuf/protobuf.go`` (binary codec),
``filters/maxinflight.go`` (lanes),
``test/integration/scheduler_perf/util.go:61-68`` (QPS discipline)."""

import http.client
import json
import socket
import threading
import time

import pytest

from kubernetes_tpu.api.types import ObjectMeta, Pod
from kubernetes_tpu.apiserver import codec
from kubernetes_tpu.apiserver.rest import APIServer, RestClient
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client.restcluster import RestClusterClient, TokenBucket
from kubernetes_tpu.testing import MakeNode, MakePod


def _serve(**kwargs):
    store = ClusterStore()
    server = APIServer(store=store, **kwargs).start()
    return store, server


# ---------------------------------------------------------------------------
# binary codec negotiation


class TestBinaryCodec:
    def test_get_and_list_negotiate_binary(self):
        store, server = _serve()
        try:
            pod = MakePod().name("b1").uid("u1").req({"cpu": "250m"}).obj()
            store.create_pod(pod)
            client = RestClusterClient(server.url)
            got = client.get_pod("default", "b1")
            # a pickled API object, not a wire dict — full fidelity
            assert isinstance(got, Pod)
            assert got.spec.containers[0].resources.requests[
                "cpu"].milli_value() == 250
            pods = client.list_pods()
            assert len(pods) == 1 and isinstance(pods[0], Pod)
        finally:
            server.shutdown_server()

    def test_json_clients_unaffected(self):
        store, server = _serve()
        try:
            store.create_pod(MakePod().name("j1").uid("u1").obj())
            plain = RestClient(server.url)
            pods, _rv = plain.list("Pod", "default")
            assert [p.name for p in pods] == ["j1"]
        finally:
            server.shutdown_server()

    def test_binary_body_requires_control_plane_identity(self):
        """codec.py trust model: only control-plane identities reach
        the unpickler on a server with authn configured — neither
        anonymous callers nor ordinary authenticated users (a leaked
        namespace token must not become code execution)."""
        store, server = _serve(tokens={"tok": "alice",
                                       "sched": "system:kube-scheduler"})
        try:
            host, port = server.url.replace("http://", "").split(":")
            conn = http.client.HTTPConnection(host, int(port))
            body = codec.encode({"kind": "PodList", "items": [
                MakePod().name("x").uid("ux").obj()]})
            for headers in (
                {"Content-Type": codec.BINARY_CONTENT_TYPE},
                {"Content-Type": codec.BINARY_CONTENT_TYPE,
                 "Authorization": "Bearer tok"},      # plain user: no
            ):
                conn.request("POST", "/api/v1/namespaces/default/pods",
                             body=body, headers=headers)
                resp = conn.getresponse()
                assert resp.status == 403
                resp.read()
            # a control-plane identity lands
            conn.request("POST", "/api/v1/namespaces/default/pods",
                         body=body,
                         headers={"Content-Type":
                                  codec.BINARY_CONTENT_TYPE,
                                  "Authorization": "Bearer sched"})
            resp = conn.getresponse()
            assert resp.status == 201
            resp.read()
            assert store.get_pod("default", "x") is not None
        finally:
            server.shutdown_server()


# ---------------------------------------------------------------------------
# bulk wire verbs


class TestBulkVerbs:
    def test_bulk_create_reports_positional_failures(self):
        store, server = _serve()
        try:
            store.create_pod(MakePod().name("dup").uid("u0").obj())
            client = RestClusterClient(server.url)
            items = [MakePod().name("a").uid("ua").obj(),
                     MakePod().name("dup").uid("u1").obj(),
                     MakePod().name("c").uid("uc").obj()]
            code, resp = client._request(
                "POST", "/api/v1/namespaces/default/pods",
                {"kind": "PodList", "items": items}, charge=3)
            assert code == 201
            assert resp["created"] == 2
            assert [f["index"] for f in resp["failures"]] == [1]
            assert resp["failures"][0]["code"] == 409
            assert store.get_pod("default", "a") is not None
            assert store.get_pod("default", "c") is not None
        finally:
            server.shutdown_server()

    def test_bulk_bindings_match_store_bind_semantics(self):
        store, server = _serve()
        try:
            store.add_node(MakeNode().name("n1").obj())
            for n in ("p1", "p2"):
                store.create_pod(MakePod().name(n).uid(f"u-{n}").obj())
            client = RestClusterClient(server.url)
            errs = client.bind_many([
                ("default", "p1", "u-p1", "n1"),
                ("default", "ghost", "", "n1"),      # missing -> KeyError
                ("default", "p2", "wrong-uid", "n1"),  # -> ValueError
            ])
            assert errs[0] is None
            assert isinstance(errs[1], KeyError)
            assert isinstance(errs[2], ValueError)
            assert store.get_pod("default", "p1").spec.node_name == "n1"
            assert store.get_pod("default", "p2").spec.node_name == ""
        finally:
            server.shutdown_server()

    def test_bind_many_splits_large_batches(self):
        store, server = _serve()
        try:
            store.add_node(MakeNode().name("n1")
                           .capacity({"cpu": "64", "memory": "256Gi"})
                           .obj())
            pods = [MakePod().name(f"s{i}").uid(f"u{i}").obj()
                    for i in range(1500)]
            store.create_pods(pods)
            client = RestClusterClient(server.url)
            errs = client.bind_many([
                ("default", f"s{i}", f"u{i}", "n1") for i in range(1500)
            ])
            assert all(e is None for e in errs)
            bound = sum(1 for p in store.list_pods() if p.spec.node_name)
            assert bound == 1500
        finally:
            server.shutdown_server()


# ---------------------------------------------------------------------------
# max-in-flight (reference filters/maxinflight.go)


class TestMaxInFlight:
    def test_flooded_readonly_lane_answers_429_and_binds_progress(self):
        """VERDICT next #7 done-condition: flood GETs while a scheduler
        binds; binds (the mutating lane) still progress. Runs the
        LEGACY lane path (flow_control=None) — the APF default replaces
        these semantics and has its own suite in test_flowcontrol.py."""
        store, server = _serve(max_readonly_inflight=2,
                               max_mutating_inflight=50,
                               flow_control=None)
        try:
            store.add_node(MakeNode().name("n1").obj())
            store.create_pod(MakePod().name("p1").uid("u1").obj())
            host, port = server.url.replace("http://", "").split(":")

            # jam the readonly lane with slow-draining watchless GETs:
            # hold sockets open mid-response by opening raw connections
            # that request but never read, while more GETs arrive
            hold = threading.Event()
            orig_list = store.list_objects_with_rv

            def slow_list(kind, ns=None):
                hold.wait(2.0)
                return orig_list(kind, ns)

            store.list_objects_with_rv = slow_list
            jammers = []
            for _ in range(2):
                c = http.client.HTTPConnection(host, int(port))
                c.request("GET", "/api/v1/pods")
                jammers.append(c)
            time.sleep(0.2)     # both lane slots now blocked in the GET
            c = http.client.HTTPConnection(host, int(port))
            c.request("GET", "/api/v1/pods")
            resp = c.getresponse()
            assert resp.status == 429
            assert resp.headers.get("Retry-After")
            body = json.loads(resp.read())
            assert body["reason"] == "TooManyRequests"
            # the mutating lane is unaffected: a bind lands NOW
            client = RestClusterClient(server.url)
            assert client.bind_many(
                [("default", "p1", "u1", "n1")]) == [None]
            assert store.get_pod("default", "p1").spec.node_name == "n1"
            hold.set()
            for j in jammers:
                j.getresponse().read()
        finally:
            store.list_objects_with_rv = orig_list
            server.shutdown_server()

    def test_watches_are_exempt_from_the_readonly_lane(self):
        store, server = _serve(max_readonly_inflight=1,
                               max_mutating_inflight=10,
                               flow_control=None)
        try:
            got = []
            done = threading.Event()

            def watcher():
                import urllib.request

                req = urllib.request.Request(
                    server.url + "/api/v1/pods?watch=1")
                with urllib.request.urlopen(req, timeout=10) as resp:
                    for line in resp:
                        got.append(json.loads(line))
                        done.set()
                        return

            threads = [threading.Thread(target=watcher, daemon=True)
                       for _ in range(4)]
            for t in threads:
                t.start()
            time.sleep(0.3)
            # 4 concurrent watches exceed the lane of 1 — all alive,
            # and a plain GET still succeeds because watches don't count
            client = RestClusterClient(server.url)
            assert client.list_pods() == []
            store.create_pod(MakePod().name("w1").uid("u1").obj())
            assert done.wait(5.0)
        finally:
            server.shutdown_server()


# ---------------------------------------------------------------------------
# the ClusterStore-shaped REST client driving the real scheduler


class TestRestClusterClient:
    def test_token_bucket_paces_per_object(self):
        bucket = TokenBucket(qps=1000, burst=100)
        t0 = time.monotonic()
        bucket.charge(100)   # burst
        bucket.charge(200)   # must wait ~0.2s for refill
        assert time.monotonic() - t0 >= 0.15

    def test_scheduler_end_to_end_over_rest(self):
        """The whole scheduler stack against RestClusterClient: watch
        feed, cache replay, binds via the Binding subresource, status
        conditions via pods/{name}/status."""
        from kubernetes_tpu.scheduler.scheduler import Scheduler

        store, server = _serve()
        client = RestClusterClient(server.url, qps=5000)
        sched = Scheduler.create(client)
        try:
            nodes = [MakeNode().name(f"n{i}")
                     .capacity({"cpu": "8", "memory": "16Gi"}).obj()
                     for i in range(5)]
            code, resp = client._request(
                "POST", "/api/v1/nodes",
                {"kind": "NodeList", "items": nodes}, charge=5)
            assert code == 201 and resp["created"] == 5
            sched.run()
            pods = [MakePod().name(f"p{i}").uid(f"u{i}")
                    .req({"cpu": "100m"}).obj() for i in range(40)]
            code, resp = client._request(
                "POST", "/api/v1/namespaces/default/pods",
                {"kind": "PodList", "items": pods}, charge=40)
            assert code == 201 and resp["created"] == 40
            deadline = time.time() + 30
            while time.time() < deadline:
                bound = sum(1 for p in store.list_pods()
                            if p.spec.node_name)
                if bound == 40:
                    break
                time.sleep(0.1)
            assert bound == 40
            # an impossible pod gets its Unschedulable condition THROUGH
            # the status subresource
            big = MakePod().name("huge").uid("u-huge") \
                .req({"cpu": "999"}).obj()
            client.create_object("Pod", big)
            deadline = time.time() + 15
            cond = None
            while time.time() < deadline and cond is None:
                live = store.get_pod("default", "huge")
                for c in live.status.conditions:
                    if c.type == "PodScheduled" and c.status == "False":
                        cond = c
                time.sleep(0.1)
            assert cond is not None and cond.reason == "Unschedulable"
        finally:
            sched.stop()
            server.shutdown_server()

    def test_watch_reconnects_after_server_drop(self):
        """Reflector behavior: a dropped watch relists and resumes."""
        store, server = _serve()
        client = RestClusterClient(server.url, watch_kinds=("Pod",))
        seen = []
        lock = threading.Lock()

        def on_events(events):
            with lock:
                seen.extend(e.obj.name for e in events
                            if e.type == "ADDED")

        handle = client.watch(lambda e: None, batch_fn=on_events)
        try:
            time.sleep(0.3)
            store.create_pod(MakePod().name("before").uid("u1").obj())
            deadline = time.time() + 5
            while time.time() < deadline and "before" not in seen:
                time.sleep(0.05)
            assert "before" in seen
        finally:
            handle.stop()
            server.shutdown_server()


# ---------------------------------------------------------------------------
# the multiprocess REST perf harness (the measured path)


class TestRestPerfHarness:
    @pytest.mark.slow
    def test_harness_runs_and_store_truth_agrees(self):
        from kubernetes_tpu.harness.rest_perf import run_workload_rest

        result = run_workload_rest(
            "SchedulingBasic", nodes=20, measure_pods=150,
            use_batch=False, qps=5000, wal=True, wait_timeout=120,
        )
        assert result.metrics["server_pods_bound"] == 150
        assert result.metrics["scheduler_bound"] == 150
        # WAL carried every mutation (nodes + creates + binds + ...)
        assert result.metrics["wal_entries"] >= 20 + 150 * 2
        assert result.pods_per_second > 0
        # freshness SLIs measured through REAL child processes: the
        # row's sub-object carries the watch-delivery p99 (commit →
        # decode over the wire) and the SLO verdicts
        assert result.freshness.get("watch_delivery_p99_ms", 0) > 0
        assert result.freshness["watch_delivery_events"] > 0
        assert "slo" in result.freshness
        # metrics federation merged ≥ 2 spawned components' registries
        # (instance label cardinality is the acceptance bar)
        assert len(result.metrics["federation_instances"]) >= 2

    @pytest.mark.slow
    def test_harness_generalizes_beyond_basic(self):
        """The REST harness walks any declarative workload: a
        TopologySpreading run (spread constraints + zoned nodes over
        the wire) completes with store truth agreeing."""
        from kubernetes_tpu.harness.rest_perf import run_workload_rest

        result = run_workload_rest(
            "TopologySpreading", nodes=20, measure_pods=120,
            use_batch=False, qps=5000, wal=False, wait_timeout=120,
        )
        assert result.metrics["server_pods_bound"] == \
            result.metrics["scheduler_bound"]
        assert result.metrics["server_pods_bound"] >= 120
        assert result.pods_per_second > 0
