"""Shared CSI volumes on the batch path (VERDICT r4 next #5): per-volume
attach planes carry "volume v attached on node n" in solver state, so a
shared (RWX/ROX) claim's attach demand is CONDITIONAL per node — 1 only
where the volume isn't attached yet — matching csi.go's
``len(in_use | wanted)`` set semantics exactly (reference
``nodevolumelimits/csi.go``). Before round 5 these pods rode the serial
path (the 10% slice that held SchedulingSharedPVs at ~413 pods/s)."""

import time

from kubernetes_tpu.api.types import (
    CSINode,
    CSINodeDriver,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    Volume,
)
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config.feature_gates import FeatureGates
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.sidecar import attach_batch_scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


def _cluster(n_nodes=4, limit=2, driver="csi.x"):
    store = ClusterStore()
    for i in range(n_nodes):
        store.add_node(MakeNode().name(f"n{i}")
                       .capacity({"cpu": "32", "memory": "64Gi"}).obj())
        store.add_csi_node(CSINode(
            metadata=ObjectMeta(name=f"n{i}"),
            drivers=[CSINodeDriver(name=driver,
                                   allocatable_count=limit)],
        ))
    return store


def _shared_claim(store, name, driver="csi.x"):
    store.add_pv(PersistentVolume(
        metadata=ObjectMeta(name=f"pv-{name}"),
        access_modes=["ReadWriteMany"], csi_driver=driver,
        claim_ref=f"default/{name}", phase="Bound",
    ))
    store.add_pvc(PersistentVolumeClaim(
        metadata=ObjectMeta(name=name, namespace="default"),
        access_modes=["ReadWriteMany"], volume_name=f"pv-{name}",
    ))


def _pod(name, claim, cpu="100m"):
    p = MakePod().name(name).uid(f"u-{name}").req({"cpu": cpu}).obj()
    p.spec.volumes = [Volume(name="data",
                             persistent_volume_claim=claim)]
    return p


def _run_batch(store, pods, max_batch=64, timeout=120.0):
    gates = FeatureGates({"TPUBatchScheduler": True})
    sched = Scheduler.create(store, feature_gates=gates,
                             provider="GangSchedulingProvider")
    bs = attach_batch_scheduler(sched, max_batch=max_batch)
    sched.start()
    store.create_pods(pods)
    deadline = time.time() + timeout
    while time.time() < deadline:
        bs.run_batch(pop_timeout=0.05)
        sched.queue.flush_backoff_completed()
        if all(p.spec.node_name or p.status.phase in ("Failed",)
               or any(c.type == "PodScheduled" and c.status == "False"
                      for c in p.status.conditions)
               for p in store.list_pods()):
            break
    bs.flush()
    sched.wait_for_inflight_bindings()
    placements = {p.metadata.name: p.spec.node_name
                  for p in store.list_pods() if p.spec.node_name}
    backend = bs.session._active.name
    sched.stop()
    return placements, backend


def _attach_sets(store):
    per_node = {}
    for p in store.list_pods():
        if p.spec.node_name and p.spec.volumes:
            pvc = store.get_pvc("default",
                                p.spec.volumes[0].persistent_volume_claim)
            if pvc and pvc.volume_name:
                per_node.setdefault(p.spec.node_name,
                                    set()).add(pvc.volume_name)
    return per_node


class TestSharedVolumePlanes:
    def test_shared_claims_ride_the_batch_path(self):
        """10 pods per shared claim schedule on-device (not serial) and
        never violate the per-node attach limit set-wise."""
        store = _cluster(n_nodes=8, limit=2)
        for c in range(4):
            _shared_claim(store, f"claim{c}")
        pods = [_pod(f"p{i}", f"claim{i % 4}") for i in range(40)]
        placements, backend = _run_batch(store, pods)
        assert len(placements) == 40
        assert backend in ("xla-planes", "cpp")   # the sv-capable backends
        for node, vols in _attach_sets(store).items():
            assert len(vols) <= 2, (node, vols)

    def test_attached_volume_costs_nothing_on_its_node(self):
        """A node whose budget is FULL but already holds the pod's
        volume must still admit the pod (demand 0 there) — the exact
        set-semantics case the additive column model cannot express."""
        store = _cluster(n_nodes=2, limit=1)
        _shared_claim(store, "shared")
        _shared_claim(store, "other")
        # n0 holds pv-shared (existing pod); n1's single slot is
        # consumed by pv-other
        seed0 = _pod("seed0", "shared")
        seed1 = _pod("seed1", "other")
        store.create_pod(seed0)
        store.bind("default", "seed0", seed0.uid, "n0")
        store.create_pod(seed1)
        store.bind("default", "seed1", seed1.uid, "n1")
        placements, _backend = _run_batch(
            store, [_pod("joiner", "shared")])
        # n1 is infeasible (attach 1/1 with a DIFFERENT volume); n0 is
        # free because the volume is already attached there
        assert placements["joiner"] == "n0"

    def test_in_batch_attachment_is_reused(self):
        """Two same-claim pods in ONE batch: the second sees the
        first's attachment in carried solver state. With every other
        node's budget exhausted, both must co-locate."""
        store = _cluster(n_nodes=3, limit=1)
        _shared_claim(store, "shared")
        for i, blocker in enumerate(("blk-a", "blk-b")):
            _shared_claim(store, blocker)
            seed = _pod(f"seed{i}", blocker)
            store.create_pod(seed)
            store.bind("default", f"seed{i}", seed.uid, f"n{i + 1}")
        placements, _backend = _run_batch(
            store, [_pod("first", "shared"), _pod("second", "shared")])
        assert placements["first"] == "n0"
        assert placements["second"] == "n0"   # attach slot reused

    def test_serial_and_batch_agree_on_bound_sets(self):
        """Differential: same pods bound on both paths, attach
        invariant holds on both (the repo's serial==batch contract)."""
        def build():
            store = _cluster(n_nodes=6, limit=2)
            for c in range(5):
                _shared_claim(store, f"claim{c}")
            pods = [_pod(f"p{i}", f"claim{i % 5}") for i in range(60)]
            return store, pods

        store_b, pods = build()
        batch_placements, _ = _run_batch(store_b, pods)

        store_s, pods = build()
        sched = Scheduler.create(
            store_s, feature_gates=FeatureGates(
                {"TPUBatchScheduler": False}),
            provider="GangSchedulingProvider")
        sched.start()
        store_s.create_pods(pods)
        deadline = time.time() + 60
        while time.time() < deadline:
            sched.schedule_one(pop_timeout=0.05)
            sched.queue.flush_backoff_completed()
            if sum(1 for p in store_s.list_pods()
                   if p.spec.node_name) >= len(batch_placements):
                break
        sched.wait_for_inflight_bindings()
        serial_placements = {
            p.metadata.name: p.spec.node_name
            for p in store_s.list_pods() if p.spec.node_name
        }
        sched.stop()
        assert set(serial_placements) == set(batch_placements)
        for store in (store_b, store_s):
            for node, vols in _attach_sets(store).items():
                assert len(vols) <= 2, (node, vols)

    def test_multi_shared_volume_pod_keeps_host_path(self):
        """A pod with TWO shared CSI volumes is inexpressible (one
        plane reference per step) — it still schedules, serially."""
        store = _cluster(n_nodes=2, limit=2)
        _shared_claim(store, "a")
        _shared_claim(store, "b")
        p = MakePod().name("multi").uid("u-multi").req(
            {"cpu": "100m"}).obj()
        p.spec.volumes = [
            Volume(name="v1", persistent_volume_claim="a"),
            Volume(name="v2", persistent_volume_claim="b"),
        ]
        placements, _backend = _run_batch(store, [p])
        assert "multi" in placements

    def test_over_limit_node_rejects_even_attached_volume_pods(self):
        """csi.go rejects ANY csi-volume pod on a node whose existing
        attachments exceed its (shrunk) limit — including a pod whose
        shared volume is already attached there. The device mirrors
        this by clearing attached bits on over-limit nodes (demand
        reads 1, the clamped column rejects)."""
        store = _cluster(n_nodes=2, limit=1)
        _shared_claim(store, "sharedA")
        _shared_claim(store, "sharedB")
        # n0 carries BOTH volumes (over its limit of 1 — e.g. the
        # CSINode limit shrank after they attached)
        for i, c in enumerate(("sharedA", "sharedB")):
            seed = _pod(f"seed{i}", c)
            store.create_pod(seed)
            store.bind("default", f"seed{i}", seed.uid, "n0")
        placements, _backend = _run_batch(store, [_pod("j", "sharedA")])
        # n0 is over-limit (2 > 1): host refuses it; n1 takes the pod
        # with a fresh attachment
        assert placements.get("j") == "n1"

    def test_sharded_matches_single_chip_on_shared_volumes(self):
        """The mesh-sharded backend carries the sv planes too (node-
        sharded, fully local update): placements are IDENTICAL to the
        single-chip batch path on a shared-volume workload."""
        import jax
        import pytest

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices (virtual CPU mesh)")
        from kubernetes_tpu.parallel import ShardedBackend, make_mesh

        def build():
            store = _cluster(n_nodes=8, limit=2)
            for c in range(4):
                _shared_claim(store, f"claim{c}")
            pods = [_pod(f"p{i}", f"claim{i % 4}") for i in range(48)]
            return store, pods

        store_b, pods = build()
        batch_placements, _ = _run_batch(store_b, pods)

        store_m, pods = build()
        gates = FeatureGates({"TPUBatchScheduler": True})
        sched = Scheduler.create(store_m, feature_gates=gates,
                                 provider="GangSchedulingProvider")
        bs = attach_batch_scheduler(
            sched, max_batch=64,
            backend=ShardedBackend(make_mesh(8, batch_axis=2)))
        sched.start()
        store_m.create_pods(pods)
        deadline = time.time() + 120
        while time.time() < deadline:
            bs.run_batch(pop_timeout=0.05)
            sched.queue.flush_backoff_completed()
            if sum(1 for p in store_m.list_pods()
                   if p.spec.node_name) >= 48:
                break
        bs.flush()
        sched.wait_for_inflight_bindings()
        sharded_placements = {
            p.metadata.name: p.spec.node_name
            for p in store_m.list_pods() if p.spec.node_name
        }
        assert bs.session._active.name == "sharded"
        sched.stop()
        diverged = [
            (k, batch_placements.get(k), sharded_placements.get(k))
            for k in set(batch_placements) | set(sharded_placements)
            if batch_placements.get(k) != sharded_placements.get(k)
        ]
        assert not diverged, diverged[:10]
        for node, vols in _attach_sets(store_m).items():
            assert len(vols) <= 2, (node, vols)
