"""Workload-level differential ring (SURVEY.md section 4 carry-over):
the same randomized workload through the serial host path, the TPU batch
path, and the mesh-sharded batch path must yield equivalent outcomes —
identical bound-pod sets (the paths are serial-equivalent in queue
order), identical batch-vs-sharded placements (the solvers are
differentially exact), and placements that satisfy every constraint from
first principles — plus preemption equivalence under contention and
crash-recovery: a scheduler restart rebuilds all state from the store
(the control plane's "checkpoint" is the API server; SURVEY.md
section 5).

The random mix covers resource fit, hard/soft topology spread, pod
anti-affinity, node selectors, required/preferred node affinity,
preferred pod anti-affinity, taints+tolerations, priorities, gangs
(coscheduling), and PVC pods (serial-fallback contract)."""

import random
import time

import pytest

from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.api.types import (
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
)
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config.feature_gates import FeatureGates
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.sidecar import attach_batch_scheduler
from kubernetes_tpu.testing import MakeNode, MakePod

ZONE_KEY = "topology.kubernetes.io/zone"
N_ZONES = 4
TAINT_KEY = "dedicated"
TAINT_VAL = "batch"


def _random_cluster(rng, n_nodes, taints=True):
    """Nodes over 4 zones with mixed capacity, gold/std tiers, and ~10%
    tainted (dedicated=batch:NoSchedule)."""
    nodes = []
    for i in range(n_nodes):
        w = (
            MakeNode().name(f"n{i:04d}")
            .label(ZONE_KEY, f"z{i % N_ZONES}")
            .label("tier", "gold" if i % 4 == 0 else "std")
            .capacity({
                "cpu": str(rng.choice([8, 16, 32])),
                "memory": f"{rng.choice([16, 32, 64])}Gi",
            })
        )
        if taints and i % 10 == 9:
            w.taint(TAINT_KEY, TAINT_VAL, "NoSchedule")
        nodes.append(w.obj())
    return nodes


CSI_DRIVER = "csi.diff.driver"
CSI_LIMIT = 16


def _csi_nodes(store: ClusterStore, nodes):
    """CSINode attach limits on every node so bound CSI-PV pods exercise
    the encoder's attach-limit resource columns on the batch path."""
    from kubernetes_tpu.api.types import CSINode, CSINodeDriver

    for n in nodes:
        store.add_csi_node(CSINode(
            metadata=ObjectMeta(name=n.name),
            drivers=[CSINodeDriver(
                name=CSI_DRIVER, node_id=n.name,
                allocatable_count=CSI_LIMIT,
            )],
        ))


def _pvc_setup(store: ClusterStore, claim: str, variant: int = 0):
    """A 1:1 PV/PVC pair in six variants (round-3 coverage — bound
    claims are batch-expressible, VERDICT r2 #1 — plus the round-4
    carve-outs):

    0. bound, CSI driver (attach-limit columns), unconstrained PV
    1. bound, PV zone-labelled z0 (VolumeZone mask)
    2. bound, PV node-affinity to z1 (VolumeBinding bound-claim mask)
    3. unbound immediate — UnschedulableAndUnresolvable on both paths
       (the serial-fallback contract's original coverage)
    4. SHARED RWX claim on a non-CSI PV (one claim, many pods) —
       round-4 batchable (no attach budget)
    5. unbound WaitForFirstConsumer claim over an affinity-free
       Available PV — round-4 batchable with commit-time binding
    """
    from kubernetes_tpu.api.types import (
        NodeSelector, NodeSelectorRequirement, NodeSelectorTerm,
    )

    if store.get_storage_class("diff-sc") is None:
        store.add_storage_class(StorageClass(
            metadata=ObjectMeta(name="diff-sc"),
            provisioner="kubernetes.io/fake",
            volume_binding_mode="Immediate",
        ))
    if variant == 4:
        if store.get_pvc("default", claim) is not None:
            return      # the shared claim exists once, consumed by many
        store.add_pv(PersistentVolume(
            metadata=ObjectMeta(name=f"pv-{claim}"),
            capacity={"storage": parse_quantity("100Gi")},
            storage_class_name="diff-sc",
            access_modes=["ReadWriteMany"],
            claim_ref=f"default/{claim}",
            phase="Bound",
        ))
        store.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name=claim, namespace="default"),
            storage_class_name="diff-sc",
            requests={"storage": parse_quantity("1Gi")},
            access_modes=["ReadWriteMany"],
            volume_name=f"pv-{claim}",
            phase="Bound",
        ))
        return
    if variant == 5:
        if store.get_storage_class("diff-wfc-sc") is None:
            store.add_storage_class(StorageClass(
                metadata=ObjectMeta(name="diff-wfc-sc"),
                provisioner="kubernetes.io/fake",
                volume_binding_mode="WaitForFirstConsumer",
            ))
        store.add_pv(PersistentVolume(
            metadata=ObjectMeta(name=f"pv-{claim}"),
            capacity={"storage": parse_quantity("1Gi")},
            storage_class_name="diff-wfc-sc",
            phase="Available",
        ))
        store.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name=claim, namespace="default"),
            storage_class_name="diff-wfc-sc",
            requests={"storage": parse_quantity("1Gi")},
        ))
        return
    if variant == 3:
        store.add_pv(PersistentVolume(
            metadata=ObjectMeta(name=f"pv-{claim}"),
            capacity={"storage": parse_quantity("1Gi")},
            storage_class_name="diff-sc",
        ))
        store.add_pvc(PersistentVolumeClaim(
            metadata=ObjectMeta(name=claim, namespace="default"),
            storage_class_name="diff-sc",
            requests={"storage": parse_quantity("1Gi")},
        ))
        return
    labels = {}
    node_affinity = None
    driver = ""
    if variant == 0:
        driver = CSI_DRIVER
    elif variant == 1:
        labels = {ZONE_KEY: "z0"}
    elif variant == 2:
        node_affinity = NodeSelector(node_selector_terms=[
            NodeSelectorTerm(match_expressions=[
                NodeSelectorRequirement(
                    key=ZONE_KEY, operator="In", values=["z1"]),
            ])
        ])
    store.add_pv(PersistentVolume(
        metadata=ObjectMeta(name=f"pv-{claim}", labels=labels),
        capacity={"storage": parse_quantity("1Gi")},
        storage_class_name="diff-sc",
        claim_ref=f"default/{claim}",
        phase="Bound",
        node_affinity=node_affinity,
        csi_driver=driver,
    ))
    store.add_pvc(PersistentVolumeClaim(
        metadata=ObjectMeta(name=claim, namespace="default"),
        storage_class_name="diff-sc",
        requests={"storage": parse_quantity("1Gi")},
        volume_name=f"pv-{claim}",
        phase="Bound",
    ))


def _random_pods(rng, count, store=None, gangs=False, pvcs=False,
                 priorities=False, apps=20):
    """The randomized constraint mix. ``store`` is required when ``pvcs``
    is set (PV/PVC objects must exist before the pod arrives)."""
    pods = []
    gang_id = 0
    i = 0
    while i < count:
        if gangs and rng.random() < 0.05 and i + 4 <= count:
            # a 4-pod coscheduling gang (Permit-phase all-or-nothing)
            for m in range(4):
                pods.append(
                    MakePod().name(f"p{i:05d}").uid(f"u{i}")
                    .label("app", "gang")
                    .label("pod-group.scheduling.k8s.io/name",
                           f"g{gang_id}")
                    .label("pod-group.scheduling.k8s.io/min-available", "4")
                    .req({"cpu": "500m", "memory": "256Mi"}).obj()
                )
                i += 1
            gang_id += 1
            continue
        app = f"a{i % apps}"
        w = (
            MakePod().name(f"p{i:05d}").uid(f"u{i}")
            .label("app", app)
            .req({
                "cpu": f"{rng.choice([100, 250, 500, 1000])}m",
                "memory": f"{rng.choice([128, 256, 512])}Mi",
            })
        )
        if priorities:
            w.priority(rng.choice([0, 0, 0, 100, 1000]))
        kind = rng.randrange(12)
        if kind == 0:
            # dedicated label group: EVERY pod matching the selector
            # declares the constraint, so the final-state skew invariant
            # is well-defined (a plain pod sharing the label would shift
            # counts the scheduler never polices — upstream semantics)
            sp = f"sp{i % 6}"
            w.label("app", sp)
            w.spread_constraint(2, ZONE_KEY, "DoNotSchedule", {"app": sp})
        elif kind == 1:
            w.pod_anti_affinity("app", [app], "kubernetes.io/hostname")
        elif kind == 2:
            w.node_selector({"tier": "gold"})
        elif kind == 3:
            w.node_affinity_in(ZONE_KEY, ["z0", "z1"])
        elif kind == 4:
            w.preferred_node_affinity(10, "tier", ["gold"])
        elif kind == 5:
            w.preferred_pod_anti_affinity(5, "app", [app],
                                          "kubernetes.io/hostname")
        elif kind == 6:
            ss = f"ss{i % 6}"
            w.label("app", ss)
            w.spread_constraint(3, ZONE_KEY, "ScheduleAnyway", {"app": ss})
        elif kind == 7:
            w.toleration(TAINT_KEY, TAINT_VAL, "NoSchedule")
        elif kind == 8 and pvcs and store is not None:
            variant = i % 6
            claim = "claim-shared-rwx" if variant == 4 else f"claim-{i}"
            _pvc_setup(store, claim, variant=variant)
            w.pvc(claim)
        # remaining kinds: plain fit pods
        pods.append(w.obj())
        i += 1
    return pods


def _pump(sched, bs, deadline_s=120.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        sched.queue.flush_backoff_completed()
        progressed = (
            bs.run_batch(pop_timeout=0.0) if bs
            else sched.schedule_one(pop_timeout=0.0)
        )
        if progressed:
            continue
        if bs is not None and bs.flush():
            continue
        if sched.queue.num_active() == 0 and sched.queue.num_backoff() == 0:
            break
        time.sleep(0.01)
    assert sched.wait_for_inflight_bindings()


def _run(nodes, pods, mode, store=None, max_batch=512):
    """mode: 'serial' | 'batch' | 'sharded'."""
    store = store or ClusterStore()
    for n in nodes:
        store.add_node(n)
    use_batch = mode != "serial"
    sched = Scheduler.create(
        store, feature_gates=FeatureGates({"TPUBatchScheduler": use_batch}),
        provider="GangSchedulingProvider",
    )
    bs = None
    if use_batch:
        backend = None
        if mode == "sharded":
            from kubernetes_tpu.parallel import ShardedBackend, make_mesh

            backend = ShardedBackend(make_mesh(8, batch_axis=2))
        bs = attach_batch_scheduler(sched, max_batch=max_batch,
                                    backend=backend)
    sched.start()
    store.create_pods(pods)
    _pump(sched, bs)
    bound = {
        p.metadata.name: p.spec.node_name
        for p in store.list_pods() if p.spec.node_name
    }
    sched.stop()
    return bound, store


# ----------------------------------------------------------------------
# the first-principles invariant checker: placements must satisfy every
# constraint independent of any scheduler code path
def _assert_valid(bound, store):
    nodes = {n.name: n for n in store.list_nodes()}
    pods = {p.metadata.name: p for p in store.list_pods()}
    cpu_used = {n: 0 for n in nodes}
    mem_used = {n: 0 for n in nodes}
    for name, node_name in bound.items():
        pod = pods[name]
        node = nodes[node_name]
        req = pod.spec.containers[0].resources.requests
        cpu_used[node_name] += int(req["cpu"].milli_value())
        mem_used[node_name] += int(req["memory"].value())
        # node selector
        for k, val in pod.spec.node_selector.items():
            assert node.metadata.labels.get(k) == val, name
        # required node affinity (In terms only, as generated here)
        aff = pod.spec.affinity
        if aff is not None and aff.node_affinity is not None:
            sel = (aff.node_affinity
                   .required_during_scheduling_ignored_during_execution)
            if sel is not None:
                ok = False
                for term in sel.node_selector_terms:
                    term_ok = all(
                        node.metadata.labels.get(expr.key) in expr.values
                        for expr in term.match_expressions
                        if expr.operator == "In"
                    )
                    ok = ok or term_ok
                assert ok, f"{name}: node affinity violated on {node_name}"
        # taints: every NoSchedule taint must be tolerated
        for taint in node.spec.taints:
            if taint.effect != "NoSchedule":
                continue
            tolerated = any(
                t.tolerates(taint) for t in pod.spec.tolerations
            )
            assert tolerated, (
                f"{name} on {node_name}: untolerated taint {taint.key}"
            )
    for n in nodes:
        alloc = nodes[n].status.allocatable
        assert cpu_used[n] <= int(alloc["cpu"].milli_value()), n
        assert mem_used[n] <= int(alloc["memory"].value()), n
    # hostname anti-affinity: at most one pod per (app, node) among
    # pods that declare it
    seen = set()
    for name, node_name in bound.items():
        pod = pods[name]
        aff = pod.spec.affinity
        if aff is None or aff.pod_anti_affinity is None:
            continue
        if not (aff.pod_anti_affinity
                .required_during_scheduling_ignored_during_execution):
            continue
        key = (pod.metadata.labels.get("app"), node_name)
        assert key not in seen, f"anti-affinity violated on {node_name}"
        seen.add(key)
    # hard topology-spread: final max-min skew over eligible domains must
    # respect maxSkew (each placement respected it stepwise, and domain
    # minima only grow, so the final state inherits the bound)
    constraints = {}
    for name, node_name in bound.items():
        pod = pods[name]
        for sc in pod.spec.topology_spread_constraints:
            if sc.when_unsatisfiable != "DoNotSchedule":
                continue
            app = pod.metadata.labels.get("app")
            constraints.setdefault(
                (sc.topology_key, app), sc.max_skew
            )
    for (key, app), max_skew in constraints.items():
        domain_values = {
            n.metadata.labels.get(key)
            for n in nodes.values() if key in n.metadata.labels
        }
        counts = {v: 0 for v in domain_values}
        for name, node_name in bound.items():
            if pods[name].metadata.labels.get("app") != app:
                continue
            v = nodes[node_name].metadata.labels.get(key)
            if v in counts:
                counts[v] += 1
        if counts:
            skew = max(counts.values()) - min(counts.values())
            assert skew <= max_skew, (
                f"spread {key}/{app}: skew {skew} > {max_skew} ({counts})"
            )
    # gang all-or-nothing
    gangs = {}
    for name, pod in pods.items():
        g = pod.metadata.labels.get("pod-group.scheduling.k8s.io/name")
        if g:
            gangs.setdefault(g, []).append(name)
    for g, members in gangs.items():
        n_bound = sum(1 for m in members if m in bound)
        assert n_bound in (0, len(members)), (
            f"gang {g}: {n_bound}/{len(members)} bound (not all-or-nothing)"
        )
    # volume feasibility: bound-PV zone labels and node affinity must
    # admit the chosen node; CSI attach counts within CSINode limits
    from kubernetes_tpu.scheduler.framework.plugins.helpers import (
        node_matches_node_selector,
    )

    attach = {}
    for name, node_name in bound.items():
        pod = pods[name]
        node = nodes[node_name]
        for v in pod.spec.volumes:
            if not v.persistent_volume_claim:
                continue
            pvc = store.get_pvc(pod.namespace, v.persistent_volume_claim)
            if pvc is None or not pvc.volume_name:
                continue
            pv = store.get_pv(pvc.volume_name)
            if pv is None:
                continue
            zone = pv.metadata.labels.get(ZONE_KEY)
            if zone is not None:
                assert node.metadata.labels.get(ZONE_KEY) in \
                    set(zone.split("__")), (
                        f"{name}: PV zone {zone} violated on {node_name}"
                    )
            assert node_matches_node_selector(node, pv.node_affinity), (
                f"{name}: PV node affinity violated on {node_name}"
            )
            if pv.csi_driver:
                attach.setdefault(
                    (node_name, pv.csi_driver), set()
                ).add(pv.name)
    for (node_name, drv), vols in attach.items():
        cn = store.get_csi_node(node_name)
        if cn is None:
            continue
        for d in cn.drivers:
            if d.name == drv and d.allocatable_count is not None:
                assert len(vols) <= d.allocatable_count, (
                    f"{node_name}: {len(vols)} {drv} attachments > "
                    f"{d.allocatable_count}"
                )


# ----------------------------------------------------------------------
class TestSerialBatchEquivalence:
    """VERDICT r1 #4: >=200 nodes / >=2k pods x >=10 seeds, full
    constraint mix, serial == batch on bound sets + invariants."""

    @pytest.mark.parametrize("seed", [7, 23, 99, 131, 204, 311, 442,
                                      557, 613, 787])
    def test_randomized_workloads(self, seed):
        rng = random.Random(seed)
        nodes = _random_cluster(rng, 200)
        store_s = ClusterStore()
        _csi_nodes(store_s, nodes)
        pods = _random_pods(rng, 2000, store=store_s, gangs=True,
                            pvcs=True, priorities=True)
        serial_bound, serial_store = _run(nodes, pods, "serial",
                                          store=store_s)
        rng = random.Random(seed)
        nodes = _random_cluster(rng, 200)
        store_b = ClusterStore()
        _csi_nodes(store_b, nodes)
        pods = _random_pods(rng, 2000, store=store_b, gangs=True,
                            pvcs=True, priorities=True)
        batch_bound, batch_store = _run(nodes, pods, "batch",
                                        store=store_b)
        assert set(serial_bound) == set(batch_bound), (
            f"seed {seed}: bound sets differ: "
            f"{sorted(set(serial_bound) ^ set(batch_bound))[:20]}"
        )
        _assert_valid(serial_bound, serial_store)
        _assert_valid(batch_bound, batch_store)


class TestShardedEquivalence:
    """serial == batch == sharded at the workload level: the sharded
    backend rides the full sidecar path on the 8-device CPU mesh, and
    its placements must be IDENTICAL to the single-chip batch path
    (differential exactness), which must match serial on bound sets."""

    @pytest.mark.parametrize("seed", [11, 47, 83])
    def test_three_way(self, seed):
        def make(seed):
            rng = random.Random(seed)
            nodes = _random_cluster(rng, 200)
            pods = _random_pods(rng, 600, priorities=False)
            return nodes, pods

        nodes, pods = make(seed)
        serial_bound, serial_store = _run(nodes, pods, "serial")
        nodes, pods = make(seed)
        batch_bound, batch_store = _run(nodes, pods, "batch")
        nodes, pods = make(seed)
        sharded_bound, sharded_store = _run(nodes, pods, "sharded")

        assert batch_bound == sharded_bound, (
            f"seed {seed}: batch vs sharded placements diverge: "
            f"{[(k, batch_bound.get(k), sharded_bound.get(k)) for k in set(batch_bound) ^ set(sharded_bound) or list(batch_bound)[:1] if batch_bound.get(k) != sharded_bound.get(k)][:10]}"
        )
        assert set(serial_bound) == set(batch_bound)
        _assert_valid(serial_bound, serial_store)
        _assert_valid(batch_bound, batch_store)
        _assert_valid(sharded_bound, sharded_store)


class TestPreemptionEquivalence:
    """Contention + priorities: high-priority pods must preempt enough
    victims to bind on BOTH paths (the batch path's mass-decline branch
    feeds the same PostFilter/preemption flow), and every evicted victim
    must be lower-priority than some preemptor."""

    @pytest.mark.parametrize("seed", [5, 61])
    def test_preemption_under_contention(self, seed):
        for mode in ("serial", "batch"):
            rng = random.Random(seed)
            nodes = _random_cluster(rng, 40, taints=False)
            # fill the cluster solid with low-priority 1-cpu pods
            total_cpu = sum(
                int(n.status.allocatable["cpu"].milli_value()) // 1000
                for n in nodes
            )
            fillers = [
                MakePod().name(f"low{i:04d}").uid(f"lu{i}")
                .label("app", "low").priority(0)
                .req({"cpu": "1", "memory": "64Mi"}).obj()
                for i in range(total_cpu)
            ]
            store = ClusterStore()
            for n in nodes:
                store.add_node(n)
            use_batch = mode == "batch"
            sched = Scheduler.create(store, feature_gates=FeatureGates(
                {"TPUBatchScheduler": use_batch}))
            bs = attach_batch_scheduler(sched, max_batch=256) \
                if use_batch else None
            sched.start()
            try:
                self._drive(sched, bs, store, fillers, total_cpu, mode)
            finally:
                sched.stop()

    def _drive(self, sched, bs, store, fillers, total_cpu, mode):
            store.create_pods(fillers)
            _pump(sched, bs)
            n_filled = sum(
                1 for p in store.list_pods() if p.spec.node_name
            )
            assert n_filled == total_cpu  # solid
            # now 100 high-priority pods: all must preempt their way in
            high = [
                MakePod().name(f"high{i:03d}").uid(f"hu{i}")
                .label("app", "high").priority(1000)
                .req({"cpu": "1", "memory": "64Mi"}).obj()
                for i in range(100)
            ]
            store.create_pods(high)
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                sched.queue.flush_backoff_completed()
                if bs is not None:
                    bs.run_batch(pop_timeout=0.0)
                else:
                    sched.schedule_one(pop_timeout=0.0)
                bound_high = sum(
                    1 for p in store.list_pods()
                    if p.metadata.labels.get("app") == "high"
                    and p.spec.node_name
                )
                if bound_high == 100:
                    break
                time.sleep(0.005)
            sched.wait_for_inflight_bindings()
            bound_high = sum(
                1 for p in store.list_pods()
                if p.metadata.labels.get("app") == "high"
                and p.spec.node_name
            )
            assert bound_high == 100, (
                f"{mode}: only {bound_high}/100 high-priority pods bound"
            )
            # every victim evicted was lower-priority (only "low" pods
            # may have disappeared)
            remaining = {p.metadata.name for p in store.list_pods()}
            assert all(p.metadata.name in remaining for p in high)
            bound = {
                p.metadata.name: p.spec.node_name
                for p in store.list_pods() if p.spec.node_name
            }
            _assert_valid(bound, store)


class TestPreemptionPlannerEquivalence:
    """Round-3 victim planner under HETEROGENEOUS contention: mixed
    victim sizes and priorities, PDB-covered pods the planner must
    never evict, and preemptors needing MULTI-victim sets — on both
    paths, with invariants on who died."""

    @pytest.mark.parametrize("seed", [17, 43])
    def test_mixed_priority_preemption(self, seed):
        from kubernetes_tpu.api.types import (
            ObjectMeta, PodDisruptionBudget,
        )
        from kubernetes_tpu.api.labels import LabelSelector

        for mode in ("serial", "batch"):
            rng = random.Random(seed)
            nodes = _random_cluster(rng, 30, taints=False)
            store = ClusterStore()
            for n in nodes:
                store.add_node(n)
            # mixed fillers: priorities 0/10/50, sizes 1-2 cpu; a
            # PDB-protected subset that must survive
            fillers = []
            for i, n in enumerate(nodes):
                cap = int(n.status.allocatable["cpu"].milli_value()) // 1000
                used = 0
                j = 0
                while used + 1 <= cap:
                    size = rng.choice([1, 1, 2])
                    if used + size > cap:
                        size = 1
                    prio = rng.choice([0, 0, 10, 50])
                    protected = rng.random() < 0.1
                    w = (MakePod().name(f"f{i:02d}-{j}")
                         .uid(f"fu{i}-{j}")
                         .label("app", "protected" if protected else "low")
                         .priority(prio)
                         .req({"cpu": str(size), "memory": "64Mi"}))
                    fillers.append(w.obj())
                    used += size
                    j += 1
            pdb = PodDisruptionBudget(
                metadata=ObjectMeta(name="guard", namespace="default"),
                label_selector=LabelSelector(
                    match_labels={"app": "protected"}),
            )
            pdb.status.disruptions_allowed = 0
            store.add_pdb(pdb)
            use_batch = mode == "batch"
            sched = Scheduler.create(store, feature_gates=FeatureGates(
                {"TPUBatchScheduler": use_batch}))
            bs = attach_batch_scheduler(sched, max_batch=128) \
                if use_batch else None
            sched.start()
            try:
                store.create_pods(fillers)
                _pump(sched, bs)
                protected_before = {
                    p.metadata.name for p in store.list_pods()
                    if p.metadata.labels.get("app") == "protected"
                }
                # 40 high-priority preemptors needing 2 cpu each
                # (multi-victim sets where fillers are 1-cpu)
                high = [
                    MakePod().name(f"high{i:02d}").uid(f"hi{i}")
                    .label("app", "high").priority(1000)
                    .req({"cpu": "2", "memory": "64Mi"}).obj()
                    for i in range(40)
                ]
                store.create_pods(high)
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    sched.queue.flush_backoff_completed()
                    if bs is not None:
                        if bs.run_batch(pop_timeout=0.0) or bs.flush():
                            continue
                    else:
                        sched.schedule_one(pop_timeout=0.0)
                    n_high = sum(
                        1 for p in store.list_pods()
                        if p.metadata.labels.get("app") == "high"
                        and p.spec.node_name
                    )
                    if n_high == 40:
                        break
                    time.sleep(0.005)
                sched.wait_for_inflight_bindings()
                n_high = sum(
                    1 for p in store.list_pods()
                    if p.metadata.labels.get("app") == "high"
                    and p.spec.node_name
                )
                assert n_high == 40, (
                    f"seed {seed} {mode}: {n_high}/40 preemptors bound"
                )
                # PDB-protected pods all survived on both paths
                protected_after = {
                    p.metadata.name for p in store.list_pods()
                    if p.metadata.labels.get("app") == "protected"
                }
                assert protected_after == protected_before, (
                    f"seed {seed} {mode}: PDB-protected pods evicted: "
                    f"{sorted(protected_before - protected_after)}"
                )
                # only priority < 1000 pods may have vanished
                assert all(
                    p.metadata.labels.get("app") != "high" or
                    p.spec.node_name
                    for p in store.list_pods()
                )
                bound = {
                    p.metadata.name: p.spec.node_name
                    for p in store.list_pods() if p.spec.node_name
                }
                _assert_valid(bound, store)
            finally:
                sched.stop()


class TestUnschedulableEquivalence:
    """Deterministically-impossible pods must be declined by BOTH paths
    (and by the device's mass-decline fast path), never bound."""

    def test_impossible_pods(self):
        rng = random.Random(3)
        nodes = _random_cluster(rng, 50)
        possible = _random_pods(rng, 200)
        impossible = [
            MakePod().name(f"imp{i:03d}").uid(f"iu{i}")
            .node_selector({"tier": "platinum"})  # matches nothing
            .req({"cpu": "100m"}).obj()
            for i in range(100)
        ]
        for mode in ("serial", "batch"):
            bound, store = _run(nodes, possible + impossible, mode)
            assert len(bound) == 200, mode
            assert not any(n.startswith("imp") for n in bound), mode
            _assert_valid(bound, store)


class TestCrashRecovery:
    def test_scheduler_restart_resumes_from_store(self):
        """Kill the scheduler mid-workload; a fresh instance rebuilds
        cache/queue from the store (list+watch) and finishes. Nothing is
        persisted locally — exactly the reference's recovery model."""
        store = ClusterStore()
        for i in range(6):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "8", "memory": "16Gi"}).obj()
            )
        sched1 = Scheduler.create(
            store, feature_gates=FeatureGates({"TPUBatchScheduler": True})
        )
        bs1 = attach_batch_scheduler(sched1, max_batch=8)
        sched1.start()
        for i in range(40):
            store.create_pod(
                MakePod().name(f"p{i}").uid(f"u{i}").req({"cpu": "500m"}).obj()
            )
        # schedule a little, then crash (stop without draining). Two
        # cycles: the pipelined batch path solves on the first and
        # commits on the second — and a batch still in flight at crash
        # time must be recoverable from the store regardless.
        bs1.run_batch(pop_timeout=0.1)
        bs1.run_batch(pop_timeout=0.0)
        sched1.wait_for_inflight_bindings()
        sched1.stop()
        partial = sum(1 for p in store.list_pods() if p.spec.node_name)
        assert 0 < partial < 40

        sched2 = Scheduler.create(
            store, feature_gates=FeatureGates({"TPUBatchScheduler": True})
        )
        bs2 = attach_batch_scheduler(sched2, max_batch=8)
        sched2.start()  # replays store state: bound pods -> cache, rest -> queue
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            sched2.queue.flush_backoff_completed()
            if bs2.run_batch(pop_timeout=0.0):
                continue
            if sched2.queue.num_active() == 0 and \
                    sched2.queue.num_backoff() == 0:
                break
            time.sleep(0.01)
        assert sched2.wait_for_inflight_bindings()
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 40
        # capacity respected across the restart boundary (8 cpu, 500m)
        per_node = {}
        for p in bound:
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(c <= 16 for c in per_node.values())
        sched2.stop()


class TestShardedFullMix:
    """VERDICT r4 next #4: the mesh-sharded backend under the FULL
    constraint mix — priorities+preemption, gangs, and PVCs at 200
    nodes / 2000 pods across 5 seeds. Three-way: sharded placements
    must be IDENTICAL to single-chip batch; both must match serial on
    bound sets; every placement passes the first-principles checker."""

    @pytest.mark.parametrize("seed", [7, 23, 131, 442, 787])
    def test_three_way_full_mix(self, seed):
        def make(seed):
            rng = random.Random(seed)
            nodes = _random_cluster(rng, 200)
            store = ClusterStore()
            _csi_nodes(store, nodes)
            pods = _random_pods(rng, 2000, store=store, gangs=True,
                                pvcs=True, priorities=True)
            return nodes, pods, store

        nodes, pods, store_s = make(seed)
        serial_bound, serial_store = _run(nodes, pods, "serial",
                                          store=store_s)
        nodes, pods, store_b = make(seed)
        batch_bound, batch_store = _run(nodes, pods, "batch",
                                        store=store_b)
        nodes, pods, store_m = make(seed)
        sharded_bound, sharded_store = _run(nodes, pods, "sharded",
                                            store=store_m)
        diverged = [
            (k, batch_bound.get(k), sharded_bound.get(k))
            for k in set(batch_bound) | set(sharded_bound)
            if batch_bound.get(k) != sharded_bound.get(k)
        ]
        assert not diverged, (
            f"seed {seed}: batch vs sharded diverge on "
            f"{len(diverged)} pods: {diverged[:10]}"
        )
        assert set(serial_bound) == set(batch_bound), (
            f"seed {seed}: serial vs batch bound sets differ: "
            f"{sorted(set(serial_bound) ^ set(batch_bound))[:20]}"
        )
        _assert_valid(serial_bound, serial_store)
        _assert_valid(batch_bound, batch_store)
        _assert_valid(sharded_bound, sharded_store)
