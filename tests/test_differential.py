"""Workload-level differential ring (SURVEY.md section 4 carry-over):
the same randomized workload through the serial host path and the TPU
batch path must yield equivalent outcomes — identical bound-pod sets
(both paths are serial-equivalent in queue order) and placements that
satisfy every constraint — plus crash-recovery: a scheduler restart
rebuilds all state from the store (the control plane's "checkpoint" is
the API server; SURVEY.md section 5)."""

import random
import time

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config.feature_gates import FeatureGates
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.sidecar import attach_batch_scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


def _random_cluster(rng, n_nodes):
    nodes = []
    for i in range(n_nodes):
        nodes.append(
            MakeNode().name(f"n{i}")
            .label("topology.kubernetes.io/zone", f"z{i % 3}")
            .label("tier", "gold" if i % 4 == 0 else "std")
            .capacity({
                "cpu": str(rng.choice([4, 8, 16])),
                "memory": f"{rng.choice([8, 16, 32])}Gi",
            }).obj()
        )
    return nodes


def _random_pods(rng, count):
    pods = []
    for i in range(count):
        w = (
            MakePod().name(f"p{i}").uid(f"u{i}")
            .label("app", f"a{i % 5}")
            .req({
                "cpu": f"{rng.choice([100, 250, 500, 1000])}m",
                "memory": f"{rng.choice([64, 128, 256])}Mi",
            })
        )
        kind = rng.randrange(5)
        if kind == 0:
            w.spread_constraint(2, "topology.kubernetes.io/zone",
                                "DoNotSchedule", {"app": f"a{i % 5}"})
        elif kind == 1:
            w.pod_anti_affinity("app", [f"a{i % 5}"],
                                "kubernetes.io/hostname")
        elif kind == 2:
            w.node_selector({"tier": "gold"})
        pods.append(w.obj())
    return pods


def _run(nodes, pods, use_batch):
    store = ClusterStore()
    for n in nodes:
        store.add_node(n)
    sched = Scheduler.create(
        store, feature_gates=FeatureGates({"TPUBatchScheduler": use_batch})
    )
    bs = attach_batch_scheduler(sched, max_batch=32) if use_batch else None
    sched.start()
    for p in pods:
        store.create_pod(p)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        sched.queue.flush_backoff_completed()
        progressed = (
            bs.run_batch(pop_timeout=0.0) if bs
            else sched.schedule_one(pop_timeout=0.0)
        )
        if progressed:
            continue
        if sched.queue.num_active() == 0 and sched.queue.num_backoff() == 0:
            break
        time.sleep(0.01)
    assert sched.wait_for_inflight_bindings()
    bound = {
        p.metadata.name: p.spec.node_name
        for p in store.list_pods() if p.spec.node_name
    }
    sched.stop()
    return bound, store


def _assert_valid(bound, store):
    """Every placement satisfies capacity, selectors, spread, and
    anti-affinity — checked from first principles, independent of any
    scheduler code path."""
    nodes = {n.name: n for n in store.list_nodes()}
    pods = {p.metadata.name: p for p in store.list_pods()}
    cpu_used = {n: 0 for n in nodes}
    for name, node_name in bound.items():
        pod = pods[name]
        cpu_used[node_name] += int(
            pod.spec.containers[0].resources.requests["cpu"].milli_value()
        )
        sel = pod.spec.node_selector
        for k, val in sel.items():
            assert nodes[node_name].metadata.labels.get(k) == val, name
    for n, used in cpu_used.items():
        cap = int(nodes[n].status.allocatable["cpu"].milli_value())
        assert used <= cap, f"{n}: {used} > {cap}"
    # hostname anti-affinity: at most one pod per (app, node) among
    # pods that declare it
    seen = set()
    for name, node_name in bound.items():
        pod = pods[name]
        aff = pod.spec.affinity
        if aff is None or aff.pod_anti_affinity is None:
            continue
        key = (pod.metadata.labels.get("app"), node_name)
        assert key not in seen, f"anti-affinity violated on {node_name}"
        seen.add(key)


class TestSerialBatchEquivalence:
    def test_randomized_workloads(self):
        for seed in (7, 23, 99):
            rng = random.Random(seed)
            nodes = _random_cluster(rng, 12)
            pods = _random_pods(rng, 60)
            serial_bound, serial_store = _run(nodes, pods, use_batch=False)
            rng = random.Random(seed)
            nodes = _random_cluster(rng, 12)
            pods = _random_pods(rng, 60)
            batch_bound, batch_store = _run(nodes, pods, use_batch=True)
            # identical schedulability outcome pod-by-pod
            assert set(serial_bound) == set(batch_bound), (
                f"seed {seed}: bound sets differ: "
                f"{set(serial_bound) ^ set(batch_bound)}"
            )
            _assert_valid(serial_bound, serial_store)
            _assert_valid(batch_bound, batch_store)


class TestCrashRecovery:
    def test_scheduler_restart_resumes_from_store(self):
        """Kill the scheduler mid-workload; a fresh instance rebuilds
        cache/queue from the store (list+watch) and finishes. Nothing is
        persisted locally — exactly the reference's recovery model."""
        store = ClusterStore()
        for i in range(6):
            store.add_node(
                MakeNode().name(f"n{i}")
                .capacity({"cpu": "8", "memory": "16Gi"}).obj()
            )
        sched1 = Scheduler.create(
            store, feature_gates=FeatureGates({"TPUBatchScheduler": True})
        )
        bs1 = attach_batch_scheduler(sched1, max_batch=8)
        sched1.start()
        for i in range(40):
            store.create_pod(
                MakePod().name(f"p{i}").uid(f"u{i}").req({"cpu": "500m"}).obj()
            )
        # schedule a little, then crash (stop without draining). Two
        # cycles: the pipelined batch path solves on the first and
        # commits on the second — and a batch still in flight at crash
        # time must be recoverable from the store regardless.
        bs1.run_batch(pop_timeout=0.1)
        bs1.run_batch(pop_timeout=0.0)
        sched1.wait_for_inflight_bindings()
        sched1.stop()
        partial = sum(1 for p in store.list_pods() if p.spec.node_name)
        assert 0 < partial < 40

        sched2 = Scheduler.create(
            store, feature_gates=FeatureGates({"TPUBatchScheduler": True})
        )
        bs2 = attach_batch_scheduler(sched2, max_batch=8)
        sched2.start()  # replays store state: bound pods -> cache, rest -> queue
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            sched2.queue.flush_backoff_completed()
            if bs2.run_batch(pop_timeout=0.0):
                continue
            if sched2.queue.num_active() == 0 and \
                    sched2.queue.num_backoff() == 0:
                break
            time.sleep(0.01)
        assert sched2.wait_for_inflight_bindings()
        bound = [p for p in store.list_pods() if p.spec.node_name]
        assert len(bound) == 40
        # capacity respected across the restart boundary (8 cpu, 500m)
        per_node = {}
        for p in bound:
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(c <= 16 for c in per_node.values())
        sched2.stop()
