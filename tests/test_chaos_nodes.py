"""Node-churn resilience (ISSUE 3 tentpole): commit-time stale-node
guards on the batched scheduling path, the solver session's node-epoch
drift trigger, nodelifecycle flap re-registration, the eviction →
requeue rescue pipeline, and — marked slow — the full seeded node-churn
suite (``kubernetes_tpu.harness.chaos_nodes``).

Reference anchors: ``pkg/controller/nodelifecycle`` (monitorNodeHealth,
unreachable taint, pod eviction), ``pkg/controller/podgc`` (gcOrphaned),
and the scheduler's assume/bind contract — the store accepts binds to
nonexistent nodes, so the host-side guard is the only thing standing
between a stale solve and a pod bound into the void.
"""

import time

import pytest

from kubernetes_tpu.api.types import (
    NO_EXECUTE,
    NO_SCHEDULE,
    TAINT_NODE_UNREACHABLE,
    TAINT_NODE_UNSCHEDULABLE,
    Taint,
    Toleration,
)
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config.feature_gates import FeatureGates
from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics
from kubernetes_tpu.scheduler.core import ScheduleResult
from kubernetes_tpu.scheduler.framework.cycle_state import CycleState
from kubernetes_tpu.scheduler.scheduler import (
    Scheduler,
    commit_target_stale,
)
from kubernetes_tpu.sidecar import attach_batch_scheduler
from kubernetes_tpu.testing import MakeNode, MakePod

pytestmark = pytest.mark.chaos


def _stale_rejected_total() -> float:
    return sum(v for _, _, v in
               fabric_metrics().stale_binds_rejected_total.collect())


def _make_sched(store, batch: bool = False, max_batch: int = 16):
    gates = FeatureGates({"TPUBatchScheduler": batch})
    sched = Scheduler.create(store, feature_gates=gates)
    bs = attach_batch_scheduler(sched, max_batch=max_batch) if batch \
        else None
    sched.start()
    return sched, bs


def _pop_with_result(sched, node_name: str):
    qpi = sched.queue.pop(timeout=2.0)
    assert qpi is not None
    result = ScheduleResult(suggested_host=node_name, evaluated_nodes=1,
                            feasible_nodes=1)
    return qpi, result


# ---------------------------------------------------------------------------
# commit_target_stale verdicts (pure)


class TestCommitTargetStale:
    def test_missing_node_is_always_stale(self):
        pod = MakePod().name("p").obj()
        assert commit_target_stale(pod, None) == "deleted"

    def test_cordoned_node_rejects_unless_tolerated(self):
        node = MakeNode().name("n").unschedulable().obj()
        pod = MakePod().name("p").obj()
        assert commit_target_stale(pod, node) == "cordoned"
        pod.spec.tolerations.append(
            Toleration(key=TAINT_NODE_UNSCHEDULABLE, operator="Exists",
                       effect=NO_SCHEDULE))
        assert commit_target_stale(pod, node) is None

    def test_unreachable_taint_rejects_unless_tolerated(self):
        node = MakeNode().name("n").obj()
        node.spec.taints.append(
            Taint(TAINT_NODE_UNREACHABLE, "", NO_EXECUTE))
        pod = MakePod().name("p").obj()
        assert commit_target_stale(pod, node) == "unreachable"
        pod.spec.tolerations.append(
            Toleration(key=TAINT_NODE_UNREACHABLE, operator="Exists"))
        assert commit_target_stale(pod, node) is None

    def test_healthy_node_passes(self):
        node = MakeNode().name("n").obj()
        assert commit_target_stale(MakePod().name("p").obj(), node) is None


# ---------------------------------------------------------------------------
# cache probe


class TestCommitTargetFlags:
    def test_only_suspect_nodes_are_flagged(self):
        from kubernetes_tpu.scheduler.cache import SchedulerCache

        cache = SchedulerCache()
        cache.add_node(MakeNode().name("ok").obj())
        cache.add_node(MakeNode().name("cordoned").unschedulable().obj())
        tainted = MakeNode().name("unreachable").obj()
        tainted.spec.taints.append(
            Taint(TAINT_NODE_UNREACHABLE, "", NO_EXECUTE))
        cache.add_node(tainted)
        flags = cache.commit_target_flags(
            {"ok", "cordoned", "unreachable", "ghost"})
        assert "ok" not in flags
        assert flags["ghost"] is None
        assert flags["cordoned"].spec.unschedulable
        assert any(t.key == TAINT_NODE_UNREACHABLE
                   for t in flags["unreachable"].spec.taints)

    def test_node_set_seq_tracks_appear_and_vanish_only(self):
        from kubernetes_tpu.scheduler.cache import SchedulerCache

        cache = SchedulerCache()
        node = MakeNode().name("n").obj()
        seq0 = cache.node_set_seq
        cache.add_node(node)
        assert cache.node_set_seq == seq0 + 1
        updated = MakeNode().name("n").unschedulable().obj()
        cache.update_node(node, updated)       # update: set unchanged
        assert cache.node_set_seq == seq0 + 1
        cache.remove_node(updated)
        assert cache.node_set_seq == seq0 + 2


# ---------------------------------------------------------------------------
# serial-path guard


class TestSerialCommitGuard:
    def test_deleted_node_rejected_and_requeued(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n1")
                       .capacity({"cpu": "4", "memory": "8Gi"}).obj())
        sched, _ = _make_sched(store)
        try:
            store.create_pod(MakePod().name("p").uid("u")
                             .req({"cpu": "100m"}).obj())
            deadline = time.time() + 5
            while time.time() < deadline and sched.queue.num_active() == 0:
                time.sleep(0.02)
            qpi, result = _pop_with_result(sched, "n1")
            fwk = sched.profiles["default-scheduler"]
            # the node dies between schedule and commit
            sched.cache.remove_node(store.get_node("n1"))
            before = _stale_rejected_total()
            committed = sched.commit_assignment(
                fwk, CycleState(), qpi, result, 0, time.monotonic(),
                sync_bind=True)
            assert committed is False
            assert _stale_rejected_total() == before + 1
            # never bound; requeued, not lost
            assert store.get_pod("default", "p").spec.node_name == ""
            assert not sched.cache.is_assumed_pod(qpi.pod)
        finally:
            sched.stop()

    def test_bulk_commit_filters_stale_targets_only(self):
        store = ClusterStore()
        for name in ("n1", "n2"):
            store.add_node(MakeNode().name(name)
                           .capacity({"cpu": "4", "memory": "8Gi"}).obj())
        sched, _ = _make_sched(store)
        try:
            for i in range(2):
                store.create_pod(MakePod().name(f"p{i}").uid(f"u{i}")
                                 .req({"cpu": "100m"}).obj())
            deadline = time.time() + 5
            while time.time() < deadline and sched.queue.num_active() < 2:
                time.sleep(0.02)
            items, first_cycle = sched.queue.pop_batch(2, timeout=2.0)
            assert len(items) == 2
            fwk = sched.profiles["default-scheduler"]
            # p0 -> n1 (dies), p1 -> n2 (lives)
            targets = {"p0": "n1", "p1": "n2"}
            commits = [
                (qpi, ScheduleResult(
                    suggested_host=targets[qpi.pod.name],
                    evaluated_nodes=2, feasible_nodes=1),
                 first_cycle + i, time.monotonic())
                for i, qpi in enumerate(items)
            ]
            sched.cache.remove_node(store.get_node("n1"))
            before = _stale_rejected_total()
            committed, failed = sched.commit_assignments_bulk(fwk, commits)
            assert committed == 1 and failed == 1
            assert _stale_rejected_total() == before + 1
            assert store.get_pod("default", "p1").spec.node_name == "n2"
            assert store.get_pod("default", "p0").spec.node_name == ""
        finally:
            sched.stop()


# ---------------------------------------------------------------------------
# batch-path guard + session drift


class TestBatchPathStaleRouting:
    def test_node_death_between_solve_and_commit_routes_serial(self):
        """A batch solved against a snapshot containing a node that dies
        mid-cycle (after the pipelined mirror check, before the commit)
        must not bind there: the sidecar's guard routes the pod to the
        serial path, which places it on the surviving node, and the
        session is told the node planes drifted."""
        store = ClusterStore()
        for name in ("n1", "n2"):
            store.add_node(MakeNode().name(name)
                           .capacity({"cpu": "8", "memory": "16Gi"}).obj())
        sched, bs = _make_sched(store, batch=True)
        try:
            store.create_pod(MakePod().name("p").uid("u")
                             .req({"cpu": "100m"}).obj())
            deadline = time.time() + 5
            while time.time() < deadline and sched.queue.num_active() == 0:
                time.sleep(0.02)
            qpis = bs._drain(0.5)
            assert len(qpis) == 1
            res = bs.session.solve([q.pod for q, _ in qpis], lazy=True)
            handle, cluster, _seq = res
            pending = {
                "batchable": qpis,
                "handle": handle,
                "materializer": bs.session.last_materializer,
                "cluster": cluster,
                "profiles": bs.session.last_profile_idx,
                "inexpressible": bs.session.last_inexpressible,
                "masks": bs.session.static_masks_host,
                "start": time.monotonic(),
                "pad": bs._chunk,
            }
            mat = pending["materializer"] or (lambda h: h)
            assignments = mat(pending["handle"])
            pending["handle"] = assignments
            pending["materializer"] = None
            target = cluster.node_names[int(assignments[0])]
            survivor = "n2" if target == "n1" else "n1"
            # the solved target dies mid-cycle, before the commit
            sched.cache.remove_node(store.get_node(target))
            store.delete_node(target)
            before = _stale_rejected_total()
            serial = []
            committed = bs._commit_pending(pending, serial)
            assert committed == 0
            assert [q.pod.name for q in serial] == ["p"]
            assert _stale_rejected_total() == before + 1
            assert not bs.session.mirror_current()   # drift noted
            # the serial fallback gives the pod a live placement
            bs._run_serial(serial)
            deadline = time.time() + 10
            while time.time() < deadline and \
                    not store.get_pod("default", "p").spec.node_name:
                time.sleep(0.02)
            assert store.get_pod("default", "p").spec.node_name == survivor
        finally:
            sched.stop()

    def test_session_node_epoch_forces_reencode(self):
        """Mass node deletion must force an encoding rebuild: the
        incremental path may not serve an encoding whose node columns
        describe a vanished epoch, even if the mutation arithmetic is
        laundered back into agreement."""
        store = ClusterStore()
        for i in range(4):
            store.add_node(MakeNode().name(f"n{i}")
                           .capacity({"cpu": "8", "memory": "16Gi"}).obj())
        sched, bs = _make_sched(store, batch=True)
        try:
            store.create_pod(MakePod().name("p0").uid("u0")
                             .req({"cpu": "100m"}).obj())
            deadline = time.time() + 10
            while time.time() < deadline and \
                    not store.get_pod("default", "p0").spec.node_name:
                bs.run_batch(pop_timeout=0.05)
            bs.flush()
            session = bs.session
            rebuilds_before = session.rebuilds
            # forge mutation-arithmetic agreement, but move the node set
            session._last_seq = sched.cache.mutation_seq
            session._poisoned = False
            sched.cache.remove_node(store.get_node("n3"))
            session._last_seq = sched.cache.mutation_seq
            assert not session.mirror_current()
            res = session.solve(
                [MakePod().name("px").uid("ux")
                 .req({"cpu": "100m"}).obj()],
                incremental_only=True)
            assert res is None   # refused: rebuild required
            assert session.rebuilds == rebuilds_before
        finally:
            sched.stop()

    def test_note_drift_clears_static_fingerprint(self):
        store = ClusterStore()
        store.add_node(MakeNode().name("n1")
                       .capacity({"cpu": "8", "memory": "16Gi"}).obj())
        sched, bs = _make_sched(store, batch=True)
        try:
            session = bs.session
            session.solve([MakePod().name("p").uid("u")
                           .req({"cpu": "100m"}).obj()], warming=True)
            assert session._static_fp is not None
            session.note_drift()
            assert session._static_fp is None
            assert not session.mirror_current()
        finally:
            sched.stop()


# ---------------------------------------------------------------------------
# node flap re-registration (satellite)


class TestNodeFlapReRegistration:
    def test_recreated_node_gets_fresh_grace_and_cache_converges(self):
        """Delete + recreate a node with the same name mid-workload: the
        nodelifecycle on_delete purge must hand the fresh incarnation a
        full grace period (no inherited not-ready clock → no instant
        eviction), and the scheduler cache/node_tree must converge to
        exactly one live node."""
        from kubernetes_tpu.client.informers import SharedInformerFactory
        from kubernetes_tpu.controllers.nodelifecycle import (
            UNREACHABLE_TAINT,
            NodeLifecycleController,
        )
        from kubernetes_tpu.utils.clock import FakeClock

        store = ClusterStore()
        clock = FakeClock(start=100.0)
        store.add_node(MakeNode().name("n1")
                       .capacity({"cpu": "8", "memory": "16Gi"}).obj())
        factory = SharedInformerFactory(store)
        nlc = NodeLifecycleController(store, factory, clock=clock)
        factory.start()
        assert factory.wait_for_cache_sync()
        sched, _ = _make_sched(store)
        try:
            store.create_pod(MakePod().name("p").uid("u")
                             .req({"cpu": "100m"}).obj())
            store.bind("default", "p", "u", "n1")
            # node goes silent far past the grace: NotReady + tainted
            nlc.heartbeat("n1")
            nlc.monitor_node_health()
            clock.step(45.0)
            nlc.monitor_node_health()
            assert any(t.key == UNREACHABLE_TAINT
                       for t in store.get_node("n1").spec.taints)
            # flap: delete, then recreate under the SAME name
            store.delete_node("n1")
            deadline = time.time() + 5
            while time.time() < deadline and "n1" in nlc._not_ready_since:
                time.sleep(0.02)
            assert "n1" not in nlc._not_ready_since
            assert "n1" not in nlc._first_seen
            store.add_node(MakeNode().name("n1")
                           .capacity({"cpu": "8", "memory": "16Gi"}).obj())
            deadline = time.time() + 5
            while time.time() < deadline and \
                    nlc.node_lister.get("n1") is None:
                time.sleep(0.02)
            # the fresh incarnation is inside its own grace period: the
            # monitor must NOT taint or evict, even with no heartbeat yet
            nlc.monitor_node_health()
            node = store.get_node("n1")
            assert not any(t.key == UNREACHABLE_TAINT
                           for t in node.spec.taints)
            # half the grace later, still clean; past it, tainted again
            clock.step(nlc.grace_period / 2)
            nlc.monitor_node_health()
            assert not any(t.key == UNREACHABLE_TAINT
                           for t in store.get_node("n1").spec.taints)
            clock.step(nlc.grace_period)
            nlc.monitor_node_health()
            assert any(t.key == UNREACHABLE_TAINT
                       for t in store.get_node("n1").spec.taints)
            # scheduler cache/node_tree converged across the flap:
            # exactly one live n1
            deadline = time.time() + 5
            while time.time() < deadline and sched.cache.node_count() != 1:
                time.sleep(0.02)
            assert sched.cache.node_count() == 1
            dump = sched.cache.dump()
            live = [n for n, info in dump["nodes"].items()
                    if info.node is not None]
            assert live == ["n1"]
        finally:
            sched.stop()
            factory.stop()


# ---------------------------------------------------------------------------
# rescue pipeline (fast, store-level)


class TestRescuePipeline:
    def test_evicted_pod_is_recreated_and_rescue_latency_observed(self):
        from kubernetes_tpu.client.restcluster import RestClusterClient
        from kubernetes_tpu.apiserver.rest import APIServer
        from kubernetes_tpu.harness.chaos_nodes import PodRescuer

        store = ClusterStore()
        store.add_node(MakeNode().name("n1")
                       .capacity({"cpu": "8", "memory": "16Gi"}).obj())
        server = APIServer(store=store).start()
        client = RestClusterClient(server.url, watch_kinds=())
        rescuer = PodRescuer(store, client, name_prefix="cp-")
        rescuer.start()
        try:
            store.create_pod(MakePod().name("cp-0").uid("u0")
                             .req({"cpu": "100m"}).obj())
            store.bind("default", "cp-0", "u0", "n1")
            # eviction (what nodelifecycle does past the grace)
            store.delete_pod("default", "cp-0")
            deadline = time.time() + 10
            while time.time() < deadline:
                pod = store.get_pod("default", "cp-0")
                if pod is not None and pod.uid == "u0-r1":
                    break
                time.sleep(0.02)
            pod = store.get_pod("default", "cp-0")
            assert pod is not None and pod.uid == "u0-r1"
            assert pod.spec.node_name == ""   # re-enters scheduling
            assert rescuer.pending() == 1
            # replacement binds -> rescue completes with a latency sample
            store.bind("default", "cp-0", "u0-r1", "n1")
            deadline = time.time() + 10
            while time.time() < deadline and rescuer.pending():
                time.sleep(0.02)
            assert rescuer.pending() == 0
            assert len(rescuer.rescues) == 1 and rescuer.rescues[0] >= 0
        finally:
            rescuer.stop()
            server.shutdown_server()


# ---------------------------------------------------------------------------
# the full seeded node-churn suite (slow)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [3, 17, 29, 47, 61])
def test_node_churn_survives_death_flaps_and_stale_commits(seed):
    from kubernetes_tpu.harness.chaos_nodes import run_chaos_nodes

    result = run_chaos_nodes(seed, nodes=16, pods=96,
                             churn_profile="mixed", wait_timeout=120.0)
    assert result["ok"], (
        f"seed {seed}: {result['failure'] or result['invariants']} "
        f"(stats: {result['stats']})"
    )
    # the run was genuinely hostile: churn actually bit
    actions = result["stats"]["churn_actions"]
    assert sum(actions.values()) > 0
    assert actions["kill"] + actions["flap"] > 0
