"""Node groups + the simulated cloud provisioner.

The reference cluster-autoscaler abstracts providers behind
``cloudprovider.NodeGroup`` (TargetSize/IncreaseSize/DeleteNodes over a
template ``TemplateNodeInfo``); this module is that surface for the
harness's world: a ``NodeGroup`` is a node *template* (capacity, labels,
taints) plus min/max bounds, and the ``SimulatedProvisioner`` plays the
cloud — it creates and deletes REAL ``Node`` objects through the store
after a configurable boot latency, so nodelifecycle, the scheduler
cache/queue, and the churn guards all observe ordinary node add/remove
events (nothing downstream knows the node came from an autoscaler).

Group membership is carried on the node itself via the
``cluster-autoscaler.kubernetes.io/node-group`` label (the reference
uses provider-specific instance-group tags); statically-created nodes
can opt into a group by carrying the same label.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.api.types import Node, Taint

NODE_GROUP_LABEL = "cluster-autoscaler.kubernetes.io/node-group"
# upstream's opt-in for evicting pods without a controller during drain
SAFE_TO_EVICT_ANNOTATION = "cluster-autoscaler.kubernetes.io/safe-to-evict"


@dataclass
class NodeGroup:
    """One node template with scaling bounds (cloudprovider.NodeGroup)."""

    name: str
    cpu: str = "32"
    memory: str = "64Gi"
    max_pods: int = 110
    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    min_size: int = 0
    max_size: int = 10
    priority: int = 0          # consulted by the "priority" expander
    boot_latency: float = 0.0  # seconds between provision and Node add

    def node_template(self, index) -> Node:
        """A concrete Node of this group (TemplateNodeInfo). ``index``
        also serves the what-if simulator, which stamps virtual names
        that never reach the store."""
        name = f"{self.name}-{index}"
        node = Node()
        node.metadata.name = name
        node.metadata.labels.update({
            "kubernetes.io/hostname": name,
            NODE_GROUP_LABEL: self.name,
        })
        node.metadata.labels.update(self.labels)
        for key, value in (("cpu", self.cpu), ("memory", self.memory),
                           ("pods", str(self.max_pods))):
            q = parse_quantity(value)
            node.status.capacity[key] = q
            node.status.allocatable[key] = q
        node.spec.taints = [Taint(t.key, t.value, t.effect)
                            for t in self.taints]
        return node


class NodeGroupRegistry:
    """Name → NodeGroup registry + node→group resolution."""

    def __init__(self, groups: Optional[List[NodeGroup]] = None):
        self._groups: Dict[str, NodeGroup] = {}
        for g in groups or ():
            self.add(g)

    def add(self, group: NodeGroup) -> NodeGroup:
        self._groups[group.name] = group
        return group

    def get(self, name: str) -> Optional[NodeGroup]:
        return self._groups.get(name)

    def names(self) -> List[str]:
        return sorted(self._groups)

    def __iter__(self) -> Iterator[NodeGroup]:
        return iter([self._groups[n] for n in sorted(self._groups)])

    def __len__(self) -> int:
        return len(self._groups)

    @staticmethod
    def group_of(node: Node) -> Optional[str]:
        return node.metadata.labels.get(NODE_GROUP_LABEL)


class SimulatedProvisioner:
    """The cloud side of the autoscaler: asynchronously materializes
    group nodes as real store objects after the group's boot latency
    (instance spin-up), and deletes them on scale-down. One worker
    thread drives a ready-time heap; ``boot_latency == 0`` creates
    synchronously so unit tests stay deterministic."""

    def __init__(self, store, registry: NodeGroupRegistry):
        self._store = store
        self._registry = registry
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # (ready_at_monotonic, seq, group_name, Node)
        self._boot_heap: List[Tuple[float, int, str, Node]] = []
        # nodes popped from the heap (or provisioned synchronously) but
        # whose store add hasn't landed yet: still "booting" to every
        # reader, or the scale-up re-buy guard goes blind in the window
        # between pop and registration
        self._registering: List[Tuple[str, Node]] = []
        self._seq = itertools.count()
        self._next_index: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.provisioned_total = 0
        self.deleted_total = 0

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="node-provisioner")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- sizing --------------------------------------------------------
    def _ensure_index_seed(self, group: NodeGroup) -> None:
        """Seed the group's name counter past any statically-created
        members so names never collide (a reused name would replay the
        churn harness's flap re-registration path by accident). The
        store scan runs with NO provisioner lock held — the store
        dispatches watch handlers under its own lock, so nesting
        provisioner→store here would arm an ABBA deadlock against any
        handler that queries the provisioner."""
        with self._lock:
            if group.name in self._next_index:
                return
        prefix = f"{group.name}-"
        nxt = 0
        for node in self._store.list_nodes():
            if NodeGroupRegistry.group_of(node) != group.name:
                continue
            suffix = node.name[len(prefix):] \
                if node.name.startswith(prefix) else ""
            if suffix.isdigit():
                nxt = max(nxt, int(suffix) + 1)
        with self._lock:
            self._next_index.setdefault(group.name, nxt)

    def _allocate_index(self, group: NodeGroup) -> int:
        """Caller holds the lock and has called _ensure_index_seed."""
        nxt = self._next_index.get(group.name, 0)
        self._next_index[group.name] = nxt + 1
        return nxt

    def live_count(self, group_name: str) -> int:
        return sum(
            1 for n in self._store.list_nodes()
            if NodeGroupRegistry.group_of(n) == group_name
        )

    def booting_count(self, group_name: str) -> int:
        with self._lock:
            return sum(1 for _, _, g, _ in self._boot_heap
                       if g == group_name) \
                + sum(1 for g, _ in self._registering if g == group_name)

    def group_size(self, group_name: str) -> int:
        """Booting + live — the target-size analog the max-size cap and
        the what-if headroom must both respect (counting live only
        would double-provision while instances boot). Booting is read
        FIRST: a node completing registration between the two reads is
        then double-counted (harmless overcount) instead of counted in
        neither (headroom inflated past max size)."""
        return self.booting_count(group_name) + self.live_count(group_name)

    def booting_templates(self, group_name: Optional[str] = None
                          ) -> List[Node]:
        """Nodes provisioned but not yet registered — the reference's
        "upcoming nodes", which the scale-up simulation must count as
        capacity or every loop iteration re-buys the same nodes."""
        with self._lock:
            return [node for _, _, g, node in self._boot_heap
                    if group_name is None or g == group_name] + [
                node for g, node in self._registering
                if group_name is None or g == group_name]

    # -- provisioning --------------------------------------------------
    def provision(self, group: NodeGroup, count: int) -> List[str]:
        """Start ``count`` instances of ``group``; returns their node
        names. Registration (the store add) happens after
        ``group.boot_latency``."""
        import time

        names: List[str] = []
        immediate: List[Node] = []
        self._ensure_index_seed(group)
        ready_at = time.monotonic() + group.boot_latency
        with self._cond:
            for _ in range(max(0, count)):
                node = group.node_template(self._allocate_index(group))
                names.append(node.name)
                if group.boot_latency <= 0:
                    immediate.append(node)
                    self._registering.append((group.name, node))
                else:
                    heapq.heappush(
                        self._boot_heap,
                        (ready_at, next(self._seq), group.name, node))
            self._cond.notify_all()
        # register OUTSIDE the lock, like the worker loop: the store add
        # fans watch deliveries out synchronously, and a watch handler
        # querying the provisioner must never find the lock held
        for node in immediate:
            self._register(node)
            with self._lock:
                self._registering.remove((group.name, node))
        return names

    def deprovision(self, node_name: str) -> None:
        self._store.delete_node(node_name)
        self.deleted_total += 1

    def _register(self, node: Node) -> None:
        try:
            self._store.add_node(node)
        except Exception:  # noqa: BLE001 — e.g. name collision on replay
            return
        self.provisioned_total += 1

    def _loop(self) -> None:
        import time

        while not self._stop.is_set():
            with self._cond:
                if not self._boot_heap:
                    self._cond.wait(0.5)
                    continue
                now = time.monotonic()
                ready_at = self._boot_heap[0][0]
                if ready_at > now:
                    self._cond.wait(min(ready_at - now, 0.5))
                    continue
                _, _, gname, node = heapq.heappop(self._boot_heap)
                self._registering.append((gname, node))
            # register OUTSIDE the lock: the store add fans out watch
            # deliveries (scheduler cache, informers) synchronously
            self._register(node)
            with self._lock:
                self._registering.remove((gname, node))
