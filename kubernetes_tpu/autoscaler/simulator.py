"""Scale-up/scale-down what-if simulation over virtual node columns.

The reference cluster-autoscaler answers "would a new node help, and
how many are needed?" by running the scheduler's predicate/priority
code one pending pod at a time against a hypothetical NodeInfo
(``simulator/scheduler_based_predicate_checker.go`` FitsAnyNode in a
loop over pods). That per-pod loop is exactly the shape this project
exists to batch: here the hypothetical capacity becomes K extra
template-node COLUMNS appended to the encoded node planes
(``ops/encode.py`` ``extra_nodes``), score-penalized so the scan solver
(``ops/solver.py`` ``solve_whatif``) only spills pods onto them when no
real node fits — ONE batched solve estimates placements for the whole
pending set, and reading off which virtual columns received
assignments yields the per-group node count (a vectorized bin-packing
estimator).

``serial=True`` routes the same question through a per-pod numpy loop
(``_serial_whatif``) — the reference-shaped serial simulation that the
differential tests hold the batched path against.

Three penalty tiers order capacity preference:
real nodes (no penalty) > upcoming/booting nodes (half penalty) >
hypothetical new nodes (full ``VIRTUAL_NODE_PENALTY``) — pods use
capacity that exists, then capacity already paid for, and only then
demand new nodes.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api.types import Node, Pod, shallow_copy
from kubernetes_tpu.autoscaler.nodegroups import NodeGroup
from kubernetes_tpu.ops.encode import BatchEncoder, EncodedBatch, EncodedCluster
from kubernetes_tpu.ops.solver import (
    BIG,
    NEG_INF,
    SolverParams,
    VIRTUAL_NODE_PENALTY,
    solve_whatif,
)
from kubernetes_tpu.scheduler.snapshot import new_snapshot

UPCOMING_NODE_PENALTY = float(VIRTUAL_NODE_PENALTY) / 2.0
# Graded per-column step WITHIN a tier: column k gets tier + k*STEP, so
# the scan fills virtual column 0 until infeasible before touching
# column 1 — first-fit bin-packing. Without it the least-allocated
# score prefers the emptiest virtual node and every pod buys its own.
# The step must dominate the real score range (balanced+least+spread
# sum to a few hundred) while staying far below the tier separation
# (5e5) times the column budget.
VIRTUAL_COLUMN_STEP = 1000.0
WHATIF_PREFIX = "whatif"


@dataclass
class WhatIfResult:
    assignments: np.ndarray      # [num_real_pods] node column or -1
    counts: np.ndarray           # [N] pods assigned per column
    cluster: EncodedCluster
    batch: EncodedBatch
    virtual_cols: List[int]      # columns of the hypothetical new nodes
    upcoming_cols: List[int]     # columns of still-booting nodes


@dataclass
class ScaleUpOption:
    """One group's what-if outcome (cloudprovider expansion.Option)."""

    group: str
    nodes_needed: int    # virtual columns that received >= 1 pod
    pods_placed: int     # pending pods that received ANY assignment
    pods_on_new: int     # of those, pods that needed a NEW node
    waste: float         # mean unused capacity fraction of the new nodes


@dataclass
class ScaleUpPlan:
    chosen: Optional[ScaleUpOption]
    options: List[ScaleUpOption]
    solves: int          # what-if solves issued (== candidate groups)


# ---------------------------------------------------------------------------
# the core what-if


def run_whatif(
    nodes: Sequence[Node],
    bound_pods: Sequence[Pod],
    batch_pods: Sequence[Pod],
    *,
    new_nodes: Sequence[Node] = (),
    upcoming_nodes: Sequence[Node] = (),
    disabled_names: Sequence[str] = (),
    serial: bool = False,
    params: SolverParams = SolverParams(),
    pad_pods: int = 64,
) -> WhatIfResult:
    """Encode (real cluster + extra columns) and solve. ``new_nodes``
    get the full virtual penalty, ``upcoming_nodes`` the half tier,
    ``disabled_names`` are removed from the solve (scale-down)."""
    snapshot = new_snapshot(bound_pods, list(nodes))
    extras = list(upcoming_nodes) + list(new_nodes)
    enc = BatchEncoder(snapshot, extra_nodes=extras)
    cluster, batch = enc.encode(list(batch_pods), pad_pods=pad_pods)
    base = enc.num_snapshot_nodes
    upcoming_cols = list(range(base, base + len(upcoming_nodes)))
    virtual_cols = list(range(base + len(upcoming_nodes),
                              base + len(extras)))
    # clamp the graded upcoming tier strictly below the virtual tier:
    # past ~500 booting columns the j*STEP ramp would otherwise cross
    # VIRTUAL_NODE_PENALTY and the scan would buy new nodes over
    # capacity that is already spinning up
    upcoming_cap = float(VIRTUAL_NODE_PENALTY) - VIRTUAL_COLUMN_STEP
    penalties: Dict[int, float] = {
        c: min(UPCOMING_NODE_PENALTY + j * VIRTUAL_COLUMN_STEP,
               upcoming_cap)
        for j, c in enumerate(upcoming_cols)
    }
    penalties.update({
        c: float(VIRTUAL_NODE_PENALTY) + j * VIRTUAL_COLUMN_STEP
        for j, c in enumerate(virtual_cols)
    })
    col_of = {name: i for i, name in enumerate(cluster.node_names)}
    disabled = [col_of[n] for n in disabled_names if n in col_of]
    solver = _serial_whatif if serial else solve_whatif
    assignments, counts = solver(
        cluster, batch, params,
        deprioritized_cols=penalties, disabled_cols=disabled,
    )
    return WhatIfResult(
        assignments=assignments, counts=counts, cluster=cluster,
        batch=batch, virtual_cols=virtual_cols,
        upcoming_cols=upcoming_cols,
    )


def _pending_order(pods: Sequence[Pod]) -> List[Pod]:
    """Queue-equivalent order (PrioritySort): priority desc, then age."""
    return sorted(
        pods,
        key=lambda p: (-p.priority(),
                       p.metadata.creation_timestamp or 0.0,
                       p.metadata.name),
    )


def scale_up_option(
    nodes: Sequence[Node],
    bound_pods: Sequence[Pod],
    pending: Sequence[Pod],
    group: NodeGroup,
    headroom: int,
    *,
    upcoming_nodes: Sequence[Node] = (),
    serial: bool = False,
    max_virtual: int = 64,
    params: SolverParams = SolverParams(),
    pad_pods: int = 64,
) -> Optional[ScaleUpOption]:
    """One group's what-if: append K = min(headroom, |pending|,
    max_virtual) virtual columns of this group's template and read off
    how many received assignments."""
    k = max(0, min(int(headroom), len(pending), int(max_virtual)))
    if k == 0:
        return None
    virt = [group.node_template(f"{WHATIF_PREFIX}-{i}") for i in range(k)]
    res = run_whatif(
        nodes, bound_pods, pending, new_nodes=virt,
        upcoming_nodes=upcoming_nodes, serial=serial, params=params,
        pad_pods=pad_pods,
    )
    vset = set(res.virtual_cols)
    placed = int((res.assignments >= 0).sum())
    pods_on_new = int(sum(int(res.counts[c]) for c in res.virtual_cols))
    nodes_needed = int(sum(1 for c in res.virtual_cols
                           if res.counts[c] > 0))
    return ScaleUpOption(
        group=group.name, nodes_needed=nodes_needed,
        pods_placed=placed, pods_on_new=pods_on_new,
        waste=_waste(res, vset),
    )


def _waste(res: WhatIfResult, vset: set) -> float:
    """Mean unused cpu/mem fraction across the virtual columns that
    were used (the least-waste expander's criterion)."""
    used: Dict[int, Tuple[int, int]] = {}
    for bi, col in enumerate(res.assignments):
        col = int(col)
        if col in vset:
            uc, um = used.get(col, (0, 0))
            used[col] = (uc + int(res.batch.requests[bi, 0]),
                         um + int(res.batch.requests[bi, 1]))
    fracs = []
    for col, (uc, um) in used.items():
        ac = max(int(res.cluster.allocatable[col, 0]), 1)
        am = max(int(res.cluster.allocatable[col, 1]), 1)
        fracs.append(((ac - uc) / ac + (am - um) / am) / 2.0)
    return sum(fracs) / len(fracs) if fracs else 0.0


# ---------------------------------------------------------------------------
# expanders (reference cluster-autoscaler/expander)


def _expand_least_waste(options: List[ScaleUpOption], groups) -> ScaleUpOption:
    """Most pods helped first, then least wasted capacity (the
    reference waste expander), then fewest nodes, then name."""
    return min(options, key=lambda o: (-o.pods_placed, o.waste,
                                       o.nodes_needed, o.group))


def _expand_priority(options: List[ScaleUpOption], groups) -> ScaleUpOption:
    """Highest configured group priority wins (the reference priority
    expander); pods helped / fewest nodes / name break ties."""
    def prio(o: ScaleUpOption) -> int:
        g = groups.get(o.group)
        return g.priority if g is not None else 0

    return min(options, key=lambda o: (-prio(o), -o.pods_placed,
                                       o.nodes_needed, o.group))


EXPANDERS = {
    "least-waste": _expand_least_waste,
    "priority": _expand_priority,
}


def plan_scale_up(
    nodes: Sequence[Node],
    bound_pods: Sequence[Pod],
    pending: Sequence[Pod],
    groups: Sequence[Tuple[NodeGroup, int]],
    expander: str = "least-waste",
    *,
    upcoming: Sequence[Node] = (),
    serial: bool = False,
    max_virtual: int = 64,
    max_pods: int = 2048,
    params: SolverParams = SolverParams(),
    pad_pods: int = 64,
) -> ScaleUpPlan:
    """The full scale-up decision: one what-if per candidate group
    (NOT one per pod), then the expander picks among the options.
    ``groups`` pairs each candidate with its remaining headroom."""
    pending = _pending_order(pending)[: max_pods]
    options: List[ScaleUpOption] = []
    solves = 0
    for group, headroom in groups:
        opt = scale_up_option(
            nodes, bound_pods, pending, group, headroom,
            upcoming_nodes=upcoming, serial=serial,
            max_virtual=max_virtual, params=params, pad_pods=pad_pods,
        )
        if opt is None:
            continue
        solves += 1
        if opt.pods_on_new > 0 and opt.nodes_needed > 0:
            options.append(opt)
    chosen = None
    if options:
        by_name = {g.name: g for g, _ in groups}
        chosen = EXPANDERS[expander](options, by_name)
    return ScaleUpPlan(chosen=chosen, options=options, solves=solves)


# ---------------------------------------------------------------------------
# scale-down: the same machinery with a column removed


def _unbound_copy(pod: Pod) -> Pod:
    p = shallow_copy(pod)
    p.spec = copy.copy(pod.spec)
    p.spec.node_name = ""
    return p


def pods_fit_elsewhere(
    nodes: Sequence[Node],
    bound_pods: Sequence[Pod],
    node_name: str,
    its_pods: Sequence[Pod],
    *,
    serial: bool = False,
    params: SolverParams = SolverParams(),
    pad_pods: int = 64,
) -> bool:
    """Scale-down feasibility: with ``node_name``'s column disabled,
    does every one of its pods receive an assignment somewhere else?
    Conservative by construction — the candidate's existing pods stay
    in the encoded usage planes (on the disabled column, where they no
    longer matter) and in the topology counts (where they can only make
    re-placement harder, never easier)."""
    if not its_pods:
        return True
    unbound = [_unbound_copy(p) for p in its_pods]
    res = run_whatif(
        nodes, bound_pods, unbound, disabled_names=[node_name],
        serial=serial, params=params, pad_pods=pad_pods,
    )
    return bool(np.all(res.assignments[: len(unbound)] >= 0))


# ---------------------------------------------------------------------------
# the serial oracle (per-pod loop, numpy — reference-shaped simulation)


def _serial_whatif(
    cluster: EncodedCluster, batch: EncodedBatch,
    params: SolverParams = SolverParams(),
    deprioritized_cols=(),
    disabled_cols=(),
):
    """Per-pod re-simulation over the same encoded planes: one Python
    loop iteration per pod, full-width numpy per node — the shape of
    upstream's serial simulation, used as the differential oracle for
    ``solve_whatif``. Same contract: (assignments, per-node counts).
    All float arithmetic is float32 to match the device solver."""
    f32 = np.float32
    n = cluster.allocatable.shape[0]
    v = batch.num_values
    allocatable = cluster.allocatable.astype(np.int32)
    max_pods = cluster.max_pods.astype(np.int32)
    requested = cluster.requested.astype(np.int32).copy()
    nonzero_requested = cluster.nonzero_requested.astype(np.int32).copy()
    pod_count = cluster.pod_count.astype(np.int32).copy()
    sc_counts = batch.sc_counts.astype(np.int32).copy()
    term_counts = batch.term_counts.astype(np.int32).copy()
    term_owners = batch.term_owners.astype(np.int32).copy()
    sc_codes = np.minimum(
        cluster.topo_codes[:, batch.sc_key_idx].T, v).astype(np.int32)
    term_codes = np.minimum(
        cluster.topo_codes[:, batch.term_key_idx].T, v).astype(np.int32)

    node_valid = np.zeros(n, dtype=bool)
    node_valid[: cluster.num_real_nodes] = True
    if len(disabled_cols):
        node_valid[np.asarray(list(disabled_cols), dtype=np.int64)] = False
    static_scores = np.array(batch.static_scores, dtype=f32, copy=True)
    if len(deprioritized_cols):
        if hasattr(deprioritized_cols, "items"):
            for col, penalty in deprioritized_cols.items():
                static_scores[:, int(col)] -= f32(penalty)
        else:
            cols = np.asarray(list(deprioritized_cols), dtype=np.int64)
            static_scores[:, cols] -= VIRTUAL_NODE_PENALTY

    b = batch.num_real_pods
    assignments = np.full(b, -1, dtype=np.int32)
    arange_sc = np.arange(sc_counts.shape[0])
    arange_t = np.arange(term_counts.shape[0])
    for bi in range(b):
        if batch.inexpressible[bi]:
            continue
        req = batch.requests[bi].astype(np.int32)
        nz = batch.nonzero_requests[bi].astype(np.int32)
        profile = int(batch.profile_idx[bi])
        pod_sc = batch.pod_sc[bi]
        pod_sc_match = batch.pod_sc_match[bi]
        match_by = batch.match_by[bi]
        own_aff = batch.own_aff[bi]
        own_anti = batch.own_anti[bi]
        pref_weight = batch.pref_weight[bi].astype(f32)

        fit = np.all(requested + req[None, :] <= allocatable, axis=1)
        fit &= pod_count < max_pods
        static_ok = batch.static_masks[profile]

        counts_at = np.take_along_axis(sc_counts, sc_codes, axis=1)
        domain = batch.sc_domain[profile]
        min_c = np.min(np.where(domain[:, :v], sc_counts[:, :v], BIG),
                       axis=1)
        min_c = np.where(np.any(domain[:, :v], axis=1), min_c, 0)
        skew = counts_at + pod_sc_match[:, None].astype(np.int32) \
            - min_c[:, None]
        missing = sc_codes >= v
        active_hard = pod_sc & batch.sc_hard
        spread_violation = np.any(
            active_hard[:, None]
            & ((skew > batch.sc_max_skew[:, None]) | missing),
            axis=0,
        )

        tcounts_at = np.take_along_axis(term_counts, term_codes, axis=1)
        towners_at = np.take_along_axis(term_owners, term_codes, axis=1)
        t_missing = term_codes >= v
        existing_anti_block = np.any(
            match_by[:, None] & (towners_at > 0), axis=0)
        own_anti_block = np.any(
            own_anti[:, None] & (tcounts_at > 0), axis=0)
        aff_here = (tcounts_at > 0) & ~t_missing
        aff_sat = np.all(~own_aff[:, None] | aff_here, axis=0)
        totals = np.sum(term_counts[:, :v], axis=1)
        no_any = bool(np.all(~own_aff | (totals == 0)))
        self_all = bool(np.all(~own_aff | match_by))
        if np.any(own_aff):
            aff_ok = aff_sat | (no_any and self_all)
        else:
            aff_ok = np.ones(n, dtype=bool)

        feasible = (
            node_valid & static_ok & fit & ~spread_violation
            & ~existing_anti_block & ~own_anti_block & aff_ok
        )

        alloc_cpu = np.maximum(allocatable[:, 0], 1).astype(f32)
        alloc_mem = np.maximum(allocatable[:, 1], 1).astype(f32)
        cpu_frac = (nonzero_requested[:, 0] + nz[0]).astype(f32) / alloc_cpu
        mem_frac = (nonzero_requested[:, 1] + nz[1]).astype(f32) / alloc_mem
        over = (cpu_frac >= 1.0) | (mem_frac >= 1.0)
        balanced = np.where(
            over, f32(0.0),
            (f32(1.0) - np.abs(cpu_frac - mem_frac)) * f32(100.0))
        least = (
            np.clip(f32(1.0) - cpu_frac, 0.0, 1.0)
            + np.clip(f32(1.0) - mem_frac, 0.0, 1.0)
        ) * f32(50.0)

        active_soft = pod_sc & ~batch.sc_hard
        soft_counts = np.sum(
            np.where(active_soft[:, None], counts_at, 0), axis=0
        ).astype(f32)
        if np.any(active_soft):
            spread_score = f32(100.0) / (f32(1.0) + soft_counts)
        else:
            spread_score = np.zeros(n, dtype=f32)

        pref_score = np.sum(
            pref_weight[:, None] * tcounts_at.astype(f32), axis=0)

        score = (
            f32(params.balanced_weight) * balanced
            + f32(params.least_weight) * least
            + f32(params.spread_weight) * spread_score
            + f32(params.affinity_weight) * pref_score
            + f32(params.static_weight) * static_scores[profile]
        )
        score = np.where(feasible, score, f32(NEG_INF))
        if not np.any(feasible):
            continue
        chosen = int(np.argmax(score))
        assignments[bi] = chosen
        requested[chosen] += req
        nonzero_requested[chosen] += nz
        pod_count[chosen] += 1
        np.add.at(sc_counts, (arange_sc, sc_codes[:, chosen]),
                  pod_sc_match.astype(np.int32))
        np.add.at(term_counts, (arange_t, term_codes[:, chosen]),
                  match_by.astype(np.int32))
        np.add.at(term_owners, (arange_t, term_codes[:, chosen]),
                  own_anti.astype(np.int32))
    counts = np.bincount(assignments[assignments >= 0], minlength=n)
    return assignments, counts
